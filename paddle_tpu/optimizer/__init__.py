"""Optimizers.

TPU-native redesign of the reference's optimizer family
(/root/reference/paddle/fluid/operators/optimizers/: sgd_op.cc,
momentum_op.cc, lars_momentum_op.cc, adam_op.cc/adam_op.h, adamax_op.cc,
adagrad_op.cc, adadelta_op.cc, rmsprop_op.cc, ftrl_op.cc, lamb_op.cc,
dpsgd_op.cc + python/paddle/fluid/optimizer.py:55). In the reference each
optimizer is a graph op mutating params in a scope; here each is a pure
``(params, grads, state, step) -> (new_params, new_state)`` transform that
compiles INTO the jitted train step with donated buffers — the in-graph
update capability, the XLA way. The stateful ``step()`` method gives eager
(dygraph) parity on an attached Layer.

Sparse RowSlices grads (ops/sparse.py, SelectedRows analogue) get row-wise
updates for SGD/Adagrad/Momentum (lazy-mode semantics of the reference's
selected-rows kernels, adam_op.h:473).
"""

from __future__ import annotations

from typing import Any, Callable, Dict, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp

from ..core.random import make_key
from ..nn.layer import Layer, Parameter
from ..ops.sparse import RowSlices, scatter_apply, to_dense
from . import lr as lr_module
from .lr import LRScheduler, resolve_lr


def _tree_map(fn, *trees):
    return jax.tree.map(fn, *trees,
                        is_leaf=lambda x: isinstance(x, RowSlices))


def _moment_dtype(default):
    """Storage dtype for Adam-family moments: FLAGS_optimizer_moment_
    dtype=bfloat16 halves the m/v HBM traffic (update math stays fp32;
    fp32 masters unaffected)."""
    from ..flags import GLOBAL_FLAGS
    val = GLOBAL_FLAGS.get("optimizer_moment_dtype")
    if val == "bfloat16":
        return jnp.bfloat16
    if val != "float32":
        # a typo'd value silently measuring the fp32 baseline would
        # corrupt exactly the A/B evidence this flag exists to produce
        raise ValueError(
            f"optimizer_moment_dtype={val!r}: expected 'float32' or "
            "'bfloat16'")
    return default


def _as_f32(x):
    """Upcast a low-precision leaf to fp32 for optimizer math.

    Master-weight semantics of the reference's AMP path
    (/root/reference/python/paddle/fluid/contrib/mixed_precision/decorator.py):
    update math always runs in fp32 even when params/grads are bf16/fp16;
    apply_gradients casts the result back to the param's own dtype.
    """
    dtype = getattr(x, "dtype", None)
    if dtype in (jnp.bfloat16, jnp.float16):
        return x.astype(jnp.float32)
    return x


def _fused_eligible(p) -> bool:
    """Leaves that can join the flat fused-state pack: dense floating
    arrays (RowSlices params/ints stay on the per-leaf path)."""
    if isinstance(p, RowSlices):
        return False
    dt = getattr(p, "dtype", None)
    return dt is not None and jnp.issubdtype(dt, jnp.floating)


class Optimizer:
    """Base optimizer.

    Functional protocol (used by jitted train steps):
      state = opt.init(params)
      new_params, new_state = opt.apply_gradients(params, grads, state)

    Eager protocol (dygraph parity):
      opt = Adam(parameters=model.parameters()); loss_grads = ...;
      opt.step(grads)  # or attach via set_grads then step()
    """

    # Optimizers whose update() is purely elementwise can run the fused
    # flat-state path (flags.optimizer_fused_state): m/v/master packed
    # into ONE fp32 vector each, collapsing ~3 runtime buffers per
    # parameter into 3 total. Lamb/Lars need per-parameter norms and
    # stay per-leaf.
    _elementwise_update = False

    def __init__(self, learning_rate=0.001, parameters=None,
                 weight_decay: Optional[float] = None, grad_clip=None,
                 name: Optional[str] = None,
                 fused_state: Optional[bool] = None,
                 regularization=None) -> None:
        self.learning_rate = learning_rate
        self._parameter_list = list(parameters) if parameters else None
        # the reference's ``regularization=L2Decay(...)`` spelling is an
        # alias for weight_decay; both floats and regularizer objects
        # (called as reg(param, grad)) are accepted either way
        if weight_decay is None and regularization is not None:
            weight_decay = regularization
        self.weight_decay = weight_decay
        self.grad_clip = grad_clip
        self._fused_state = fused_state
        self._eager_state = None
        # per-parameter ParamAttr metadata (set_param_meta): {name:
        # (need_clip, regularizer)}; consumed when grads/params are
        # name-keyed dicts (the TrainStep contract)
        self._param_meta: Dict[str, Any] = {}

    def set_param_meta(self, meta) -> None:
        """Record per-parameter ParamAttr metadata: ``{name:
        (need_clip, regularizer)}``. need_clip=False excludes that
        parameter from grad_clip; a per-param regularizer replaces the
        optimizer-level weight_decay for that parameter (reference
        semantics: ParamAttr.regularizer overrides optimizer
        regularization)."""
        self._param_meta = dict(meta)

    def _with_zeroed_attr(self, attr: str, fn):
        """Run ``fn`` with ``self.<attr>`` temporarily set to 0.0 —
        trace-time only (the per-leaf loop is sequential Python), used
        by name-filtered decay exclusions."""
        saved = getattr(self, attr)
        setattr(self, attr, 0.0)
        try:
            return fn()
        finally:
            setattr(self, attr, saved)

    def _decay_grad(self, g, p32, reg=None):
        """Apply weight decay to a grad: per-param regularizer if set,
        else the optimizer-level weight_decay (float coefficient or a
        regularizer object called as reg(param, grad))."""
        wd = reg if reg is not None else self.weight_decay
        if not wd:
            return g
        if callable(wd):
            return wd(p32, g)
        return g + wd * p32

    def _use_fused(self) -> bool:
        if not self._elementwise_update:
            return False
        if self._fused_state is not None:
            return bool(self._fused_state)
        from ..flags import GLOBAL_FLAGS
        return bool(GLOBAL_FLAGS.get("optimizer_fused_state"))

    # ------------------------------------------------------------------
    # functional API
    # ------------------------------------------------------------------
    def init(self, params) -> Dict[str, Any]:
        # Slots always live in fp32 regardless of param dtype (bf16 moment
        # buffers diverge); update math runs in fp32 and the new param is
        # cast back to its own dtype — see apply_gradients. This also keeps
        # the train state's dtypes fixed across steps (a dtype that drifts
        # bf16->fp32 between calls forces jit recompiles).
        #
        # Low-precision params additionally get a persistent fp32 MASTER
        # copy in their slots: without it, p32 - lr*u rounds back to the
        # old bf16 value whenever the update is below half an ulp (~0.4%
        # relative for bf16), silently freezing training. The master
        # accumulates sub-ulp updates; the bf16 param is its cast-down view
        # (reference AMP master weights: contrib/mixed_precision/
        # decorator.py _create_master_weight).
        def mk(p):
            slots = dict(self.init_slots(_as_f32(p)))
            if getattr(p, "dtype", None) in (jnp.bfloat16, jnp.float16):
                slots["master"] = jnp.asarray(p, jnp.float32)
            return slots

        if self._use_fused():
            # Fused flat state: ONE fp32 master + one buffer per slot
            # kind for ALL eligible leaves (offsets are recomputed from
            # the params structure at apply time — pure trace-time
            # Python). Non-eligible leaves keep per-leaf slots.
            flat_p = jax.tree.flatten(
                params, is_leaf=lambda x: isinstance(x, RowSlices))[0]
            elig = [p for p in flat_p if _fused_eligible(p)]
            master = jnp.concatenate(
                [jnp.asarray(p, jnp.float32).reshape(-1) for p in elig]) \
                if elig else jnp.zeros((0,), jnp.float32)
            fused = dict(self.init_slots(master), master=master)
            slots = _tree_map(
                lambda p: {} if _fused_eligible(p) else mk(p), params)
            return {"step": jnp.zeros((), jnp.int32), "slots": slots,
                    "fused": fused}

        slots = _tree_map(mk, params)
        return {"step": jnp.zeros((), jnp.int32), "slots": slots}

    def init_slots(self, p) -> Dict[str, jax.Array]:
        return {}

    def apply_gradients(self, params, grads, state,
                        lr_override=None) -> Tuple[Any, Dict[str, Any]]:
        step = state["step"] + 1
        lr_t = lr_override if lr_override is not None \
            else resolve_lr(self.learning_rate, step)
        # Upcast grads BEFORE clip/decay: a global-norm clip in fp16
        # overflows (sum of squares vs fp16 max 65504) and silently zeroes
        # every grad; all optimizer math is fp32 (master weights). This is
        # the single upcast site for grads — the loop below only upcasts p.
        def _g32(g):
            if g is None:
                return None
            if isinstance(g, RowSlices):
                return RowSlices(g.rows, _as_f32(g.values), g.dense_rows)
            return _as_f32(g)

        grads = jax.tree.map(
            _g32, grads,
            is_leaf=lambda x: x is None or isinstance(x, RowSlices))
        meta = self._param_meta if isinstance(grads, dict) else {}
        has_name_filter = \
            getattr(self, "apply_decay_param_fun", None) is not None or \
            getattr(self, "exclude_fn", None) is not None
        if has_name_filter and not isinstance(params, dict):
            # positional pytrees name leaves "[0]", "[1].bias", ... —
            # a name filter would silently mis-apply decay (same hazard
            # the eager step() guard refuses)
            raise NotImplementedError(
                "apply_decay_param_fun / exclude_from_weight_decay_fn "
                "need name-keyed dict params (the TrainStep contract)")
        flat_p, treedef = jax.tree.flatten(
            params, is_leaf=lambda x: isinstance(x, RowSlices))
        flat_g = treedef.flatten_up_to(grads)
        flat_s = treedef.flatten_up_to(state["slots"])
        need_names = bool(meta) or has_name_filter
        if need_names:
            # align per-leaf regularizers/names with the flat order via
            # the actual tree paths (works for nested dicts too;
            # unmatched paths just get defaults)
            from jax.tree_util import tree_flatten_with_path
            paths, _ = tree_flatten_with_path(
                params, is_leaf=lambda x: isinstance(x, RowSlices))
            names = [".".join(str(getattr(k, "key", k)) for k in path)
                     for path, _leaf in paths]
            regs = [meta.get(n, (True, None))[1] for n in names]
        else:
            names = [None] * len(flat_p)
            regs = [None] * len(flat_p)

        if self.grad_clip is not None:
            no_clip = {n for n, (nc, _) in meta.items() if not nc}
            if no_clip:  # implies meta, hence need_names
                # excluded params keep their raw grads and do not feed
                # the (global) norm (ref: ParamAttr need_clip=False);
                # clipping runs on an index-keyed flat view so nesting
                # cannot hide an exclusion
                sub = {i: g for i, (g, n) in
                       enumerate(zip(flat_g, names)) if n not in no_clip}
                if sub:  # all-excluded: nothing to clip
                    clipped = self.grad_clip(sub)
                    flat_g = [clipped.get(i, g)
                              for i, g in enumerate(flat_g)]
            else:
                flat_g = treedef.flatten_up_to(self.grad_clip(grads))

        if "fused" in state:
            if any(r is not None for r in regs):
                raise ValueError(
                    "per-parameter regularizers are not supported with "
                    "optimizer_fused_state; set fused_state=False")
            if getattr(self, "apply_decay_param_fun", None) is not None:
                raise ValueError(
                    "apply_decay_param_fun needs per-parameter updates; "
                    "set fused_state=False")
            return self._apply_fused(flat_p, flat_g, flat_s, treedef,
                                     state, lr_t, step)

        new_p, new_s = [], []
        for p, g, s, r, n in zip(flat_p, flat_g, flat_s, regs, names):
            np_, ns_ = self._update_leaf(p, g, s, lr_t, step, reg=r,
                                         name=n)
            new_p.append(np_)
            new_s.append(ns_)
        return (jax.tree.unflatten(treedef, new_p),
                {"step": step, "slots": jax.tree.unflatten(treedef, new_s)})

    def _update_leaf(self, p, g, s, lr_t, step, reg=None, name=None):
        """One per-leaf update (shared by the per-leaf and fused paths'
        non-eligible branch): fp32 master handling, RowSlices dispatch,
        decay, cast back to the param dtype."""
        if g is None:
            return p, s
        out_dtype = getattr(p, "dtype", None)
        # fp32 master copy (see init): the update reads and writes the
        # master; the low-precision param is its cast-down view.
        has_master = isinstance(s, dict) and "master" in s
        p32 = s["master"] if has_master else _as_f32(p)
        s_upd = {k: v for k, v in s.items() if k != "master"} \
            if has_master else s
        if isinstance(g, RowSlices):
            np_, ns_ = self.update_sparse(p32, g, s_upd, lr_t, step)
        else:
            g = self._decay_grad(g, p32, reg)
            np_, ns_ = self.update(p32, g, s_upd, lr_t, step)
        if has_master:
            ns_ = dict(ns_, master=np_)
        if out_dtype is not None and np_.dtype != out_dtype:
            np_ = np_.astype(out_dtype)
        return np_, ns_

    def _apply_fused(self, flat_p, flat_g, flat_s, treedef, state,
                     lr_t, step):
        """Flat fused-state update: eligible leaves update as slices of
        ONE fp32 master vector (concat grads -> one elementwise update
        -> split/cast back). Trades two large contiguous copies for the
        per-leaf buffer traffic of ~3 runtime buffers per parameter —
        the reference's fused multi-tensor optimizer capability
        (ref: incubate multi_tensor_apply / merged_adam direction).
        None-grad (frozen) leaves are masked to exact no-ops; RowSlices
        grads densify on this path (the per-leaf path keeps them
        sparse — pick per leaf structure, not per batch)."""
        elig = [_fused_eligible(p) for p in flat_p]
        master = state["fused"]["master"]

        g_parts, mask_parts, any_none = [], [], False
        decay_parts, any_sparse = [], False
        for p, g, e in zip(flat_p, flat_g, elig):
            if not e:
                continue
            n = int(jnp.size(p))
            if g is None:
                any_none = True
                g_parts.append(jnp.zeros((n,), jnp.float32))
                mask_parts.append(jnp.zeros((n,), jnp.float32))
                decay_parts.append(jnp.zeros((n,), jnp.float32))
            elif isinstance(g, RowSlices):
                # densified for the flat update, but the per-leaf path's
                # update_sparse never applies weight decay to sparse
                # grads — keep that contract here too
                any_sparse = True
                g_parts.append(to_dense(g).reshape(-1)
                               .astype(jnp.float32))
                mask_parts.append(jnp.ones((n,), jnp.float32))
                decay_parts.append(jnp.zeros((n,), jnp.float32))
            else:
                g_parts.append(g.reshape(-1).astype(jnp.float32))
                mask_parts.append(jnp.ones((n,), jnp.float32))
                decay_parts.append(jnp.ones((n,), jnp.float32))
        gflat = jnp.concatenate(g_parts) if g_parts else \
            jnp.zeros((0,), jnp.float32)
        mask_flat = jnp.concatenate(mask_parts) if any_none else None
        if self.weight_decay:
            decay = master if not any_sparse else \
                master * jnp.concatenate(decay_parts)
            gflat = self._decay_grad(gflat, decay)
        if mask_flat is not None:
            # after decay: a frozen leaf must be an exact no-op, decay
            # included
            gflat = gflat * mask_flat

        s_upd = {k: v for k, v in state["fused"].items() if k != "master"}
        new_master, ns_fused = self.update(master, gflat, s_upd, lr_t,
                                           step)
        if mask_flat is not None:
            # a zeroed grad is NOT enough for a frozen leaf: decoupled
            # decay (AdamW) moves the param with g=0, and moment slots
            # decay by beta — pin BOTH so fused == per-leaf (which skips
            # frozen leaves entirely)
            frozen = mask_flat <= 0
            new_master = jnp.where(frozen, master, new_master)
            ns_fused = {
                k: jnp.where(frozen, state["fused"][k], v)
                if hasattr(v, "shape") and v.shape == master.shape else v
                for k, v in ns_fused.items()}
        ns_fused = dict(ns_fused, master=new_master)

        new_p, new_s = [], []
        off = 0
        for p, g, s, e in zip(flat_p, flat_g, flat_s, elig):
            if e:
                n = int(jnp.size(p))
                sl = new_master[off:off + n]  # static offsets: plain slice
                new_p.append(sl.reshape(jnp.shape(p)).astype(p.dtype))
                new_s.append(s)
                off += n
            else:
                np_, ns_ = self._update_leaf(p, g, s, lr_t, step)
                new_p.append(np_)
                new_s.append(ns_)
        return (jax.tree.unflatten(treedef, new_p),
                {"step": step, "slots": jax.tree.unflatten(treedef, new_s),
                 "fused": ns_fused})

    def update(self, p, g, slots, lr_t, step):
        raise NotImplementedError

    def update_sparse(self, p, g: RowSlices, slots, lr_t, step):
        # default: densify (correct, not bandwidth-optimal)
        return self.update(p, to_dense(g), slots, lr_t, step)

    # ------------------------------------------------------------------
    # eager API
    # ------------------------------------------------------------------
    def _eager_params(self) -> Dict[int, Parameter]:
        if self._parameter_list is None:
            raise ValueError(
                "pass parameters= to the optimizer for eager step()")
        return {i: p for i, p in enumerate(self._parameter_list)
                if p.trainable}

    def step(self, grads: Optional[Sequence[jax.Array]] = None) -> None:
        params = self._eager_params()
        if grads is None:
            raise ValueError("eager step() needs grads aligned with "
                             "the optimizer's parameter list")
        if self._param_meta or \
                getattr(self, "apply_decay_param_fun", None) is not None \
                or getattr(self, "exclude_fn", None) is not None:
            # eager grads are index-keyed, so name filters would match
            # nothing and silently mis-apply decay — refuse instead
            raise NotImplementedError(
                "name-based decay/clip filters (ParamAttr metadata, "
                "apply_decay_param_fun, exclude_from_weight_decay_fn) "
                "need name-keyed grads; train through TrainStep or call "
                "apply_gradients with a name-keyed dict")
        values = {i: p.value for i, p in params.items()}
        gdict = {i: g for (i, _), g in zip(params.items(), grads)}
        if self._eager_state is None:
            self._eager_state = self.init(values)
        new_values, self._eager_state = self.apply_gradients(
            values, gdict, self._eager_state)
        for i, p in params.items():
            p.value = new_values[i]
        from ..observability import metrics as _obs_metrics
        if _obs_metrics.enabled():
            _obs_metrics.counter("optimizer_steps_total",
                                 "optimizer update steps applied").inc()

    def clear_grad(self) -> None:
        pass  # grads are values, not state, in the functional design

    def get_lr(self) -> float:
        if isinstance(self.learning_rate, LRScheduler):
            return self.learning_rate.get_lr()
        return float(self.learning_rate)

    def set_lr(self, value: float) -> None:
        self.learning_rate = value

    def state_dict(self):
        return self._eager_state or {}

    def set_state_dict(self, state) -> None:
        self._eager_state = state

    # reference-style one-call minimize for eager models
    def minimize(self, loss_fn: Callable, model: Layer):
        params = model.param_dict()
        buffers = model.buffer_dict()

        def lf(p):
            from ..nn.layer import functional_call
            out, new_buf = functional_call(model, p, buffers,
                                           capture_buffers=True)
            return out, new_buf

        raise NotImplementedError(
            "use paddle_tpu.static.TrainStep or jax.value_and_grad with "
            "apply_gradients; minimize() of arbitrary closures is not "
            "supported in the functional design")


class SGD(Optimizer):
    """(ref: sgd_op.cc)."""
    _elementwise_update = True

    def update(self, p, g, slots, lr_t, step):
        return p - lr_t * g.astype(p.dtype), slots

    def update_sparse(self, p, g: RowSlices, slots, lr_t, step):
        return scatter_apply(p, g, lambda rows, vals:
                             rows - lr_t * vals.astype(p.dtype)), slots


class Momentum(Optimizer):
    """(ref: momentum_op.cc; use_nesterov attr)."""
    _elementwise_update = True

    def __init__(self, learning_rate=0.001, momentum: float = 0.9,
                 use_nesterov: bool = False, **kw) -> None:
        super().__init__(learning_rate, **kw)
        self.momentum = momentum
        self.use_nesterov = use_nesterov

    def init_slots(self, p):
        return {"velocity": jnp.zeros_like(p)}

    def update(self, p, g, slots, lr_t, step):
        g = g.astype(p.dtype)
        v = self.momentum * slots["velocity"] + g
        if self.use_nesterov:
            new_p = p - lr_t * (g + self.momentum * v)
        else:
            new_p = p - lr_t * v
        return new_p, {"velocity": v}


class LarsMomentum(Optimizer):
    """(ref: lars_momentum_op.cc) layer-adaptive rate scaling."""

    def __init__(self, learning_rate=0.001, momentum: float = 0.9,
                 lars_coeff: float = 0.001, lars_weight_decay: float = 0.0005,
                 epsilon: float = 1e-9, **kw) -> None:
        super().__init__(learning_rate, **kw)
        self.momentum = momentum
        self.lars_coeff = lars_coeff
        self.lars_weight_decay = lars_weight_decay
        self.epsilon = epsilon

    def init_slots(self, p):
        return {"velocity": jnp.zeros_like(p)}

    def update(self, p, g, slots, lr_t, step):
        g = g.astype(p.dtype)
        p_norm = jnp.sqrt(jnp.sum(jnp.square(p)))
        g_norm = jnp.sqrt(jnp.sum(jnp.square(g)))
        local_lr = lr_t * self.lars_coeff * p_norm / (
            g_norm + self.lars_weight_decay * p_norm + self.epsilon)
        local_lr = jnp.where(p_norm > 0, local_lr, lr_t)
        v = self.momentum * slots["velocity"] \
            + local_lr * (g + self.lars_weight_decay * p)
        return p - v, {"velocity": v}


class Adam(Optimizer):
    """(ref: adam_op.h AdamFunctor)."""
    _elementwise_update = True

    def __init__(self, learning_rate=0.001, beta1: float = 0.9,
                 beta2: float = 0.999, epsilon: float = 1e-8,
                 lazy_mode: bool = False, **kw) -> None:
        super().__init__(learning_rate, **kw)
        self.beta1 = beta1
        self.beta2 = beta2
        self.epsilon = epsilon
        self.lazy_mode = lazy_mode

    def init_slots(self, p):
        return {"m": jnp.zeros(p.shape, _moment_dtype(p.dtype)),
                "v": jnp.zeros(p.shape, _moment_dtype(p.dtype))}

    def _bias_correct_lr(self, lr_t, step):
        step_f = step.astype(jnp.float32)
        bc1 = 1.0 - jnp.power(self.beta1, step_f)
        bc2 = 1.0 - jnp.power(self.beta2, step_f)
        return lr_t * jnp.sqrt(bc2) / bc1

    def update(self, p, g, slots, lr_t, step):
        g = g.astype(p.dtype)
        from ..flags import GLOBAL_FLAGS
        from ..kernels import pallas_enabled
        if (pallas_enabled() and GLOBAL_FLAGS.get("fused_adam")
                and p.dtype == jnp.float32
                and slots["m"].dtype == jnp.float32
                and slots["v"].dtype == jnp.float32):
            # layout-preserving fused kernel; bitwise-identical to the
            # unfused expression below (takes precedence over the
            # ravel-based use_pallas_adam path)
            from ..kernels.fused_adam import fused_adam_leaf
            lr_c = self._bias_correct_lr(lr_t, step)
            p_new, m, v = fused_adam_leaf(
                p, g, slots["m"], slots["v"], lr_c, self.beta1,
                self.beta2, self.epsilon)
            return p_new, {"m": m, "v": v}
        if (pallas_enabled() and GLOBAL_FLAGS.get("use_pallas_adam")
                and p.dtype == jnp.float32
                and slots["m"].dtype == jnp.float32 and p.size >= 1024):
            from ..kernels.fused_adam import fused_adam_flat
            lr_c = self._bias_correct_lr(lr_t, step)
            p_new, m, v = fused_adam_flat(
                p.ravel(), g.ravel(), slots["m"].ravel(),
                slots["v"].ravel(), lr_c, self.beta1, self.beta2,
                self.epsilon)
            return (p_new.reshape(p.shape),
                    {"m": m.reshape(p.shape), "v": v.reshape(p.shape)})
        # moments may be STORED low-precision (FLAGS_optimizer_moment_
        # dtype): math always runs fp32, storage casts back
        m_dt, v_dt = slots["m"].dtype, slots["v"].dtype
        m = self.beta1 * _as_f32(slots["m"]) + (1 - self.beta1) * g
        v = self.beta2 * _as_f32(slots["v"]) \
            + (1 - self.beta2) * jnp.square(g)
        lr_c = self._bias_correct_lr(lr_t, step)
        new_p = p - lr_c * m / (jnp.sqrt(v) + self.epsilon)
        return new_p, {"m": m.astype(m_dt), "v": v.astype(v_dt)}

    def update_sparse(self, p, g: RowSlices, slots, lr_t, step):
        if not self.lazy_mode:
            return self.update(p, to_dense(g), slots, lr_t, step)
        # lazy: only touched rows updated (ref: adam_op.h:473 sparse functor)
        lr_c = self._bias_correct_lr(lr_t, step)
        m, v = slots["m"], slots["v"]
        safe_rows = jnp.minimum(g.rows, p.shape[0] - 1)
        valid = (g.rows < p.shape[0])[:, None].astype(p.dtype)
        g_rows = g.values.astype(p.dtype) * valid
        m_rows = self.beta1 * _as_f32(m[safe_rows]) \
            + (1 - self.beta1) * g_rows
        v_rows = self.beta2 * _as_f32(v[safe_rows]) + (1 - self.beta2) \
            * jnp.square(g_rows)
        p_rows = p[safe_rows] - lr_c * m_rows / (jnp.sqrt(v_rows)
                                                 + self.epsilon)
        return (p.at[safe_rows].set(p[safe_rows] * (1 - valid)
                                    + p_rows * valid),
                {"m": m.at[safe_rows].set(
                    (_as_f32(m[safe_rows]) * (1 - valid)
                     + m_rows * valid).astype(m.dtype)),
                 "v": v.at[safe_rows].set(
                    (_as_f32(v[safe_rows]) * (1 - valid)
                     + v_rows * valid).astype(v.dtype))})


class AdamW(Adam):
    """(ref: adamw in optimizer.py — decoupled weight decay)."""

    def __init__(self, learning_rate=0.001, beta1=0.9, beta2=0.999,
                 epsilon=1e-8, weight_decay: float = 0.01,
                 apply_decay_param_fun=None, **kw) -> None:
        if kw.pop("regularization", None) is not None:
            # the base class would fold it into coupled weight_decay,
            # which the next line resets — reject loudly instead of
            # silently training without decay (explicit None is fine)
            raise TypeError(
                "AdamW uses DECOUPLED weight decay: pass weight_decay="
                "<float> (regularization= is the coupled-L2 spelling; "
                "use Adam for that)")
        kw.pop("weight_decay", None)
        super().__init__(learning_rate, beta1, beta2, epsilon, **kw)
        self.decoupled_weight_decay = weight_decay
        self.apply_decay_param_fun = apply_decay_param_fun
        self.weight_decay = None  # decoupled, not L2

    def update(self, p, g, slots, lr_t, step):
        new_p, new_slots = super().update(p, g, slots, lr_t, step)
        new_p = new_p - lr_t * self.decoupled_weight_decay * p
        return new_p, new_slots

    def _update_leaf(self, p, g, s, lr_t, step, reg=None, name=None):
        fn = self.apply_decay_param_fun
        if fn is not None and name is not None and not fn(name):
            # reference: apply_decay_param_fun(name) False => NO decay
            return self._with_zeroed_attr(
                "decoupled_weight_decay",
                lambda: super(AdamW, self)._update_leaf(
                    p, g, s, lr_t, step, reg, name))
        return super()._update_leaf(p, g, s, lr_t, step, reg, name)


class Adamax(Optimizer):
    """(ref: adamax_op.cc)."""
    _elementwise_update = True

    def __init__(self, learning_rate=0.001, beta1=0.9, beta2=0.999,
                 epsilon=1e-8, **kw) -> None:
        super().__init__(learning_rate, **kw)
        self.beta1, self.beta2, self.epsilon = beta1, beta2, epsilon

    def init_slots(self, p):
        return {"m": jnp.zeros_like(p), "u": jnp.zeros_like(p)}

    def update(self, p, g, slots, lr_t, step):
        g = g.astype(p.dtype)
        m = self.beta1 * slots["m"] + (1 - self.beta1) * g
        u = jnp.maximum(self.beta2 * slots["u"], jnp.abs(g))
        step_f = step.astype(jnp.float32)
        lr_c = lr_t / (1.0 - jnp.power(self.beta1, step_f))
        return p - lr_c * m / (u + self.epsilon), {"m": m, "u": u}


class Adagrad(Optimizer):
    """(ref: adagrad_op.cc)."""
    _elementwise_update = True

    def __init__(self, learning_rate=0.001, epsilon: float = 1e-6,
                 initial_accumulator_value: float = 0.0, **kw) -> None:
        super().__init__(learning_rate, **kw)
        self.epsilon = epsilon
        self.initial_accumulator_value = initial_accumulator_value

    def init_slots(self, p):
        return {"moment": jnp.full_like(p, self.initial_accumulator_value)}

    def update(self, p, g, slots, lr_t, step):
        g = g.astype(p.dtype)
        moment = slots["moment"] + jnp.square(g)
        return p - lr_t * g / (jnp.sqrt(moment) + self.epsilon), \
            {"moment": moment}


class Adadelta(Optimizer):
    """(ref: adadelta_op.cc)."""
    _elementwise_update = True

    def __init__(self, learning_rate=1.0, rho: float = 0.95,
                 epsilon: float = 1e-6, **kw) -> None:
        super().__init__(learning_rate, **kw)
        self.rho, self.epsilon = rho, epsilon

    def init_slots(self, p):
        return {"avg_sq_grad": jnp.zeros_like(p),
                "avg_sq_update": jnp.zeros_like(p)}

    def update(self, p, g, slots, lr_t, step):
        g = g.astype(p.dtype)
        asg = self.rho * slots["avg_sq_grad"] + (1 - self.rho) * jnp.square(g)
        upd = g * jnp.sqrt(slots["avg_sq_update"] + self.epsilon) \
            / jnp.sqrt(asg + self.epsilon)
        asu = self.rho * slots["avg_sq_update"] \
            + (1 - self.rho) * jnp.square(upd)
        return p - lr_t * upd, {"avg_sq_grad": asg, "avg_sq_update": asu}


class RMSProp(Optimizer):
    """(ref: rmsprop_op.cc; centered variant supported)."""
    _elementwise_update = True

    def __init__(self, learning_rate=0.001, rho: float = 0.95,
                 epsilon: float = 1e-6, momentum: float = 0.0,
                 centered: bool = False, **kw) -> None:
        super().__init__(learning_rate, **kw)
        self.rho, self.epsilon = rho, epsilon
        self.momentum_coef = momentum
        self.centered = centered

    def init_slots(self, p):
        s = {"mean_square": jnp.zeros_like(p),
             "moment": jnp.zeros_like(p)}
        if self.centered:
            s["mean_grad"] = jnp.zeros_like(p)
        return s

    def update(self, p, g, slots, lr_t, step):
        g = g.astype(p.dtype)
        ms = self.rho * slots["mean_square"] + (1 - self.rho) * jnp.square(g)
        new_slots = {"mean_square": ms}
        if self.centered:
            mg = self.rho * slots["mean_grad"] + (1 - self.rho) * g
            denom = jnp.sqrt(ms - jnp.square(mg) + self.epsilon)
            new_slots["mean_grad"] = mg
        else:
            denom = jnp.sqrt(ms + self.epsilon)
        mom = self.momentum_coef * slots["moment"] + lr_t * g / denom
        new_slots["moment"] = mom
        return p - mom, new_slots


class Lamb(Optimizer):
    """(ref: lamb_op.cc) layer-adaptive Adam for large-batch."""

    def __init__(self, learning_rate=0.001, lamb_weight_decay: float = 0.01,
                 beta1: float = 0.9, beta2: float = 0.999,
                 epsilon: float = 1e-6, exclude_from_weight_decay_fn=None,
                 **kw) -> None:
        super().__init__(learning_rate, **kw)
        self.beta1, self.beta2, self.epsilon = beta1, beta2, epsilon
        self.lamb_weight_decay = lamb_weight_decay
        self.exclude_fn = exclude_from_weight_decay_fn

    def init_slots(self, p):
        return {"m": jnp.zeros_like(p), "v": jnp.zeros_like(p)}

    def _update_leaf(self, p, g, s, lr_t, step, reg=None, name=None):
        if self.exclude_fn is not None and name is not None \
                and self.exclude_fn(name):
            # reference: exclude_from_weight_decay_fn(name) True =>
            # no lamb weight decay for this parameter
            return self._with_zeroed_attr(
                "lamb_weight_decay",
                lambda: super(Lamb, self)._update_leaf(
                    p, g, s, lr_t, step, reg, name))
        return super()._update_leaf(p, g, s, lr_t, step, reg, name)

    def update(self, p, g, slots, lr_t, step):
        g = g.astype(p.dtype)
        m = self.beta1 * slots["m"] + (1 - self.beta1) * g
        v = self.beta2 * slots["v"] + (1 - self.beta2) * jnp.square(g)
        step_f = step.astype(jnp.float32)
        m_hat = m / (1.0 - jnp.power(self.beta1, step_f))
        v_hat = v / (1.0 - jnp.power(self.beta2, step_f))
        r = m_hat / (jnp.sqrt(v_hat) + self.epsilon) \
            + self.lamb_weight_decay * p
        p_norm = jnp.sqrt(jnp.sum(jnp.square(p)))
        r_norm = jnp.sqrt(jnp.sum(jnp.square(r)))
        trust = jnp.where((p_norm > 0) & (r_norm > 0), p_norm / r_norm, 1.0)
        return p - lr_t * trust * r, {"m": m, "v": v}


class Ftrl(Optimizer):
    """(ref: ftrl_op.cc)."""

    def __init__(self, learning_rate=0.001, l1: float = 0.0,
                 l2: float = 0.0, lr_power: float = -0.5, **kw) -> None:
        super().__init__(learning_rate, **kw)
        self.l1, self.l2, self.lr_power = l1, l2, lr_power

    def init_slots(self, p):
        return {"squared": jnp.zeros_like(p), "linear": jnp.zeros_like(p)}

    def update(self, p, g, slots, lr_t, step):
        g = g.astype(p.dtype)
        sq = slots["squared"]
        new_sq = sq + jnp.square(g)
        sigma = (jnp.power(new_sq, -self.lr_power)
                 - jnp.power(jnp.maximum(sq, 1e-20), -self.lr_power)) / lr_t
        lin = slots["linear"] + g - sigma * p
        quad = jnp.power(new_sq, -self.lr_power) / lr_t + 2 * self.l2
        pre_shrink = (self.l1 * jnp.sign(lin) - lin) / quad
        new_p = jnp.where(jnp.abs(lin) > self.l1, pre_shrink, 0.0)
        return new_p, {"squared": new_sq, "linear": lin}


class Dpsgd(Optimizer):
    """(ref: dpsgd_op.cc) differentially-private SGD: clip + noise."""

    def __init__(self, learning_rate=0.001, clip: float = 10.0,
                 batch_size: float = 16.0, sigma: float = 1.0, seed: int = 0,
                 **kw) -> None:
        super().__init__(learning_rate, **kw)
        self.clip = clip
        self.batch_size = batch_size
        self.sigma = sigma
        self.seed = seed

    def update(self, p, g, slots, lr_t, step):
        g = g.astype(p.dtype)
        g_norm = jnp.sqrt(jnp.sum(jnp.square(g)))
        scale = jnp.minimum(1.0, self.clip / jnp.maximum(g_norm, 1e-12))
        g = g * scale
        key = jax.random.fold_in(make_key(self.seed), step)
        noise = self.sigma * self.clip / self.batch_size \
            * jax.random.normal(key, g.shape, g.dtype)
        return p - lr_t * (g + noise), slots


# Reference-era aliases (fluid.optimizer spellings)
SGDOptimizer = SGD
MomentumOptimizer = Momentum
AdamOptimizer = Adam
AdamaxOptimizer = Adamax
AdagradOptimizer = Adagrad
AdadeltaOptimizer = Adadelta
RMSPropOptimizer = RMSProp
LambOptimizer = Lamb
FtrlOptimizer = Ftrl
LarsMomentumOptimizer = LarsMomentum

from .extras import (ExponentialMovingAverage, GradientMerge,  # noqa: E402
                     Lookahead, ModelAverage)  # noqa: F401


class DecayedAdagrad(Optimizer):
    """(ref: decayed_adagrad_op.cc)."""

    def __init__(self, learning_rate=0.001, decay: float = 0.95,
                 epsilon: float = 1e-6, **kw) -> None:
        super().__init__(learning_rate, **kw)
        self.decay, self.epsilon = decay, epsilon

    def init_slots(self, p):
        return {"moment": jnp.zeros_like(p)}

    def update(self, p, g, slots, lr_t, step):
        g = g.astype(p.dtype)
        moment = self.decay * slots["moment"] \
            + (1 - self.decay) * jnp.square(g)
        return p - lr_t * g / (jnp.sqrt(moment) + self.epsilon), \
            {"moment": moment}


class ProximalGD(Optimizer):
    """(ref: proximal_gd_op.cc) SGD with L1/L2 proximal projection."""

    def __init__(self, learning_rate=0.001, l1: float = 0.0,
                 l2: float = 0.0, **kw) -> None:
        super().__init__(learning_rate, **kw)
        self.l1, self.l2 = l1, l2

    def init_slots(self, p):
        return {}

    def update(self, p, g, slots, lr_t, step):
        g = g.astype(p.dtype)
        prox = p - lr_t * g
        new_p = jnp.sign(prox) * jnp.maximum(
            jnp.abs(prox) - lr_t * self.l1, 0.0) / (1.0 + lr_t * self.l2)
        return new_p, {}


class ProximalAdagrad(Optimizer):
    """(ref: proximal_adagrad_op.cc)."""

    def __init__(self, learning_rate=0.001, l1: float = 0.0,
                 l2: float = 0.0, epsilon: float = 1e-10, **kw) -> None:
        super().__init__(learning_rate, **kw)
        self.l1, self.l2, self.epsilon = l1, l2, epsilon

    def init_slots(self, p):
        return {"moment": jnp.zeros_like(p)}

    def update(self, p, g, slots, lr_t, step):
        g = g.astype(p.dtype)
        moment = slots["moment"] + jnp.square(g)
        adapted_lr = lr_t / (jnp.sqrt(moment) + self.epsilon)
        prox = p - adapted_lr * g
        new_p = jnp.sign(prox) * jnp.maximum(
            jnp.abs(prox) - adapted_lr * self.l1, 0.0) \
            / (1.0 + adapted_lr * self.l2)
        return new_p, {"moment": moment}
