"""Learning-rate schedulers.

TPU-native redesign of the reference's LR schedule machinery
(/root/reference/python/paddle/fluid/dygraph/learning_rate_scheduler.py and
layers/learning_rate_scheduler.py — schedules are graph ops there). Here a
scheduler is a pure function ``lr(step) -> float`` of a traced step counter,
so the schedule compiles INTO the jitted train step (no retrace per epoch,
no host sync); the object wrapper adds the stateful ``step()/get_lr()`` API
for eager parity.
"""

from __future__ import annotations

import math
from typing import Callable, List, Optional, Sequence

import jax
import jax.numpy as jnp


class LRScheduler:
    """Base: subclasses implement lr_at(step) with jnp-traceable math.

    ``host_driven = True`` subclasses (metric-driven schedules like
    ReduceOnPlateau) cannot be traced — their current LR is fed into the
    compiled step as a runtime scalar input by TrainStep instead of
    being baked in at trace time.
    """

    host_driven = False

    def __init__(self, learning_rate: float = 0.1,
                 last_epoch: int = -1, verbose: bool = False) -> None:
        self.base_lr = learning_rate
        self.last_epoch = last_epoch
        self.verbose = verbose
        self.step()  # advance to epoch 0 like the reference

    def lr_at(self, step):
        raise NotImplementedError

    def __call__(self, step):
        return self.lr_at(step)

    def get_lr(self):
        return float(self.lr_at(jnp.asarray(self.last_epoch)))

    def step(self, epoch: Optional[int] = None) -> None:
        self.last_epoch = epoch if epoch is not None else self.last_epoch + 1
        if self.verbose:
            # reference parity: announce the new LR on every step
            print(f"Epoch {self.last_epoch}: {type(self).__name__} set "
                  f"learning rate to {self.get_lr()}.")

    def state_dict(self):
        return {"last_epoch": self.last_epoch}

    def set_state_dict(self, state):
        self.last_epoch = state["last_epoch"]


class NoamDecay(LRScheduler):
    """(ref: learning_rate_scheduler.py NoamDecay)."""

    def __init__(self, d_model: int, warmup_steps: int,
                 learning_rate: float = 1.0, last_epoch: int = -1,
                 verbose: bool = False) -> None:
        self.d_model = d_model
        self.warmup_steps = warmup_steps
        super().__init__(learning_rate, last_epoch, verbose)

    def lr_at(self, step):
        step = jnp.maximum(step, 1).astype(jnp.float32)
        a = step ** -0.5
        b = step * (self.warmup_steps ** -1.5)
        return self.base_lr * (self.d_model ** -0.5) * jnp.minimum(a, b)


class PiecewiseDecay(LRScheduler):
    def __init__(self, boundaries: Sequence[int], values: Sequence[float],
                 last_epoch: int = -1, verbose: bool = False) -> None:
        self.boundaries = list(boundaries)
        self.values = list(values)
        super().__init__(values[0], last_epoch, verbose)

    def lr_at(self, step):
        idx = jnp.searchsorted(jnp.asarray(self.boundaries), step,
                               side="right")
        return jnp.asarray(self.values)[idx]


class NaturalExpDecay(LRScheduler):
    def __init__(self, learning_rate: float, gamma: float,
                 last_epoch: int = -1, verbose: bool = False) -> None:
        self.gamma = gamma
        super().__init__(learning_rate, last_epoch, verbose)

    def lr_at(self, step):
        return self.base_lr * jnp.exp(-self.gamma * step)


class ExponentialDecay(LRScheduler):
    def __init__(self, learning_rate: float, gamma: float,
                 last_epoch: int = -1, verbose: bool = False) -> None:
        self.gamma = gamma
        super().__init__(learning_rate, last_epoch, verbose)

    def lr_at(self, step):
        return self.base_lr * jnp.power(self.gamma, step.astype(jnp.float32)
                                        if hasattr(step, "astype") else step)


class InverseTimeDecay(LRScheduler):
    def __init__(self, learning_rate: float, gamma: float,
                 last_epoch: int = -1, verbose: bool = False) -> None:
        self.gamma = gamma
        super().__init__(learning_rate, last_epoch, verbose)

    def lr_at(self, step):
        return self.base_lr / (1.0 + self.gamma * step)


class PolynomialDecay(LRScheduler):
    def __init__(self, learning_rate: float, decay_steps: int,
                 end_lr: float = 0.0001, power: float = 1.0,
                 cycle: bool = False, last_epoch: int = -1,
                 verbose: bool = False) -> None:
        self.decay_steps = decay_steps
        self.end_lr = end_lr
        self.power = power
        self.cycle = cycle
        super().__init__(learning_rate, last_epoch, verbose)

    def lr_at(self, step):
        step_f = jnp.asarray(step, jnp.float32)
        if self.cycle:
            ratio = jnp.ceil(jnp.maximum(step_f, 1.0) / self.decay_steps)
            ds = self.decay_steps * jnp.maximum(ratio, 1.0)
        else:
            ds = float(self.decay_steps)
            step_f = jnp.minimum(step_f, ds)
        frac = (1.0 - step_f / ds) ** self.power
        return (self.base_lr - self.end_lr) * frac + self.end_lr


class CosineAnnealingDecay(LRScheduler):
    def __init__(self, learning_rate: float, T_max: int,
                 eta_min: float = 0.0, last_epoch: int = -1,
                 verbose: bool = False) -> None:
        self.T_max = T_max
        self.eta_min = eta_min
        super().__init__(learning_rate, last_epoch, verbose)

    def lr_at(self, step):
        cos = jnp.cos(jnp.pi * jnp.asarray(step, jnp.float32) / self.T_max)
        return self.eta_min + (self.base_lr - self.eta_min) * (1 + cos) / 2


class LinearWarmup(LRScheduler):
    """(ref: layers/learning_rate_scheduler.py linear_lr_warmup)."""

    def __init__(self, learning_rate, warmup_steps: int, start_lr: float,
                 end_lr: float, last_epoch: int = -1,
                 verbose: bool = False) -> None:
        self.lr_after = learning_rate
        self.warmup_steps = warmup_steps
        self.start_lr = start_lr
        self.end_lr = end_lr
        base = learning_rate if isinstance(learning_rate, float) \
            else learning_rate.base_lr
        super().__init__(base, last_epoch, verbose)

    def lr_at(self, step):
        step_f = jnp.asarray(step, jnp.float32)
        warm = self.start_lr + (self.end_lr - self.start_lr) \
            * step_f / max(self.warmup_steps, 1)
        if isinstance(self.lr_after, LRScheduler):
            after = self.lr_after.lr_at(step - self.warmup_steps)
        else:
            after = self.lr_after
        return jnp.where(step_f < self.warmup_steps, warm, after)


class StepDecay(LRScheduler):
    def __init__(self, learning_rate: float, step_size: int,
                 gamma: float = 0.1, last_epoch: int = -1,
                 verbose: bool = False) -> None:
        self.step_size = step_size
        self.gamma = gamma
        super().__init__(learning_rate, last_epoch, verbose)

    def lr_at(self, step):
        return self.base_lr * jnp.power(
            self.gamma, (jnp.asarray(step) // self.step_size).astype(
                jnp.float32))


class MultiStepDecay(LRScheduler):
    def __init__(self, learning_rate: float, milestones: Sequence[int],
                 gamma: float = 0.1, last_epoch: int = -1,
                 verbose: bool = False) -> None:
        self.milestones = list(milestones)
        self.gamma = gamma
        super().__init__(learning_rate, last_epoch, verbose)

    def lr_at(self, step):
        idx = jnp.searchsorted(jnp.asarray(self.milestones), step,
                               side="right").astype(jnp.float32)
        return self.base_lr * jnp.power(self.gamma, idx)


class LambdaDecay(LRScheduler):
    def __init__(self, learning_rate: float, lr_lambda: Callable,
                 last_epoch: int = -1, verbose: bool = False) -> None:
        self.lr_lambda = lr_lambda
        super().__init__(learning_rate, last_epoch, verbose)

    def lr_at(self, step):
        return self.base_lr * self.lr_lambda(step)


class ReduceOnPlateau(LRScheduler):
    """Host-side stateful schedule (metric-driven; not jit-traceable —
    call .step(metric) per epoch like the reference). TrainStep feeds
    current_lr into the compiled step as a runtime input."""

    host_driven = True

    def __init__(self, learning_rate: float, mode: str = "min",
                 factor: float = 0.1, patience: int = 10,
                 threshold: float = 1e-4, cooldown: int = 0,
                 min_lr: float = 0.0, verbose: bool = False) -> None:
        self.mode = mode
        self.factor = factor
        self.patience = patience
        self.threshold = threshold
        self.cooldown = cooldown
        self.min_lr = min_lr
        self.best = None
        self.num_bad = 0
        self.cooldown_counter = 0
        self.current_lr = learning_rate
        self.base_lr = learning_rate
        self.last_epoch = 0
        self.verbose = verbose

    def get_lr(self):
        # pure host state: the step classes read this every call — no
        # device array / sync in the hot loop
        return float(self.current_lr)

    def lr_at(self, step):
        return jnp.asarray(self.current_lr)

    def step(self, metrics=None, epoch: Optional[int] = None) -> None:
        if metrics is None:
            return
        m = float(metrics)
        improved = (self.best is None
                    or (self.mode == "min" and m < self.best - self.threshold)
                    or (self.mode == "max" and m > self.best + self.threshold))
        if improved:
            self.best = m
            self.num_bad = 0
        elif self.cooldown_counter > 0:
            self.cooldown_counter -= 1
        else:
            self.num_bad += 1
            if self.num_bad > self.patience:
                self.current_lr = max(self.current_lr * self.factor,
                                      self.min_lr)
                self.cooldown_counter = self.cooldown
                self.num_bad = 0
        self.last_epoch += 1


class OneCycleLR(LRScheduler):
    def __init__(self, max_learning_rate: float, total_steps: int,
                 divide_factor: float = 25.0, end_learning_rate=None,
                 phase_pct: float = 0.3, last_epoch: int = -1,
                 verbose: bool = False) -> None:
        self.max_lr = max_learning_rate
        self.total_steps = total_steps
        self.initial_lr = max_learning_rate / divide_factor
        self.min_lr = end_learning_rate if end_learning_rate is not None \
            else self.initial_lr / 1e4
        self.phase_pct = phase_pct
        super().__init__(self.initial_lr, last_epoch, verbose)

    def lr_at(self, step):
        step_f = jnp.asarray(step, jnp.float32)
        up_steps = self.phase_pct * self.total_steps
        down_steps = self.total_steps - up_steps
        up = self.initial_lr + (self.max_lr - self.initial_lr) \
            * jnp.minimum(step_f / jnp.maximum(up_steps, 1.0), 1.0)
        pct = jnp.clip((step_f - up_steps) / jnp.maximum(down_steps, 1.0),
                       0.0, 1.0)
        down = self.min_lr + (self.max_lr - self.min_lr) \
            * (1 + jnp.cos(jnp.pi * pct)) / 2
        return jnp.where(step_f < up_steps, up, down)


def resolve_lr(lr, step):
    """Evaluate a float or scheduler at a (possibly traced) step.

    A host-driven scheduler under tracing would bake its current LR into
    the compiled program as a constant — .step(metric) would silently
    never change the training LR. Refuse instead; step classes feed the
    live value via apply_gradients(lr_override=...). Eager callers (the
    PS trainer updates on host) re-read the host state each call, which
    is correct.
    """
    if isinstance(lr, LRScheduler):
        if getattr(lr, "host_driven", False) and isinstance(
                step, jax.core.Tracer):
            raise RuntimeError(
                f"{type(lr).__name__} is host-driven (metric-dependent) "
                "and cannot be traced into a compiled step; pass its "
                "current value via apply_gradients(lr_override=...) "
                "(TrainStep/ShardedTrainStep and the mesh steps do this "
                "automatically).")
        return lr.lr_at(step)
    return jnp.asarray(lr, jnp.float32)
