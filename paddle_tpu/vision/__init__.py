from . import transforms  # noqa: F401
from ..models.lenet import LeNet  # noqa: F401
from ..models.resnet import (ResNet, resnet18, resnet34, resnet50,  # noqa
                             resnet101, resnet152)
from ..models.mobilenet import (MobileNetV1, MobileNetV2,  # noqa: F401
                                mobilenet_v1, mobilenet_v2)
from ..models.vgg import VGG, vgg11, vgg13, vgg16, vgg19  # noqa: F401
