from . import transforms  # noqa: F401
from ..models.lenet import LeNet  # noqa: F401
from ..models.resnet import (ResNet, resnet18, resnet34, resnet50,  # noqa
                             resnet101, resnet152)
