"""Vision transforms.

Capability parity with the reference's hapi vision transforms
(/root/reference/python/paddle/incubate/hapi/vision/transforms/
transforms.py: Compose, Resize, RandomCrop, RandomHorizontalFlip,
Normalize, CenterCrop, Transpose…). Pure numpy, CHW float arrays —
transforms run inside DataLoader worker *processes* (data/worker.py), so
they must not touch JAX (the backend is not fork-safe and device work
belongs to the training step).
"""

from __future__ import annotations

from typing import Optional, Sequence

import numpy as np

__all__ = ["Compose", "Normalize", "Resize", "CenterCrop", "RandomCrop",
           "RandomHorizontalFlip", "Transpose", "ToCHW", "Pad",
           "BrightnessTransform"]


class Compose:
    def __init__(self, transforms: Sequence) -> None:
        self.transforms = list(transforms)

    def __call__(self, img):
        for t in self.transforms:
            img = t(img)
        return img


class Normalize:
    """(ref: transforms.py Normalize) channel-wise (x - mean) / std on
    CHW float arrays."""

    def __init__(self, mean, std) -> None:
        self.mean = np.asarray(mean, np.float32).reshape(-1, 1, 1)
        self.std = np.asarray(std, np.float32).reshape(-1, 1, 1)

    def __call__(self, img):
        return ((np.asarray(img, np.float32) - self.mean)
                / self.std).astype(np.float32)


def _resize_bilinear_chw(img: np.ndarray, h: int, w: int) -> np.ndarray:
    """Separable bilinear resize without PIL/cv2 (zero extra deps)."""
    c, ih, iw = img.shape
    if (ih, iw) == (h, w):
        return img
    ys = (np.arange(h) + 0.5) * ih / h - 0.5
    xs = (np.arange(w) + 0.5) * iw / w - 0.5
    y0 = np.clip(np.floor(ys).astype(np.int64), 0, ih - 1)
    x0 = np.clip(np.floor(xs).astype(np.int64), 0, iw - 1)
    y1 = np.minimum(y0 + 1, ih - 1)
    x1 = np.minimum(x0 + 1, iw - 1)
    wy = np.clip(ys - y0, 0.0, 1.0).astype(np.float32)
    wx = np.clip(xs - x0, 0.0, 1.0).astype(np.float32)
    rows0 = img[:, y0, :]
    rows1 = img[:, y1, :]
    rows = rows0 * (1 - wy)[None, :, None] + rows1 * wy[None, :, None]
    cols0 = rows[:, :, x0]
    cols1 = rows[:, :, x1]
    return (cols0 * (1 - wx)[None, None, :]
            + cols1 * wx[None, None, :]).astype(img.dtype, copy=False)


class Resize:
    def __init__(self, size) -> None:
        self.size = (size, size) if isinstance(size, int) else tuple(size)

    def __call__(self, img):
        img = np.asarray(img, np.float32)
        return _resize_bilinear_chw(img, self.size[0], self.size[1])


class CenterCrop:
    def __init__(self, size) -> None:
        self.size = (size, size) if isinstance(size, int) else tuple(size)

    def __call__(self, img):
        _, h, w = img.shape
        th, tw = self.size
        i = max((h - th) // 2, 0)
        j = max((w - tw) // 2, 0)
        return img[:, i:i + th, j:j + tw]


class RandomCrop:
    def __init__(self, size, padding: int = 0,
                 seed: Optional[int] = None) -> None:
        self.size = (size, size) if isinstance(size, int) else tuple(size)
        self.padding = padding
        self.rng = np.random.default_rng(seed)

    def __call__(self, img):
        if self.padding:
            img = np.pad(img, ((0, 0), (self.padding, self.padding),
                               (self.padding, self.padding)))
        _, h, w = img.shape
        th, tw = self.size
        if h < th or w < tw:
            raise ValueError(
                f"RandomCrop{(th, tw)} on image {h}x{w} (after padding "
                f"{self.padding}): crop larger than input")
        i = int(self.rng.integers(0, h - th + 1))
        j = int(self.rng.integers(0, w - tw + 1))
        return img[:, i:i + th, j:j + tw]


class RandomHorizontalFlip:
    def __init__(self, prob: float = 0.5,
                 seed: Optional[int] = None) -> None:
        self.prob = prob
        self.rng = np.random.default_rng(seed)

    def __call__(self, img):
        if self.rng.random() < self.prob:
            return img[:, :, ::-1].copy()
        return img


class Transpose:
    """HWC → CHW (or any order)."""

    def __init__(self, order=(2, 0, 1)) -> None:
        self.order = order

    def __call__(self, img):
        return np.ascontiguousarray(np.transpose(img, self.order))


ToCHW = Transpose


class Pad:
    def __init__(self, padding: int) -> None:
        self.padding = padding

    def __call__(self, img):
        p = self.padding
        return np.pad(img, ((0, 0), (p, p), (p, p)))


class BrightnessTransform:
    def __init__(self, value: float, seed: Optional[int] = None) -> None:
        self.value = value
        self.rng = np.random.default_rng(seed)

    def __call__(self, img):
        alpha = 1 + self.rng.uniform(-self.value, self.value)
        return np.clip(img * alpha, 0, 1).astype(np.float32)
