"""Per-op micro-benchmark harness.

Capability parity with the reference's op_tester
(/root/reference/paddle/fluid/operators/benchmark/op_tester.cc — runs a
single op from a config of shapes/dtypes, reports ms/op). Here a
benchmark case is (callable, example inputs); the op runs jitted on the
ambient backend, synced by fetching a scalar (reliable over
remote-dispatch backends, unlike block_until_ready).

CLI: ``python -m paddle_tpu.utils.op_bench matmul 512x512`` runs a
registered op at the given shape.
"""

from __future__ import annotations

import sys
import time
from typing import Callable, Dict, Sequence

import jax
import jax.numpy as jnp
import numpy as np

__all__ = ["bench_op", "registered_ops"]


def bench_op(fn: Callable, *args, iters: int = 50,
             warmup: int = 5) -> Dict[str, float]:
    """Time `fn(*args)` jitted; returns {ms, ops_per_sec}."""
    def scalar(*a):
        out = fn(*a)
        leaf = jax.tree.leaves(out)[0]
        return jnp.sum(leaf.astype(jnp.float32))

    jf = jax.jit(scalar)
    for _ in range(warmup):
        float(jf(*args))
    t0 = time.perf_counter()
    for _ in range(iters):
        r = jf(*args)
    float(r)
    dt = (time.perf_counter() - t0) / iters
    return {"ms": dt * 1e3, "ops_per_sec": 1.0 / dt}


def _parse_shape(s: str):
    return tuple(int(t) for t in s.split("x"))


def registered_ops() -> Dict[str, Callable]:
    from ..ops import nn_functional as F
    rng = np.random.default_rng(0)

    def matmul(shape):
        a = jnp.asarray(rng.normal(0, 1, shape), jnp.float32)
        b = jnp.asarray(rng.normal(0, 1, (shape[-1], shape[0])),
                        jnp.float32)
        return lambda: bench_op(jnp.matmul, a, b)

    def softmax(shape):
        x = jnp.asarray(rng.normal(0, 1, shape), jnp.float32)
        return lambda: bench_op(jax.nn.softmax, x)

    def layer_norm(shape):
        x = jnp.asarray(rng.normal(0, 1, shape), jnp.float32)
        w = jnp.ones((shape[-1],), jnp.float32)
        b = jnp.zeros((shape[-1],), jnp.float32)
        return lambda: bench_op(
            lambda x, w, b: F.layer_norm(x, w, b, 1e-5, x.ndim - 1),
            x, w, b)

    def conv2d(shape):
        x = jnp.asarray(rng.normal(0, 1, (1, 8) + shape), jnp.float32)
        w = jnp.asarray(rng.normal(0, 0.1, (16, 8, 3, 3)), jnp.float32)
        return lambda: bench_op(lambda x, w: F.conv2d(x, w, None), x, w)

    return {"matmul": matmul, "softmax": softmax,
            "layer_norm": layer_norm, "conv2d": conv2d}


def main(argv: Sequence[str] = None) -> int:
    argv = list(argv if argv is not None else sys.argv[1:])
    ops = registered_ops()
    if not argv or argv[0] not in ops:
        print(f"usage: op_bench <{'|'.join(ops)}> [HxW[xD..]]")
        return 2
    shape = _parse_shape(argv[1]) if len(argv) > 1 else (512, 512)
    res = ops[argv[0]](shape)()
    print(f"{argv[0]} {shape}: {res['ms']:.3f} ms/op "
          f"({res['ops_per_sec']:.1f} ops/s)")
    return 0


if __name__ == "__main__":
    sys.exit(main())
