"""DLPack interop (ref: /root/reference/paddle/fluid/framework/
dlpack_tensor.cc + python paddle.utils.dlpack). Zero-copy tensor
exchange with torch/numpy/cupy via the DLPack protocol; jax implements
the capsule plumbing, this module provides the reference's API names.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

__all__ = ["to_dlpack", "from_dlpack"]


def to_dlpack(x):
    """Export a tensor as a DLPack capsule (ref: pybind dlpack_tensor
    binding). The array itself supports __dlpack__, so modern consumers
    can also take it directly."""
    arr = jnp.asarray(x)
    return arr.__dlpack__()


def from_dlpack(capsule):
    """Import a DLPack capsule or any __dlpack__-capable object
    (torch/cupy/numpy arrays included) as a framework tensor."""
    return jax.dlpack.from_dlpack(capsule)
