"""Install verification (ref: /root/reference/python/paddle/fluid/
install_check.py run_check — train a tiny linear model eagerly and
under the parallel executor, report success/diagnostics).

TPU adaptation: verifies (1) the backend initializes and reports its
platform/devices, (2) a jitted train step runs and the loss decreases,
(3) when >1 device is visible, the same step runs sharded over a dp
mesh — the three failure classes operators actually hit (wedged PJRT
tunnel, broken compile cache, bad mesh/sharding install).
"""

from __future__ import annotations

__all__ = ["run_check"]


def run_check(verbose: bool = True) -> bool:
    import numpy as np

    def say(msg):
        if verbose:
            print(f"[paddle_tpu] {msg}", flush=True)

    say("Running install check ...")
    try:
        import jax
        backend = jax.default_backend()
        devices = jax.devices()
        say(f"backend={backend} devices={len(devices)} "
            f"({devices[0].platform})")
    except Exception as e:  # noqa: BLE001
        say(f"FAIL: backend initialization raised: {e!r}")
        say("Hint: on a TPU host a hang/failure here usually means the "
            "accelerator runtime is unreachable; try JAX_PLATFORMS=cpu "
            "to confirm the CPU path.")
        return False

    import paddle_tpu as pt
    from paddle_tpu.static import TrainStep

    pt.seed(0)
    model = pt.nn.Linear(4, 3)
    opt = pt.optimizer.SGD(learning_rate=0.1)
    step = TrainStep(model, opt,
                     lambda out, y: pt.nn.functional.mse_loss(out, y))
    rng = np.random.default_rng(0)
    x = rng.normal(0, 1, (8, 4)).astype(np.float32)
    y = rng.normal(0, 1, (8, 3)).astype(np.float32)
    try:
        first = float(step(x, labels=y)["loss"])
        for _ in range(10):
            last = float(step(x, labels=y)["loss"])
    except Exception as e:  # noqa: BLE001
        say(f"FAIL: jitted train step raised: {e!r}")
        return False
    if not (np.isfinite(last) and last < first):
        say(f"FAIL: loss did not decrease ({first} -> {last})")
        return False
    say(f"single-device train step OK (loss {first:.4f} -> {last:.4f})")

    if len(devices) > 1:
        try:
            from jax.sharding import PartitionSpec as P

            from paddle_tpu.parallel import (ShardedTrainStep,
                                             data_parallel_mesh)
            mesh = data_parallel_mesh()
            pt.seed(0)
            m2 = pt.nn.Linear(4, 3)
            s2 = ShardedTrainStep(
                m2, pt.optimizer.SGD(learning_rate=0.1),
                lambda out, yy: pt.nn.functional.mse_loss(out, yy),
                mesh=mesh, batch_spec=P("dp"))
            n = mesh.shape["dp"] * 2
            reps = -(-n // len(x))  # ceil-divide: tile to >= n rows
            l0 = float(s2(np.tile(x, (reps, 1))[:n],
                          labels=np.tile(y, (reps, 1))[:n])["loss"])
            if not np.isfinite(l0):
                say(f"FAIL: sharded step produced non-finite loss "
                    f"({l0}) — miswired collective/sharding")
                return False
            say(f"{len(devices)}-device sharded step OK (loss {l0:.4f})")
        except Exception as e:  # noqa: BLE001
            say(f"FAIL: sharded step over {len(devices)} devices "
                f"raised: {e!r}")
            return False
    say("paddle_tpu is installed and working.")
    return True
