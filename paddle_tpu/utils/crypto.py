"""Encrypted model files.

Capability parity with the reference's crypto subsystem
(/root/reference/paddle/fluid/framework/io/crypto/cipher.cc,
aes_cipher.cc, cipher_utils.cc — AES-GCM encryption of saved models,
exposed as CipherFactory/CipherUtils in python). Design difference, on
purpose: the image ships no AES implementation (no OpenSSL binding, no
pycryptodome) and hand-rolling AES invites timing bugs, so the cipher
is **HMAC-SHA256 in counter mode** (a standard PRF-CTR stream
construction) with an encrypt-then-MAC integrity tag. Same capability
surface — keygen, encrypt/decrypt bytes and files, key files — with
authenticated encryption the reference's CBC mode lacks.
"""

from __future__ import annotations

import hashlib
import hmac
import os
import struct

__all__ = ["CipherUtils", "CipherFactory", "Cipher"]

_MAGIC = b"PTENC1\x00"
_BLOCK = 32  # sha256 output


def _keystream(key: bytes, nonce: bytes, n: int) -> bytes:
    out = bytearray()
    counter = 0
    while len(out) < n:
        out += hmac.new(key, nonce + struct.pack(">Q", counter),
                        hashlib.sha256).digest()
        counter += 1
    return bytes(out[:n])


def _xor(data: bytes, stream: bytes) -> bytes:
    """Bulk XOR via one big-int op (a per-byte Python loop is ~100x
    slower — model files are hundreds of MB)."""
    return (int.from_bytes(data, "big")
            ^ int.from_bytes(stream, "big")).to_bytes(len(data), "big")


class Cipher:
    """(ref: cipher.h Cipher interface: Encrypt/Decrypt/EncryptToFile/
    DecryptFromFile)."""

    def encrypt(self, plaintext: bytes, key: bytes) -> bytes:
        if len(key) < 16:
            raise ValueError("key must be at least 16 bytes")
        nonce = os.urandom(16)
        enc_key = hashlib.sha256(b"enc" + key).digest()
        mac_key = hashlib.sha256(b"mac" + key).digest()
        stream = _keystream(enc_key, nonce, len(plaintext))
        ct = _xor(plaintext, stream)
        body = _MAGIC + nonce + ct
        tag = hmac.new(mac_key, body, hashlib.sha256).digest()
        return body + tag

    def decrypt(self, ciphertext: bytes, key: bytes) -> bytes:
        if len(ciphertext) < len(_MAGIC) + 16 + _BLOCK:
            raise ValueError("ciphertext too short")
        body, tag = ciphertext[:-_BLOCK], ciphertext[-_BLOCK:]
        if not body.startswith(_MAGIC):
            raise ValueError("not a paddle_tpu encrypted blob")
        mac_key = hashlib.sha256(b"mac" + key).digest()
        want = hmac.new(mac_key, body, hashlib.sha256).digest()
        if not hmac.compare_digest(tag, want):
            raise ValueError("integrity check failed: wrong key or "
                             "corrupted data")
        nonce = body[len(_MAGIC):len(_MAGIC) + 16]
        ct = body[len(_MAGIC) + 16:]
        enc_key = hashlib.sha256(b"enc" + key).digest()
        stream = _keystream(enc_key, nonce, len(ct))
        return _xor(ct, stream)

    def encrypt_to_file(self, plaintext: bytes, key: bytes,
                        path: str) -> None:
        with open(path, "wb") as f:
            f.write(self.encrypt(plaintext, key))

    def decrypt_from_file(self, key: bytes, path: str) -> bytes:
        with open(path, "rb") as f:
            return self.decrypt(f.read(), key)


class CipherFactory:
    """(ref: cipher.cc CipherFactory::CreateCipher)."""

    @staticmethod
    def create_cipher(config_file: str = "") -> Cipher:
        return Cipher()


class CipherUtils:
    """(ref: cipher_utils.cc GenKey/GenKeyToFile/ReadKeyFromFile)."""

    @staticmethod
    def gen_key(length_bits: int = 256) -> bytes:
        if length_bits % 8:
            raise ValueError("key length must be a multiple of 8 bits")
        return os.urandom(length_bits // 8)

    @staticmethod
    def gen_key_to_file(length_bits: int, path: str) -> bytes:
        key = CipherUtils.gen_key(length_bits)
        with open(path, "wb") as f:
            f.write(key)
        return key

    @staticmethod
    def read_key_from_file(path: str) -> bytes:
        with open(path, "rb") as f:
            return f.read()
