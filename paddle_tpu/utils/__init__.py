from . import dlpack  # noqa: F401
from . import crypto  # noqa: F401
from . import op_bench  # noqa: F401

from .install_check import run_check  # noqa: F401
