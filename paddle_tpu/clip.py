"""Gradient clipping.

TPU-native analogue of /root/reference/python/paddle/fluid/clip.py
(ClipGradByValue, ClipGradByNorm, ClipGradByGlobalNorm :309). Clips are
callables over grad pytrees used by Optimizer.apply_gradients inside the
jitted step — global-norm reduction fuses with the optimizer update.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from .ops.sparse import RowSlices


def _leaves(tree):
    return jax.tree.leaves(tree, is_leaf=lambda x: isinstance(x, RowSlices))


def _map(fn, tree):
    return jax.tree.map(fn, tree, is_leaf=lambda x: isinstance(x, RowSlices))


def _values(g):
    return g.values if isinstance(g, RowSlices) else g


def _scale(g, s):
    if isinstance(g, RowSlices):
        return RowSlices(g.rows, g.values * s, g.dense_rows)
    return g * s


class ClipGradBase:
    def __call__(self, grads):
        raise NotImplementedError


class ClipGradByValue(ClipGradBase):
    def __init__(self, max: float, min=None) -> None:
        self.max = max
        self.min = -max if min is None else min

    def __call__(self, grads):
        def clip_one(g):
            if isinstance(g, RowSlices):
                return RowSlices(g.rows,
                                 jnp.clip(g.values, self.min, self.max),
                                 g.dense_rows)
            return jnp.clip(g, self.min, self.max)
        return _map(clip_one, grads)


class ClipGradByNorm(ClipGradBase):
    """Per-tensor L2 norm clip (ref: clip.py ClipGradByNorm)."""

    def __init__(self, clip_norm: float) -> None:
        self.clip_norm = clip_norm

    def __call__(self, grads):
        def clip_one(g):
            v = _values(g)
            norm = jnp.sqrt(jnp.sum(jnp.square(v)))
            scale = jnp.where(norm > self.clip_norm,
                              self.clip_norm / jnp.maximum(norm, 1e-12), 1.0)
            return _scale(g, scale)
        return _map(clip_one, grads)


class ClipGradByGlobalNorm(ClipGradBase):
    """Global L2 norm clip (ref: clip.py:309)."""

    def __init__(self, clip_norm: float) -> None:
        self.clip_norm = clip_norm

    def __call__(self, grads):
        sq = [jnp.sum(jnp.square(_values(g))) for g in _leaves(grads)
              if g is not None]
        global_norm = jnp.sqrt(jnp.sum(jnp.stack(sq)))
        # grad-norm gauge: inserts a debug callback into the traced
        # program only when metrics are on AT TRACE TIME (off = zero
        # compiled overhead; flipping the flag later needs a retrace)
        from .observability import observe_traced
        observe_traced("grad_global_norm", global_norm)
        scale = self.clip_norm / jnp.maximum(global_norm, self.clip_norm)
        return _map(lambda g: _scale(g, scale), grads)


def clip_grad_value_(grads, clip_value: float):
    return ClipGradByValue(clip_value)(grads)


def clip_grad_norm_(grads, max_norm: float):
    return ClipGradByGlobalNorm(max_norm)(grads)
