"""paddle.distributed analogue: collectives + fleet orchestration."""

from ..parallel import (all_gather, all_reduce, barrier, broadcast,
                        get_rank, get_world_size, init_parallel_env,
                        new_group, reduce, scatter)
from ..parallel.env import ParallelEnv
from . import fleet
from . import ps
from .launch import spawn  # noqa: F401
