"""Parameter-server training (sync / async / geo).

Python orchestration over the native PS service (csrc/ps_service.cc),
covering the reference's PS capability stack:

- DistributeTranspiler program rewriting (transpiler/
  distribute_transpiler.py:545): params are split into blocks and spread
  across pserver shards (`_split_blocks` ≈ _init_splited_vars :1678);
  trainer steps push grads / pull params instead of running optimizer ops.
- listen_and_serv optimize blocks (distributed_ops/listen_and_serv_op.cc)
  run as C++ server-side optimizers.
- Communicator modes (operators/distributed/communicator.h:253): sync
  (barriered per-step apply), async (hogwild immediate apply), and geo
  (communicator.h:396 GeoCommunicator: trainers train locally and
  exchange parameter deltas every k steps).
- distributed_lookup_table / large_scale_kv sparse tables
  (operators/distributed/large_scale_kv.h): `SparseEmbeddingPS` pulls
  rows by id before forward and pushes row grads after backward.

On TPU the data path of real jobs should be ICI collectives; this stack
exists for capability parity where a host-side parameter service is
genuinely wanted (giant embeddings, heterogeneous clusters).
"""

from __future__ import annotations

import threading
import time
import zlib
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

from ..core import as_label_tuple
import jax
import jax.numpy as jnp
import numpy as np

from ..core import random as _random
from ..native import PsClient, PsServer
from ..nn.layer import Layer, functional_call

__all__ = [
    "PsServer", "PSCluster", "DensePSAdapter", "SparseEmbeddingPS",
    "PSTrainStep", "run_server",
]


class PSCluster:
    """Connections to every pserver shard."""

    def __init__(self, endpoints: Sequence[str], timeout_ms: int = 30000):
        self.endpoints = list(endpoints)
        self.clients: List[PsClient] = []
        for ep in self.endpoints:
            host, port = ep.rsplit(":", 1)
            self.clients.append(PsClient(host, int(port), timeout_ms))

    def __len__(self) -> int:
        return len(self.clients)

    def close(self) -> None:
        for c in self.clients:
            c.close()


def _split_blocks(name: str, size: int, n_servers: int,
                  min_block: int = 8192) -> List[Tuple[int, str, int, int]]:
    """Split a flat param into ≤n_servers blocks: (server, key, start, stop).

    Mirrors the reference's even block split across pservers
    (distribute_transpiler.py:1678 _init_splited_vars); small params stay
    whole on one shard (chosen by name hash for balance).
    """
    if size <= min_block or n_servers == 1:
        # crc32, not builtin hash(): hash() is salted per process, so two
        # trainer processes would map the same param to different shards
        # (sync accumulation never completes; async trains disjoint copies).
        server = zlib.crc32(name.encode("utf-8")) % n_servers
        return [(server, f"{name}.block0", 0, size)]
    n_blocks = min(n_servers, (size + min_block - 1) // min_block)
    per = (size + n_blocks - 1) // n_blocks
    blocks = []
    for b in range(n_blocks):
        start, stop = b * per, min((b + 1) * per, size)
        if start >= stop:
            break
        blocks.append((b % n_servers, f"{name}.block{b}", start, stop))
    return blocks


class DensePSAdapter:
    """Dense-parameter bridge: local param dict <-> sharded PS tables."""

    def __init__(self, cluster: PSCluster, params: Dict[str, np.ndarray],
                 optimizer: str = "sgd", lr: float = 0.01,
                 beta1: float = 0.9, beta2: float = 0.999, eps: float = 1e-8,
                 sync_world: int = 0):
        self.cluster = cluster
        self.shapes = {k: np.asarray(v).shape for k, v in params.items()}
        self.blocks: Dict[str, List[Tuple[int, str, int, int]]] = {}
        for name, value in params.items():
            flat = np.ascontiguousarray(value, np.float32).reshape(-1)
            blocks = _split_blocks(name, flat.size, len(cluster))
            self.blocks[name] = blocks
            for server, key, start, stop in blocks:
                cluster.clients[server].dense_init(
                    key, flat[start:stop], stop - start, optimizer=optimizer,
                    lr=lr, beta1=beta1, beta2=beta2, eps=eps,
                    sync_world=sync_world)

    def push(self, grads: Dict[str, np.ndarray]) -> int:
        version = 0
        for name, g in grads.items():
            flat = np.ascontiguousarray(g, np.float32).reshape(-1)
            for server, key, start, stop in self.blocks[name]:
                version = self.cluster.clients[server].dense_push(
                    key, flat[start:stop])
        return version

    def pull(self, min_version: int = 0,
             timeout_ms: int = 60000) -> Dict[str, np.ndarray]:
        out = {}
        for name, blocks in self.blocks.items():
            size = int(np.prod(self.shapes[name])) if self.shapes[name] \
                else 1
            flat = np.empty(size, np.float32)
            for server, key, start, stop in blocks:
                vals, _ = self.cluster.clients[server].dense_pull(
                    key, stop - start, min_version, timeout_ms)
                flat[start:stop] = vals
            out[name] = flat.reshape(self.shapes[name])
        return out


class SparseEmbeddingPS:
    """Embedding whose rows live on the PS (distributed_lookup_table).

    forward: pull rows for the batch's ids -> jnp table slice;
    backward: push per-row grads (optimizer applies server-side).
    Rows shard across servers by id modulo.
    """

    def __init__(self, cluster: PSCluster, name: str, dim: int,
                 optimizer: str = "sgd", lr: float = 0.01,
                 init_scale: float = 0.05):
        self.cluster = cluster
        self.name = name
        self.dim = dim
        for c in cluster.clients:
            c.sparse_init(name, dim, optimizer=optimizer, lr=lr,
                          init_scale=init_scale)

    def _shard(self, ids: np.ndarray) -> List[np.ndarray]:
        n = len(self.cluster)
        return [np.where(ids % n == s)[0] for s in range(n)]

    def pull(self, ids: np.ndarray) -> np.ndarray:
        ids = np.ascontiguousarray(ids, np.int64).reshape(-1)
        out = np.empty((ids.size, self.dim), np.float32)
        for s, idx in enumerate(self._shard(ids)):
            if idx.size:
                out[idx] = self.cluster.clients[s].sparse_pull(
                    self.name, ids[idx], self.dim)
        return out

    def push(self, ids: np.ndarray, grads: np.ndarray) -> None:
        ids = np.ascontiguousarray(ids, np.int64).reshape(-1)
        grads = np.ascontiguousarray(grads, np.float32).reshape(
            ids.size, self.dim)
        # Merge duplicate ids before pushing (reference merge_sparse_grad
        # semantics): the server applies its per-row optimizer once per
        # received row, so duplicates would take multiple adagrad/adam slot
        # steps for one batch.
        if ids.size:
            uniq, inv = np.unique(ids, return_inverse=True)
            if uniq.size != ids.size:
                summed = np.zeros((uniq.size, self.dim), np.float32)
                np.add.at(summed, inv, grads)
                ids, grads = uniq, summed
        for s, idx in enumerate(self._shard(ids)):
            if idx.size:
                self.cluster.clients[s].sparse_push(
                    self.name, ids[idx], grads[idx], self.dim)

    def size(self) -> int:
        return sum(c.sparse_size(self.name) for c in self.cluster.clients)


class PSTrainStep:
    """Trainer-side step for PS training.

    mode="sync":  push grad, pull params at version=step (barriered like
                  the reference's fetch_barrier/send_barrier protocol).
    mode="async": push grad (applies immediately), pull latest (hogwild).
    mode="geo":   run `geo_k` local optimizer steps, then push the param
                  delta to 'sum' tables and adopt the merged value
                  (GeoCommunicator semantics).
    """

    def __init__(self, model: Layer, loss_fn: Callable, cluster: PSCluster,
                 mode: str = "sync", n_trainers: int = 1,
                 optimizer: str = "sgd", lr: float = 0.01,
                 geo_k: int = 8, local_optimizer=None, seed: int = 0):
        if mode not in ("sync", "async", "geo"):
            raise ValueError(f"unknown PS mode {mode!r}")
        self.model = model
        self.loss_fn = loss_fn
        self.mode = mode
        self.geo_k = geo_k
        self._step_no = 0
        self._rng_key = _random.make_key(seed)
        params = {k: np.asarray(v, np.float32)
                  for k, v in model.param_dict().items()}
        self._buffers = model.buffer_dict()

        if mode == "geo":
            if local_optimizer is None:
                raise ValueError("geo mode needs local_optimizer")
            self.local_opt = local_optimizer
            self._opt_state = local_optimizer.init(params)
            self.adapter = DensePSAdapter(cluster, params, optimizer="sum")
            self._base = {k: v.copy() for k, v in params.items()}
        else:
            sync_world = n_trainers if mode == "sync" else 0
            self.adapter = DensePSAdapter(
                cluster, params, optimizer=optimizer, lr=lr,
                sync_world=sync_world)
        self._params = params
        self._grad_fn = None

    def _build_grad_fn(self):
        def loss_of(p, key, args, labels):
            with _random.rng_scope(default=key, dropout=key):
                out, _ = functional_call(self.model, p, self._buffers,
                                         *args, capture_buffers=True)
                return self.loss_fn(out, *labels)

        return jax.jit(jax.value_and_grad(loss_of))

    def __call__(self, *args, labels=()) -> Dict[str, float]:
        if self._grad_fn is None:
            self._grad_fn = self._build_grad_fn()
        self._rng_key, sub = jax.random.split(self._rng_key)
        loss, grads = self._grad_fn(self._params, sub, tuple(args),
                                    as_label_tuple(labels))
        grads = {k: np.asarray(v, np.float32) for k, v in grads.items()}
        self._step_no += 1

        if self.mode == "geo":
            new_p, self._opt_state = self.local_opt.apply_gradients(
                self._params, grads, self._opt_state)
            self._params = {k: np.asarray(v, np.float32)
                            for k, v in new_p.items()}
            if self._step_no % self.geo_k == 0:
                deltas = {k: self._params[k] - self._base[k]
                          for k in self._params}
                self.adapter.push(deltas)
                merged = self.adapter.pull()
                self._params = merged
                self._base = {k: v.copy() for k, v in merged.items()}
        else:
            self.adapter.push(grads)
            min_version = self._step_no if self.mode == "sync" else 0
            self._params = self.adapter.pull(min_version=min_version)
        return {"loss": float(loss)}

    @property
    def params(self) -> Dict[str, np.ndarray]:
        return self._params

    def sync_to_model(self) -> None:
        self.model.set_state_dict(dict(self._params), strict=False)


def run_server(port: int = 0, ready_callback: Optional[Callable] = None,
               stop_event: Optional[threading.Event] = None) -> PsServer:
    """Start a PS shard; blocks until stop_event (if given) else returns.

    The reference blocks inside ListenAndServOp::RunImpl; here the server
    runs on background threads, so blocking is optional.
    """
    server = PsServer(port)
    if ready_callback is not None:
        ready_callback(server)
    if stop_event is not None:
        try:
            while not stop_event.wait(0.2):
                pass
        finally:
            server.stop()
    return server


class HeartbeatMonitor:
    """Worker liveness over the PS (ref: heart_beat_monitor.cc — the
    pserver-side monitor flagging workers that stop calling in).

    Each worker runs ``start_beating(worker_id)`` (background thread,
    one beat per ``interval_s``); any process can ask
    ``dead_workers(workers, timeout_ms)``. Failure DETECTION half of
    the elastic story — restart orchestration is
    ``distributed.launch --elastic``.
    """

    def __init__(self, client, interval_s: float = 2.0) -> None:
        self.client = client
        self.interval_s = interval_s
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    def start_beating(self, worker_id: str) -> None:
        if self._thread is not None:
            raise RuntimeError("already beating")
        self._stop.clear()  # allow stop() -> start_beating() restarts
        self.client.heartbeat(worker_id)  # immediate first beat

        def loop():
            while not self._stop.wait(self.interval_s):
                try:
                    self.client.heartbeat(worker_id)
                except Exception:
                    return  # connection gone; the monitor sees silence

        self._thread = threading.Thread(target=loop, daemon=True)
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5)
            self._thread = None

    def dead_workers(self, workers, timeout_ms: int):
        """Workers whose last beat is older than timeout_ms (or that
        never beat)."""
        dead = []
        for w in workers:
            ms = self.client.liveness_ms(w)
            if ms is None or ms > timeout_ms:
                dead.append(w)
        return dead

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.stop()
