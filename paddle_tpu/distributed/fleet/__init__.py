"""Fleet: distributed training orchestration.

TPU-native redesign of the reference's Fleet
(/root/reference/python/paddle/distributed/fleet/base/fleet_base.py:42
fleet.init/minimize, distributed_strategy.py over
framework/distributed_strategy.proto:94, meta_optimizers/ composition via
strategy_compiler.py). The meta-optimizer pass pipeline (AMP ∘ Recompute ∘
GradientMerge ∘ LocalSGD ∘ GraphExecution...) becomes a **strategy
compiler over functional transforms**: each enabled strategy wraps the
train-step construction (remat policy, grad accumulation scan, periodic
param sync, sharded pjit compile) — same composition semantics, no graph
rewriting.
"""

from .base import (DistributedStrategy, Fleet, PaddleCloudRoleMaker,
                   UserDefinedRoleMaker, fleet, init, distributed_optimizer)
from .strategy_compiler import apply_strategy
from . import metrics  # noqa: F401 — fleet.metrics.* (ref: fleet/metrics/)
