"""Fleet base: DistributedStrategy, role makers, the fleet singleton.

Reference: fleet_base.py:42 (Fleet), :266 (minimize);
distributed_strategy.proto:94 (20+ toggles, per-feature config messages
:25-92); role_maker.py (:481 PaddleCloudRoleMaker reads PADDLE_* env).
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, Callable, Dict, Optional

from ...parallel.env import ParallelEnv, init_parallel_env


@dataclass
class RecomputeConfig:
    checkpoints: list = field(default_factory=list)
    policy: str = "nothing_saveable"


@dataclass
class GradientMergeConfig:
    k_steps: int = 1
    avg: bool = True


@dataclass
class LocalSGDConfig:
    k_steps: int = 1


@dataclass
class AMPConfig:
    init_loss_scaling: float = 2.0 ** 15
    use_dynamic_loss_scaling: bool = True
    dtype: str = "bfloat16"


@dataclass
class PipelineConfig:
    micro_batch: int = 1
    stages: int = 1


@dataclass
class DGCConfig:
    # dense warm-up steps before compression kicks in (paper §3.3
    # warm-up training; DGCTrainStep's own default)
    rampup_begin_step: int = 3
    sparsity: float = 0.999


@dataclass
class ShardingConfig:
    """ZeRO-style optimizer state sharding over dp."""
    stage: int = 1


class DistributedStrategy:
    """(ref: distributed_strategy.proto:94 + python wrapper). Feature
    toggles consumed by the strategy compiler."""

    def __init__(self) -> None:
        self.amp = False
        self.amp_configs = AMPConfig()
        self.recompute = False
        self.recompute_configs = RecomputeConfig()
        self.gradient_merge = False
        self.gradient_merge_configs = GradientMergeConfig()
        self.localsgd = False
        self.localsgd_configs = LocalSGDConfig()
        self.pipeline = False
        self.pipeline_configs = PipelineConfig()
        self.dgc = False
        self.dgc_configs = DGCConfig()
        self.sharding = False
        self.sharding_configs = ShardingConfig()
        self.lamb = False
        self.lars = False
        self.nccl_comm_num = 1          # parity: multiple comm rings
        self.hierarchical_allreduce = False  # ICI/DCN two-level (auto)
        self.sync_batch_norm = False
        self.fuse_grad_size_in_MB = 32
        self.cudnn_exhaustive_search = False  # no-op on TPU
        self.tensor_parallel = False
        self.tensor_parallel_configs = {"tensor_parallel_degree": 1}

    def to_dict(self) -> Dict[str, Any]:
        return {k: v for k, v in self.__dict__.items()}


class RoleMakerBase:
    def __init__(self) -> None:
        self.env = ParallelEnv()

    def worker_index(self) -> int:
        return self.env.rank

    def worker_num(self) -> int:
        return self.env.world_size

    def is_first_worker(self) -> bool:
        return self.env.rank == 0

    def is_worker(self) -> bool:
        return True

    def is_server(self) -> bool:
        return False


class PaddleCloudRoleMaker(RoleMakerBase):
    """(ref: role_maker.py:481) — env-var driven."""

    def __init__(self, is_collective: bool = True) -> None:
        super().__init__()
        self.is_collective = is_collective


class UserDefinedRoleMaker(RoleMakerBase):
    def __init__(self, current_id: int = 0, workers: int = 1,
                 **kw) -> None:
        super().__init__()
        self.env.rank = current_id
        self.env.world_size = workers


class Fleet:
    """(ref: fleet_base.py:42). Singleton via module-level ``fleet``."""

    def __init__(self) -> None:
        self._role_maker: Optional[RoleMakerBase] = None
        self._strategy: Optional[DistributedStrategy] = None
        self._is_initialized = False

    def init(self, role_maker: Optional[RoleMakerBase] = None,
             is_collective: bool = True,
             strategy: Optional[DistributedStrategy] = None) -> "Fleet":
        self._role_maker = role_maker or PaddleCloudRoleMaker(is_collective)
        self._strategy = strategy or DistributedStrategy()
        if self._role_maker.worker_num() > 1:
            init_parallel_env()
        self._is_initialized = True
        return self

    @property
    def strategy(self) -> DistributedStrategy:
        return self._strategy or DistributedStrategy()

    def worker_index(self) -> int:
        return self._role_maker.worker_index() if self._role_maker else 0

    def worker_num(self) -> int:
        return self._role_maker.worker_num() if self._role_maker else 1

    def is_first_worker(self) -> bool:
        return self.worker_index() == 0

    def distributed_optimizer(self, optimizer,
                              strategy: Optional[DistributedStrategy]
                              = None):
        """(ref: fleet_base.py distributed_optimizer → meta-opt pipeline).
        Returns the optimizer annotated with the strategy; the strategy is
        applied when a sharded step is built (strategy_compiler.py)."""
        if strategy is not None:
            self._strategy = strategy
        optimizer._fleet_strategy = self.strategy
        return optimizer

    def build_train_step(self, model, optimizer, loss_fn, mesh=None,
                         **kwargs):
        """Compile a distributed train step under the current strategy —
        the minimize() analogue for the functional design."""
        from .strategy_compiler import apply_strategy
        return apply_strategy(self.strategy, model, optimizer, loss_fn,
                              mesh=mesh, **kwargs)

    def barrier_worker(self) -> None:
        from ...parallel.collective import barrier
        barrier()

    def save_persistables(self, state, path: str) -> None:
        from ... import io
        if self.is_first_worker():
            io.save(state, path)

    def stop_worker(self) -> None:
        pass


fleet = Fleet()


def init(role_maker=None, is_collective: bool = True, strategy=None):
    return fleet.init(role_maker, is_collective, strategy)


def distributed_optimizer(optimizer, strategy=None):
    return fleet.distributed_optimizer(optimizer, strategy)
