"""Fleet metrics: statistics aggregated across all trainer processes.

Capability parity with the reference's
/root/reference/python/paddle/distributed/fleet/metrics/metric.py
(sum/max/min/auc/mae/mse/rmse/acc — each allreduces local numpy stats
across workers through fleet util's gloo allreduce). TPU-native
difference: the transport is the native control plane
(csrc/control_plane.cc — the same service that replaces the gloo
barrier/KV role, SURVEY §2.9), so metric aggregation works in any
multi-process job launched by distributed/launch.py without a device
mesh. Single-process jobs (including one process driving a whole TPU
slice) aggregate trivially.

All functions follow the reference's collective contract: every worker
calls the same functions in the same order.
"""

from __future__ import annotations

import os
from typing import Optional

import numpy as np

__all__ = ["sum", "max", "min", "acc", "mae", "mse", "rmse", "auc"]

_client = None
_round = 0


def _env():
    from ...parallel.env import ParallelEnv
    return ParallelEnv()


def _cp():
    global _client
    if _client is None:
        from ... import native
        ep = os.environ.get("PT_CP_ENDPOINT", "")
        if not ep:
            raise RuntimeError(
                "fleet.metrics needs PT_CP_ENDPOINT (set by "
                "distributed/launch.py) to aggregate across processes")
        host, port = ep.rsplit(":", 1)
        _client = native.ControlPlaneClient(host, int(port))
    return _client


def _allreduce(local: np.ndarray, op: str) -> np.ndarray:
    """Reduce a small numpy array across all trainers.

    Every rank publishes its value, reads all ranks' values, and
    reduces locally — the gloo-allreduce role of the reference
    (metric.py `fleet.util.all_reduce`). Values are tiny (metric
    stats), so O(world²) reads are irrelevant.

    Key usage is BOUNDED (the control plane has no delete): each rank
    double-buffers two fixed keys by round parity, with the round id
    embedded in the value. A slot is only overwritten two rounds later,
    by which time every rank has provably read it (the collective
    contract — all ranks call in the same order — means finishing round
    N+1 required reading everyone's N+1, which required them to have
    finished reading round N).
    """
    import struct
    import time as _time

    global _round
    env = _env()
    world = env.world_size
    if world <= 1:
        return local
    cp = _cp()
    _round += 1
    want = _round
    payload = struct.pack(">Q", want) \
        + np.ascontiguousarray(local).tobytes()
    cp.set(f"__fmetric_{env.rank}_{want % 2}", payload)
    # Peers can legitimately lag minutes behind (XLA compiles, data
    # skew): keep waiting up to a 10-minute deadline rather than dying
    # on the client's 30s default get timeout.
    deadline = _time.monotonic() + 600.0
    parts = []
    for r in range(world):
        key = f"__fmetric_{r}_{want % 2}"
        while True:
            try:
                raw = cp.get(key, block=True, timeout_ms=30000)
            except (TimeoutError, KeyError):
                if _time.monotonic() > deadline:
                    raise TimeoutError(
                        f"fleet.metrics: rank {r} never published round "
                        f"{want} within 600s — peer dead or collective "
                        f"call order diverged")
                continue
            (got,) = struct.unpack(">Q", raw[:8])
            if got >= want:
                break
            if _time.monotonic() > deadline:
                raise TimeoutError(
                    f"fleet.metrics: rank {r} stuck at round {got} < "
                    f"{want} after 600s")
            _time.sleep(0.002)
        parts.append(np.frombuffer(raw[8:], local.dtype)
                     .reshape(local.shape))
    stacked = np.stack(parts)
    if op == "sum":
        return stacked.sum(axis=0)
    if op == "max":
        return stacked.max(axis=0)
    if op == "min":
        return stacked.min(axis=0)
    raise ValueError(f"unknown reduce op {op!r}")


def sum(input) -> np.ndarray:  # noqa: A001 — reference name
    """(ref: metric.py sum) global sum of a local stat array/scalar."""
    return _allreduce(np.asarray(input, np.float64), "sum")


def max(input) -> np.ndarray:  # noqa: A001
    return _allreduce(np.asarray(input, np.float64), "max")


def min(input) -> np.ndarray:  # noqa: A001
    return _allreduce(np.asarray(input, np.float64), "min")


def _ratio_of_sums(num, den) -> float:
    """One packed allreduce for numerator+denominator (halves the
    cross-rank latency of acc/mae/mse)."""
    packed = _allreduce(
        np.asarray([float(np.asarray(num).sum()),
                    float(np.asarray(den).sum())], np.float64), "sum")
    return float(packed[0] / np.maximum(packed[1], 1e-12))


def acc(correct, total) -> float:
    """(ref: metric.py acc) global accuracy from local counts."""
    return _ratio_of_sums(correct, total)


def mae(abserr, total_ins_num) -> float:
    return _ratio_of_sums(abserr, total_ins_num)


def mse(sqrerr, total_ins_num) -> float:
    return _ratio_of_sums(sqrerr, total_ins_num)


def rmse(sqrerr, total_ins_num) -> float:
    return float(np.sqrt(mse(sqrerr, total_ins_num)))


def auc(stat_pos, stat_neg) -> float:
    """(ref: metric.py auc) global AUC from per-threshold pos/neg
    histograms (the reference's distributed AUC computes the same
    trapezoid over summed stat buckets)."""
    local_pos = np.asarray(stat_pos, np.float64).ravel()
    local_neg = np.asarray(stat_neg, np.float64).ravel()
    both = _allreduce(np.concatenate([local_pos, local_neg]), "sum")
    pos, neg = both[:len(local_pos)], both[len(local_pos):]
    # walk thresholds high→low accumulating TP/FP (trapezoid area)
    tot_pos = float(pos.sum())
    tot_neg = float(neg.sum())
    if tot_pos == 0.0 or tot_neg == 0.0:
        return 0.5
    area = 0.0
    tp = fp = 0.0
    for i in range(len(pos) - 1, -1, -1):
        new_tp = tp + float(pos[i])
        new_fp = fp + float(neg[i])
        area += (new_fp - fp) * (tp + new_tp) / 2.0
        tp, fp = new_tp, new_fp
    return float(area / (tot_pos * tot_neg))
