"""Strategy compiler: DistributedStrategy → composed train step.

TPU-native replacement for the reference's meta-optimizer composition
(/root/reference/python/paddle/distributed/fleet/base/strategy_compiler.py
+ meta_optimizers/: amp_optimizer.py, recompute_optimizer.py,
gradient_merge_optimizer.py, localsgd_optimizer.py, lamb/lars, pipeline,
graph_execution_optimizer.py:92). Each reference meta-optimizer rewrites
the program; here each strategy is a functional wrapper applied while
building the sharded step:

- recompute      → jax.checkpoint on the model's forward (remat)
- gradient_merge → lax.scan over micro-batches accumulating grads
- amp            → bf16 cast policy (+ GradScaler for fp16 parity)
- localsgd       → periodic param allreduce instead of per-step
- lars/lamb      → optimizer substitution
- graph_execution → the pjit compile itself (ShardedTrainStep)
"""

from __future__ import annotations

from typing import Any, Callable, Dict, Optional

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from ...nn.layer import Layer, functional_call
from ...optimizer import Lamb, LarsMomentum, Momentum, Optimizer
from ...parallel.mesh import (create_mesh, create_multislice_mesh,
                              data_parallel_mesh, multislice_data_spec,
                              num_slices)
from ...parallel.spmd import ShardedTrainStep, megatron_param_rule


def apply_strategy(strategy, model: Layer, optimizer: Optimizer,
                   loss_fn: Callable, mesh=None, seed: int = 0,
                   param_rule=None, batch_spec: P = P("dp")):
    if mesh is None:
        tp = strategy.tensor_parallel_configs.get(
            "tensor_parallel_degree", 1) if strategy.tensor_parallel else 1
        if strategy.hierarchical_allreduce and (strategy.dgc
                                                or strategy.localsgd):
            # DGC/LocalSGD sync over a single dp axis; a (dcn, dp) hybrid
            # mesh would leave the dcn replicas unsynced. Use a flat dp
            # mesh — XLA still decomposes the allreduce across slice
            # boundaries from the physical topology.
            mesh = data_parallel_mesh()
        elif strategy.hierarchical_allreduce:
            # two-level reduction: intra-slice over ICI, inter-slice over
            # DCN (ref: distributed_strategy.proto:110, nccl_helper.h:185)
            slices = max(num_slices(), 1)
            ici = {"dp": -1, "mp": tp} if tp > 1 else {"dp": -1}
            mesh = create_multislice_mesh({"dcn": slices}, ici)
            if batch_spec == P("dp"):
                batch_spec = multislice_data_spec(mesh)
        elif strategy.tensor_parallel:
            mesh = create_mesh({"dp": -1, "mp": tp})
        else:
            mesh = data_parallel_mesh()

    # dgc / localsgd replace the whole step structure (they change how
    # gradients cross replicas), so they take precedence and compose only
    # with optimizer substitution
    if strategy.amp and (strategy.dgc or strategy.localsgd):
        raise ValueError(
            "strategy.amp does not compose with dgc/localsgd yet — "
            "those steps bypass the AMP pipeline, so enabling both "
            "would silently train in full precision. Disable one.")
    if strategy.dgc:
        from ...parallel.dgc import DGCTrainStep
        return DGCTrainStep(
            model, optimizer, loss_fn, mesh,
            sparsity=strategy.dgc_configs.sparsity,
            rampup_steps=strategy.dgc_configs.rampup_begin_step, seed=seed)
    if strategy.localsgd:
        from ...parallel.localsgd import LocalSGDStep
        return LocalSGDStep(
            model, optimizer, loss_fn, mesh,
            k_steps=strategy.localsgd_configs.k_steps, seed=seed)

    # lars/lamb: optimizer substitution (ref: lars/lamb meta-optimizers)
    if strategy.lamb and not isinstance(optimizer, Lamb):
        optimizer = Lamb(learning_rate=optimizer.learning_rate)
    if strategy.lars and isinstance(optimizer, Momentum) and \
            not isinstance(optimizer, LarsMomentum):
        optimizer = LarsMomentum(learning_rate=optimizer.learning_rate,
                                 momentum=optimizer.momentum)

    if strategy.tensor_parallel and param_rule is None:
        param_rule = megatron_param_rule("mp")

    model_call = None
    if strategy.recompute:
        # remat the forward (ref: recompute_optimizer.py / backward.py:629)
        policy = getattr(jax.checkpoint_policies,
                         strategy.recompute_configs.policy,
                         jax.checkpoint_policies.nothing_saveable)
        model_call = policy  # consumed by _RematStep below

    k_steps = strategy.gradient_merge_configs.k_steps \
        if strategy.gradient_merge else 1
    local_k = strategy.localsgd_configs.k_steps if strategy.localsgd else 1

    amp_dtype = None
    scaler = None
    if strategy.amp:
        # (ref: amp meta-optimizer, contrib/mixed_precision/decorator.py
        # :218 OptimizerWithMixedPrecision). bf16 needs no loss scaling;
        # fp16 gets the in-graph dynamic scaler (the reference's
        # update_loss_scaling + amp_check_finite_and_scale ops).
        from ...amp import GradScaler
        from ...core.dtype import convert_dtype
        amp_dtype = strategy.amp_configs.dtype
        if str(convert_dtype(amp_dtype)) == "float16":
            if strategy.amp_configs.use_dynamic_loss_scaling:
                scaler = GradScaler(
                    init_loss_scaling=strategy.amp_configs
                    .init_loss_scaling)
            else:
                # static scaling (ref: decorator.py use_dynamic_loss_
                # scaling=False): constant scale, still skip-on-inf
                scaler = GradScaler(
                    init_loss_scaling=strategy.amp_configs
                    .init_loss_scaling,
                    incr_ratio=1.0, decr_ratio=1.0)

    zero_stage = strategy.sharding_configs.stage if strategy.sharding else 0
    step = _ComposedTrainStep(
        model, optimizer, loss_fn, mesh, batch_spec=batch_spec,
        param_rule=param_rule, seed=seed,
        remat_policy=model_call,
        grad_accum_steps=k_steps,
        grad_accum_avg=strategy.gradient_merge_configs.avg,
        localsgd_k=local_k, zero_stage=zero_stage,
        amp_dtype=amp_dtype, scaler=scaler)
    return step


class _ComposedTrainStep(ShardedTrainStep):
    """ShardedTrainStep with remat / grad-merge / localsgd composition."""

    def __init__(self, model, optimizer, loss_fn, mesh, batch_spec=P("dp"),
                 param_rule=None, seed: int = 0, remat_policy=None,
                 grad_accum_steps: int = 1, grad_accum_avg: bool = True,
                 localsgd_k: int = 1, zero_stage: int = 0,
                 extra_metrics=None, amp_dtype=None, scaler=None) -> None:
        self.remat_policy = remat_policy
        self.grad_accum_steps = grad_accum_steps
        self.grad_accum_avg = grad_accum_avg
        self.localsgd_k = localsgd_k
        self.amp_dtype = amp_dtype
        self.scaler = scaler
        super().__init__(model, optimizer, loss_fn, mesh,
                         batch_spec=batch_spec, param_rule=param_rule,
                         seed=seed, extra_metrics=extra_metrics,
                         zero_stage=zero_stage)

    def extra_state(self):
        if self.scaler is None:
            return {}
        st = self.scaler.init()
        return {"amp": (st, jax.tree.map(lambda _: P(), st))}

    def _loss_and_buffers(self, params, buffers, args, labels, key,
                          kwargs=None):
        import contextlib

        from ...core import random as _random
        kwargs = kwargs or {}

        def run(p, *xs):
            ctx = contextlib.nullcontext()
            if self.amp_dtype is not None:
                from ...amp import auto_cast
                ctx = auto_cast(enable=True, dtype=self.amp_dtype)
            with ctx, _random.rng_scope(default=key, dropout=key):
                out, new_buffers = functional_call(
                    self.model, p, buffers, *xs, capture_buffers=True,
                    **kwargs)
            return self.loss_fn(out, *labels), (new_buffers, out)

        if self.remat_policy is not None:
            run = jax.checkpoint(run, policy=self.remat_policy)
        return run(params, *args)

    def _step(self, state, batch):
        params = state["params"]
        buffers = state["buffers"]
        rng, step_key = jax.random.split(state["rng"])
        args, labels = batch["args"], batch["labels"]
        kwargs = batch.get("kwargs", {})

        if self.grad_accum_steps > 1:
            # micro-batch scan (ref: gradient_merge_optimizer.py)
            k = self.grad_accum_steps

            def micro(i, carry):
                g_acc, loss_acc, bufs = carry
                m_args = tuple(_micro_slice(a, i, k) for a in args)
                m_labels = tuple(_micro_slice(l, i, k) for l in labels)
                # kwargs are where non-batch tensors ride (broadcast
                # masks, replicated tables): micro-slice only leaves
                # that share the batch-leading dim (taken from the
                # first arg, else the first label — kwargs-only models
                # still slice consistently), pass the rest whole to
                # every micro-step. Convention: a kwarg whose leading
                # dim EQUALS the batch size is treated as per-sample
                # data — a replicated table that coincides must be
                # reshaped (e.g. [1, N, ...]) by the caller.
                from ...parallel.spmd import leading_batch_size
                bsz = leading_batch_size(args, labels)
                m_kwargs = {
                    n: _micro_slice(v, i, k)
                    if (bsz is not None and hasattr(v, "shape")
                        and getattr(v, "ndim", 0) >= 1
                        and v.shape[0] == bsz) else v
                    for n, v in kwargs.items()}

                def lf(p):
                    loss, aux = self._loss_and_buffers(
                        p, bufs, m_args, m_labels,
                        jax.random.fold_in(step_key, i), m_kwargs)
                    if self.scaler is not None:
                        loss = self.scaler.scale(loss, state["amp"])
                    return loss, aux

                (loss, (new_bufs, _)), grads = jax.value_and_grad(
                    lf, has_aux=True)(params)
                g_acc = jax.tree.map(jnp.add, g_acc, grads)
                return (g_acc, loss_acc + loss, new_bufs)

            zero_g = jax.tree.map(jnp.zeros_like, params)
            grads, loss_sum, new_buffers = jax.lax.fori_loop(
                0, k, micro, (zero_g, jnp.zeros(()), buffers))
            scale = 1.0 / k if self.grad_accum_avg else 1.0
            grads = jax.tree.map(lambda g: g * scale, grads)
            loss = loss_sum / k
        else:
            def lf(p):
                loss, aux = self._loss_and_buffers(p, buffers, args,
                                                   labels, step_key,
                                                   kwargs)
                if self.scaler is not None:
                    loss = self.scaler.scale(loss, state["amp"])
                return loss, aux

            (loss, (new_buffers, _)), grads = jax.value_and_grad(
                lf, has_aux=True)(params)

        from ...amp import all_finite, select_update
        from ...static import probe_nonfinite
        extra = {}
        if self.scaler is not None:
            # unscale + finite check; on inf/nan skip the update and let
            # the scaler back off (ref: amp_check_finite_and_scale op +
            # update_loss_scaling, contrib/mixed_precision)
            grads, found_inf = self.scaler.unscale(grads, state["amp"])
            upd_params, upd_opt = self.optimizer.apply_gradients(
                params, grads, state["opt"], lr_override=batch.get("lr"))
            new_params = select_update(found_inf, upd_params, params)
            new_opt = select_update(found_inf, upd_opt, state["opt"])
            # a skipped step must not commit anything from the overflowed
            # forward — including BN running stats
            new_buffers = select_update(found_inf, new_buffers, buffers)
            extra["amp"] = self.scaler.update(state["amp"], found_inf)
            loss = loss / state["amp"]["scale"].astype(loss.dtype)
            probe_nonfinite(found_inf)
        else:
            new_params, new_opt = self.optimizer.apply_gradients(
                params, grads, state["opt"], lr_override=batch.get("lr"))
            if self._skip_guard:
                # bf16/fp32 runs get the skip-step guard alone
                found_inf = ~all_finite(grads)
                new_params = select_update(found_inf, new_params,
                                           params)
                new_opt = select_update(found_inf, new_opt,
                                        state["opt"])
                new_buffers = select_update(found_inf, new_buffers,
                                            buffers)
                probe_nonfinite(found_inf)

        return ({**state, "params": new_params, "buffers": new_buffers,
                 "opt": new_opt, "rng": rng, **extra}, {"loss": loss})


def _micro_slice(x, i, k):
    if not hasattr(x, "shape") or x.ndim == 0:
        return x
    micro = x.shape[0] // k
    return jax.lax.dynamic_slice_in_dim(x, i * micro, micro, axis=0)
