"""Multi-process launcher with failure watch + control-plane bootstrap.

TPU-native rebuild of the reference's launcher
(/root/reference/python/paddle/distributed/launch.py:193 launch —
spawns one process per device with PADDLE_TRAINER_ID/ENDPOINTS env;
utils.py:252 terminate_local_procs + the watch loop launch.py:219 that
tears the job down when any child dies). Differences by design:

- On TPU one process typically drives a whole host's chips, so `nproc`
  defaults to 1 per host; multi-process is for multi-host emulation and
  CPU-mesh tests.
- Rank 0's process environment hosts the native control-plane server
  (csrc/control_plane.cc) and its address rides PT_CP_ENDPOINT — children
  rendezvous through it (the reference exchanges ncclUniqueId through a
  bespoke gRPC server, c_gen_nccl_id_op.cc:49).
"""

from __future__ import annotations

import os
import signal
import subprocess
import sys
import time
from typing import Dict, List, Optional, Sequence

__all__ = ["launch_procs", "terminate_local_procs", "get_cluster_env"]


def get_cluster_env(rank: int, world: int, cp_endpoint: str) \
        -> Dict[str, str]:
    """Env block for one trainer process (reference names kept for
    drop-in parity + PT_* spellings)."""
    return {
        "PADDLE_TRAINER_ID": str(rank),
        "PADDLE_TRAINERS_NUM": str(world),
        "PT_TRAINER_ID": str(rank),
        "PT_TRAINERS_NUM": str(world),
        "PT_CP_ENDPOINT": cp_endpoint,
    }


def terminate_local_procs(procs: Sequence[subprocess.Popen],
                          grace_s: float = 5.0) -> None:
    """(ref: distributed/utils.py:252)."""
    for p in procs:
        if p.poll() is None:
            p.terminate()
    deadline = time.time() + grace_s
    for p in procs:
        while p.poll() is None and time.time() < deadline:
            time.sleep(0.1)
        if p.poll() is None:
            p.kill()


def launch_procs(cmd: Sequence[str], nproc: int,
                 env_extra: Optional[Dict[str, str]] = None,
                 start_control_plane: bool = True,
                 poll_interval: float = 0.5) -> int:
    """Spawn `nproc` copies of cmd with rank env; watch until all exit.

    Any child failing tears the whole job down (reference watch loop
    launch.py:219-226). Returns the first nonzero exit code, or 0.
    """
    server = None
    cp_endpoint = ""
    if start_control_plane:
        from .. import native
        server = native.ControlPlaneServer()
        cp_endpoint = f"127.0.0.1:{server.port}"
    procs: List[subprocess.Popen] = []
    try:
        for rank in range(nproc):
            env = dict(os.environ)
            env.update(get_cluster_env(rank, nproc, cp_endpoint))
            if env_extra:
                env.update(env_extra)
            procs.append(subprocess.Popen(list(cmd), env=env))
        exit_code = 0
        while True:
            states = [p.poll() for p in procs]
            if any(s not in (None, 0) for s in states):
                exit_code = next(s for s in states if s not in (None, 0))
                terminate_local_procs(procs)
                break
            if all(s == 0 for s in states):
                break
            time.sleep(poll_interval)
        return exit_code
    finally:
        terminate_local_procs(procs)
        if server is not None:
            server.stop()


def main(argv: Optional[Sequence[str]] = None) -> int:
    """CLI: python -m paddle_tpu.distributed.launch --nproc N script.py
    args... (ref: python -m paddle.distributed.launch)."""
    import argparse
    parser = argparse.ArgumentParser(prog="paddle_tpu.distributed.launch")
    parser.add_argument("--nproc", type=int, default=1)
    parser.add_argument("script")
    parser.add_argument("script_args", nargs=argparse.REMAINDER)
    args = parser.parse_args(argv)
    cmd = [sys.executable, args.script] + list(args.script_args)
    return launch_procs(cmd, args.nproc)


if __name__ == "__main__":
    sys.exit(main())
