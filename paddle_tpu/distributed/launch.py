"""Multi-process launcher with failure watch + control-plane bootstrap.

TPU-native rebuild of the reference's launcher
(/root/reference/python/paddle/distributed/launch.py:193 launch —
spawns one process per device with PADDLE_TRAINER_ID/ENDPOINTS env;
utils.py:252 terminate_local_procs + the watch loop launch.py:219 that
tears the job down when any child dies). Differences by design:

- On TPU one process typically drives a whole host's chips, so `nproc`
  defaults to 1 per host; multi-process is for multi-host emulation and
  CPU-mesh tests.
- Rank 0's process environment hosts the native control-plane server
  (csrc/control_plane.cc) and its address rides PT_CP_ENDPOINT — children
  rendezvous through it (the reference exchanges ncclUniqueId through a
  bespoke gRPC server, c_gen_nccl_id_op.cc:49).
"""

from __future__ import annotations

import os
import signal
import subprocess
import sys
import time
from typing import Dict, List, Optional, Sequence

__all__ = ["launch_procs", "launch_elastic", "terminate_local_procs",
           "get_cluster_env", "spawn"]


def get_cluster_env(rank: int, world: int, cp_endpoint: str) \
        -> Dict[str, str]:
    """Env block for one trainer process (reference names kept for
    drop-in parity + PT_* spellings)."""
    return {
        "PADDLE_TRAINER_ID": str(rank),
        "PADDLE_TRAINERS_NUM": str(world),
        "PT_TRAINER_ID": str(rank),
        "PT_TRAINERS_NUM": str(world),
        "PT_CP_ENDPOINT": cp_endpoint,
    }


def terminate_local_procs(procs: Sequence[subprocess.Popen],
                          grace_s: float = 5.0) -> None:
    """(ref: distributed/utils.py:252)."""
    for p in procs:
        if p.poll() is None:
            p.terminate()
    deadline = time.time() + grace_s
    for p in procs:
        while p.poll() is None and time.time() < deadline:
            time.sleep(0.1)
        if p.poll() is None:
            p.kill()


def launch_procs(cmd: Sequence[str], nproc: int,
                 env_extra: Optional[Dict[str, str]] = None,
                 start_control_plane: bool = True,
                 poll_interval: float = 0.5) -> int:
    """Spawn `nproc` copies of cmd with rank env; watch until all exit.

    Any child failing tears the whole job down (reference watch loop
    launch.py:219-226). Returns the first nonzero exit code, or 0.
    """
    server = None
    cp_endpoint = ""
    if start_control_plane:
        from .. import native
        server = native.ControlPlaneServer()
        cp_endpoint = f"127.0.0.1:{server.port}"
    procs: List[subprocess.Popen] = []
    try:
        for rank in range(nproc):
            env = dict(os.environ)
            env.update(get_cluster_env(rank, nproc, cp_endpoint))
            if env_extra:
                env.update(env_extra)
            procs.append(subprocess.Popen(list(cmd), env=env))
        exit_code = 0
        while True:
            states = [p.poll() for p in procs]
            if any(s not in (None, 0) for s in states):
                exit_code = next(s for s in states if s not in (None, 0))
                terminate_local_procs(procs)
                break
            if all(s == 0 for s in states):
                break
            time.sleep(poll_interval)
        return exit_code
    finally:
        terminate_local_procs(procs)
        if server is not None:
            server.stop()


def launch_elastic(cmd: Sequence[str], nproc: int,
                   max_restarts: int = 3,
                   env_extra: Optional[Dict[str, str]] = None,
                   poll_interval: float = 0.5) -> int:
    """Gang-restart orchestration: when any worker dies, the whole job
    is torn down (launch_procs's watch loop) and relaunched, up to
    ``max_restarts`` times. Training scripts resume from their last
    checkpoint via incubate.TrainEpochRange / io.AsyncCheckpointer.

    This is the half the reference never implemented — its watch loop
    only detects child exit and tears down
    (/root/reference/python/paddle/distributed/launch.py:219-226,
    utils.py:252 terminate_local_procs; DistributedStrategy.elastic is
    a stub, distributed_strategy.proto:105). Restart counter rides in
    PT_ELASTIC_ATTEMPT; each attempt gets a fresh control plane.

    Goodput accounting: the launcher counts restarts
    (``elastic_restarts_total``) and hands each relaunched gang the
    cumulative teardown-to-respawn dead time via ``PT_RESTART_IDLE_S``
    — the child's goodput ledger seeds its ``restart_idle`` bucket
    from it (plus its own import-to-resume time, anchored by
    PT_ELASTIC_ATTEMPT > 0), so /goodput on a restarted worker shows
    what the crash actually cost.
    """
    from ..observability import flight as _flight
    from ..observability import metrics as _metrics

    code = 0
    idle_s = 0.0
    for attempt in range(max_restarts + 1):
        env = dict(env_extra or {})
        env["PT_ELASTIC_ATTEMPT"] = str(attempt)
        env["PT_RESTART_IDLE_S"] = f"{idle_s:.3f}"
        code = launch_procs(cmd, nproc, env_extra=env,
                            poll_interval=poll_interval)
        if code == 0:
            return 0
        t_dead = time.time()
        _metrics.counter(
            "elastic_restarts_total",
            "gang restarts performed by launch_elastic after a worker "
            "failure", always=True).inc()
        _flight.record("elastic_restart", force=True, attempt=attempt,
                       exit_code=code)
        if attempt < max_restarts:
            print(f"[launch] job failed rc={code}; gang restart "
                  f"{attempt + 1}/{max_restarts}", file=sys.stderr,
                  flush=True)
        # respawn is immediate, so the measured gap is small — but the
        # mechanism is what matters: schedulers that add backoff (or a
        # slow control-plane re-bootstrap) surface here automatically
        idle_s += time.time() - t_dead
    return code


def main(argv: Optional[Sequence[str]] = None) -> int:
    """CLI: python -m paddle_tpu.distributed.launch --nproc N
    [--elastic R] script.py args...
    (ref: python -m paddle.distributed.launch)."""
    import argparse
    parser = argparse.ArgumentParser(prog="paddle_tpu.distributed.launch")
    parser.add_argument("--nproc", type=int, default=1)
    parser.add_argument("--elastic", type=int, default=0, metavar="R",
                        help="gang-restart the job up to R times on "
                             "worker failure (resume via checkpoints)")
    parser.add_argument("script")
    parser.add_argument("script_args", nargs=argparse.REMAINDER)
    args = parser.parse_args(argv)
    cmd = [sys.executable, args.script] + list(args.script_args)
    if args.elastic > 0:
        return launch_elastic(cmd, args.nproc,
                              max_restarts=args.elastic)
    return launch_procs(cmd, args.nproc)


def spawn(func, args=(), nprocs: int = 1, join: bool = True,
          timeout: Optional[float] = None):
    """Programmatic multi-process launcher
    (ref: python/paddle/distributed/spawn.py paddle.distributed.spawn —
    run ``func(*args)`` in ``nprocs`` processes with the cluster env
    set, the API equivalent of the ``launch`` CLI).

    ``func`` must be a module-level callable (pickled to workers). Each
    worker gets PT_TRAINER_ID/PT_TRAINERS_NUM/PT_CP_ENDPOINT exactly as
    the CLI would set them; call ``init_parallel_env()`` inside ``func``
    to join the job. With ``join`` (default) blocks until every worker
    exits — ``timeout`` bounds the TOTAL wall-clock — returns exit
    codes, terminating the gang and raising if any worker fails (a
    crashed rank must never deadlock the rest at a barrier). With
    ``join=False`` returns (processes, control_plane_server); the
    caller owns both.
    """
    import multiprocessing as mp

    from ..native import ControlPlaneServer

    ctx = mp.get_context("spawn")  # never fork a process holding jax
    server = None
    procs = []
    try:
        server = ControlPlaneServer()
        endpoint = f"127.0.0.1:{server.port}"
        for rank in range(nprocs):
            env = get_cluster_env(rank, nprocs, endpoint)
            p = ctx.Process(target=_spawn_entry,
                            args=(func, args, env), daemon=False)
            p.start()
            procs.append(p)
        if not join:
            out_procs, out_server = procs, server
            procs, server = [], None  # ownership transferred
            return out_procs, out_server
        # failure watch (launch_procs' poll-loop invariant): any dead
        # worker with a nonzero code tears the gang down immediately
        deadline = None if timeout is None else time.time() + timeout
        while True:
            codes = [p.exitcode for p in procs]
            if any(c not in (None, 0) for c in codes):
                bad = [(i, c) for i, c in enumerate(codes)
                       if c not in (None, 0)]
                raise RuntimeError(
                    f"spawn: workers failed (rank, code): {bad}")
            if all(c == 0 for c in codes):
                return codes
            if deadline is not None and time.time() > deadline:
                raise TimeoutError(
                    f"spawn: workers still running after {timeout}s")
            time.sleep(0.1)
    finally:
        for p in procs:
            if p.is_alive():
                p.terminate()
        if server is not None:
            server.stop()


def _spawn_entry(func, args, env) -> None:
    """Worker bootstrap: install the cluster env BEFORE anything reads
    it (module-level so the spawn context can pickle it)."""
    import os as _os
    _os.environ.update(env)
    func(*args)


if __name__ == "__main__":
    sys.exit(main())
