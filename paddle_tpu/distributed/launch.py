"""Multi-process launcher with failure watch + control-plane bootstrap.

TPU-native rebuild of the reference's launcher
(/root/reference/python/paddle/distributed/launch.py:193 launch —
spawns one process per device with PADDLE_TRAINER_ID/ENDPOINTS env;
utils.py:252 terminate_local_procs + the watch loop launch.py:219 that
tears the job down when any child dies). Differences by design:

- On TPU one process typically drives a whole host's chips, so `nproc`
  defaults to 1 per host; multi-process is for multi-host emulation and
  CPU-mesh tests.
- Rank 0's process environment hosts the native control-plane server
  (csrc/control_plane.cc) and its address rides PT_CP_ENDPOINT — children
  rendezvous through it (the reference exchanges ncclUniqueId through a
  bespoke gRPC server, c_gen_nccl_id_op.cc:49).
"""

from __future__ import annotations

import os
import random
import signal
import socket
import subprocess
import sys
import time
from collections import deque
from typing import Dict, List, Optional, Sequence

__all__ = ["launch_procs", "launch_elastic", "terminate_local_procs",
           "get_cluster_env", "fleet_observability_env",
           "classify_exit", "spawn"]


def classify_exit(code: int) -> str:
    """Exit-code triage for the restart policy: ``clean`` (0),
    ``preempt`` (died by SIGTERM — the scheduler's preemption signal,
    re-raised by preemption.guard after the graceful checkpoint), or
    ``crash`` (anything else). Accepts both Popen's negative-signal
    convention and the shell's 128+N."""
    if code == 0:
        return "clean"
    if code == -int(signal.SIGTERM) or code == 128 + int(signal.SIGTERM):
        return "preempt"
    return "crash"


def get_cluster_env(rank: int, world: int, cp_endpoint: str) \
        -> Dict[str, str]:
    """Env block for one trainer process (reference names kept for
    drop-in parity + PT_* spellings)."""
    return {
        "PADDLE_TRAINER_ID": str(rank),
        "PADDLE_TRAINERS_NUM": str(world),
        "PT_TRAINER_ID": str(rank),
        "PT_TRAINERS_NUM": str(world),
        "PT_CP_ENDPOINT": cp_endpoint,
    }


def fleet_observability_env(rank: int, env: Dict[str, str]
                            ) -> Dict[str, str]:
    """Per-worker observability wiring (docs/observability.md, "Fleet
    view"). With a positive FLAGS_metrics_port in the job env as the
    *base* port, every worker gets its own exporter port (base + rank
    — N workers on one host no longer collide on one bind) and the
    fleet-federation discovery env: PT_FLEET_AGGREGATOR points every
    worker at rank 0's exporter (the aggregator) and PT_FLEET_HOST
    names the worker in the merged view. The assigned port is both in
    the worker's env and in every snapshot it pushes (fleet.py
    local_snapshot), so /fleet/health lists where each worker serves.
    Base <= 0 (ephemeral/off) leaves everything untouched — federation
    then needs explicit fleet.start_reporter wiring."""
    try:
        base = int(env.get("FLAGS_metrics_port",
                           os.environ.get("FLAGS_metrics_port", "0")))
    except ValueError:
        return {}
    if base <= 0:
        return {}
    return {
        "FLAGS_metrics_port": str(base + rank),
        "PT_FLEET_AGGREGATOR": f"127.0.0.1:{base}",
        "PT_FLEET_HOST": f"{socket.gethostname()}:{rank}",
    }


class _WedgeWatch:
    """Launcher-side hang forensics (the elastic-launch heartbeat
    path of the hang doctor, observability/stacks.py).

    When the fleet wiring is active every worker serves /healthz on
    its assigned exporter port; the watch polls each live child every
    ``POLL_S`` seconds (0.5 s timeout — a wedged worker's exporter
    thread still answers while its step thread hangs) and, on the
    *transition* to wedged (heartbeat stale or a serving engine
    stalled), records a forced ``worker_wedged`` flight event in the
    launcher and sends the child SIGUSR2 — which makes the worker
    dump its own all-thread stacks into its flight file
    (stacks.install_signal_dump). One poke per wedge episode; a
    worker that recovers re-arms."""

    POLL_S = 5.0

    def __init__(self, ports: Dict[int, int]) -> None:
        self.ports = ports
        self._last_mono: Optional[float] = None
        self._wedged: Dict[int, bool] = {}

    @staticmethod
    def _wedged_payload(body: bytes) -> bool:
        import json
        try:
            h = json.loads(body)
        except ValueError:
            return False
        serving = h.get("serving") or {}
        return bool(h.get("wedged")
                    or any(e.get("stalled")
                           for e in serving.get("engines", [])))

    def poll(self, procs: Sequence[subprocess.Popen]) -> None:
        if not self.ports:
            return
        now = time.monotonic()
        if self._last_mono is not None \
                and now - self._last_mono < self.POLL_S:
            return
        self._last_mono = now
        import urllib.error
        import urllib.request
        for rank, port in self.ports.items():
            if rank >= len(procs) or procs[rank].poll() is not None:
                continue
            wedged = False
            try:
                with urllib.request.urlopen(
                        f"http://127.0.0.1:{port}/healthz",
                        timeout=0.5) as r:
                    r.read()
            except urllib.error.HTTPError as e:
                wedged = e.code == 503 and self._wedged_payload(
                    e.read())
            # ptlint: disable=silent-failure -- worker still booting or exporter off; liveness is the exit-code watch's job
            except Exception:  # noqa: BLE001
                continue
            if wedged and not self._wedged.get(rank):
                from ..observability import flight as _flight
                _flight.record("worker_wedged", force=True, rank=rank,
                               port=port, action="SIGUSR2")
                try:
                    os.kill(procs[rank].pid, signal.SIGUSR2)
                # ptlint: disable=silent-failure -- raced the worker's death; the worker_wedged flight event above already records the episode and the exit watch owns dead children
                except OSError:
                    pass
            self._wedged[rank] = wedged


def terminate_local_procs(procs: Sequence[subprocess.Popen],
                          grace_s: float = 5.0) -> None:
    """(ref: distributed/utils.py:252)."""
    for p in procs:
        if p.poll() is None:
            p.terminate()
    deadline = time.time() + grace_s
    for p in procs:
        while p.poll() is None and time.time() < deadline:
            time.sleep(0.1)
        if p.poll() is None:
            p.kill()


def launch_procs(cmd: Sequence[str], nproc: int,
                 env_extra: Optional[Dict[str, str]] = None,
                 start_control_plane: bool = True,
                 poll_interval: float = 0.5) -> int:
    """Spawn `nproc` copies of cmd with rank env; watch until all exit.

    Any child failing tears the whole job down (reference watch loop
    launch.py:219-226). Returns the first nonzero exit code, or 0.
    """
    server = None
    cp_endpoint = ""
    if start_control_plane:
        from .. import native
        server = native.ControlPlaneServer()
        cp_endpoint = f"127.0.0.1:{server.port}"
    procs: List[subprocess.Popen] = []
    worker_ports: Dict[int, int] = {}
    try:
        for rank in range(nproc):
            env = dict(os.environ)
            env.update(get_cluster_env(rank, nproc, cp_endpoint))
            if env_extra:
                env.update(env_extra)
            # per-worker exporter port + fleet discovery (base+rank
            # scheme; no-op unless a positive base port is configured)
            fleet_env = fleet_observability_env(rank, env)
            env.update(fleet_env)
            if fleet_env:
                worker_ports[rank] = int(fleet_env["FLAGS_metrics_port"])
            procs.append(subprocess.Popen(list(cmd), env=env))
        exit_code = 0
        wedge_watch = _WedgeWatch(worker_ports)
        while True:
            states = [p.poll() for p in procs]
            if any(s not in (None, 0) for s in states):
                exit_code = next(s for s in states if s not in (None, 0))
                terminate_local_procs(procs)
                break
            if all(s == 0 for s in states):
                break
            wedge_watch.poll(procs)
            time.sleep(poll_interval)
        return exit_code
    finally:
        terminate_local_procs(procs)
        if server is not None:
            server.stop()


def launch_elastic(cmd: Sequence[str], nproc: int,
                   max_restarts: int = 3,
                   env_extra: Optional[Dict[str, str]] = None,
                   poll_interval: float = 0.5,
                   backoff_s: float = 0.0,
                   backoff_max_s: float = 30.0,
                   restart_budget: int = 0,
                   restart_window_s: float = 60.0,
                   start_control_plane: bool = True) -> int:
    """Gang-restart orchestration: when any worker dies, the whole job
    is torn down (launch_procs's watch loop) and relaunched, up to
    ``max_restarts`` times. Training scripts resume from their last
    checkpoint via incubate.TrainEpochRange / io.AsyncCheckpointer /
    hapi.Model.fit(ckpt_dir=).

    This is the half the reference never implemented — its watch loop
    only detects child exit and tears down
    (/root/reference/python/paddle/distributed/launch.py:219-226,
    utils.py:252 terminate_local_procs; DistributedStrategy.elastic is
    a stub, distributed_strategy.proto:105). Restart counter rides in
    PT_ELASTIC_ATTEMPT; each attempt gets a fresh control plane.

    Restart policy (docs/fault_tolerance.md): exits are classified by
    :func:`classify_exit`. A *preemption* (SIGTERM death — the worker
    already checkpointed via preemption.guard) respawns immediately and
    never burns the failure budget. A *crash* backs off exponentially
    from ``backoff_s`` (doubling per consecutive crash, capped at
    ``backoff_max_s``, +0-25% jitter so gangs don't thunder) and is
    charged against the failure budget: more than ``restart_budget``
    crashes inside the sliding ``restart_window_s`` window aborts the
    job immediately (``elastic_budget_exhausted_total``) — a
    deterministic crash-loop fails fast instead of burning
    ``max_restarts`` on one bad step. ``restart_budget=0`` disables the
    budget; ``backoff_s=0`` disables backoff.

    Goodput accounting: the launcher counts restarts
    (``elastic_restarts_total``, labeled by exit kind) and hands each
    relaunched gang the cumulative teardown-to-respawn dead time
    (backoff included) via ``PT_RESTART_IDLE_S`` — the child's goodput
    ledger seeds its ``restart_idle`` bucket from it (plus its own
    import-to-resume time, anchored by PT_ELASTIC_ATTEMPT > 0), so
    /goodput on a restarted worker shows what the crash actually cost.
    """
    from ..observability import flight as _flight
    from ..observability import metrics as _metrics

    attempt = 0
    idle_s = 0.0
    consecutive_crashes = 0
    crash_times: deque = deque()
    while True:
        env = dict(env_extra or {})
        env["PT_ELASTIC_ATTEMPT"] = str(attempt)
        env["PT_RESTART_IDLE_S"] = f"{idle_s:.3f}"
        code = launch_procs(cmd, nproc, env_extra=env,
                            poll_interval=poll_interval,
                            start_control_plane=start_control_plane)
        if code == 0:
            return 0
        t_dead = time.monotonic()
        kind = classify_exit(code)
        _metrics.counter(
            "elastic_restarts_total",
            "gang restarts performed by launch_elastic after a worker "
            "failure (kind: preempt | crash)", always=True).inc(kind=kind)
        _flight.record("elastic_restart", force=True, attempt=attempt,
                       exit_code=code, exit_kind=kind)
        if attempt >= max_restarts:
            return code
        if kind == "crash":
            now = time.monotonic()
            crash_times.append(now)
            while crash_times and now - crash_times[0] > restart_window_s:
                crash_times.popleft()
            if restart_budget > 0 and len(crash_times) > restart_budget:
                _metrics.counter(
                    "elastic_budget_exhausted_total",
                    "jobs aborted by launch_elastic's sliding-window "
                    "failure budget (crash-loop fail-fast)",
                    always=True).inc()
                _flight.record("elastic_budget_exhausted", force=True,
                               crashes=len(crash_times),
                               window_s=restart_window_s)
                print(f"[launch] {len(crash_times)} crashes within "
                      f"{restart_window_s:.0f}s exceed the restart "
                      f"budget ({restart_budget}); giving up rc={code}",
                      file=sys.stderr, flush=True)
                return code
            consecutive_crashes += 1
            if backoff_s > 0:
                delay = min(backoff_max_s,
                            backoff_s * 2 ** (consecutive_crashes - 1))
                delay *= 1.0 + random.uniform(0.0, 0.25)
                time.sleep(delay)
        else:  # preemption: the worker already checkpointed — respawn
            consecutive_crashes = 0
        print(f"[launch] job {'preempted' if kind == 'preempt' else 'failed'}"
              f" rc={code}; gang restart {attempt + 1}/{max_restarts}",
              file=sys.stderr, flush=True)
        idle_s += time.monotonic() - t_dead
        attempt += 1


def main(argv: Optional[Sequence[str]] = None) -> int:
    """CLI: python -m paddle_tpu.distributed.launch --nproc N
    [--elastic R] script.py args...
    (ref: python -m paddle.distributed.launch)."""
    import argparse
    parser = argparse.ArgumentParser(prog="paddle_tpu.distributed.launch")
    parser.add_argument("--nproc", type=int, default=1)
    parser.add_argument("--elastic", type=int, default=0, metavar="R",
                        help="gang-restart the job up to R times on "
                             "worker failure (resume via checkpoints)")
    parser.add_argument("--backoff", type=float, default=0.0,
                        metavar="S",
                        help="initial crash-restart backoff in seconds "
                             "(doubles per consecutive crash, capped, "
                             "jittered; 0 = immediate respawn)")
    parser.add_argument("--restart-budget", type=int, default=0,
                        metavar="R",
                        help="abort when more than R crash-restarts "
                             "fall inside the sliding window "
                             "(0 = no budget)")
    parser.add_argument("--restart-window", type=float, default=60.0,
                        metavar="S",
                        help="sliding window (seconds) for "
                             "--restart-budget")
    parser.add_argument("script")
    parser.add_argument("script_args", nargs=argparse.REMAINDER)
    args = parser.parse_args(argv)
    cmd = [sys.executable, args.script] + list(args.script_args)
    if args.elastic > 0:
        return launch_elastic(cmd, args.nproc,
                              max_restarts=args.elastic,
                              backoff_s=args.backoff,
                              restart_budget=args.restart_budget,
                              restart_window_s=args.restart_window)
    return launch_procs(cmd, args.nproc)


def spawn(func, args=(), nprocs: int = 1, join: bool = True,
          timeout: Optional[float] = None):
    """Programmatic multi-process launcher
    (ref: python/paddle/distributed/spawn.py paddle.distributed.spawn —
    run ``func(*args)`` in ``nprocs`` processes with the cluster env
    set, the API equivalent of the ``launch`` CLI).

    ``func`` must be a module-level callable (pickled to workers). Each
    worker gets PT_TRAINER_ID/PT_TRAINERS_NUM/PT_CP_ENDPOINT exactly as
    the CLI would set them; call ``init_parallel_env()`` inside ``func``
    to join the job. With ``join`` (default) blocks until every worker
    exits — ``timeout`` bounds the TOTAL wall-clock — returns exit
    codes, terminating the gang and raising if any worker fails (a
    crashed rank must never deadlock the rest at a barrier). With
    ``join=False`` returns (processes, control_plane_server); the
    caller owns both.
    """
    import multiprocessing as mp

    from ..native import ControlPlaneServer

    ctx = mp.get_context("spawn")  # never fork a process holding jax
    server = None
    procs = []
    try:
        server = ControlPlaneServer()
        endpoint = f"127.0.0.1:{server.port}"
        for rank in range(nprocs):
            env = get_cluster_env(rank, nprocs, endpoint)
            env.update(fleet_observability_env(rank, env))
            p = ctx.Process(target=_spawn_entry,
                            args=(func, args, env), daemon=False)
            p.start()
            procs.append(p)
        if not join:
            out_procs, out_server = procs, server
            procs, server = [], None  # ownership transferred
            return out_procs, out_server
        # failure watch (launch_procs' poll-loop invariant): any dead
        # worker with a nonzero code tears the gang down immediately
        deadline = None if timeout is None else time.time() + timeout
        while True:
            codes = [p.exitcode for p in procs]
            if any(c not in (None, 0) for c in codes):
                bad = [(i, c) for i, c in enumerate(codes)
                       if c not in (None, 0)]
                raise RuntimeError(
                    f"spawn: workers failed (rank, code): {bad}")
            if all(c == 0 for c in codes):
                return codes
            if deadline is not None and time.time() > deadline:
                raise TimeoutError(
                    f"spawn: workers still running after {timeout}s")
            time.sleep(0.1)
    finally:
        # terminate AND join: terminate() alone leaves zombies (the
        # exit status is never reaped) — mirror terminate_local_procs'
        # bounded grace period, escalating to SIGKILL
        for p in procs:
            if p.is_alive():
                p.terminate()
        grace_deadline = time.monotonic() + 5.0
        for p in procs:
            p.join(max(0.0, grace_deadline - time.monotonic()))
            if p.is_alive():
                p.kill()
                p.join(1.0)
        if server is not None:
            server.stop()


def _spawn_entry(func, args, env) -> None:
    """Worker bootstrap: install the cluster env BEFORE anything reads
    it (module-level so the spawn context can pickle it)."""
    import os as _os
    _os.environ.update(env)
    func(*args)


if __name__ == "__main__":
    sys.exit(main())
