"""Streaming metrics.

Analogue of /root/reference/python/paddle/metric/metrics.py (Metric base,
Accuracy, Precision, Recall, Auc) and the metric ops in
operators/metrics/ (accuracy_op.cc, auc_op.cc). Per-batch compute is pure
(ops/metrics_ops.py, jit-safe); accumulation is host-side Python state.
"""

from __future__ import annotations

from typing import Optional

import jax.numpy as jnp
import numpy as np

from ..ops import metrics_ops as M


class Metric:
    def reset(self) -> None:
        raise NotImplementedError

    def update(self, *args) -> None:
        raise NotImplementedError

    def accumulate(self):
        raise NotImplementedError

    def name(self) -> str:
        return type(self).__name__.lower()


class Accuracy(Metric):
    def __init__(self, topk=(1,)) -> None:
        self.topk = topk if isinstance(topk, (list, tuple)) else (topk,)
        self.reset()

    def reset(self) -> None:
        self.total = np.zeros(len(self.topk))
        self.count = np.zeros(len(self.topk))

    def compute(self, pred, label):
        return [M.accuracy(pred, label, k) for k in self.topk]

    def update(self, correct) -> None:
        batch = 1
        for i, c in enumerate(correct if isinstance(correct, (list, tuple))
                              else [correct]):
            self.total[i] += float(c)
            self.count[i] += batch

    def accumulate(self):
        acc = self.total / np.maximum(self.count, 1)
        return acc[0] if len(self.topk) == 1 else list(acc)


class Precision(Metric):
    def __init__(self) -> None:
        self.reset()

    def reset(self) -> None:
        self.tp = 0.0
        self.fp = 0.0

    def update(self, preds, labels) -> None:
        p = (np.asarray(preds) > 0.5).astype(np.int32).reshape(-1)
        l = np.asarray(labels).astype(np.int32).reshape(-1)
        self.tp += float(np.sum((p == 1) & (l == 1)))
        self.fp += float(np.sum((p == 1) & (l == 0)))

    def accumulate(self) -> float:
        denom = self.tp + self.fp
        return self.tp / denom if denom > 0 else 0.0


class Recall(Metric):
    def __init__(self) -> None:
        self.reset()

    def reset(self) -> None:
        self.tp = 0.0
        self.fn = 0.0

    def update(self, preds, labels) -> None:
        p = (np.asarray(preds) > 0.5).astype(np.int32).reshape(-1)
        l = np.asarray(labels).astype(np.int32).reshape(-1)
        self.tp += float(np.sum((p == 1) & (l == 1)))
        self.fn += float(np.sum((p == 0) & (l == 1)))

    def accumulate(self) -> float:
        denom = self.tp + self.fn
        return self.tp / denom if denom > 0 else 0.0


class Auc(Metric):
    """(ref: auc_op.cc streaming histogram AUC)."""

    def __init__(self, num_thresholds: int = 2048) -> None:
        self.num_thresholds = num_thresholds
        self.reset()

    def reset(self) -> None:
        self.tp_buckets = np.zeros(self.num_thresholds)
        self.fp_buckets = np.zeros(self.num_thresholds)

    def update(self, preds, labels) -> None:
        preds = jnp.asarray(preds)
        pred_pos = preds[:, 1] if preds.ndim == 2 and preds.shape[1] == 2 \
            else preds.reshape(-1)
        tp, fp = M.auc_stats(pred_pos, jnp.asarray(labels),
                             self.num_thresholds)
        self.tp_buckets += np.asarray(tp)
        self.fp_buckets += np.asarray(fp)

    def accumulate(self) -> float:
        return float(M.auc_from_stats(jnp.asarray(self.tp_buckets),
                                      jnp.asarray(self.fp_buckets)))


def accuracy(input, label, k: int = 1):
    return M.accuracy(input, label, k)
