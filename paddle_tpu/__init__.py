"""paddle_tpu — a TPU-native deep learning framework.

A from-scratch rebuild of the capabilities of PaddlePaddle Fluid
(/root/reference — see SURVEY.md) designed for TPU: jax/XLA is the compiler
and executor, Pallas provides custom kernels for hot fused ops, pjit/
shard_map over device meshes provide the distributed runtime, and C++
components back the data pipeline and the distributed control plane.

Top-level namespace mirrors the reference's 2.0 API surface (paddle.*):
tensor ops at the root, ``nn`` layers, ``optimizer``, ``static``
(Program/Executor), ``distributed``/``fleet``, ``amp``, ``io``, ``metric``.
"""

from . import errors, flags, sysconfig, version
from .flags import get_flags, set_flags
from .version import __version__

# NOTE: nothing in this module may touch a JAX backend (jax.devices/
# jax.default_backend/key creation) at import time — a slow or contended
# accelerator plugin would hang `import paddle_tpu`. Backend decisions
# (incl. the fast TPU RngBitGenerator PRNG, FLAGS_use_fast_rng) are made
# lazily at first use — see core/random.py:_configure_fast_rng_once.

from .core import (CPUPlace, Place, TPUPlace, convert_dtype,
                   get_default_dtype, get_device, is_compiled_with_tpu, seed,
                   set_default_dtype, set_device)
from .core.place import CUDAPlace, device_count  # reference-parity alias
from .core.dtype import (bfloat16, bool_, complex64, complex128, float16,
                         float32, float64, int8, int16, int32, int64, uint8)

# Functional op surface at the root (paddle.add, paddle.matmul, ...)
from .ops import *  # noqa: F401,F403
from .ops import sparse
from .tensor import Tensor, to_tensor

from . import amp, data, datasets, distribution, hapi, inference, io, \
    jit, layers, metric, nets, nn, observability, optimizer, preemption, \
    reader, testing
from . import utils, vision  # noqa: F401
from . import parallel
from . import static
from .distributed import fleet  # noqa: F401
from . import distributed  # noqa: F401
from . import incubate  # noqa: F401
from . import slim  # noqa: F401
from . import fluid  # noqa: F401  (migration namespace; must be last)
from .param_attr import ParamAttr, WeightNormParamAttr  # noqa: F401

# grad / no_grad utilities (dygraph parity)
from .autograd import grad, no_grad, value_and_grad  # noqa: F401
from .reader import batch  # noqa: F401  (paddle.batch parity)
