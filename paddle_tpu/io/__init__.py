"""Checkpoint / serialization.

TPU-native redesign of the reference's save/load stack
(/root/reference/python/paddle/fluid/io.py save/load_persistables :598,
save_inference_model :52-57; C++ framework/save_load_util.cc tensor file
format; dygraph/checkpoint.py state-dict save). Format here is a directory:

  checkpoint/
    manifest.json        — names, shapes, dtypes, tree structure, step
    data/<name>.npy      — one npy per leaf (host-sharded in multi-host)

This keeps the reference's "inspectable per-variable files" property while
being pytree-native. Async save (orbax-style) runs serialization on a
background thread so the train loop isn't blocked — the reference's save is
fully synchronous. Orbax itself is supported as an opt-in backend.
"""

from __future__ import annotations

import contextlib
import json
import os
import shutil
import threading
from typing import Any, Dict, Optional

import jax
import numpy as np


def _ckpt_measure():
    """Goodput-ledger context for the HOST-BLOCKING parts of a save
    (the async writer thread overlaps training and is not charged).
    A no-op context unless a fit is running with metrics on."""
    try:
        from ..observability import goodput as _goodput
        return _goodput.ledger().measure("checkpoint")
    except Exception:  # telemetry must never break a save
        return contextlib.nullcontext()

_SENTINEL_KEY = "__paddle_tpu_ckpt__"
_VERSION = 1


def _flatten(state) -> Dict[str, np.ndarray]:
    flat = {}
    leaves_with_path = jax.tree_util.tree_flatten_with_path(state)[0]
    for path, leaf in leaves_with_path:
        key = "/".join(_path_str(p) for p in path)
        if getattr(leaf, "is_deleted", None) and leaf.is_deleted():
            # the layer's arrays were donated to a jitted train step;
            # without this the user sees jax's bare "Array has been
            # deleted" with no hint at the fix
            raise ValueError(
                f"cannot save {key!r}: its buffer was donated to a "
                "train step (in-place HBM update). Call the step's "
                ".sync_to_model() first to write the trained values "
                "back into the layer, then save.")
        flat[key] = np.asarray(leaf)
    return flat


def _path_str(p) -> str:
    if hasattr(p, "key"):
        return str(p.key)
    if hasattr(p, "idx"):
        return str(p.idx)
    return str(p)


_BUILTIN_DTYPES = {
    "bool", "int8", "int16", "int32", "int64", "uint8", "uint16",
    "uint32", "uint64", "float16", "float32", "float64", "complex64",
    "complex128"}


def save(state: Any, path: str, step: Optional[int] = None,
         overwrite: bool = True) -> None:
    """Save a pytree (state dict, TrainStep.state, ...) to ``path``."""
    # a trailing separator would stage the tmp dir INSIDE the target,
    # which the overwrite rmtree then destroys mid-save
    path = os.path.normpath(path)
    tmp = path + ".tmp"
    if os.path.exists(tmp):
        shutil.rmtree(tmp)
    os.makedirs(os.path.join(tmp, "data"), exist_ok=True)
    treedef = jax.tree.structure(state)
    flat = _flatten(state)
    manifest = {
        _SENTINEL_KEY: _VERSION,
        "step": step,
        "treedef": str(treedef),
        "leaves": {k: {"shape": list(v.shape), "dtype": str(v.dtype)}
                   for k, v in flat.items()},
    }
    for k, v in flat.items():
        fname = k.replace("/", "__") + ".npy"
        arr = np.asarray(v)
        # numpy serializes ml_dtypes extension floats (bfloat16,
        # float8_*) as raw void records and np.load hands back 'V2'
        # garbage — store those as uintN bits and restore via the
        # manifest's dtype string. Strings/objects keep plain np.save.
        if (arr.dtype.kind in "Vf"
                and str(arr.dtype) not in _BUILTIN_DTYPES):
            arr = arr.view(np.dtype(f"u{arr.dtype.itemsize}"))
        np.save(os.path.join(tmp, "data", fname), arr)
    with open(os.path.join(tmp, "manifest.json"), "w") as f:
        json.dump(manifest, f, indent=1)
    if os.path.exists(path):
        if not overwrite:
            raise FileExistsError(path)
        shutil.rmtree(path)
    os.replace(tmp, path)


def load(path: str, target: Optional[Any] = None) -> Any:
    path = os.path.normpath(path)
    """Load a checkpoint. With ``target`` (a pytree of the same structure),
    leaves are restored into that structure; otherwise returns a flat
    name→array dict."""
    with open(os.path.join(path, "manifest.json")) as f:
        manifest = json.load(f)
    if manifest.get(_SENTINEL_KEY) != _VERSION:
        raise ValueError(f"{path} is not a paddle_tpu checkpoint")
    flat = {}
    for k, meta in manifest["leaves"].items():
        fname = k.replace("/", "__") + ".npy"
        arr = np.load(os.path.join(path, "data", fname))
        want = meta.get("dtype") if isinstance(meta, dict) else None
        if want and str(arr.dtype) != want:
            if want not in _BUILTIN_DTYPES:
                import ml_dtypes
                arr = arr.view(getattr(ml_dtypes, want))
            elif arr.dtype.kind == "V":  # legacy bf16-as-void files
                import ml_dtypes
                arr = arr.view(ml_dtypes.bfloat16).astype(want)
        flat[k] = arr
    if target is None:
        return flat
    leaves_with_path = jax.tree_util.tree_flatten_with_path(target)[0]
    treedef = jax.tree.structure(target)
    new_leaves = []
    for path_elems, leaf in leaves_with_path:
        key = "/".join(_path_str(p) for p in path_elems)
        if key in flat:
            new_leaves.append(jax.numpy.asarray(flat[key]))
        else:
            new_leaves.append(leaf)
    return jax.tree.unflatten(treedef, new_leaves)


def load_step(path: str) -> Optional[int]:
    with open(os.path.join(path, "manifest.json")) as f:
        return json.load(f).get("step")


class AsyncCheckpointer:
    """Non-blocking save (ref capability: auto_checkpoint.py:71 —
    periodic job checkpointing; here additionally async)."""

    def __init__(self, directory: str, max_to_keep: int = 3) -> None:
        self.directory = directory
        self.max_to_keep = max_to_keep
        self._thread: Optional[threading.Thread] = None
        os.makedirs(directory, exist_ok=True)

    def save(self, state: Any, step: int) -> None:
        with _ckpt_measure():
            self.wait()
            # materialize on host before handing to the thread;
            # _flatten's donated-buffer guard (with its sync_to_model()
            # hint) runs too late for this path, so check here before
            # np.asarray can raise jax's bare "Array has been deleted"
            for path, leaf in \
                    jax.tree_util.tree_flatten_with_path(state)[0]:
                if getattr(leaf, "is_deleted", None) \
                        and leaf.is_deleted():
                    key = "/".join(_path_str(p) for p in path)
                    raise ValueError(
                        f"cannot checkpoint {key!r}: its buffer was "
                        "donated to a train step (in-place HBM "
                        "update). Call the step's .sync_to_model() "
                        "first, or checkpoint step.state directly.")
            host_state = jax.tree.map(np.asarray, state)

        def work():
            path = os.path.join(self.directory, f"ckpt-{step}")
            save(host_state, path, step=step)
            self._gc()

        self._thread = threading.Thread(target=work, daemon=True)
        self._thread.start()

    def wait(self) -> None:
        if self._thread is None:
            return
        with _ckpt_measure():
            self._thread.join()
            self._thread = None

    def _complete_steps(self) -> Dict[int, str]:
        """Only ckpt-<digits> entries count: a hard crash mid-save can
        strand ckpt-N.tmp staging dirs, which must never be parsed as
        checkpoints (they'd crash every elastic restart) or restored
        from (they're incomplete)."""
        out: Dict[int, str] = {}
        for d in os.listdir(self.directory):
            if not d.startswith("ckpt-"):
                continue
            suffix = d.split("-", 1)[1]
            if suffix.isdigit():
                out[int(suffix)] = d
            else:
                # stale staging leftover from a crashed save
                shutil.rmtree(os.path.join(self.directory, d),
                              ignore_errors=True)
        return out

    def _gc(self) -> None:
        steps = self._complete_steps()
        for s in sorted(steps)[:-self.max_to_keep]:
            shutil.rmtree(os.path.join(self.directory, steps[s]),
                          ignore_errors=True)

    def latest_step(self) -> Optional[int]:
        steps = self._complete_steps()
        return max(steps) if steps else None

    def restore(self, target: Any = None, step: Optional[int] = None):
        step = step if step is not None else self.latest_step()
        if step is None:
            return None
        return load(os.path.join(self.directory, f"ckpt-{step}"), target)


# reference-parity entry points -------------------------------------------

def save_dygraph(state_dict: Dict[str, Any], path: str) -> None:
    save(state_dict, path + ".pdparams")


def load_dygraph(path: str):
    return load(path + ".pdparams"), None


def save_inference_model(dirname: str, model, example_args,
                         params: Optional[Dict[str, Any]] = None) -> None:
    """Export a pruned serving artifact (ref: io.py save_inference_model:52
    — saves the feed/fetch-pruned ProgramDesc + persistables; here the
    pruned program is a serialized jax.export StableHLO module of the eval
    forward, via paddle_tpu.jit.save).
    """
    from ..nn.layer import Layer
    from .. import jit as jit_mod
    if isinstance(model, Layer):
        spec = [jit_mod.InputSpec(tuple(np.asarray(a).shape),
                                  str(np.asarray(a).dtype))
                for a in example_args]
        jit_mod.save(model, dirname, input_spec=spec)
        return
    # non-Layer fallback: params-only blob for Python-side reload
    save(params or {}, os.path.join(dirname, "params"))
    meta = {"format": "paddle_tpu_inference", "version": _VERSION}
    with open(os.path.join(dirname, "inference.json"), "w") as f:
        json.dump(meta, f)


def load_inference_model(dirname: str, model=None):
    from .. import jit as jit_mod
    if os.path.exists(os.path.join(dirname, "module.bin")):
        translated = jit_mod.load(dirname)
        if model is not None:
            model.set_state_dict(
                {k.replace("/", "."): v
                 for k, v in translated._params.items()}, strict=False)
            return model
        return translated
    params = load(os.path.join(dirname, "params"))
    if model is not None:
        model.set_state_dict({k.replace("/", "."): v
                              for k, v in params.items()}, strict=False)
        return model
    return params


def _array_like(v) -> bool:
    return hasattr(v, "shape") and hasattr(v, "dtype")


def save_persistables(executor, dirname: str, main_program=None,
                      filename: Optional[str] = None) -> None:
    """Save every persistable variable reachable from the executor's
    scope (ref: io.py save_persistables:491). In this design the scope
    IS the persistent state — parameters, optimizer slots, stats — so
    the snapshot covers exactly what the reference's persistable flag
    selects. ``main_program``/``filename`` are accepted for signature
    parity (the directory format already stores one manifest + one file
    per leaf)."""
    # walk the scope chain parents-first so child bindings shadow —
    # find_var resolves through parents, and so must the snapshot
    chain = []
    sc = executor.scope
    while sc is not None:
        chain.append(sc)
        sc = sc._parent
    state: Dict[str, Any] = {}
    for sc in reversed(chain):
        for k, v in sc.as_dict().items():
            if _array_like(v):
                state[k] = v
    if not state:
        raise ValueError(
            "save_persistables: no array variables reachable from the "
            "executor's scope — nothing to checkpoint")
    save(state, dirname)


def save_params(executor, dirname: str, main_program=None,
                filename: Optional[str] = None) -> None:
    """Reference save_params (io.py:185) saves only Parameters; the
    scope design carries no parameter/persistable distinction, so this
    is the same snapshot as :func:`save_persistables` — reference code
    calling either gets a working checkpoint (the difference there is
    excluding optimizer state, which costs only disk here)."""
    save_persistables(executor, dirname, main_program, filename)


def load_persistables(executor, dirname: str, main_program=None,
                      filename: Optional[str] = None) -> None:
    """Restore a :func:`save_persistables` snapshot into the executor's
    scope (ref: io.py load_persistables:734)."""
    state = load(dirname)
    for k, v in state.items():
        executor.scope.set_var(k, v)


def load_params(executor, dirname: str, main_program=None,
                filename: Optional[str] = None) -> None:
    """Alias of :func:`load_persistables` (see :func:`save_params`)."""
    load_persistables(executor, dirname, main_program, filename)
