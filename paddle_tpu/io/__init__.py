"""Checkpoint / serialization.

TPU-native redesign of the reference's save/load stack
(/root/reference/python/paddle/fluid/io.py save/load_persistables :598,
save_inference_model :52-57; C++ framework/save_load_util.cc tensor file
format; dygraph/checkpoint.py state-dict save). Format here is a directory:

  checkpoint/
    manifest.json        — names, shapes, dtypes, per-leaf crc32+nbytes,
                           tree structure, step
    data/<name>.npy      — one npy per leaf (host-sharded in multi-host)
    COMMIT               — terminal marker, written LAST; carries the
                           manifest's own crc32

This keeps the reference's "inspectable per-variable files" property while
being pytree-native. Async save (orbax-style) runs serialization on a
background thread so the train loop isn't blocked — the reference's save is
fully synchronous. Orbax itself is supported as an opt-in backend.

Integrity (docs/fault_tolerance.md): a directory without COMMIT is an
unfinished save and is never restored; :func:`load` re-checks each
leaf's size and CRC32 before deserializing (opt-out:
FLAGS_checkpoint_verify); :func:`verify` validates a directory without
materializing any array; ``AsyncCheckpointer.restore`` falls back to
the newest *intact* checkpoint, counting skips in
``checkpoint_corrupt_total``.
"""

from __future__ import annotations

import contextlib
import io as _pyio
import json
import os
import shutil
import threading
import zlib
from typing import Any, Dict, List, Optional, Tuple

import jax
import numpy as np

try:  # chaos-injection hook (paddle_tpu.testing.faults, FLAGS_fault_spec)
    from ..testing import faults as _faults
except ImportError:  # pragma: no cover - partial installs
    _faults = None


def _ckpt_measure():
    """Goodput-ledger context for the HOST-BLOCKING parts of a save
    (the async writer thread overlaps training and is not charged).
    A no-op context unless a fit is running with metrics on."""
    try:
        from ..observability import goodput as _goodput
        return _goodput.ledger().measure("checkpoint")
    except Exception:  # telemetry must never break a save
        return contextlib.nullcontext()

_SENTINEL_KEY = "__paddle_tpu_ckpt__"
_VERSION = 3                    # v3 adds host_state + PRNG-key leaves
_SUPPORTED_VERSIONS = (1, 2, 3)  # v1 (pre-integrity) / v2 stay loadable
_COMMIT_NAME = "COMMIT"
_KEY_DTYPE_PREFIX = "prng_key:"  # manifest dtype marker for key arrays


class _KeyLeaf:
    """Host-side stand-in for a jax PRNG key array: the raw counter
    bits plus the impl name, so a key survives the host materialization
    + background-writer round trip and restores bit-exactly (exact
    resume needs the dropout stream, not just the weights)."""

    __slots__ = ("data", "impl")

    def __init__(self, data, impl: str) -> None:
        self.data = np.asarray(data)
        self.impl = str(impl)


def _is_key_array(x) -> bool:
    try:
        import jax.numpy as jnp
        return jnp.issubdtype(x.dtype, jax.dtypes.prng_key)
    except (AttributeError, TypeError):
        return False


def host_leaf(x):
    """Materialize one state leaf on host (np.asarray), keeping PRNG
    key arrays restorable via :class:`_KeyLeaf`."""
    if isinstance(x, _KeyLeaf):
        return x
    if _is_key_array(x):
        return _KeyLeaf(np.asarray(jax.random.key_data(x)),
                        jax.random.key_impl(x))
    return np.asarray(x)


def _verify_default() -> bool:
    try:
        from ..flags import GLOBAL_FLAGS
        return bool(GLOBAL_FLAGS.get("checkpoint_verify"))
    except Exception:  # flag registry unavailable (direct import)
        return True


def _note_corrupt(path: str, error: Any,
                  step: Optional[int] = None) -> None:
    """Count + flight-record a checkpoint skipped as corrupt or
    uncommitted. Telemetry must never break a restore."""
    try:
        from ..observability import flight as _flight
        from ..observability import metrics as _metrics
        _metrics.counter(
            "checkpoint_corrupt_total",
            "checkpoints skipped at restore time because they were "
            "corrupt or uncommitted (restore fell back to the newest "
            "intact one)", always=True).inc()
        _flight.record("checkpoint_corrupt", force=True, path=str(path),
                       step=step, error=str(error)[:300])
    # ptlint: disable=silent-failure -- this IS the telemetry helper for a checkpoint failure; it must never mask the original error path with its own
    except Exception:  # noqa: BLE001
        pass


def _note_save_failure(step: Optional[int], error: BaseException) -> None:
    try:
        from ..observability import flight as _flight
        from ..observability import metrics as _metrics
        _metrics.counter(
            "checkpoint_failures_total",
            "checkpoint saves that raised (background writer failures "
            "are re-raised at the next save()/wait())",
            always=True).inc()
        _flight.record("checkpoint_write_failed", force=True, step=step,
                       error=str(error)[:300])
    # ptlint: disable=silent-failure -- this IS the telemetry helper for a checkpoint failure; it must never mask the original error path with its own
    except Exception:  # noqa: BLE001
        pass


def _flatten(state) -> Dict[str, np.ndarray]:
    flat = {}
    leaves_with_path = jax.tree_util.tree_flatten_with_path(state)[0]
    for path, leaf in leaves_with_path:
        key = "/".join(_path_str(p) for p in path)
        if getattr(leaf, "is_deleted", None) and leaf.is_deleted():
            # the layer's arrays were donated to a jitted train step;
            # without this the user sees jax's bare "Array has been
            # deleted" with no hint at the fix
            raise ValueError(
                f"cannot save {key!r}: its buffer was donated to a "
                "train step (in-place HBM update). Call the step's "
                ".sync_to_model() first to write the trained values "
                "back into the layer, then save.")
        flat[key] = host_leaf(leaf)
    return flat


def _path_str(p) -> str:
    if hasattr(p, "key"):
        return str(p.key)
    if hasattr(p, "idx"):
        return str(p.idx)
    return str(p)


_BUILTIN_DTYPES = {
    "bool", "int8", "int16", "int32", "int64", "uint8", "uint16",
    "uint32", "uint64", "float16", "float32", "float64", "complex64",
    "complex128"}


def save(state: Any, path: str, step: Optional[int] = None,
         overwrite: bool = True,
         host_state: Optional[Dict[str, Any]] = None) -> None:
    """Save a pytree (state dict, TrainStep.state, ...) to ``path``.

    ``host_state`` (v3) is a JSON-serializable dict of host-side
    training position — data-loader batch offset, epoch, global step —
    stored in the manifest next to the array leaves, so a resume can
    re-enter the data stream exactly where the save left it
    (docs/fault_tolerance.md "Numerical faults & exact resume").
    PRNG key arrays are first-class leaves: their counter bits and impl
    name round-trip bit-exactly."""
    # a trailing separator would stage the tmp dir INSIDE the target,
    # which the overwrite rmtree then destroys mid-save
    path = os.path.normpath(path)
    tmp = path + ".tmp"
    if os.path.exists(tmp):
        shutil.rmtree(tmp)
    os.makedirs(os.path.join(tmp, "data"), exist_ok=True)
    treedef = jax.tree.structure(state)
    flat = _flatten(state)
    leaves: Dict[str, Dict[str, Any]] = {}
    for k, v in flat.items():
        fname = k.replace("/", "__") + ".npy"
        if isinstance(v, _KeyLeaf):
            # PRNG keys: the raw counter bits on disk, the impl in the
            # dtype string — load() wraps them back into a key array
            arr = v.data
            dtype_str = _KEY_DTYPE_PREFIX + v.impl
        else:
            arr = np.asarray(v)
            dtype_str = str(v.dtype)
        # numpy serializes ml_dtypes extension floats (bfloat16,
        # float8_*) as raw void records and np.load hands back 'V2'
        # garbage — store those as uintN bits and restore via the
        # manifest's dtype string. Strings/objects keep plain np.save.
        if (arr.dtype.kind in "Vf"
                and str(arr.dtype) not in _BUILTIN_DTYPES):
            arr = arr.view(np.dtype(f"u{arr.dtype.itemsize}"))
        if _faults is not None:
            _faults.hit("ckpt_write", step=step)
        # serialize to memory first so the recorded CRC covers exactly
        # the bytes that land on disk (one write, no read-back pass)
        buf = _pyio.BytesIO()
        np.save(buf, arr)
        raw = buf.getvalue()
        with open(os.path.join(tmp, "data", fname), "wb") as f:
            f.write(raw)
        leaves[k] = {"shape": list(arr.shape), "dtype": dtype_str,
                     "crc32": zlib.crc32(raw), "nbytes": len(raw)}
    manifest = {
        _SENTINEL_KEY: _VERSION,
        "step": step,
        "treedef": str(treedef),
        "leaves": leaves,
    }
    if host_state is not None:
        manifest["host_state"] = host_state
    mbytes = json.dumps(manifest, indent=1).encode()
    with open(os.path.join(tmp, "manifest.json"), "wb") as f:
        f.write(mbytes)
    # COMMIT is written LAST: a directory without it is an unfinished
    # save. The atomic os.replace below already guarantees that on
    # POSIX; the marker extends the guarantee to filesystems without
    # atomic rename (object-store mounts) and to readers that see the
    # tmp dir mid-write.
    with open(os.path.join(tmp, _COMMIT_NAME), "w") as f:
        json.dump({"manifest_crc32": zlib.crc32(mbytes), "step": step,
                   "n_leaves": len(leaves)}, f)
    if os.path.exists(path):
        if not overwrite:
            raise FileExistsError(path)
        shutil.rmtree(path)
    os.replace(tmp, path)


def _read_manifest(path: str) -> Dict[str, Any]:
    try:
        with open(os.path.join(path, "manifest.json")) as f:
            manifest = json.load(f)
    except json.JSONDecodeError as e:
        raise ValueError(
            f"checkpoint {path!r}: manifest.json is corrupt ({e}) — "
            f"run paddle_tpu.io.verify({path!r}) for a full report")
    if manifest.get(_SENTINEL_KEY) not in _SUPPORTED_VERSIONS:
        raise ValueError(f"{path} is not a paddle_tpu checkpoint")
    return manifest


def is_committed(path: str) -> bool:
    """Cheap intact check: manifest parses and, for v2+ checkpoints,
    the terminal COMMIT marker exists. No data files are touched."""
    path = os.path.normpath(path)
    try:
        manifest = _read_manifest(path)
    except (OSError, ValueError):
        return False
    if manifest.get(_SENTINEL_KEY, 0) >= 2:
        return os.path.exists(os.path.join(path, _COMMIT_NAME))
    return True


def load(path: str, target: Optional[Any] = None,
         verify_integrity: Optional[bool] = None) -> Any:
    """Load a checkpoint. With ``target`` (a pytree of the same structure),
    leaves are restored into that structure; otherwise returns a flat
    name→array dict.

    ``verify_integrity`` (default: FLAGS_checkpoint_verify) re-checks
    the COMMIT marker and each leaf's recorded CRC32 before
    deserializing; missing or size-mismatched leaf files always raise
    a descriptive ``ValueError`` (they cost nothing to detect)."""
    path = os.path.normpath(path)
    if verify_integrity is None:
        verify_integrity = _verify_default()
    manifest = _read_manifest(path)
    version = manifest.get(_SENTINEL_KEY, 0)
    if verify_integrity and version >= 2 \
            and not os.path.exists(os.path.join(path, _COMMIT_NAME)):
        raise ValueError(
            f"checkpoint {path!r}: missing its COMMIT marker — the "
            "save never completed; restore from an older checkpoint "
            f"(run paddle_tpu.io.verify({path!r}) for a full report)")
    flat = {}
    for k, meta in manifest["leaves"].items():
        fname = k.replace("/", "__") + ".npy"
        fpath = os.path.join(path, "data", fname)
        meta_d = meta if isinstance(meta, dict) else {}
        if not os.path.exists(fpath):
            raise ValueError(
                f"checkpoint {path!r}: leaf {k!r} is missing its data "
                f"file ({fname}) — run paddle_tpu.io.verify({path!r}) "
                "for a full report")
        nbytes = meta_d.get("nbytes")
        if nbytes is not None and os.path.getsize(fpath) != nbytes:
            raise ValueError(
                f"checkpoint {path!r}: leaf {k!r} is "
                f"{os.path.getsize(fpath)} bytes on disk but the "
                f"manifest records {nbytes} — truncated or corrupt; "
                f"run paddle_tpu.io.verify({path!r}) for a full report")
        crc = meta_d.get("crc32")
        if verify_integrity and crc is not None:
            with open(fpath, "rb") as f:
                raw = f.read()
            if zlib.crc32(raw) != crc:
                raise ValueError(
                    f"checkpoint {path!r}: leaf {k!r} fails its CRC32 "
                    "check — corrupt data file; run "
                    f"paddle_tpu.io.verify({path!r}) for a full report")
            arr = np.load(_pyio.BytesIO(raw))
        else:
            arr = np.load(fpath)
        want = meta_d.get("dtype")
        if want and want.startswith(_KEY_DTYPE_PREFIX):
            arr = jax.random.wrap_key_data(
                jax.numpy.asarray(arr),
                impl=want[len(_KEY_DTYPE_PREFIX):])
        elif want and str(arr.dtype) != want:
            if want not in _BUILTIN_DTYPES:
                import ml_dtypes
                arr = arr.view(getattr(ml_dtypes, want))
            elif arr.dtype.kind == "V":  # legacy bf16-as-void files
                import ml_dtypes
                arr = arr.view(ml_dtypes.bfloat16).astype(want)
        flat[k] = arr
    if target is None:
        return flat
    leaves_with_path = jax.tree_util.tree_flatten_with_path(target)[0]
    treedef = jax.tree.structure(target)
    new_leaves = []
    for path_elems, leaf in leaves_with_path:
        key = "/".join(_path_str(p) for p in path_elems)
        if key in flat:
            new_leaves.append(jax.numpy.asarray(flat[key]))
        else:
            new_leaves.append(leaf)
    return jax.tree.unflatten(treedef, new_leaves)


def load_step(path: str) -> Optional[int]:
    with open(os.path.join(path, "manifest.json")) as f:
        return json.load(f).get("step")


def load_host_state(path: str) -> Optional[Dict[str, Any]]:
    """The manifest's ``host_state`` section (v3), or None for
    pre-v3 checkpoints / saves without one. Reads only the manifest —
    no array data is touched."""
    with open(os.path.join(os.path.normpath(path),
                           "manifest.json")) as f:
        return json.load(f).get("host_state")


def verify(path: str) -> List[str]:
    """Validate a checkpoint directory WITHOUT deserializing arrays.

    Checks: manifest parses and carries the sentinel; v2+ directories
    have the COMMIT marker and the manifest matches the CRC recorded in
    it; every leaf's data file exists with the recorded size and CRC32
    (bytes are read for the CRC, never parsed into arrays). Returns a
    list of problem strings — empty means intact.
    """
    path = os.path.normpath(path)
    problems: List[str] = []
    try:
        manifest = _read_manifest(path)
    except FileNotFoundError:
        return [f"{path}: manifest.json missing"]
    except OSError as e:
        return [f"{path}: manifest.json unreadable ({e})"]
    except ValueError as e:
        return [str(e)]
    version = manifest.get(_SENTINEL_KEY, 0)
    if version >= 2:
        commit_path = os.path.join(path, _COMMIT_NAME)
        if not os.path.exists(commit_path):
            problems.append(
                f"{path}: COMMIT marker missing (unfinished save)")
        else:
            try:
                with open(commit_path) as f:
                    commit = json.load(f)
                with open(os.path.join(path, "manifest.json"),
                          "rb") as f:
                    mcrc = zlib.crc32(f.read())
                want = commit.get("manifest_crc32")
                if want is not None and want != mcrc:
                    problems.append(
                        f"{path}: manifest.json does not match the CRC "
                        "recorded in COMMIT")
            except (OSError, json.JSONDecodeError) as e:
                problems.append(f"{path}: COMMIT unreadable ({e})")
    for k, meta in manifest.get("leaves", {}).items():
        fname = k.replace("/", "__") + ".npy"
        fpath = os.path.join(path, "data", fname)
        meta_d = meta if isinstance(meta, dict) else {}
        if not os.path.exists(fpath):
            problems.append(f"leaf {k!r}: data file missing ({fname})")
            continue
        nbytes = meta_d.get("nbytes")
        if nbytes is not None and os.path.getsize(fpath) != nbytes:
            problems.append(
                f"leaf {k!r}: {os.path.getsize(fpath)} bytes on disk, "
                f"manifest records {nbytes}")
            continue
        crc = meta_d.get("crc32")
        if crc is not None:
            with open(fpath, "rb") as f:
                have = zlib.crc32(f.read())
            if have != crc:
                problems.append(f"leaf {k!r}: CRC32 mismatch")
    return problems


class AsyncCheckpointer:
    """Non-blocking save (ref capability: auto_checkpoint.py:71 —
    periodic job checkpointing; here additionally async)."""

    def __init__(self, directory: str, max_to_keep: int = 3) -> None:
        self.directory = directory
        self.max_to_keep = max_to_keep
        self._thread: Optional[threading.Thread] = None
        self._error: Optional[BaseException] = None
        os.makedirs(directory, exist_ok=True)

    def _raise_pending(self) -> None:
        """Surface a background-writer failure (satellite fix: an
        exception in the daemon writer thread used to vanish)."""
        if self._error is not None:
            err, self._error = self._error, None
            raise RuntimeError(
                f"background checkpoint save failed in {self.directory}:"
                f" {err!r} (re-raised at the next save()/wait())"
            ) from err

    def save(self, state: Any, step: int,
             host_state: Optional[Dict[str, Any]] = None) -> None:
        with _ckpt_measure():
            self.wait()
            # materialize on host before handing to the thread;
            # _flatten's donated-buffer guard (with its sync_to_model()
            # hint) runs too late for this path, so check here before
            # np.asarray can raise jax's bare "Array has been deleted"
            for path, leaf in \
                    jax.tree_util.tree_flatten_with_path(state)[0]:
                if getattr(leaf, "is_deleted", None) \
                        and leaf.is_deleted():
                    key = "/".join(_path_str(p) for p in path)
                    raise ValueError(
                        f"cannot checkpoint {key!r}: its buffer was "
                        "donated to a train step (in-place HBM "
                        "update). Call the step's .sync_to_model() "
                        "first, or checkpoint step.state directly.")
            host_tree = jax.tree.map(host_leaf, state)

        def work():
            path = os.path.join(self.directory, f"ckpt-{step}")
            try:
                save(host_tree, path, step=step, host_state=host_state)
                self._gc()
            except BaseException as e:  # noqa: BLE001 — captured, not lost
                self._error = e
                _note_save_failure(step, e)

        self._thread = threading.Thread(target=work, daemon=True)
        self._thread.start()

    def wait(self) -> None:
        if self._thread is None:
            self._raise_pending()
            return
        with _ckpt_measure():
            self._thread.join()
            self._thread = None
        self._raise_pending()

    def _complete_steps(self) -> Dict[int, str]:
        """Only ckpt-<digits> entries count: a hard crash mid-save can
        strand ckpt-N.tmp staging dirs, which must never be parsed as
        checkpoints (they'd crash every elastic restart) or restored
        from (they're incomplete)."""
        writing = self._thread is not None and self._thread.is_alive()
        out: Dict[int, str] = {}
        for d in os.listdir(self.directory):
            if not d.startswith("ckpt-"):
                continue
            suffix = d.split("-", 1)[1]
            if suffix.isdigit():
                out[int(suffix)] = d
            elif not writing:
                # stale staging leftover from a crashed save — but only
                # reap when no background save is in flight, or we would
                # delete the live .tmp dir out from under the writer
                shutil.rmtree(os.path.join(self.directory, d),
                              ignore_errors=True)
        return out

    def _gc(self) -> None:
        steps = self._complete_steps()
        for s in sorted(steps)[:-self.max_to_keep]:
            shutil.rmtree(os.path.join(self.directory, steps[s]),
                          ignore_errors=True)

    def intact_steps(self) -> List[int]:
        """Steps whose directories pass the cheap commit check
        (manifest parses + COMMIT marker for v2 saves), ascending."""
        return [s for s in sorted(self._complete_steps())
                if is_committed(os.path.join(self.directory,
                                             f"ckpt-{s}"))]

    def latest_step(self) -> Optional[int]:
        steps = self.intact_steps()
        return steps[-1] if steps else None

    def host_state(self, step: Optional[int] = None
                   ) -> Optional[Dict[str, Any]]:
        """host_state section of one checkpoint (default: newest
        committed); None when absent (pre-v3) or nothing committed."""
        step = step if step is not None else self.latest_step()
        if step is None:
            return None
        return load_host_state(os.path.join(self.directory,
                                            f"ckpt-{step}"))

    def verify(self, step: Optional[int] = None) -> List[str]:
        """Full integrity report for one checkpoint (default: newest
        committed) without loading arrays; see :func:`verify`."""
        step = step if step is not None else self.latest_step()
        if step is None:
            return [f"{self.directory}: no committed checkpoints"]
        return verify(os.path.join(self.directory, f"ckpt-{step}"))

    def restore_latest(self, target: Any = None
                       ) -> Tuple[Optional[Any], Optional[int]]:
        """Restore the newest INTACT checkpoint, skipping corrupt or
        uncommitted ones (each skip increments
        ``checkpoint_corrupt_total`` and records a flight event).
        Returns ``(state, step)`` or ``(None, None)`` when nothing
        intact exists."""
        for s in reversed(sorted(self._complete_steps())):
            path = os.path.join(self.directory, f"ckpt-{s}")
            try:
                if not is_committed(path):
                    raise ValueError(
                        f"checkpoint {path!r}: missing COMMIT marker "
                        "(unfinished save)")
                return load(path, target), s
            except (OSError, ValueError) as e:
                _note_corrupt(path, e, step=s)
                continue
        return None, None

    def restore(self, target: Any = None, step: Optional[int] = None):
        if step is not None:
            return load(os.path.join(self.directory, f"ckpt-{step}"),
                        target)
        state, _ = self.restore_latest(target)
        return state


# reference-parity entry points -------------------------------------------

def save_dygraph(state_dict: Dict[str, Any], path: str) -> None:
    save(state_dict, path + ".pdparams")


def load_dygraph(path: str):
    return load(path + ".pdparams"), None


def save_inference_model(dirname: str, model, example_args,
                         params: Optional[Dict[str, Any]] = None) -> None:
    """Export a pruned serving artifact (ref: io.py save_inference_model:52
    — saves the feed/fetch-pruned ProgramDesc + persistables; here the
    pruned program is a serialized jax.export StableHLO module of the eval
    forward, via paddle_tpu.jit.save).
    """
    from ..nn.layer import Layer
    from .. import jit as jit_mod
    if isinstance(model, Layer):
        spec = [jit_mod.InputSpec(tuple(np.asarray(a).shape),
                                  str(np.asarray(a).dtype))
                for a in example_args]
        jit_mod.save(model, dirname, input_spec=spec)
        return
    # non-Layer fallback: params-only blob for Python-side reload
    save(params or {}, os.path.join(dirname, "params"))
    meta = {"format": "paddle_tpu_inference", "version": _VERSION}
    with open(os.path.join(dirname, "inference.json"), "w") as f:
        json.dump(meta, f)


def load_inference_model(dirname: str, model=None):
    from .. import jit as jit_mod
    if os.path.exists(os.path.join(dirname, "module.bin")):
        translated = jit_mod.load(dirname)
        if model is not None:
            model.set_state_dict(
                {k.replace("/", "."): v
                 for k, v in translated._params.items()}, strict=False)
            return model
        return translated
    params = load(os.path.join(dirname, "params"))
    if model is not None:
        model.set_state_dict({k.replace("/", "."): v
                              for k, v in params.items()}, strict=False)
        return model
    return params


def _array_like(v) -> bool:
    return hasattr(v, "shape") and hasattr(v, "dtype")


def save_persistables(executor, dirname: str, main_program=None,
                      filename: Optional[str] = None) -> None:
    """Save every persistable variable reachable from the executor's
    scope (ref: io.py save_persistables:491). In this design the scope
    IS the persistent state — parameters, optimizer slots, stats — so
    the snapshot covers exactly what the reference's persistable flag
    selects. ``main_program``/``filename`` are accepted for signature
    parity (the directory format already stores one manifest + one file
    per leaf)."""
    # walk the scope chain parents-first so child bindings shadow —
    # find_var resolves through parents, and so must the snapshot
    chain = []
    sc = executor.scope
    while sc is not None:
        chain.append(sc)
        sc = sc._parent
    state: Dict[str, Any] = {}
    for sc in reversed(chain):
        for k, v in sc.as_dict().items():
            if _array_like(v):
                state[k] = v
    if not state:
        raise ValueError(
            "save_persistables: no array variables reachable from the "
            "executor's scope — nothing to checkpoint")
    save(state, dirname)


def save_params(executor, dirname: str, main_program=None,
                filename: Optional[str] = None) -> None:
    """Reference save_params (io.py:185) saves only Parameters; the
    scope design carries no parameter/persistable distinction, so this
    is the same snapshot as :func:`save_persistables` — reference code
    calling either gets a working checkpoint (the difference there is
    excluding optimizer state, which costs only disk here)."""
    save_persistables(executor, dirname, main_program, filename)


def load_persistables(executor, dirname: str, main_program=None,
                      filename: Optional[str] = None) -> None:
    """Restore a :func:`save_persistables` snapshot into the executor's
    scope (ref: io.py load_persistables:734)."""
    state = load(dirname)
    for k, v in state.items():
        executor.scope.set_var(k, v)


def load_params(executor, dirname: str, main_program=None,
                filename: Optional[str] = None) -> None:
    """Alias of :func:`load_persistables` (see :func:`save_params`)."""
    load_persistables(executor, dirname, main_program, filename)
