"""High-level Model API (Model.fit/evaluate/predict).

TPU-native analogue of /root/reference/python/paddle/incubate/hapi/model.py
(Model.fit :632, evaluate :1079, predict; callbacks in hapi/callbacks.py;
ProgBarLogger). The reference switches between static/dygraph adapters;
here there is one path — the jitted TrainStep/EvalStep — so fit() is a
thin loop: DataLoader → step → metrics/callbacks → checkpoints.
"""

from __future__ import annotations

import time
from typing import Callable, Dict, List, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from . import io as io_mod
from . import observability as _obs
from . import preemption as _preempt
from .flags import GLOBAL_FLAGS
from .testing import faults as _faults
from .metric import Metric
from .nn.layer import Layer
from .optimizer import Optimizer
from .static import EvalStep, TrainStep


class Callback:
    def on_train_begin(self, logs=None):
        pass

    def on_train_end(self, logs=None):
        pass

    def on_epoch_begin(self, epoch, logs=None):
        pass

    def on_epoch_end(self, epoch, logs=None):
        pass

    def on_batch_end(self, step, logs=None):
        pass


class ProgBarLogger(Callback):
    """(ref: hapi/callbacks.py ProgBarLogger)."""

    def __init__(self, log_freq: int = 10, verbose: int = 1) -> None:
        self.log_freq = log_freq
        self.verbose = verbose
        self._t0 = 0.0

    def on_epoch_begin(self, epoch, logs=None):
        self._t0 = time.perf_counter()
        self._epoch = epoch

    def on_batch_end(self, step, logs=None):
        if self.verbose and step % self.log_freq == 0:
            items = " ".join(f"{k}={float(v):.4f}"
                             for k, v in (logs or {}).items())
            print(f"[epoch {self._epoch} step {step}] {items}")

    def on_epoch_end(self, epoch, logs=None):
        if self.verbose:
            dt = time.perf_counter() - self._t0
            items = " ".join(f"{k}={float(v):.4f}"
                             for k, v in (logs or {}).items())
            print(f"[epoch {epoch} done in {dt:.1f}s] {items}")


class ModelCheckpoint(Callback):
    """(ref: hapi/callbacks.py ModelCheckpoint)."""

    def __init__(self, model: "Model", save_dir: str,
                 save_freq: int = 1) -> None:
        self.model = model
        self.save_dir = save_dir
        self.save_freq = save_freq

    def on_epoch_end(self, epoch, logs=None):
        if (epoch + 1) % self.save_freq == 0:
            self.model.save(f"{self.save_dir}/epoch-{epoch}")


class EarlyStopping(Callback):
    def __init__(self, monitor: str = "loss", patience: int = 3,
                 mode: str = "min") -> None:
        self.monitor = monitor
        self.patience = patience
        self.mode = mode
        self.best = None
        self.bad = 0
        self.stop_training = False

    def on_epoch_end(self, epoch, logs=None):
        val = float((logs or {}).get(self.monitor, np.nan))
        better = (self.best is None
                  or (self.mode == "min" and val < self.best)
                  or (self.mode == "max" and val > self.best))
        if better:
            self.best = val
            self.bad = 0
        else:
            self.bad += 1
            if self.bad >= self.patience:
                self.stop_training = True


class LRSchedulerCallback(Callback):
    """(ref: hapi/callbacks.py LRScheduler callback). Feeds the epoch
    metric to a host-driven scheduler (ReduceOnPlateau) — the compiled
    TrainStep picks the new LR up as a runtime input.

    In-graph schedulers need no callback: their lr_at(step) is compiled
    into the train step over the per-batch step counter (by design —
    SURVEY §7 'optimizer as ops in the program'), so host-side .step()
    would only desynchronize get_lr() from the LR actually applied.
    """

    def __init__(self, optimizer: Optimizer,
                 monitor: str = "loss") -> None:
        self.optimizer = optimizer
        self.monitor = monitor

    def on_epoch_end(self, epoch, logs=None):
        sched = getattr(self.optimizer, "learning_rate", None)
        if getattr(sched, "host_driven", False):
            val = (logs or {}).get(self.monitor)
            if val is not None:
                sched.step(float(val))


_CKPT_KEYS = ("params", "buffers", "opt")


def _ckpt_state_of(step) -> Optional[Dict]:
    """The checkpointable slice of a train step's state: the FULL
    training state — params, buffers, optimizer slots, the RNG key
    stream, and (under fp16 AMP) the GradScaler state. This is the
    checkpoint-v3 exact-resume contract: a SIGKILLed-then-resumed run
    continues the uninterrupted trajectory bit-for-bit (PRNG keys
    round-trip via io's prng_key leaves). A v2 checkpoint without the
    extra leaves still restores — the step keeps its fresh rng/scaler,
    which is the old approximate-resume behavior."""
    state = getattr(step, "state", None)
    if not isinstance(state, dict) \
            or not all(k in state for k in _CKPT_KEYS):
        return None
    return dict(state)


def _fit_host_state(global_step: int, epoch: int,
                    batch_in_epoch: int) -> Dict:
    """Manifest host_state section for fit checkpoints: where in the
    data stream the save landed, so a resume (or a human reading the
    manifest) can re-enter exactly there."""
    return {"global_step": int(global_step), "epoch": int(epoch),
            "batch_in_epoch": int(batch_in_epoch)}


def _parse_amp(amp):
    """``fit(amp=...)`` → ``(amp_dtype, GradScaler | None)``.

    fp16 gets the dynamic loss scaler (scale-up/scale-down +
    skip-on-inf compiled into the step); bf16 — the TPU-native low
    precision, same exponent range as fp32 — needs no scaling and gets
    the skip-step guard alone. A GradScaler instance implies fp16."""
    from . import amp as amp_mod
    if amp is None or amp is False:
        return None, None
    if isinstance(amp, amp_mod.GradScaler):
        return "float16", amp
    if amp is True:
        amp = "bfloat16"
    from .core.dtype import convert_dtype
    dtype = str(convert_dtype(amp))
    if dtype == "float16":
        return dtype, amp_mod.GradScaler()
    if dtype == "bfloat16":
        return dtype, None
    raise ValueError(
        "fit(amp=...) expects 'float16'/'bfloat16' (or a GradScaler "
        f"instance), got {amp!r}")


def _as_metric_list(metrics) -> List[Metric]:
    if metrics is None:
        return []
    if isinstance(metrics, Metric):  # single metric accepted like reference
        return [metrics]
    return list(metrics)


class Model:
    """(ref: hapi/model.py Model)."""

    def __init__(self, network: Layer, loss: Optional[Callable] = None,
                 optimizer: Optional[Optimizer] = None,
                 metrics: Optional[Sequence[Metric]] = None) -> None:
        self.network = network
        self._loss = loss
        self._optimizer = optimizer
        self._metrics = _as_metric_list(metrics)
        self._train_step: Optional[TrainStep] = None
        self._eval_step: Optional[EvalStep] = None
        self._fitting = False
        self._mesh = None
        self._mesh_kwargs: Dict = {}
        self._amp_dtype = None
        self._scaler = None

    def prepare(self, optimizer: Optional[Optimizer] = None,
                loss: Optional[Callable] = None,
                metrics: Optional[Sequence[Metric]] = None,
                mesh=None, **mesh_kwargs) -> "Model":
        """Configure training. With ``mesh=`` the same Model API trains
        distributed — fit() routes to a ShardedTrainStep over the mesh
        (the reference's "same Model, ParallelExecutor underneath":
        hapi/model.py adapters picking CompiledProgram.with_data_parallel).
        Extra kwargs (batch_spec, param_rule, zero_stage, dp_axis) pass
        through to ShardedTrainStep.
        """
        if optimizer is not None:
            self._optimizer = optimizer
        if loss is not None:
            self._loss = loss
        if metrics is not None:
            self._metrics = _as_metric_list(metrics)
        allowed = {"batch_spec", "param_rule", "zero_stage", "dp_axis",
                   "seed"}
        unknown = set(mesh_kwargs) - allowed
        if unknown or (mesh_kwargs and mesh is None):
            raise TypeError(
                f"prepare() got unexpected keyword arguments "
                f"{sorted(unknown or mesh_kwargs)}; mesh options "
                f"({sorted(allowed)}) require mesh=")
        if mesh is not None:
            self._mesh = mesh
            self._mesh_kwargs = dict(mesh_kwargs)
            self._train_step = None
        return self

    def _get_train_step(self) -> TrainStep:
        if self._train_step is None:
            loss_fn = self._loss
            if isinstance(loss_fn, Layer):
                fn = loss_fn

                def loss_call(out, *labels):
                    return fn(out, *labels)
            else:
                loss_call = loss_fn
            extra = {}
            for m in self._metrics:
                if hasattr(m, "compute") and hasattr(m, "topk"):
                    from .ops.metrics_ops import accuracy as acc_fn
                    extra["acc"] = (lambda out, *ls:
                                    acc_fn(out, ls[0]))
            if self._mesh is not None:
                from .parallel import ShardedTrainStep
                self._train_step = ShardedTrainStep(
                    self.network, self._optimizer, loss_call, self._mesh,
                    extra_metrics=extra, amp_dtype=self._amp_dtype,
                    scaler=self._scaler, **self._mesh_kwargs)
            else:
                self._train_step = TrainStep(
                    self.network, self._optimizer, loss_call,
                    extra_metrics=extra, amp_dtype=self._amp_dtype,
                    scaler=self._scaler)
        return self._train_step

    def train_batch(self, inputs, labels) -> Dict[str, float]:
        step = self._get_train_step()
        inputs = inputs if isinstance(inputs, (list, tuple)) else [inputs]
        labels = labels if isinstance(labels, (list, tuple)) else [labels]
        if not self._fitting:
            # standalone train_batch: the eager model is authoritative on
            # both sides of the call (user may have mutated weights). The
            # finally matters: the step donates (deletes) the model's own
            # arrays, so even on error the state must be pushed back.
            step.reset_from_model()
            try:
                metrics = step(*inputs, labels=tuple(labels))
            finally:
                step.sync_to_model()
        else:
            metrics = step(*inputs, labels=tuple(labels))
        return {k: float(v) for k, v in metrics.items()}

    def fit(self, train_loader, eval_loader=None, epochs: int = 1,
            callbacks: Optional[List[Callback]] = None,
            verbose: int = 1, log_freq: int = 10,
            ckpt_dir: Optional[str] = None, save_steps: int = 0,
            ckpt_max_to_keep: int = 3,
            amp=None) -> Dict[str, List[float]]:
        """Train; returns per-epoch history {metric: [v_epoch0, ...]}.

        With ``ckpt_dir=`` fit becomes fault-tolerant at STEP
        granularity (docs/fault_tolerance.md): an ``io.AsyncCheckpointer``
        saves the FULL training state (params/buffers/optimizer plus
        the RNG stream and GradScaler state — checkpoint v3) every
        ``save_steps`` steps (plus once at the end), and a fresh fit
        over the same directory auto-resumes bit-exactly: the newest
        intact checkpoint is restored and the data stream re-entered at
        the saved offset (``DataLoader.iter_from``; loaders without a
        sampler are fast-forwarded by replay). SIGTERM (scheduler
        preemption) is caught by a preemption guard: the in-flight step
        finishes, a final synchronous checkpoint is forced at the
        preempted step, and the signal is re-raised so the process
        still dies with the SIGTERM wait status.

        ``amp='float16'`` compiles dynamic loss scaling
        (``amp.GradScaler``: scale-up after clean steps, back-off +
        skip on overflow) into the train step; ``amp='bfloat16'`` runs
        the forward under bf16 autocast with the skip-step guard alone.
        Non-finite gradients never poison the weights either way — the
        update is discarded in-graph and counted in
        ``nonfinite_steps_total`` (FLAGS_skip_nonfinite_steps).

        Divergence rollback: while metrics are on and ``ckpt_dir`` is
        set, a watchdog fed by the anomaly sentinel's loss probes rolls
        fit back to the newest intact checkpoint after
        FLAGS_divergence_streak consecutive NaN/spike loss samples — at
        most FLAGS_rollback_budget times, optionally rescaling the LR
        by FLAGS_rollback_lr_factor on each re-entry."""
        callbacks = list(callbacks or [])
        if amp is not None:
            from . import amp as amp_mod
            amp_dtype, scaler = _parse_amp(amp)
            changed = (amp_dtype != self._amp_dtype
                       or (scaler is None) != (self._scaler is None)
                       or (isinstance(amp, amp_mod.GradScaler)
                           and scaler is not self._scaler))
            if changed:
                # the compiled step bakes the AMP policy in — rebuild
                # (weights live in the network between fits; optimizer
                # slots restart unless a checkpoint restores them)
                self._amp_dtype, self._scaler = amp_dtype, scaler
                self._train_step = None
        if verbose:
            callbacks.append(ProgBarLogger(log_freq, verbose))
        if self._optimizer is not None and not any(
                isinstance(cb, LRSchedulerCallback) for cb in callbacks):
            if getattr(getattr(self._optimizer, "learning_rate", None),
                       "host_driven", False):
                callbacks.append(LRSchedulerCallback(self._optimizer))
        history: Dict[str, List[float]] = {}
        # persistent compile cache (env-set FLAGS_compile_cache_dir
        # never fires on_change — apply here, before the first trace)
        from . import sysconfig as _sysconfig
        _sysconfig.apply_compile_cache_flag()
        # live observability plane: flag-gated, idempotent, daemon thread
        _obs.server.maybe_start()
        ledger = _obs.goodput_ledger()
        if _obs.enabled():
            # goodput ledger + crash flight recorder cover the whole fit
            ledger.start()
            _obs.flight.install()
            _obs.flight.record("fit_begin", epochs=epochs)
        if self._train_step is not None:
            # weights may have been set_value'd/loaded since the last fit
            self._train_step.reset_from_model()
        # graceful preemption: SIGTERM only sets a flag here; the loop
        # finishes the current step, checkpoints, then re-raises
        guard = _preempt.guard()
        guard.__enter__()
        preempted = False
        watchdog = None
        self._fitting = True
        try:
            for cb in callbacks:
                cb.on_train_begin()
            step = self._get_train_step()
            ckptr = None
            resume_step = 0
            if ckpt_dir:
                target = _ckpt_state_of(step)
                if target is None:
                    raise ValueError(
                        "fit(ckpt_dir=...) needs a train step exposing "
                        "state{params, buffers, opt} (got "
                        f"{type(step).__name__})")
                ckptr = io_mod.AsyncCheckpointer(
                    ckpt_dir, max_to_keep=ckpt_max_to_keep)
                restored, at = ckptr.restore_latest(target=target)
                if restored is not None:
                    step.state.update(restored)
                    resume_step = int(at or 0)
                    _obs.flight.record("fit_resume", force=True,
                                       step=resume_step)
            straggler = None
            if _obs.enabled():
                mesh = getattr(step, "mesh", None)
                axis = getattr(step, "axis", "dp")
                if mesh is not None and axis in dict(mesh.shape) \
                        and mesh.shape[axis] > 1:
                    straggler = _obs.goodput.StragglerDetector(mesh, axis)
            watchdog = None
            if ckptr is not None and _obs.enabled() \
                    and int(GLOBAL_FLAGS.get("rollback_budget")) > 0:
                # divergence rollback: fed by the loss probes the
                # anomaly sentinel already streams out of the compiled
                # step — no extra sync, no extra probes
                watchdog = _obs.anomaly.DivergenceWatchdog().attach(
                    _obs.anomaly.sentinel())
            rollbacks = 0
            global_step = 0
            epoch = 0
            i = -1
            # while (not for): a divergence rollback rewinds `epoch`
            # and replays from the restored step
            while epoch < epochs:
                rollback = False
                for cb in callbacks:
                    cb.on_epoch_begin(epoch)
                # HOT LOOP: no host sync per step. Metrics stay device
                # arrays (callbacks that float() them sync only when they
                # do, e.g. ProgBarLogger every log_freq); the epoch mean is
                # fetched once at epoch end. The reference keeps Python out
                # of the loop entirely (hogwild_worker.cc:191) — here the
                # loop is Python but every iteration is one async XLA
                # dispatch.
                totals: Dict[str, jnp.ndarray] = {}
                count = 0
                logs: Dict[str, float] = {}
                obs_on = _obs.enabled()
                if obs_on:
                    step_hist = _obs.histogram(
                        "hapi_step_time_seconds",
                        "fit() per-step wall time (dispatch, not sync)")
                    tput_g = _obs.gauge(
                        "hapi_throughput_items_per_sec",
                        "items/s of the latest fit() step")
                    loss_g = _obs.gauge(
                        "hapi_loss",
                        "latest training loss (held as a device array; "
                        "synced only at snapshot time)")
                    mem_g = _obs.gauge(
                        "device_mem_bytes_in_use",
                        "per-device allocator true-peak watermark "
                        "(peak_bytes_in_use where the backend reports "
                        "it, else the bytes_in_use high-water mark)")
                    headroom_g = _obs.gauge(
                        "memory_headroom_bytes",
                        "per-device bytes_limit - bytes_in_use (absent "
                        "on backends without an allocator limit)")
                    hb_g = _obs.gauge(
                        _obs.server.HEARTBEAT_GAUGE,
                        "unix time of the latest completed fit() step "
                        "dispatch; /healthz flags staleness")
                    flops_g = _obs.gauge(
                        "achieved_flops_per_sec",
                        "XLA cost-model FLOPs of the compiled train "
                        "step divided by measured step wall time")
                    scale_g = _obs.gauge(
                        "amp_loss_scale",
                        "current GradScaler dynamic loss scale "
                        "(fp16 AMP; held as a device array, synced "
                        "only at snapshot time)") \
                        if "scaler" in getattr(step, "state", {}) \
                        else None
                batches = iter(train_loader)
                i = -1
                skip = resume_step - global_step
                if skip > 0 and hasattr(train_loader, "iter_from"):
                    # checkpointable sampler offset: re-enter the data
                    # stream at the saved batch index without fetching
                    # or collating the skipped batches (the loader
                    # still consumes its sampler, so a seeded shuffle
                    # replays the identical order)
                    try:
                        n_epoch = len(train_loader)
                    except TypeError:
                        n_epoch = None
                    if n_epoch:
                        take = min(skip, n_epoch)
                        batches = train_loader.iter_from(take)
                        global_step += take
                        i = take - 1
                while True:
                    if _faults.active() and global_step >= resume_step:
                        _faults.hit("loader", step=global_step)
                    if obs_on:
                        # goodput ledger: blocking on the pipeline is
                        # data_wait badput, split out from the step
                        t_wait = time.perf_counter()
                    try:
                        batch = next(batches)
                    except StopIteration:
                        break
                    if obs_on:
                        ledger.attribute("data_wait",
                                         time.perf_counter() - t_wait)
                    i += 1
                    *inputs, label = batch
                    if global_step < resume_step:
                        # auto-resume fast-forward: replay the data
                        # stream past the restored step without running
                        # compute, metrics, or callbacks
                        global_step += 1
                        continue
                    if _faults.active():
                        _faults.set_step_context(global_step)
                        _faults.hit("train_step", step=global_step)
                        _faults.hit("sigterm", step=global_step)
                    if obs_on:
                        compile_before = _obs.goodput.compile_seconds_total()
                        cache_before = _obs.goodput.compile_cache_stats()
                        t0 = time.perf_counter()
                    metrics = step(*inputs, labels=(label,))
                    if obs_on:
                        # host-side accounting only: the loss gauge keeps
                        # the device array (no sync), memory stats query
                        # the allocator, never the stream
                        dt = time.perf_counter() - t0
                        # a dispatch that traced spent its wall time in
                        # XLA, not the model: charge it to the compile
                        # bucket — cold, or cache_hit when the persistent
                        # cache (FLAGS_compile_cache_dir) served it
                        compile_dt = min(dt, max(
                            0.0,
                            _obs.goodput.compile_seconds_total()
                            - compile_before))
                        if compile_dt > 0:
                            ledger.attribute(
                                _obs.goodput.classify_compile_bucket(
                                    cache_before), compile_dt)
                        ledger.attribute("step_compute", dt - compile_dt)
                        _obs.flight.record("step", epoch=epoch, step=i)
                        if straggler is not None:
                            straggler.observe(global_step, dt)
                        step_hist.observe(dt)
                        items = int(np.shape(label)[0]) \
                            if np.ndim(label) else 1
                        tput_g.set(items / dt if dt > 0 else 0.0)
                        loss_g.set(metrics.get("loss"))
                        if scale_g is not None:
                            scale_g.set(step.state["scaler"]["scale"])
                        hb_g.set(time.time())
                        for dev, ms in _obs.device_memory_stats(
                                include_unavailable=True,
                                full=True).items():
                            mem_g.set_max(
                                ms["peak_bytes_in_use"]
                                or ms["bytes_in_use"], device=dev)
                            if ms["bytes_limit"]:
                                headroom_g.set(
                                    ms["bytes_limit"]
                                    - ms["bytes_in_use"], device=dev)
                        flops = _obs.xprof.flops_of(
                            getattr(step, "_span_name", ""))
                        if flops and dt > 0:
                            flops_g.set(flops / dt)
                    for k, v in metrics.items():
                        # running device-side sum: O(1) buffers, still one
                        # async dispatch per step (no host sync)
                        totals[k] = v if k not in totals else totals[k] + v
                    count += 1
                    for cb in callbacks:
                        cb.on_batch_end(i, metrics)
                    global_step += 1
                    if ckptr is not None and save_steps > 0 \
                            and global_step % save_steps == 0:
                        ckptr.save(_ckpt_state_of(step),
                                   step=global_step,
                                   host_state=_fit_host_state(
                                       global_step, epoch, i))
                        _obs.flight.record("checkpoint_save",
                                           step=global_step)
                    if guard.preempted:
                        # finish-the-step done; leave both loops and
                        # take the final-checkpoint path below
                        preempted = True
                        break
                    if watchdog is not None and watchdog.tripped():
                        rollback = True
                        break
                if preempted:
                    break
                if rollback:
                    budget = int(GLOBAL_FLAGS.get("rollback_budget"))
                    rollbacks += 1
                    _obs.counter(
                        "rollbacks_total",
                        "divergence-watchdog checkpoint rollbacks "
                        "performed by Model.fit", always=True).inc()
                    _obs.flight.record("fit_rollback", force=True,
                                       at_step=global_step,
                                       n=rollbacks)
                    if rollbacks > budget:
                        raise FloatingPointError(
                            f"training diverged again after {budget} "
                            "rollback(s) — FLAGS_rollback_budget "
                            "exhausted; newest intact checkpoint is "
                            f"step {ckptr.latest_step()}")
                    # drain in-flight probe callbacks so stale
                    # pre-rollback anomalies cannot re-trip the fresh
                    # watchdog state
                    jax.effects_barrier()
                    restored, at = ckptr.restore_latest(
                        target=_ckpt_state_of(step))
                    if restored is None:
                        raise FloatingPointError(
                            "training diverged and no intact "
                            "checkpoint exists to roll back to "
                            f"(ckpt_dir={ckpt_dir!r})")
                    step.state.update(restored)
                    resume_step = int(at or 0)
                    global_step = 0
                    factor = float(
                        GLOBAL_FLAGS.get("rollback_lr_factor"))
                    if factor != 1.0 and hasattr(step, "lr_scale"):
                        # picked up as a runtime scalar by the step
                        # (one retrace on first rescale)
                        step.lr_scale = step.lr_scale * factor
                    _obs.anomaly.sentinel().reset()
                    watchdog.reset()
                    _obs.flight.record(
                        "fit_rollback_resume", force=True,
                        resume_step=resume_step,
                        lr_scale=getattr(step, "lr_scale", 1.0))
                    epoch = 0
                    continue
                logs = {k: float(v) / max(count, 1)
                        for k, v in totals.items()}
                if eval_loader is not None:
                    with ledger.measure("eval"):
                        logs.update(self.evaluate(eval_loader, verbose=0))
                if obs_on:
                    ledger.publish()
                for k, v in logs.items():
                    history.setdefault(k, []).append(v)
                for cb in callbacks:
                    cb.on_epoch_end(epoch, logs)
                if any(getattr(cb, "stop_training", False)
                       for cb in callbacks):
                    break
                epoch += 1
            if preempted:
                _obs.flight.record("preempted", force=True,
                                   step=global_step)
                if ckptr is not None:
                    # final SYNCHRONOUS checkpoint — the point of the
                    # graceful path: resume from the step the
                    # preemption landed on, not the last save interval
                    try:
                        ckptr.save(_ckpt_state_of(step),
                                   step=global_step,
                                   host_state=_fit_host_state(
                                       global_step, epoch, i))
                        ckptr.wait()
                        _obs.flight.record("preempt_checkpoint",
                                           force=True, step=global_step)
                    except Exception as e:  # noqa: BLE001
                        _obs.flight.record("preempt_checkpoint_failed",
                                           force=True, step=global_step,
                                           error=str(e)[:300])
                guard.reraise()  # dies with SIGTERM wait status
            for cb in callbacks:
                cb.on_train_end()
            if ckptr is not None:
                # make the end state durable before fit returns; skip
                # the save when the cadence just wrote this exact step
                if save_steps <= 0 or global_step % save_steps != 0:
                    ckptr.save(_ckpt_state_of(step), step=global_step,
                               host_state=_fit_host_state(
                                   global_step, epoch, i))
                ckptr.wait()
            if _obs.enabled():
                _obs.flight.record("fit_end", steps_run=global_step)
                ledger.stop()
                ledger.publish()
                if GLOBAL_FLAGS.get("trace_dir"):
                    # host chrome-trace + metrics/goodput snapshot for
                    # tools/trace_report.py and tools/goodput_report.py
                    _obs.export_all()
        finally:
            guard.__exit__(None, None, None)
            self._fitting = False
            if watchdog is not None:
                watchdog.detach(_obs.anomaly.sentinel())
            if _faults.active():
                _faults.set_step_context(None)
            if ledger.running():  # interrupted fit: close the books
                ledger.stop()
            # Must run even on an interrupted fit: the jitted step donated
            # (deleted) the network's own arrays into the training state, so
            # skipping the sync-back would leave the eager model holding
            # dead buffers.
            if self._train_step is not None:
                self._train_step.sync_to_model()
        return history

    def _current_state(self):
        if self._fitting and self._train_step is not None:
            # mid-fit: live (donated) training state
            st = self._train_step.state
            return st["params"], st["buffers"]
        # outside fit the eager network is the source of truth
        return self.network.param_dict(), self.network.buffer_dict()

    def _get_eval_step(self) -> EvalStep:
        if self._eval_step is None:
            self._eval_step = EvalStep(self.network)
        return self._eval_step

    def evaluate(self, eval_loader, verbose: int = 1) -> Dict[str, float]:
        if verbose:
            print("Eval begin...")
        params, buffers = self._current_state()
        ev = self._get_eval_step()
        for m in self._metrics:
            m.reset()
        # HOT LOOP like fit (VERDICT r2 weak 7): no host sync per batch.
        # Losses stay device arrays (one fetch at the end); metric
        # compute() outputs (small per-batch summaries) are deferred —
        # update() may convert to numpy, so it runs after the whole
        # epoch has been dispatched. Metrics WITHOUT compute() update
        # per batch: deferring would keep every batch's full model
        # output alive on device (O(dataset) HBM).
        losses = []
        pending: List[tuple] = []
        for batch in eval_loader:
            *inputs, label = batch
            out, _ = ev(params, buffers, *inputs)
            if self._loss is not None:
                losses.append(self._loss(out, jnp.asarray(label)))
            for m in self._metrics:
                if hasattr(m, "compute"):
                    pending.append((m, m.compute(out, jnp.asarray(label))))
                else:
                    m.update(out, label)
        result = {}
        if losses:
            result["eval_loss"] = float(jnp.mean(jnp.stack(losses)))
        for m, computed in pending:
            m.update(computed)
        for m in self._metrics:
            result[f"eval_{m.name()}"] = m.accumulate()
        if verbose:
            def _fmt(v):
                try:
                    return f"{v:.4f}"
                except (TypeError, ValueError):  # list-valued metrics
                    return str(v)
            print("Eval done: " + " - ".join(
                f"{k}: {_fmt(v)}" for k, v in result.items()))
        return result

    def predict_batch(self, inputs):
        params, buffers = self._current_state()
        ev = self._get_eval_step()
        out, _ = ev(params, buffers,
                    *(inputs if isinstance(inputs, (list, tuple))
                      else [inputs]))
        return out

    def predict(self, loader) -> List:
        # lag-1 conversion: batch N's (blocking) np.asarray runs after
        # batch N+1 has been dispatched, overlapping transfer with
        # compute while keeping device residency at one batch —
        # converting inline would serialize, converting at the end
        # would hold every output on device (O(dataset) HBM, the
        # pattern evaluate() documents against)
        results: List = []
        pending = None
        for b in loader:
            out = self.predict_batch(list(b)[:-1]
                                     if isinstance(b, tuple) else b)
            if pending is not None:
                results.append(np.asarray(pending))
            pending = out
        if pending is not None:
            results.append(np.asarray(pending))
        return results

    def save(self, path: str, training: bool = True,
             input_spec=None) -> None:
        """training=True: checkpoint (params+buffers). training=False:
        inference export — serialized StableHLO + params via jit.save
        (ref: hapi/model.py Model.save(training=False) →
        save_inference_model)."""
        # Mid-fit (ModelCheckpoint callback) the live training state must be
        # pulled back first; outside fit the eager network is authoritative
        # and syncing would clobber user weight mutations.
        if self._fitting and self._train_step is not None:
            self._train_step.sync_to_model()
        with _obs.goodput_ledger().measure("checkpoint"):
            if not training:
                # jit.save itself forces eval mode for the export trace
                # and restores the layer's mode afterwards
                from . import jit as jit_mod
                jit_mod.save(self.network, path, input_spec=input_spec)
                return
            io_mod.save(self.network.state_dict(), path + ".pdparams")

    def load(self, path: str) -> None:
        state = io_mod.load(path + ".pdparams")
        self.network.set_state_dict(
            {k.replace("/", "."): v for k, v in state.items()},
            strict=False)
        self._train_step = None
        self._eval_step = None

    def parameters(self):
        return self.network.parameters()

    def summary(self) -> str:
        lines = ["Layer (type)                 Param #"]
        total = 0
        for name, p in self.network.named_parameters():
            n = int(np.prod(p.value.shape))
            total += n
            lines.append(f"{name:<30} {n}")
        lines.append(f"Total params: {total}")
        out = "\n".join(lines)
        print(out)
        return out
