"""LLM decode engine: paged KV pools + continuous-batching step loop.

The execution half of the serving subsystem. The engine owns the
per-layer K/V block POOLS (``[num_blocks, block_size, heads,
head_dim]`` arrays — the layout kernels/paged_attention.py scans),
drives the scheduler, and turns ``step()`` calls into token events:

* admitted sequences are PREFILLED — one dense causal forward over
  the prompt whose attention callback also scatters each layer's K/V
  into the sequence's pool blocks, yielding the first sampled token
  (the TTFT token);
* the running set then takes ONE decode step as a single ragged
  batch: every sequence's newest token is written into its next pool
  slot and attention runs through the Pallas ragged paged kernel over
  the block tables (interpret-mode on CPU — the same code path tier-1
  tests).

The model is any ``GPTLanguageModel``-shaped layer exposing
``forward_with_attn(ids, positions, attn_fn)``; the engine never
copies or concatenates cache tensors, so per-step cost tracks real
context tokens, not max context.

``step()`` returns plain event dicts (token / finished / error) and
knows nothing about sockets; serving_llm/server.py turns events into
streaming wire frames, which keeps this whole file testable without a
server.
"""

from __future__ import annotations

import time
from typing import Any, Dict, List, Optional

import jax
import jax.numpy as jnp
import numpy as np

from ..models.gpt_lm import dense_causal_attention
from .kv_cache import KVBlockAllocator
from .scheduler import ContinuousBatchingScheduler, Sequence

__all__ = ["LLMEngine"]


class LLMEngine:
    def __init__(self, model, block_size: Optional[int] = None,
                 pool_blocks: Optional[int] = None,
                 max_decode_batch: Optional[int] = None):
        from ..flags import GLOBAL_FLAGS
        cfg = model.config
        self.model = model
        self.block_size = int(block_size
                              or GLOBAL_FLAGS.get("kv_block_size"))
        self.pool_blocks = int(pool_blocks
                               or GLOBAL_FLAGS.get("kv_pool_blocks"))
        self.allocator = KVBlockAllocator(self.pool_blocks,
                                          self.block_size)
        self.scheduler = ContinuousBatchingScheduler(
            self.allocator, max_decode_batch=max_decode_batch)
        self._heads = cfg.num_heads
        self._head_dim = cfg.hidden_size // cfg.num_heads
        shape = (self.pool_blocks, self.block_size, self._heads,
                 self._head_dim)
        self._k_pools = [jnp.zeros(shape, jnp.float32)
                         for _ in range(cfg.num_layers)]
        self._v_pools = [jnp.zeros(shape, jnp.float32)
                         for _ in range(cfg.num_layers)]
        self._seqs: Dict[int, Sequence] = {}
        self._next_seq = 0
        self.tokens_generated = 0

    # -- request lifecycle ------------------------------------------------

    def add_request(self, prompt_ids, max_new_tokens: int = 16,
                    eos_token_id: Optional[int] = None,
                    temperature: float = 0.0, seed: int = 0) -> int:
        prompt = [int(t) for t in np.asarray(prompt_ids).reshape(-1)]
        if not prompt:
            raise ValueError("empty prompt")
        vocab = self.model.config.vocab_size
        if any(t < 0 or t >= vocab for t in prompt):
            raise ValueError(f"prompt token out of range [0, {vocab})")
        if max_new_tokens < 1:
            raise ValueError("max_new_tokens must be >= 1")
        self._next_seq += 1
        seq = Sequence(seq_id=self._next_seq, prompt=prompt,
                       max_new_tokens=int(max_new_tokens),
                       eos_token_id=eos_token_id,
                       temperature=float(temperature), seed=int(seed))
        self._seqs[seq.seq_id] = seq
        self.scheduler.add(seq)
        return seq.seq_id

    def cancel(self, seq_id: int) -> bool:
        """Drop a sequence (client disconnect): blocks freed, no
        further events for it. True if it was live."""
        seq = self.scheduler.cancel(seq_id)
        self._seqs.pop(seq_id, None)
        return seq is not None

    def active(self) -> bool:
        return self.scheduler.active()

    # -- one engine step --------------------------------------------------

    def step(self) -> List[Dict[str, Any]]:
        """Admit + prefill new sequences, then one decode step for the
        running batch. Returns token/finished/error event dicts in
        emission order (a sequence's events are ordered; the chunk
        stream is built from exactly this order)."""
        events: List[Dict[str, Any]] = []
        for seq in self.scheduler.admit():
            try:
                events += self._prefill(seq)
            except Exception as e:  # noqa: BLE001 — fail ONE request
                events.append(self._fail(seq, str(e)))
        events += self._decode()
        self._publish()
        return events

    # -- internals --------------------------------------------------------

    def _slots(self, seq: Sequence, positions: np.ndarray):
        """(block, offset) pool coordinates for absolute token
        positions of one sequence."""
        table = np.asarray(self.allocator.table(seq.seq_id), np.int32)
        return table[positions // self.block_size], \
            positions % self.block_size

    def _prefill(self, seq: Sequence) -> List[Dict[str, Any]]:
        if seq.dispatch_unix is None:
            seq.dispatch_unix = time.time()
        ids = seq.prompt + seq.generated  # re-prefill keeps generated
        t = len(ids)
        pos = np.arange(t, dtype=np.int32)
        blks, offs = self._slots(seq, pos)

        def attn_fn(i, q, k, v):
            self._k_pools[i] = self._k_pools[i].at[blks, offs].set(
                k[0].astype(jnp.float32))
            self._v_pools[i] = self._v_pools[i].at[blks, offs].set(
                v[0].astype(jnp.float32))
            return dense_causal_attention(q, k, v)

        logits = self.model.forward_with_attn(
            jnp.asarray([ids], jnp.int32), jnp.asarray([pos], jnp.int32),
            attn_fn)[0, -1]
        seq.ctx_len = t
        return self._emit(seq, self._sample(seq, logits))

    def _decode(self) -> List[Dict[str, Any]]:
        events: List[Dict[str, Any]] = []
        # oldest-first growth: preemption evicts from the young end,
        # so by the time a young sequence grows it may already be gone
        todo = sorted((s for s in self.scheduler.running
                       if s.ctx_len > 0 and s.generated),
                      key=lambda s: s.admit_order)
        batch: List[Sequence] = []
        for seq in todo:
            if seq not in self.scheduler.running:
                continue  # preempted by an older sequence's growth
            if not self.scheduler.grow(seq, seq.ctx_len + 1):
                events.append(self._fail(
                    seq, f"sequence needs {seq.ctx_len + 1} tokens of "
                         f"KV cache but the pool holds "
                         f"{self.pool_blocks * self.block_size}"))
                continue
            batch.append(seq)
        batch = [s for s in batch if s in self.scheduler.running]
        if not batch:
            return events
        b = len(batch)
        feed = np.asarray([[s.generated[-1]] for s in batch], np.int32)
        newpos = np.asarray([s.ctx_len for s in batch], np.int32)
        slots = [self._slots(s, np.asarray([s.ctx_len]))
                 for s in batch]
        blks = np.asarray([s[0][0] for s in slots], np.int32)
        offs = np.asarray([s[1][0] for s in slots], np.int32)
        tables = [self.allocator.table(s.seq_id) for s in batch]
        maxb = max(len(tb) for tb in tables)
        tbl = np.zeros((b, maxb), np.int32)
        for i, tb in enumerate(tables):
            tbl[i, :len(tb)] = tb
        lens = newpos + 1

        def attn_fn(i, q, k, v):
            from ..kernels import maybe_paged_attention
            self._k_pools[i] = self._k_pools[i].at[blks, offs].set(
                k[:, 0].astype(jnp.float32))
            self._v_pools[i] = self._v_pools[i].at[blks, offs].set(
                v[:, 0].astype(jnp.float32))
            out = maybe_paged_attention(q[:, 0], self._k_pools[i],
                                        self._v_pools[i], tbl, lens)
            return out[:, None].astype(q.dtype)

        logits = self.model.forward_with_attn(
            jnp.asarray(feed), jnp.asarray(newpos[:, None]),
            attn_fn)[:, -1]
        from .. import observability as obs
        if obs.enabled():
            obs.histogram("llm_decode_batch_size",
                          "sequences per continuous-batching decode "
                          "step",
                          buckets=[1.0, 2.0, 4.0, 8.0, 16.0, 32.0]
                          ).observe(float(b))
        for i, seq in enumerate(batch):
            seq.ctx_len += 1
            events += self._emit(seq, self._sample(seq, logits[i]))
        return events

    def _sample(self, seq: Sequence, logits) -> int:
        if seq.temperature > 0.0:
            key = jax.random.fold_in(jax.random.PRNGKey(seq.seed),
                                     len(seq.generated))
            return int(jax.random.categorical(
                key, logits / jnp.float32(seq.temperature)))
        return int(jnp.argmax(logits))

    def _emit(self, seq: Sequence, token: int) -> List[Dict[str, Any]]:
        idx = len(seq.generated)
        seq.generated.append(token)
        self.tokens_generated += 1
        events: List[Dict[str, Any]] = [{
            "type": "token", "seq_id": seq.seq_id, "token": token,
            "index": idx, "dispatch_unix": seq.dispatch_unix}]
        reason = None
        if seq.eos_token_id is not None and token == seq.eos_token_id:
            reason = "eos"
        elif len(seq.generated) >= seq.max_new_tokens:
            reason = "length"
        if reason is not None:
            self.scheduler.finish(seq)
            self._seqs.pop(seq.seq_id, None)
            events.append({"type": "finished", "seq_id": seq.seq_id,
                           "reason": reason,
                           "tokens": len(seq.generated)})
        return events

    def _fail(self, seq: Sequence, error: str) -> Dict[str, Any]:
        self.scheduler.finish(seq)
        self._seqs.pop(seq.seq_id, None)
        return {"type": "error", "seq_id": seq.seq_id, "error": error,
                "tokens": len(seq.generated)}

    def _publish(self) -> None:
        from .. import observability as obs
        if not obs.enabled():
            return
        obs.gauge("llm_running_seqs",
                  "sequences in the continuous-batching running set"
                  ).set(float(len(self.scheduler.running)))
        obs.gauge("llm_waiting_seqs",
                  "sequences queued for admission (prefill pending)"
                  ).set(float(len(self.scheduler.waiting)))
