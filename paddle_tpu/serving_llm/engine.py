"""LLM decode engine: paged KV pools + continuous-batching step loop.

The execution half of the serving subsystem. The engine owns the
per-layer K/V block POOLS (``[num_blocks, block_size, heads,
head_dim]`` arrays — the layout kernels/paged_attention.py scans),
drives the scheduler, and turns ``step()`` calls into token events:

* admitted sequences are PREFILLED — a dense causal forward over the
  not-yet-cached suffix of the prompt whose attention callback also
  scatters each layer's K/V into the sequence's pool blocks. Under
  ``FLAGS_kv_prefix_sharing`` the already-resident shared prefix is
  skipped (its K/V rows are gathered from the pool instead of
  recomputed), and the first write into a still-shared block goes
  through copy-on-write. Under ``FLAGS_prefill_chunk_tokens`` the
  prefill is CHUNKED: a sequence advances one chunk per step —
  interleaved with the decode tick below, so one long prompt no
  longer spikes every running stream's TPOT — and yields its first
  sampled token (the TTFT token) only when the last chunk lands;
* the running set (sequences whose prefill is done) then takes ONE
  decode step as a single ragged batch: every sequence's newest token
  is written into its next pool slot and attention runs through the
  Pallas ragged paged kernel over the block tables (interpret-mode on
  CPU — the same code path tier-1 tests). Under
  ``FLAGS_speculative_k`` the step is SPECULATIVE instead: a small
  draft model proposes up to k tokens per sequence, the target
  verifies every window in one batched ragged MULTI-QUERY paged
  forward, the longest accepted prefix is committed plus the
  target's bonus token, and draft K/V past the accepted point is
  rolled back (``KVBlockAllocator.truncate_to``) — output stays
  token-for-token identical to non-speculative decode because both
  paths sample through the same position-keyed RNG.

The model is any ``GPTLanguageModel``-shaped layer exposing
``forward_with_attn(ids, positions, attn_fn)``; the engine never
copies or concatenates cache tensors, so per-step cost tracks real
context tokens, not max context.

``step()`` returns plain event dicts (token / finished / error) and
knows nothing about sockets; serving_llm/server.py turns events into
streaming wire frames, which keeps this whole file testable without a
server.
"""

from __future__ import annotations

import time
import weakref
from typing import Any, Dict, List, Optional

import jax
import jax.numpy as jnp
import numpy as np

from ..models.gpt_lm import dense_causal_attention
from ..observability import seqtrace as _seqtrace
from ..observability import stepprof as _stepprof
from . import tenancy
from .kv_cache import KVBlockAllocator
from .scheduler import ContinuousBatchingScheduler, Sequence

__all__ = ["LLMEngine", "AdmissionRejected", "health_snapshot"]

# stall watchdog floor: a step (or inter-step gap) must exceed both
# the floor and stall_factor * EWMA before the engine reads as stalled
# (tests monkeypatch this to exercise the path deterministically)
STALL_MIN_S = 0.5

# live engines, for the /healthz "serving" section
# (observability/server.py calls health_snapshot via sys.modules so an
# unused serving subsystem costs nothing)
_ENGINES: "weakref.WeakSet[LLMEngine]" = weakref.WeakSet()


class AdmissionRejected(RuntimeError):
    """New sequence refused by the KV-watermark admission gate
    (FLAGS_kv_admission_watermark). Fail-fast overload control: the
    pool could not cover the projected peak demand, so the request is
    rejected before prefill instead of admitted into preempt-thrash.
    ``retry_after_ms`` is a backoff hint sized to the current load."""

    def __init__(self, msg: str, retry_after_ms: int):
        super().__init__(msg)
        self.retry_after_ms = int(retry_after_ms)


def health_snapshot() -> Dict[str, Any]:
    """Aggregate engine health for /healthz: per-engine stall /
    KV-audit state, ok=False when any live engine is unhealthy."""
    engines = [eng.health() for eng in list(_ENGINES)]
    ok = not any(h["stalled"] or h["audit_failed"] for h in engines)
    return {"ok": ok, "engines": engines}


class LLMEngine:
    def __init__(self, model, block_size: Optional[int] = None,
                 pool_blocks: Optional[int] = None,
                 max_decode_batch: Optional[int] = None,
                 draft_model=None):
        from ..flags import GLOBAL_FLAGS
        cfg = model.config
        self.model = model
        self.block_size = int(block_size
                              or GLOBAL_FLAGS.get("kv_block_size"))
        self.pool_blocks = int(pool_blocks
                               or GLOBAL_FLAGS.get("kv_pool_blocks"))
        self.allocator = KVBlockAllocator(self.pool_blocks,
                                          self.block_size)
        self.scheduler = ContinuousBatchingScheduler(
            self.allocator, max_decode_batch=max_decode_batch)
        self._heads = cfg.num_heads
        self._head_dim = cfg.hidden_size // cfg.num_heads
        shape = (self.pool_blocks, self.block_size, self._heads,
                 self._head_dim)
        self._k_pools = [jnp.zeros(shape, jnp.float32)
                         for _ in range(cfg.num_layers)]
        self._v_pools = [jnp.zeros(shape, jnp.float32)
                         for _ in range(cfg.num_layers)]
        self._seqs: Dict[int, Sequence] = {}  # guarded-by: single-owner (serving thread)
        # tenant labels that ever held a live sequence (gauge zeroing)
        self._tenant_labels_seen: set = set()
        self._next_seq = 0
        self.tokens_generated = 0
        # projected peak blocks per live sequence (watermark gate)
        self._projected: Dict[int, int] = {}  # guarded-by: single-owner (serving thread)
        # stall watchdog / post-step audit state (health_snapshot)
        self._step_begin_unix: Optional[float] = None
        self._step_end_unix: Optional[float] = None
        self._step_ewma_s: Optional[float] = None
        self._audit_failed = False
        self.stalls_total = 0
        self.admission_rejected_total = 0
        # step profiler (observability/stepprof.py): per-step phase-ms
        # accumulator, None while metrics are off or between steps
        self._steps_total = 0
        self._step_begin_mono: Optional[float] = None
        self._phase_ms: Optional[Dict[str, float]] = None
        self._spec_batch = 0  # sequences verified this step
        self._prefix_hits_snap = 0
        self._spec_snap = (0, 0)
        # speculative decoding (FLAGS_speculative_k): the draft model
        # proposing tokens for the target to verify. None here means
        # it is auto-built on first use (FLAGS_speculative_draft_*);
        # pass draft_model=model for self-drafting (accept rate 1.0
        # at temperature 0 — the CPU sanity configuration)
        self._draft_model = draft_model
        self.spec_proposed_total = 0
        self.spec_accepted_total = 0
        self.spec_verify_steps = 0
        self.spec_verify_ms_total = 0.0
        _ENGINES.add(self)

    # -- request lifecycle ------------------------------------------------

    def add_request(self, prompt_ids, max_new_tokens: int = 16,
                    eos_token_id: Optional[int] = None,
                    temperature: float = 0.0, seed: int = 0,
                    trace_id: int = 0, sample_offset: int = 0,
                    tenant: str = tenancy.DEFAULT_TENANT,
                    priority_class: str = tenancy.DEFAULT_CLASS
                    ) -> int:
        prompt = [int(t) for t in np.asarray(prompt_ids).reshape(-1)]
        if not prompt:
            raise ValueError("empty prompt")
        vocab = self.model.config.vocab_size
        if any(t < 0 or t >= vocab for t in prompt):
            raise ValueError(f"prompt token out of range [0, {vocab})")
        if max_new_tokens < 1:
            raise ValueError("max_new_tokens must be >= 1")
        if sample_offset < 0:
            raise ValueError("sample_offset must be >= 0")
        tenant = tenancy.sanitize_tenant(tenant)
        priority_class = tenancy.normalize_class(priority_class)
        projected = self._admission_gate(prompt, int(max_new_tokens),
                                         tenant, priority_class)
        self._next_seq += 1
        seq = Sequence(seq_id=self._next_seq, prompt=prompt,
                       max_new_tokens=int(max_new_tokens),
                       eos_token_id=eos_token_id,
                       temperature=float(temperature), seed=int(seed),
                       sample_offset=int(sample_offset),
                       tenant=tenant, priority_class=priority_class)
        self._seqs[seq.seq_id] = seq
        self._projected[seq.seq_id] = projected
        self.scheduler.add(seq)
        from .. import observability as obs
        if obs.enabled():
            obs.counter("llm_tenant_admitted_total",
                        "sequences accepted into the engine per "
                        "tenant label (past the watermark AND the "
                        "tenant KV budget; the fleet_status.py "
                        "--tenants admitted column)").inc(
                            tenant=tenancy.tenant_label(tenant))
        # seq timeline opens here; trace_id is the wire id the bridge
        # carries so /requests records link to this /llm/seqs entry
        _seqtrace.begin(seq.seq_id, trace_id=int(trace_id),
                        engine=id(self), prompt_tokens=len(prompt),
                        max_new_tokens=int(max_new_tokens),
                        tenant=tenant, cls=priority_class)
        return seq.seq_id

    def _projected_blocks(self, prompt: List[int],
                          max_new: int) -> int:
        """Peak private-block demand of a new sequence. With prefix
        sharing on, full prompt blocks that are already resident — or
        that a live sequence's prompt will make resident by the time
        this one admits — are satisfied by refcount bumps, so they
        are subtracted from the projection (a partially-shared tail
        block still counts: its first divergent write costs a private
        copy). This is what lets a shared-prefix flood admit ~N× more
        streams through the same watermark."""
        projected = self.allocator.blocks_for(len(prompt) + max_new)
        if not self.allocator._sharing():
            return projected
        m = self.allocator.probe_shared_tokens(prompt)
        for seq in self._seqs.values():
            other = seq.prompt
            limit = min(len(prompt) - 1, len(other))
            c = 0
            while c < limit and prompt[c] == other[c]:
                c += 1
            m = max(m, c)
        return max(1, projected - m // self.block_size)

    def _admission_gate(self, prompt: List[int], max_new: int,
                        tenant: str = tenancy.DEFAULT_TENANT,
                        priority_class: str = tenancy.DEFAULT_CLASS
                        ) -> int:
        """KV-watermark admission control: compute the sequence's
        projected peak block demand (an upper bound — blocks for
        prompt + max_new tokens, minus blocks prefix sharing will
        satisfy) and reject when the summed projection of every live
        sequence would cross the watermark, OR when this tenant's own
        summed projection would cross its FLAGS_tenant_kv_budget
        fraction of the pool (bulk load exhausts bulk's budget, never
        the headroom premium admissions need). Admitted load then
        provably fits without preemption."""
        projected = self._projected_blocks(prompt, max_new)
        from ..flags import GLOBAL_FLAGS
        try:
            watermark = float(GLOBAL_FLAGS.get("kv_admission_watermark"))
        except Exception:  # noqa: BLE001
            watermark = 0.0
        # the tenant budget gates even when the global watermark is
        # off: it is an isolation contract, not an overload valve
        frac = tenancy.tenant_budget_frac(tenant)
        if frac is not None:
            t_budget = frac * self.pool_blocks
            t_committed = sum(
                p for sid, p in self._projected.items()
                if (s := self._seqs.get(sid)) is not None
                and s.tenant == tenant)
            if t_committed + projected > t_budget:
                self._reject(projected, t_committed, t_budget, tenant,
                             reason="tenant_budget")
        if watermark <= 0:
            return projected
        budget = watermark * self.pool_blocks
        committed = sum(self._projected.values())
        if committed + projected <= budget:
            return projected
        self._reject(projected, committed, budget, tenant,
                     reason="watermark")
        raise AssertionError("unreachable")  # _reject always raises

    def _reject(self, projected: int, committed: float, budget: float,
                tenant: str, reason: str) -> None:
        """Count + flight-record one admission rejection and raise
        AdmissionRejected with the retry-after hint."""
        self.admission_rejected_total += 1
        # backoff hint scaled to how much work is ahead of the caller
        load = len(self.scheduler.running) + len(self.scheduler.waiting)
        retry_after_ms = 50 * (1 + load)
        from ..observability import flight as _flight
        _flight.record("llm_admission_rejected", force=True,
                       projected_blocks=projected,
                       committed_blocks=committed,
                       budget_blocks=round(budget, 1),
                       reason=reason, tenant=tenant,
                       retry_after_ms=retry_after_ms)
        from .. import observability as obs
        if obs.enabled():
            obs.counter("llm_admission_rejected_total",
                        "new sequences refused before prefill, per "
                        "tenant label — by the KV-watermark admission "
                        "gate (kv_admission_watermark) or the "
                        "tenant's own KV budget (tenant_kv_budget); "
                        "overload fail-fast, not a shed or a "
                        "preemption").inc(
                            tenant=tenancy.tenant_label(tenant))
        what = ("tenant KV budget" if reason == "tenant_budget"
                else "watermark budget")
        raise AdmissionRejected(
            f"admission rejected: projected {projected} KV blocks + "
            f"{committed} committed exceeds {what} "
            f"{budget:.1f} of {self.pool_blocks}; "
            f"retry_after_ms={retry_after_ms}", retry_after_ms)

    def cancel(self, seq_id: int, outcome: str = "cancelled") -> bool:
        """Drop a sequence (client disconnect; ``outcome="shed"``
        when the bridge sheds an aged waiting stream): blocks freed,
        no further events for it. True if it was live."""
        seq = self.scheduler.cancel(seq_id)
        self._seqs.pop(seq_id, None)
        self._projected.pop(seq_id, None)
        if seq is not None:
            _seqtrace.finish(seq_id, outcome,
                             tokens=len(seq.generated))
        return seq is not None

    def active(self) -> bool:
        return self.scheduler.active()

    # -- one engine step --------------------------------------------------

    def step(self) -> List[Dict[str, Any]]:
        """Admit + prefill new sequences, then one decode step for the
        running batch. Returns token/finished/error event dicts in
        emission order (a sequence's events are ordered; the chunk
        stream is built from exactly this order).

        Wrapped by the stall watchdog (EWMA of step wall time, see
        FLAGS_llm_stall_factor) and followed by the KV invariant audit
        — a leak or gauge drift raises here, loudly, instead of
        surfacing as slow corruption. Each step also emits one step
        record into the /llm/steps ring (observability/stepprof.py),
        with the in-flight half registered up front so a wedged step
        is visible there while it hangs."""
        self._step_begin_unix = time.time()
        t0 = time.perf_counter()
        self._steps_total += 1
        self._prof_begin()
        events: List[Dict[str, Any]] = []
        try:
            events = self._step_inner()
        finally:
            dt = time.perf_counter() - t0
            # fair-share ledger: resident context x step wall time
            self.scheduler.charge(dt)
            stalls_before = self.stalls_total
            self._note_step(dt)
            self._prof_end(dt, events,
                           stalled=self.stalls_total > stalls_before)
        self._audit()
        return events

    def _step_inner(self) -> List[Dict[str, Any]]:
        events: List[Dict[str, Any]] = []
        self._prof_phase("admit")
        _t = time.perf_counter()
        admitted: List[Sequence] = []
        try:
            admitted = self.scheduler.admit()
        except Exception as e:  # noqa: BLE001 — kv_alloc fault path
            # allocate() raised before the head left the waiting
            # queue: fail that one request, keep the engine alive
            if self.scheduler.waiting:
                seq = self.scheduler.waiting.popleft()
                events.append(self._fail(seq, f"kv allocation: {e}"))
        self._prof_acc("admit", (time.perf_counter() - _t) * 1e3)
        for seq in admitted:
            _seqtrace.event(seq.seq_id,
                            "readmitted" if seq.preemptions
                            else "admitted",
                            cached_tokens=seq.cached_tokens,
                            order=seq.admit_order)
        # chunked prefill tick: every running sequence with unwritten
        # context advances ONE chunk (the whole remainder when
        # FLAGS_prefill_chunk_tokens is 0), newly admitted sequences
        # included — interleaved with the decode tick below
        self._prof_phase("prefill")
        for seq in [s for s in self.scheduler.running
                    if not s.prefill_done]:
            if seq not in self.scheduler.running:
                continue  # preempted by an earlier sequence's COW
            _t = time.perf_counter()
            try:
                events += self._prefill_chunk(seq)
            except Exception as e:  # noqa: BLE001 — fail ONE request
                events.append(self._fail(seq, str(e)))
            finally:
                self._prof_acc("prefill",
                               (time.perf_counter() - _t) * 1e3)
        self._prof_phase("decode")
        _t = time.perf_counter()
        spec0 = (self._phase_ms or {}).get("spec_verify", 0.0)
        events += self._decode()
        dec_ms = (time.perf_counter() - _t) * 1e3 \
            - ((self._phase_ms or {}).get("spec_verify", 0.0) - spec0)
        self._prof_acc("decode", max(0.0, dec_ms))
        self._publish()
        return events

    # -- step profiler (observability/stepprof.py) -------------------------

    def _prof_begin(self) -> None:
        """Open the step record: arm the phase-ms accumulator and
        register the live in-flight entry on the /llm/steps ring (a
        step wedged mid-flight is diagnosable there — begin stamps +
        current phase — not just counted by health())."""
        from .. import observability as obs
        self._step_begin_mono = time.monotonic()
        self._spec_batch = 0
        if not obs.enabled():
            self._phase_ms = None
            return
        self._phase_ms = {}
        self._prefix_hits_snap = self.allocator.prefix_hit_tokens_total
        self._spec_snap = (self.spec_proposed_total,
                           self.spec_accepted_total)
        _stepprof.ring().step_begin(id(self), step=self._steps_total,
                                    begin_unix=self._step_begin_unix)

    def _prof_phase(self, phase: str) -> None:
        if self._phase_ms is not None:
            _stepprof.ring().set_phase(id(self), phase)

    def _prof_acc(self, phase: str, ms: float) -> None:
        p = self._phase_ms
        if p is not None:
            p[phase] = p.get(phase, 0.0) + ms

    def _prof_end(self, dt: float, events: List[Dict[str, Any]],
                  stalled: bool) -> None:
        """Seal the step record and append it to the /llm/steps ring
        (also observes llm_step_phase_ms{phase=})."""
        p, self._phase_ms = self._phase_ms, None
        if p is None:
            return
        run = self.scheduler.running
        dp = self.spec_proposed_total - self._spec_snap[0]
        da = self.spec_accepted_total - self._spec_snap[1]
        rec = {
            "step": self._steps_total,
            "engine": id(self) & 0xFFFF,
            "begin_unix": self._step_begin_unix,  # display only
            "begin_mono": self._step_begin_mono,
            "dur_ms": round(dt * 1e3, 3),
            "phase_ms": {k: round(v, 3) for k, v in sorted(p.items())},
            "batch": {
                "prefilling": sum(1 for s in run
                                  if not s.prefill_done),
                "decoding": sum(1 for s in run if s.prefill_done),
                "verifying": self._spec_batch,
                "waiting": len(self.scheduler.waiting)},
            "kv": {"used": self.allocator.num_used,
                   "free": self.allocator.num_free,
                   "shared": self.allocator.num_shared},
            "prefix_hit_tokens": self.allocator.prefix_hit_tokens_total
            - self._prefix_hits_snap,
            "spec": {"proposed": dp, "accepted": da,
                     "accept_rate": round(da / dp, 4) if dp else None},
            "tokens": sum(1 for e in events if e["type"] == "token"),
            "events": len(events),
            "stalled": bool(stalled),
        }
        _stepprof.ring().record(id(self), rec)

    # -- internals --------------------------------------------------------

    def _slots(self, seq: Sequence, positions: np.ndarray):
        """(block, offset) pool coordinates for absolute token
        positions of one sequence."""
        table = np.asarray(self.allocator.table(seq.seq_id), np.int32)
        return table[positions // self.block_size], \
            positions % self.block_size

    @staticmethod
    def _chunk_tokens(block_size: int) -> int:
        """FLAGS_prefill_chunk_tokens, floored to a block-size
        multiple (0 = chunking off: whole prompt in one step)."""
        from ..flags import GLOBAL_FLAGS
        try:
            chunk = int(GLOBAL_FLAGS.get("prefill_chunk_tokens"))
        # ptlint: disable=silent-failure -- flag may not be defined under direct submodule import; chunking simply stays off
        except Exception:  # noqa: BLE001
            return 0
        if chunk <= 0:
            return 0
        return max(block_size, chunk - chunk % block_size)

    def _make_writable(self, seq: Sequence, lo: int, hi: int) -> None:
        """Copy-on-write gate before writing K/V rows at positions
        [lo, hi): any still-shared block in that range is replaced
        with a private copy — the shared block's rows are copied
        in-pool via a scatter — preempting younger sequences if the
        pool cannot supply the copy target. Raises when the pool can
        never cover it (caller fails the one sequence)."""
        bs = self.block_size
        for idx in range(lo // bs, (max(lo, hi - 1)) // bs + 1):
            r = self.scheduler.make_writable(seq, idx)
            if r is None:
                continue
            if r is False:
                if seq not in self.scheduler.running:
                    # preempted itself: higher-class residents hold
                    # the pool; the write aborts and readmission
                    # retries (callers check running membership)
                    return
                raise RuntimeError(
                    f"sequence needs a private copy of a shared KV "
                    f"block but the pool holds "
                    f"{self.pool_blocks * self.block_size} tokens "
                    f"with no victims left")
            old, new = r
            _t = time.perf_counter()
            from ..testing import faults as _faults
            _faults.hit("llm_cow_copy")
            for i in range(len(self._k_pools)):
                self._k_pools[i] = self._k_pools[i].at[new].set(
                    self._k_pools[i][old])
                self._v_pools[i] = self._v_pools[i].at[new].set(
                    self._v_pools[i][old])
            _seqtrace.event(
                seq.seq_id, "cow_copy", block_old=old, block_new=new,
                ms=round((time.perf_counter() - _t) * 1e3, 3))

    def _prefill_chunk(self, seq: Sequence) -> List[Dict[str, Any]]:
        """One prefill chunk for ``seq``: forward the next
        FLAGS_prefill_chunk_tokens positions (everything left when
        chunking is off), attending over the already-cached prefix
        gathered from the pool, and scatter the fresh K/V rows into
        the sequence's blocks. The shared prefix (cached_tokens) is
        never recomputed. The final chunk samples the first token."""
        from ..testing import faults as _faults
        t0 = time.perf_counter()  # before the fault hits: an injected
        # slow chunk (sleep=) must show in this chunk's measured ms
        if seq.ctx_len == seq.cached_tokens:
            # first chunk of this (re)admission — the historical
            # per-sequence prefill fault point fires here once
            _faults.hit("llm_prefill")
        _faults.hit("llm_chunk_prefill")
        if seq.dispatch_unix is None:
            seq.dispatch_unix = time.time()
        ids = seq.prompt + seq.generated  # re-prefill keeps generated
        t = len(ids)
        c0 = seq.ctx_len
        chunk = self._chunk_tokens(self.block_size)
        n = t - c0 if chunk <= 0 else min(chunk, t - c0)
        # COW before any write: the first uncached position may land
        # in a block still shared with another sequence
        self._make_writable(seq, c0, c0 + n)
        if seq not in self.scheduler.running:
            return []  # preempted itself inside the COW gate
        pos = np.arange(c0, c0 + n, dtype=np.int32)
        blks, offs = self._slots(seq, pos)
        cb = co = None
        if c0 > 0:
            cpos = np.arange(c0, dtype=np.int32)
            cb, co = self._slots(seq, cpos)

        def attn_fn(i, q, k, v):
            _ts = time.perf_counter()
            self._k_pools[i] = self._k_pools[i].at[blks, offs].set(
                k[0].astype(jnp.float32))
            self._v_pools[i] = self._v_pools[i].at[blks, offs].set(
                v[0].astype(jnp.float32))
            self._prof_acc("scatter",
                           (time.perf_counter() - _ts) * 1e3)
            if cb is None:
                return dense_causal_attention(q, k, v)
            # cached prefix (shared blocks / earlier chunks) comes
            # from the pool; queries attend [cached + fresh] with
            # their absolute positions
            kc = self._k_pools[i][cb, co][None]
            vc = self._v_pools[i][cb, co][None]
            return dense_causal_attention(
                q,
                jnp.concatenate([kc, k.astype(jnp.float32)], axis=1),
                jnp.concatenate([vc, v.astype(jnp.float32)], axis=1),
                q_offset=c0)

        logits = self.model.forward_with_attn(
            jnp.asarray([ids[c0:c0 + n]], jnp.int32),
            jnp.asarray([pos], jnp.int32), attn_fn)[0, -1]
        seq.ctx_len = c0 + n
        self.allocator.note_written(seq.seq_id, ids[:seq.ctx_len])
        chunk_ms = (time.perf_counter() - t0) * 1e3
        from .. import observability as obs
        if obs.enabled():
            from ..observability import metrics as _m
            obs.histogram("llm_prefill_chunk_ms",
                          "wall time of one prefill chunk "
                          "(FLAGS_prefill_chunk_tokens; whole-prompt "
                          "prefill when chunking is off)",
                          buckets=_m.LATENCY_MS_BUCKETS).observe(
                              chunk_ms)
        # timeline event BEFORE the final chunk's first token, so the
        # chunk lands inside the gap the token anchors (attribution)
        _seqtrace.event(seq.seq_id, "prefill_chunk",
                        ms=round(chunk_ms, 3), ctx=seq.ctx_len,
                        done=seq.ctx_len >= t)
        if seq.ctx_len < t:
            return []  # mid-prefill: decode keeps ticking meanwhile
        seq.prefill_done = True
        return self._emit(seq, self._sample(seq, logits))

    def _decode(self) -> List[Dict[str, Any]]:
        if self._spec_k() > 0:
            return self._decode_speculative(self._spec_k())
        events: List[Dict[str, Any]] = []
        # oldest-first growth: preemption evicts from the young end,
        # so by the time a young sequence grows it may already be gone
        todo = sorted((s for s in self.scheduler.running
                       if s.prefill_done and s.generated),
                      key=lambda s: s.admit_order)
        batch: List[Sequence] = []
        from ..testing import faults as _faults
        for seq in todo:
            if seq not in self.scheduler.running:
                continue  # preempted by an older sequence's growth
            try:
                _faults.hit("llm_decode")
                grown = self.scheduler.grow(seq, seq.ctx_len + 1)
                if grown:
                    # defensive COW gate: prefill already privatized
                    # every block it wrote, so this is a refcount
                    # lookup that never copies today — it keeps the
                    # write path safe if sharing ever extends past
                    # prefill (e.g. forked sampling)
                    self._make_writable(seq, seq.ctx_len,
                                        seq.ctx_len + 1)
            except Exception as e:  # noqa: BLE001 — fail ONE sequence
                events.append(self._fail(seq, f"decode: {e}"))
                continue
            if not grown:
                if seq not in self.scheduler.running:
                    # preempted ITSELF: higher-class residents hold
                    # the pool — it waits for readmission, not death
                    continue
                events.append(self._fail(
                    seq, f"sequence needs {seq.ctx_len + 1} tokens of "
                         f"KV cache but the pool holds "
                         f"{self.pool_blocks * self.block_size}"))
                continue
            batch.append(seq)
        batch = [s for s in batch if s in self.scheduler.running]
        if not batch:
            return events
        b = len(batch)
        feed = np.asarray([[s.generated[-1]] for s in batch], np.int32)
        newpos = np.asarray([s.ctx_len for s in batch], np.int32)
        slots = [self._slots(s, np.asarray([s.ctx_len]))
                 for s in batch]
        blks = np.asarray([s[0][0] for s in slots], np.int32)
        offs = np.asarray([s[1][0] for s in slots], np.int32)
        tables = [self.allocator.table(s.seq_id) for s in batch]
        maxb = max(len(tb) for tb in tables)
        tbl = np.zeros((b, maxb), np.int32)
        for i, tb in enumerate(tables):
            tbl[i, :len(tb)] = tb
        lens = newpos + 1

        def attn_fn(i, q, k, v):
            from ..kernels import maybe_paged_attention
            _ts = time.perf_counter()
            self._k_pools[i] = self._k_pools[i].at[blks, offs].set(
                k[:, 0].astype(jnp.float32))
            self._v_pools[i] = self._v_pools[i].at[blks, offs].set(
                v[:, 0].astype(jnp.float32))
            self._prof_acc("scatter",
                           (time.perf_counter() - _ts) * 1e3)
            out = maybe_paged_attention(q[:, 0], self._k_pools[i],
                                        self._v_pools[i], tbl, lens)
            return out[:, None].astype(q.dtype)

        try:
            logits = self.model.forward_with_attn(
                jnp.asarray(feed), jnp.asarray(newpos[:, None]),
                attn_fn)[:, -1]
        except Exception as e:  # noqa: BLE001
            # a batched-forward failure would otherwise strand the
            # whole running set mid-decode forever: fail every member
            # loudly so their blocks free and clients get error frames
            for seq in batch:
                events.append(self._fail(seq, f"decode step: {e}"))
            return events
        from .. import observability as obs
        if obs.enabled():
            obs.histogram("llm_decode_batch_size",
                          "sequences per continuous-batching decode "
                          "step",
                          buckets=[1.0, 2.0, 4.0, 8.0, 16.0, 32.0]
                          ).observe(float(b))
        for i, seq in enumerate(batch):
            seq.ctx_len += 1
            self.allocator.note_written(
                seq.seq_id, (seq.prompt + seq.generated)[:seq.ctx_len])
            events += self._emit(seq, self._sample(seq, logits[i]))
        return events

    # -- speculative decoding (FLAGS_speculative_k) ------------------------

    @staticmethod
    def _spec_k() -> int:
        from ..flags import GLOBAL_FLAGS
        try:
            return max(0, int(GLOBAL_FLAGS.get("speculative_k")))
        # ptlint: disable=silent-failure -- flag may not be defined under direct submodule import; speculative decoding simply stays off
        except Exception:  # noqa: BLE001
            return 0

    def _draft(self):
        """The draft model: the one passed at construction, else a
        small GPTLanguageModel auto-built once — same geometry as the
        target with FLAGS_speculative_draft_layers layers, embedding
        tables (and therefore the tied output head) shared with the
        target under FLAGS_speculative_draft_tie_embeddings."""
        if self._draft_model is not None:
            return self._draft_model
        from ..flags import GLOBAL_FLAGS
        from ..models.gpt_lm import GPTConfig, GPTLanguageModel
        cfg = self.model.config
        layers = max(1, int(GLOBAL_FLAGS.get("speculative_draft_layers")))
        draft = GPTLanguageModel(GPTConfig(
            vocab_size=cfg.vocab_size, hidden_size=cfg.hidden_size,
            num_layers=layers, num_heads=cfg.num_heads,
            intermediate_size=cfg.intermediate_size,
            max_position_embeddings=cfg.max_position_embeddings,
            layer_norm_epsilon=cfg.layer_norm_epsilon))
        if bool(GLOBAL_FLAGS.get("speculative_draft_tie_embeddings")):
            draft.embed = self.model.embed
            draft.pos_embed = self.model.pos_embed
        self._draft_model = draft
        return draft

    def _propose(self, seq: Sequence, draft, k: int) -> List[int]:
        """Draft-propose ``k`` continuation tokens for ``seq`` with a
        dense concat KV cache rebuilt from the full token history (the
        draft is small; recompute keeps it stateless across the
        target's preemptions/rollbacks). Proposals use the SAME
        position-keyed sampler as the target (`_sample_at`), so a
        self-drafting configuration accepts every token at any
        temperature."""
        ids = seq.prompt + seq.generated
        caches: List[Optional[tuple]] = [None] * len(draft.blocks)

        def attn_fn(i, q, kk, vv):
            if caches[i] is not None:
                kk = jnp.concatenate([caches[i][0], kk], axis=1)
                vv = jnp.concatenate([caches[i][1], vv], axis=1)
            caches[i] = (kk, vv)
            return dense_causal_attention(
                q, kk, vv, q_offset=kk.shape[1] - q.shape[1])

        pos = jnp.arange(len(ids), dtype=jnp.int32)[None]
        logits = draft.forward_with_attn(
            jnp.asarray([ids], jnp.int32), pos, attn_fn)[0, -1]
        out: List[int] = []
        for j in range(k):
            tok = self._sample_at(seq, logits,
                                  len(seq.generated) + j)
            out.append(tok)
            if j + 1 == k:
                break
            p = jnp.asarray([[len(ids) + j]], jnp.int32)
            logits = draft.forward_with_attn(
                jnp.asarray([[tok]], jnp.int32), p, attn_fn)[0, -1]
        return out

    def _decode_speculative(self, k: int) -> List[Dict[str, Any]]:
        """One speculative decode step: per running sequence the
        draft proposes up to ``k`` tokens, the TARGET verifies every
        window in ONE batched ragged multi-query paged-attention
        forward, and the longest accepted prefix is committed plus
        the target's bonus token from the last verified position
        (greedy/longest-prefix acceptance against the position-keyed
        sampler — token-for-token identical to non-speculative decode
        at any temperature). Draft K/V written past the accepted
        point is rolled back through the allocator's truncate_to, so
        the post-step audit sees exactly the committed context."""
        events: List[Dict[str, Any]] = []
        todo = sorted((s for s in self.scheduler.running
                       if s.prefill_done and s.generated),
                      key=lambda s: s.admit_order)
        from ..testing import faults as _faults
        draft = self._draft()
        batch: List[Sequence] = []
        windows: Dict[int, List[int]] = {}
        prop_ms_by: Dict[int, float] = {}
        for seq in todo:
            if seq not in self.scheduler.running:
                continue  # preempted by an older sequence's growth
            # never propose past the emission budget: the window can
            # emit at most k accepted tokens + 1 bonus token
            k_eff = max(0, min(k, seq.max_new_tokens
                               - len(seq.generated) - 1))
            _t = time.perf_counter()
            try:
                _faults.hit("llm_spec_verify")
                proposal = self._propose(seq, draft, k_eff) \
                    if k_eff else []
                grown = self.scheduler.grow(
                    seq, seq.ctx_len + len(proposal) + 1)
                if grown:
                    # COW gate over the whole window: a rejected draft
                    # must never scribble a block another sequence
                    # still reads — divergence copies it private first
                    self._make_writable(
                        seq, seq.ctx_len,
                        seq.ctx_len + len(proposal) + 1)
            except Exception as e:  # noqa: BLE001 — fail ONE sequence
                events.append(self._fail(seq, f"speculative: {e}"))
                continue
            prop_ms = (time.perf_counter() - _t) * 1e3
            self._prof_acc("spec_verify", prop_ms)
            prop_ms_by[seq.seq_id] = prop_ms
            if not grown:
                if seq not in self.scheduler.running:
                    continue  # preempted itself (class-gated pool)
                events.append(self._fail(
                    seq, f"sequence needs "
                         f"{seq.ctx_len + len(proposal) + 1} tokens "
                         f"of KV cache but the pool holds "
                         f"{self.pool_blocks * self.block_size}"))
                continue
            batch.append(seq)
            windows[seq.seq_id] = proposal
        batch = [s for s in batch if s in self.scheduler.running]
        if not batch:
            return events
        b = len(batch)
        self._spec_batch = b
        q_lens = np.asarray([len(windows[s.seq_id]) + 1
                             for s in batch], np.int32)
        qmax = int(q_lens.max())
        feed = np.zeros((b, qmax), np.int32)
        newpos = np.zeros((b, qmax), np.int32)
        seq_slots = []
        for i, s in enumerate(batch):
            win = [s.generated[-1]] + windows[s.seq_id]
            feed[i, :len(win)] = win
            wpos = np.arange(s.ctx_len, s.ctx_len + qmax,
                             dtype=np.int32)
            # padded rows clamp to the last valid position (keeps
            # pos_embed in range; their outputs are discarded)
            newpos[i] = np.minimum(wpos, s.ctx_len + len(win) - 1)
            seq_slots.append(self._slots(
                s, np.arange(s.ctx_len, s.ctx_len + len(win),
                             dtype=np.int32)))
        tables = [self.allocator.table(s.seq_id) for s in batch]
        maxb = max(len(tb) for tb in tables)
        tbl = np.zeros((b, maxb), np.int32)
        for i, tb in enumerate(tables):
            tbl[i, :len(tb)] = tb
        lens = np.asarray([s.ctx_len for s in batch],
                          np.int32) + q_lens
        qlens_j = jnp.asarray(q_lens)

        def attn_fn(i, q, kk, vv):
            from ..kernels import maybe_paged_attention_multiquery
            _ts = time.perf_counter()
            for si in range(b):
                blks, offs = seq_slots[si]
                n = int(q_lens[si])
                self._k_pools[i] = self._k_pools[i].at[blks, offs].set(
                    kk[si, :n].astype(jnp.float32))
                self._v_pools[i] = self._v_pools[i].at[blks, offs].set(
                    vv[si, :n].astype(jnp.float32))
            self._prof_acc("scatter",
                           (time.perf_counter() - _ts) * 1e3)
            out = maybe_paged_attention_multiquery(
                q, qlens_j, self._k_pools[i], self._v_pools[i], tbl,
                lens)
            return out.astype(q.dtype)

        t0 = time.perf_counter()
        try:
            logits = self.model.forward_with_attn(
                jnp.asarray(feed), jnp.asarray(newpos), attn_fn)
        except Exception as e:  # noqa: BLE001
            # same stance as the non-speculative batch: a failed
            # verify forward must not strand the running set
            for seq in batch:
                events.append(self._fail(seq, f"verify step: {e}"))
            return events
        verify_ms = (time.perf_counter() - t0) * 1e3
        self._prof_acc("spec_verify", verify_ms)
        self.spec_verify_steps += 1
        self.spec_verify_ms_total += verify_ms
        accepted_step = 0
        # proposed counts only windows that actually reached the
        # verifier (a window preempted between propose and verify
        # never had an acceptance chance, so it would skew the rate)
        proposed_step = int(q_lens.sum()) - b
        self.spec_proposed_total += proposed_step
        for i, seq in enumerate(batch):
            proposal = windows[seq.seq_id]
            emitted: List[int] = []
            m = 0
            for j in range(len(proposal) + 1):
                tok = self._sample_at(seq, logits[i, j],
                                      len(seq.generated) + j)
                emitted.append(tok)
                if j < len(proposal) and tok == proposal[j]:
                    m += 1
                    continue
                break  # first divergence: tok is the bonus token
            accepted_step += m
            self.spec_accepted_total += m
            # commit: window rows 0..m hold K/V for [last, d1..dm] —
            # all part of the accepted timeline; everything past that
            # is a rejected draft and is rolled back before anyone
            # can prefix-match or audit it
            new_ctx = seq.ctx_len + m + 1
            if m < len(proposal):
                self.allocator.truncate_to(seq.seq_id, new_ctx)
            seq.ctx_len = new_ctx
            self.allocator.note_written(
                seq.seq_id,
                seq.prompt + seq.generated + proposal[:m])
            # recorded before the tokens it produced, so the window
            # lands inside the gap those tokens anchor in attribution
            _seqtrace.event(
                seq.seq_id, "spec_window", proposed=len(proposal),
                accepted=m, rollback=len(proposal) - m,
                ms=round(prop_ms_by.get(seq.seq_id, 0.0)
                         + verify_ms / b, 3))
            for tok in emitted:
                events += self._emit(seq, tok)
                if seq.seq_id not in self._seqs:
                    break  # eos/length finished the sequence
        self._publish_spec(proposed_step, accepted_step, verify_ms,
                           float(b))
        return events

    def _publish_spec(self, proposed: int, accepted: int,
                      verify_ms: float, batch: float) -> None:
        from .. import observability as obs
        if not obs.enabled():
            return
        if proposed:
            obs.counter("llm_spec_proposed_tokens_total",
                        "draft tokens proposed to the target verifier "
                        "by speculative decoding "
                        "(FLAGS_speculative_k)").inc(proposed)
        if accepted:
            obs.counter("llm_spec_accepted_tokens_total",
                        "draft tokens accepted by the target's "
                        "longest-prefix verification — each one "
                        "skipped a full target decode step"
                        ).inc(accepted)
        if self.spec_proposed_total:
            obs.gauge("llm_spec_accept_rate",
                      "cumulative accepted/proposed draft-token ratio "
                      "of this engine (1.0 = every draft token "
                      "matched the target)").set(
                          self.spec_accepted_total
                          / self.spec_proposed_total)
        from ..observability import metrics as _m
        obs.histogram("llm_spec_verify_ms",
                      "wall time of one batched ragged multi-query "
                      "verify forward (speculative decoding)",
                      buckets=_m.LATENCY_MS_BUCKETS).observe(verify_ms)
        obs.histogram("llm_decode_batch_size",
                      "sequences per continuous-batching decode step",
                      buckets=[1.0, 2.0, 4.0, 8.0, 16.0, 32.0]
                      ).observe(batch)

    def _sample(self, seq: Sequence, logits) -> int:
        return self._sample_at(seq, logits, len(seq.generated))

    def _sample_at(self, seq: Sequence, logits, index: int) -> int:
        """Sample the token at generated-index ``index``. The RNG key
        is derived from (seed, sample_offset + index) — NOT from call
        order — so speculative verification reproduces exactly the
        token the sequential sampler would have drawn at that
        position, at any temperature, and a stream resumed elsewhere
        with ``sample_offset`` set to its delivered-token count draws
        exactly the keys the original stream would have drawn next
        (the router-failover parity contract)."""
        _t = time.perf_counter()
        try:
            if seq.temperature > 0.0:
                key = jax.random.fold_in(
                    jax.random.PRNGKey(seq.seed),
                    seq.sample_offset + index)
                return int(jax.random.categorical(
                    key, logits / jnp.float32(seq.temperature)))
            return int(jnp.argmax(logits))
        finally:
            self._prof_acc("sample",
                           (time.perf_counter() - _t) * 1e3)

    def _emit(self, seq: Sequence, token: int) -> List[Dict[str, Any]]:
        idx = len(seq.generated)
        seq.generated.append(token)
        self.tokens_generated += 1
        events: List[Dict[str, Any]] = [{
            "type": "token", "seq_id": seq.seq_id, "token": token,
            "index": idx, "dispatch_unix": seq.dispatch_unix}]
        _seqtrace.event(seq.seq_id, "token", index=idx)
        reason = None
        if seq.eos_token_id is not None and token == seq.eos_token_id:
            reason = "eos"
        elif len(seq.generated) >= seq.max_new_tokens:
            reason = "length"
        if reason is not None:
            self.scheduler.finish(seq)
            self._seqs.pop(seq.seq_id, None)
            self._projected.pop(seq.seq_id, None)
            events.append({"type": "finished", "seq_id": seq.seq_id,
                           "reason": reason,
                           "tokens": len(seq.generated)})
            _seqtrace.finish(seq.seq_id, "finished", reason=reason,
                             tokens=len(seq.generated))
        return events

    def _fail(self, seq: Sequence, error: str) -> Dict[str, Any]:
        self.scheduler.finish(seq)
        self._seqs.pop(seq.seq_id, None)
        self._projected.pop(seq.seq_id, None)
        _seqtrace.finish(seq.seq_id, "error", error=error[:200],
                         tokens=len(seq.generated))
        return {"type": "error", "seq_id": seq.seq_id, "error": error,
                "tokens": len(seq.generated)}

    # -- watchdog + invariant audit ---------------------------------------

    @staticmethod
    def _stall_factor() -> float:
        from ..flags import GLOBAL_FLAGS
        try:
            return float(GLOBAL_FLAGS.get("llm_stall_factor"))
        except Exception:  # noqa: BLE001
            return 0.0

    def _note_step(self, dt: float) -> None:
        """EWMA stall watchdog: a step that took stall_factor times
        longer than the running average (and past the floor) is a
        stall — forced flight event + counter; /healthz picks up the
        live case (a step that never returns) from the stamps."""
        self._step_end_unix = time.time()
        ewma = self._step_ewma_s
        factor = self._stall_factor()
        if factor > 0 and ewma is not None \
                and dt > max(STALL_MIN_S, factor * ewma):
            self.stalls_total += 1
            from ..observability import flight as _flight
            _flight.record("llm_engine_stalled", force=True,
                           step_s=round(dt, 4),
                           ewma_s=round(ewma, 4), factor=factor)
            # hang doctor: capture + classify thread stacks for the
            # post-hoc record (the live capture mid-wedge is the hang
            # monitor's job — this path runs after the step returned).
            # Debounced per source inside the doctor; never raises.
            from ..observability import stacks as _stacks
            _stacks.doctor().on_stall(
                "serving_step",
                detail={"step_s": round(dt, 4),
                        "ewma_s": round(ewma, 4), "factor": factor})
            from .. import observability as obs
            if obs.enabled():
                obs.counter("llm_engine_stalled_total",
                            "engine steps flagged by the stall "
                            "watchdog: wall time exceeded "
                            "llm_stall_factor x the EWMA step time"
                            ).inc()
        self._step_ewma_s = dt if ewma is None \
            else 0.8 * ewma + 0.2 * dt

    def _audit(self) -> None:
        """Post-step KV invariant audit: the allocator's internal
        accounting must be consistent and the published gauges must
        agree with it, and no decode-phase sequence may hold cache
        past its committed context (a rejected draft window that was
        not rolled back would show up exactly there). Raises
        AssertionError — a serving loop that leaks blocks must fail
        loudly, not degrade quietly."""
        agree = None
        try:
            self.allocator.check()
            for seq in self.scheduler.running:
                if not seq.prefill_done:
                    continue
                held = self.allocator.tokens(seq.seq_id)
                if held != seq.ctx_len:
                    raise AssertionError(
                        f"seq {seq.seq_id} holds cache for {held} "
                        f"tokens but committed ctx_len is "
                        f"{seq.ctx_len} — speculative rollback "
                        f"missed a rejected draft window")
            agree = self.allocator.gauges_agree()
            if agree is False:
                raise AssertionError(
                    "kv_blocks_used/free gauges disagree with the "
                    f"allocator (used={self.allocator.num_used}, "
                    f"free={self.allocator.num_free})")
        except AssertionError:
            self._audit_failed = True
            from ..observability import flight as _flight
            _flight.record("llm_kv_audit_failed", force=True,
                           used=self.allocator.num_used,
                           free=self.allocator.num_free,
                           gauges_agree=agree)
            from .. import observability as obs
            if obs.enabled():
                obs.counter("llm_kv_audit_failures_total",
                            "post-step KV invariant audits that "
                            "failed (allocator accounting broken or "
                            "gauges drifted) — the engine reports "
                            "unhealthy on /healthz until restart"
                            ).inc()
            raise

    def health(self) -> Dict[str, Any]:
        """Live health for /healthz's serving section. ``stalled`` is
        judged from the step stamps so a step wedged RIGHT NOW (or a
        serving loop that stopped stepping an active engine) reads
        unhealthy without waiting for the step to return."""
        now = time.time()
        begin, end = self._step_begin_unix, self._step_end_unix
        last = max(x for x in (begin, end) if x is not None) \
            if (begin is not None or end is not None) else None
        age = None if last is None else max(0.0, now - last)
        factor = self._stall_factor()
        ewma = self._step_ewma_s
        stalled = bool(
            factor > 0 and self.active() and age is not None
            and ewma is not None
            and age > max(STALL_MIN_S, factor * ewma))
        return {"active": self.active(),
                "running": len(self.scheduler.running),
                "prefilling": sum(1 for s in self.scheduler.running
                                  if not s.prefill_done),
                "waiting": len(self.scheduler.waiting),
                "kv_blocks_used": self.allocator.num_used,
                "last_step_age_s":
                    None if age is None else round(age, 3),
                "step_ewma_s":
                    None if ewma is None else round(ewma, 4),
                "stalls_total": self.stalls_total,
                "stalled": stalled,
                "audit_failed": self._audit_failed,
                "speculative": {
                    "k": self._spec_k(),
                    "proposed_tokens": self.spec_proposed_total,
                    "accepted_tokens": self.spec_accepted_total,
                    "accept_rate":
                        round(self.spec_accepted_total
                              / self.spec_proposed_total, 4)
                        if self.spec_proposed_total else None,
                    "verify_ms_mean":
                        round(self.spec_verify_ms_total
                              / self.spec_verify_steps, 3)
                        if self.spec_verify_steps else None}}

    def _publish(self) -> None:
        from .. import observability as obs
        if not obs.enabled():
            return
        obs.gauge("llm_running_seqs",
                  "sequences in the continuous-batching running set"
                  ).set(float(len(self.scheduler.running)))
        obs.gauge("llm_waiting_seqs",
                  "sequences queued for admission (prefill pending)"
                  ).set(float(len(self.scheduler.waiting)))
        obs.gauge("llm_prefilling_seqs",
                  "admitted sequences still mid-chunked-prefill (not "
                  "yet in the decode batch)").set(float(
                      sum(1 for s in self.scheduler.running
                          if not s.prefill_done)))
        active: Dict[str, int] = {}
        for s in self.scheduler.running:
            lbl = tenancy.tenant_label(s.tenant)
            active[lbl] = active.get(lbl, 0) + 1
        g = obs.gauge("llm_tenant_active",
                      "live sequences (running set) per tenant label "
                      "— the fleet_status.py --tenants active column")
        for lbl, n in active.items():
            g.set(float(n), tenant=lbl)
        # a tenant that just drained must read 0, not its last value
        for lbl in self._tenant_labels_seen - set(active):
            g.set(0.0, tenant=lbl)
        self._tenant_labels_seen |= set(active)
