"""Front-door router: health-gated fan-out of the serving wire
protocol over N backends with deterministic mid-stream failover.

Every robustness layer below this one (admission control, graceful
drain, hang doctor) lives *inside* one ``inference.Server`` process —
when that process dies, every in-flight stream dies with it. The
router is the piece that survives backend death: a thin stdlib TCP
front that speaks the PTSV/PTSC/PTSR/PTST framing on both sides
(docs/serving_protocol.md) and spreads work over a pool of backends.

Four pillars, each independently testable:

* **Health-gated pool** (:class:`BackendPool`) — a probe thread runs
  PTSC STATS round trips (plus an optional exporter ``/healthz`` GET)
  against every backend. Each backend carries a
  :class:`CircuitBreaker`: consecutive connect/deadline failures trip
  it ``closed → open`` with exponential backoff; after the backoff a
  single half-open probe decides recovery. A backend that *answers*
  but reports ``serving.draining=1`` (or healthz 503) leaves rotation
  as ``draining`` — an orderly goodbye, not a failure, so the breaker
  stays closed and the backend rejoins the moment the drain flag
  clears.
* **Deterministic mid-stream failover** — the router records each
  stream's prompt, sampling params, and the tokens already delivered.
  When a backend dies mid-stream it re-issues prompt+delivered as the
  new prompt on a survivor with ``sample_offset=len(delivered)``, so
  the position-keyed sampler reproduces the original continuation
  bitwise (docs/serving_protocol.md, "Stream failover & resume") and
  the client sees one seamless token sequence. Bounded by
  ``FLAGS_router_failover_budget`` per stream.
* **Retry/shed discipline** — a stream that has delivered ZERO tokens
  may be retried on another backend with jittered backoff
  (``FLAGS_router_retry_budget``); a started stream is only ever
  failed over, never blind-resent (a resend without the resume offset
  could double-generate). ``AdmissionRejected`` retry-after hints are
  collected across backends; when every backend is saturated the
  router sheds at the door with the MAX hint instead of queueing —
  graceful degradation, no retry storms against open breakers.
* **Observability** — ``router_backend_state{backend=}`` gauge,
  ``router_failovers_total`` / ``router_retries_total`` /
  ``router_shed_total{tenant=}`` counters, per-hop reqtrace spans
  riding the client's trace_id, flight events for breaker transitions
  and failovers, and a ``GET /router`` JSON snapshot on the exporter
  (module-level registry, :func:`snapshot_all`).

Tenancy rides the same wire (docs/serving_protocol.md, "Tenant
descriptor"): a PTST frame may carry a uint8 tenant descriptor, which
the router decodes, forwards verbatim to the backend, and uses for
two class-aware decisions. Under ``FLAGS_router_prefix_affinity``,
``pick`` routes a prompt to the backend already holding its longest
recorded leading-block prefix (multiplying the backends'
``kv_prefix_hit_tokens_total``), falling back to a class-weighted
load pick — premium to the least-loaded backend, bulk packed onto the
busiest so the quiet one keeps premium headroom. And under
saturation the door sheds in class order: bulk gives up on the first
saturated answer, standard sweeps the whole pool once (the PR-19
default), premium re-sweeps until the retry budget is spent —
``router_shed_total{tenant=}`` records who was turned away.

Everything here is standard library + numpy; the router runs as its
own process via tools/llm_router.py or in-process for tests.
"""

from __future__ import annotations

import random
import socket
import struct
import threading
import time
import weakref
import zlib
from collections import OrderedDict
from typing import Any, Callable, Dict, List, Optional, Sequence as Seq, Tuple

import numpy as np

__all__ = ["CircuitBreaker", "Backend", "BackendPool", "Router",
           "snapshot_all"]

# wire constants mirror inference.Client (docs/serving_protocol.md)
_MAGIC = 0x56535450         # 'PTSV' tensor request
_MAGIC_CTL = 0x43535450     # 'PTSC' control frame
_MAGIC_TRACE = 0x52535450   # 'PTSR' traced tensor request
_MAGIC_STREAM = 0x54535450  # 'PTST' streaming generate request
_OP_STATS = 1
_HDR = struct.Struct("<IQI")      # magic | tag | payload len
_REPLY = struct.Struct("<QqI")    # tag | status | payload len
_GEN_HDR = struct.Struct("<IIfI")  # max_new | eos | temperature | seed
_EOS_NONE = 0xFFFFFFFF
_MAX_PAYLOAD = 64 * 1024 * 1024
_CONNECT_TIMEOUT_S = 5.0
_PROBE_DEADLINE_S = 2.0
# prefix-affinity placement map bounds (FLAGS_router_prefix_affinity):
# at most _AFFINITY_BLOCKS leading full KV blocks are hashed per
# prompt, and the LRU map holds at most _AFFINITY_CAP prefixes
_AFFINITY_BLOCKS = 32
_AFFINITY_CAP = 4096

# numeric codes for the router_backend_state gauge (and the STATS
# text): rotation-eligible is exactly code 0
STATE_CODES = {"closed": 0, "draining": 1, "unhealthy": 2,
               "half_open": 3, "open": 4}


def _flag(name: str):
    from ..flags import GLOBAL_FLAGS
    return GLOBAL_FLAGS.get(name)


class _ClientGone(Exception):
    """The router→client socket died; abandon the stream quietly."""


# -- circuit breaker ------------------------------------------------------


class CircuitBreaker:
    """Per-backend breaker: ``closed → open`` on
    ``FLAGS_router_breaker_threshold`` CONSECUTIVE failures, with
    exponential open-state backoff (doubling per re-open, capped at
    ``FLAGS_router_breaker_backoff_max_s``); once the backoff elapses
    a SINGLE caller wins the half-open probe slot and its outcome
    decides recovery (success → closed, reset) or re-open (doubled
    backoff). Pure unit: all timing goes through the injectable
    monotonic ``clock`` so tests advance time without sleeping."""

    def __init__(self, threshold: Optional[int] = None,
                 backoff_s: Optional[float] = None,
                 backoff_max_s: Optional[float] = None,
                 clock: Callable[[], float] = time.monotonic):
        self._threshold = threshold
        self._backoff_s = backoff_s
        self._backoff_max_s = backoff_max_s
        self._clock = clock
        self._lock = threading.Lock()
        self._state = "closed"      # guarded-by: self._lock
        self._failures = 0          # guarded-by: self._lock
        self._open_until = 0.0      # guarded-by: self._lock
        self._backoff = 0.0         # guarded-by: self._lock
        self.opened_total = 0       # guarded-by: self._lock

    # flag values are read lazily so tests can retune mid-run and a
    # breaker built at import time still follows the flags
    def _threshold_v(self) -> int:
        if self._threshold is not None:
            return int(self._threshold)
        return max(1, int(_flag("router_breaker_threshold")))

    def _base_backoff(self) -> float:
        if self._backoff_s is not None:
            return float(self._backoff_s)
        return float(_flag("router_breaker_backoff_s"))

    def _max_backoff(self) -> float:
        if self._backoff_max_s is not None:
            return float(self._backoff_max_s)
        return float(_flag("router_breaker_backoff_max_s"))

    @property
    def state(self) -> str:
        with self._lock:
            if self._state == "open" and self._clock() >= self._open_until:
                return "half_open"  # probe slot available but unclaimed
            return self._state

    @property
    def failures(self) -> int:
        with self._lock:
            return self._failures

    def allow(self) -> bool:
        """May the caller contact the backend right now? Closed:
        always. Open: only once the backoff elapsed, and then exactly
        ONE caller wins the probe slot (state moves to ``half_open``);
        everyone else fast-fails until the probe reports back."""
        with self._lock:
            if self._state == "closed":
                return True
            if self._state == "open" and self._clock() >= self._open_until:
                self._state = "half_open"
                return True
            return False

    def record_success(self) -> None:
        with self._lock:
            self._state = "closed"
            self._failures = 0
            self._backoff = 0.0

    def record_failure(self) -> None:
        with self._lock:
            self._failures += 1
            if self._state == "half_open":
                self._open(doubled=True)
            elif self._state == "closed" \
                    and self._failures >= self._threshold_v():
                self._open(doubled=False)
            elif self._state == "open":
                # failure reported by a non-probe path while open
                # (e.g. an in-flight stream that predates the trip):
                # keep the clock running, don't extend the backoff
                pass

    # holds-lock: self._lock
    def _open(self, doubled: bool) -> None:
        base = self._base_backoff()
        self._backoff = base if (not doubled or self._backoff <= 0) \
            else min(self._backoff * 2.0, self._max_backoff())
        self._open_until = self._clock() + self._backoff
        self._state = "open"
        self.opened_total += 1

    def snapshot(self) -> Dict[str, Any]:
        with self._lock:
            return {"state": self._state, "failures": self._failures,
                    "backoff_s": round(self._backoff, 3),
                    "opened_total": self.opened_total}


# -- backend + pool -------------------------------------------------------


class Backend:
    """One serving backend: wire address, optional exporter healthz
    address, breaker, and the probe-maintained rotation state."""

    def __init__(self, host: str, port: int,
                 healthz: Optional[Tuple[str, int]] = None,
                 name: Optional[str] = None,
                 breaker: Optional[CircuitBreaker] = None):
        self.host = host
        self.port = int(port)
        self.healthz = healthz
        self.name = name or f"{host}:{port}"
        self.breaker = breaker or CircuitBreaker()
        self._lock = threading.Lock()
        self.draining = False        # guarded-by: self._lock
        self.unhealthy = False       # guarded-by: self._lock
        self.streams_active = 0      # guarded-by: self._lock
        self.last_probe_unix: Optional[float] = None
        self.last_error: Optional[str] = None

    def state(self) -> str:
        """Rotation state, breaker first: a tripped breaker is the
        honest answer even when the last successful probe saw a drain
        flag (a drained process that finally exits would otherwise
        stay ``draining`` forever on stale data)."""
        bs = self.breaker.state
        if bs != "closed":
            return bs
        with self._lock:
            if self.draining:
                return "draining"
            if self.unhealthy:
                return "unhealthy"
        return "closed"

    def in_rotation(self) -> bool:
        return self.state() == "closed"

    def set_health(self, draining: bool, unhealthy: bool) -> None:
        with self._lock:
            self.draining = bool(draining)
            self.unhealthy = bool(unhealthy)

    def mark_draining(self) -> None:
        with self._lock:
            self.draining = True

    def stream_delta(self, d: int) -> int:
        with self._lock:
            self.streams_active += d
            return self.streams_active

    def snapshot(self) -> Dict[str, Any]:
        st = self.state()
        with self._lock:
            return {"name": self.name, "state": st,
                    "state_code": STATE_CODES[st],
                    "draining": self.draining,
                    "unhealthy": self.unhealthy,
                    "streams_active": self.streams_active,
                    "breaker": self.breaker.snapshot(),
                    "last_probe_unix": self.last_probe_unix,
                    "last_error": self.last_error}


def _default_probe(backend: Backend) -> Dict[str, Any]:
    """One probe round trip: PTSC STATS (authoritative — answered
    inline by the backend's reader thread even under queue
    saturation, and it carries ``serving.draining``), plus a
    best-effort exporter ``GET /healthz`` when the backend has one.
    Raises on STATS connect/deadline failure — that is the breaker
    food; a healthz that is merely unreachable is ignored (the
    exporter is optional telemetry, not the data plane)."""
    from .. import inference as _inf
    out: Dict[str, Any] = {}
    cli = _inf.Client(backend.host, backend.port,
                      timeout_s=_PROBE_DEADLINE_S,
                      deadline_s=_PROBE_DEADLINE_S,
                      max_reconnects=0, traced=False)
    try:
        out["stats"] = cli.stats(deadline_s=_PROBE_DEADLINE_S)
    finally:
        cli.close()
    if backend.healthz is not None:
        import http.client
        try:
            conn = http.client.HTTPConnection(
                backend.healthz[0], backend.healthz[1],
                timeout=_PROBE_DEADLINE_S)
            try:
                conn.request("GET", "/healthz")
                out["healthz"] = conn.getresponse().status
            finally:
                conn.close()
        # ptlint: disable=silent-failure -- healthz is advisory; STATS above already proved the data plane is up, and an unreachable exporter must not trip the breaker
        except Exception:
            out["healthz"] = None
    return out


class BackendPool:
    """Round-robin rotation over the healthy subset, maintained by a
    periodic probe thread. ``probe`` is injectable so the
    drain-vs-death unit tests can script backend answers without
    sockets."""

    def __init__(self, backends: Seq[Backend],
                 probe: Optional[Callable[[Backend], Dict[str, Any]]] = None,
                 probe_interval_s: Optional[float] = None):
        self.backends: List[Backend] = list(backends)
        self._probe = probe or _default_probe
        self._interval = probe_interval_s
        self._lock = threading.Lock()
        self._rr = 0                 # guarded-by: self._lock
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        for b in self.backends:
            self._set_gauge(b)

    # -- rotation ---------------------------------------------------------

    def pick(self, exclude: Seq[Backend] = ()) -> Optional[Backend]:
        """Next in-rotation backend after the round-robin pointer,
        skipping ``exclude`` (backends this stream already burned).
        None when nothing is eligible — the caller decides between
        shed and error."""
        excluded = set(id(b) for b in exclude)
        with self._lock:
            n = len(self.backends)
            for i in range(n):
                b = self.backends[(self._rr + i) % n]
                if id(b) in excluded:
                    continue
                if b.in_rotation():
                    self._rr = (self._rr + i + 1) % n
                    return b
        return None

    def available(self) -> int:
        return sum(1 for b in self.backends if b.in_rotation())

    # -- probe loop -------------------------------------------------------

    def interval_s(self) -> float:
        if self._interval is not None:
            return float(self._interval)
        return float(_flag("router_probe_interval_s"))

    def start(self) -> None:
        if self._thread is not None:
            return
        self._stop.clear()
        self._thread = threading.Thread(
            target=self._probe_loop, name="router-probe", daemon=True)
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        t, self._thread = self._thread, None
        if t is not None:
            t.join(timeout=5.0)

    def _probe_loop(self) -> None:
        while not self._stop.wait(self.interval_s()):
            self.probe_once()

    def probe_once(self) -> None:
        for b in self.backends:
            if self._stop.is_set():
                return
            self._probe_one(b)

    def _probe_one(self, b: Backend) -> None:
        before = b.state()
        # the breaker gates probes too: while open (backoff pending)
        # the backend is left alone; the first probe after the backoff
        # IS the half-open single probe
        if self.allow_probe(b):
            try:
                out = self._probe(b)
            except (OSError, ConnectionError, TimeoutError,
                    RuntimeError) as e:
                b.last_error = f"{type(e).__name__}: {e}"[:200]
                b.breaker.record_failure()
            else:
                stats = out.get("stats") or {}
                hz = out.get("healthz")
                b.set_health(
                    draining=(int(stats.get("serving.draining", 0)) > 0
                              or hz == 503),
                    unhealthy=(hz is not None and hz not in (200, 503)))
                b.last_error = None
                b.breaker.record_success()
        b.last_probe_unix = time.time()
        self.note_transition(b, before)

    def allow_probe(self, b: Backend) -> bool:
        return b.breaker.allow()

    # -- state bookkeeping (shared with the router's data path) -----------

    def note_failure(self, b: Backend, error: str = "") -> None:
        """A data-path connect/deadline failure: breaker food."""
        before = b.state()
        if error:
            b.last_error = error[:200]
        b.breaker.record_failure()
        self.note_transition(b, before)

    def note_success(self, b: Backend) -> None:
        before = b.state()
        b.breaker.record_success()
        self.note_transition(b, before)

    def note_draining(self, b: Backend) -> None:
        """The backend ANSWERED with a drain refusal: an orderly
        goodbye, not a failure — out of rotation with the breaker
        untouched (drain-vs-death distinction)."""
        before = b.state()
        b.mark_draining()
        self.note_transition(b, before)

    def note_transition(self, b: Backend, before: str) -> None:
        after = b.state()
        self._set_gauge(b)
        if after != before:
            from ..observability import flight as _flight
            _flight.record("router_backend_transition", backend=b.name,
                           before=before, after=after,
                           failures=b.breaker.failures)

    def _set_gauge(self, b: Backend) -> None:
        from .. import observability as obs
        if obs.enabled():
            obs.gauge("router_backend_state",
                      "router rotation state per backend: 0=closed "
                      "(in rotation), 1=draining, 2=unhealthy, "
                      "3=half_open, 4=open (breaker tripped)"
                      ).set(STATE_CODES[b.state()], backend=b.name)

    def snapshot(self) -> List[Dict[str, Any]]:
        return [b.snapshot() for b in self.backends]


# -- router ---------------------------------------------------------------


def _parse_backend(spec) -> Backend:
    """``Backend`` | ``(host, port)`` | ``"host:port[:healthzport]"``
    (the optional third field is the backend's exporter port for
    /healthz probes, assumed same host)."""
    if isinstance(spec, Backend):
        return spec
    if isinstance(spec, (tuple, list)):
        return Backend(spec[0], int(spec[1]))
    parts = str(spec).split(":")
    if len(parts) == 2:
        return Backend(parts[0], int(parts[1]))
    if len(parts) == 3:
        return Backend(parts[0], int(parts[1]),
                       healthz=(parts[0], int(parts[2])))
    raise ValueError(f"bad backend spec {spec!r} "
                     "(want host:port[:healthzport])")


def _retry_hint(msg: str) -> Optional[int]:
    """Extract the ``retry_after_ms=N`` hint AdmissionRejected ships
    verbatim in its refusal payload."""
    marker = "retry_after_ms="
    i = msg.find(marker)
    if i < 0:
        return None
    j = i + len(marker)
    k = j
    while k < len(msg) and msg[k].isdigit():
        k += 1
    return int(msg[j:k]) if k > j else None


# live routers for the exporter's GET /router endpoint
_ROUTERS: "weakref.WeakSet[Router]" = weakref.WeakSet()


def snapshot_all() -> List[Dict[str, Any]]:
    """JSON-ready snapshots of every live router in this process
    (the exporter's ``GET /router`` body)."""
    outs = [r.snapshot() for r in list(_ROUTERS)]
    outs.sort(key=lambda s: s.get("addr", ""))
    return outs


class Router:
    """The front-door process: accepts client connections speaking
    the serving wire framing and fans work out over the pool.

    * PTSC STATS → answered locally with router counters plus
      per-backend state codes (int-only ``key=value`` text, so the
      stock ``Client.stats()`` parses it).
    * PTSV / PTSR → proxied to one backend; idempotent, so
      connect/deadline failures retry on another backend within the
      retry budget.
    * PTST → the failover state machine: chunks are forwarded as they
      arrive and recorded; infra failures retry (zero tokens
      delivered) or fail over (resume with ``sample_offset``);
      saturation shedding aggregates retry-after hints.
    """

    def __init__(self, backends: Seq,
                 host: str = "127.0.0.1", port: int = 0,
                 pool: Optional[BackendPool] = None,
                 probe_interval_s: Optional[float] = None,
                 start_probes: bool = True):
        self.pool = pool or BackendPool(
            [_parse_backend(b) for b in backends],
            probe_interval_s=probe_interval_s)
        self._host = host
        self._port = int(port)
        self._start_probes = start_probes
        self._sock: Optional[socket.socket] = None
        self._accept_thread: Optional[threading.Thread] = None
        self._stop = threading.Event()
        self._lock = threading.Lock()
        self._conns: set = set()          # guarded-by: self._lock
        self._streams_active = 0          # guarded-by: self._lock
        # own integer counters so STATS/snapshot work with metrics off
        # guarded-by: self._lock
        self._counts = {"failovers": 0, "retries": 0, "shed": 0,
                        "streams": 0, "proxied": 0}
        # prefix-affinity placement map: crc32 of the leading full
        # prompt blocks -> backend name, LRU-bounded at _AFFINITY_CAP.
        # Advisory only — a dead/burned backend falls through to the
        # class-weighted load pick. guarded-by: self._lock
        self._affinity: "OrderedDict[int, str]" = OrderedDict()
        self._t0 = time.monotonic()

    # -- lifecycle --------------------------------------------------------

    @property
    def port(self) -> int:
        return self._port

    @property
    def addr(self) -> str:
        return f"{self._host}:{self._port}"

    def start(self) -> "Router":
        s = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        s.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        s.bind((self._host, self._port))
        s.listen(128)
        self._port = s.getsockname()[1]
        self._sock = s
        self._stop.clear()
        self._accept_thread = threading.Thread(
            target=self._accept_loop, name="router-accept", daemon=True)
        self._accept_thread.start()
        if self._start_probes:
            self.pool.start()
        _ROUTERS.add(self)
        from ..observability import flight as _flight
        _flight.record("router_start", addr=self.addr,
                       backends=[b.name for b in self.pool.backends])
        return self

    def stop(self) -> None:
        self._stop.set()
        self.pool.stop()
        s, self._sock = self._sock, None
        if s is not None:
            try:
                s.close()
            # ptlint: disable=silent-failure -- teardown: the listener fd is gone either way
            except Exception:
                pass
        with self._lock:
            conns = list(self._conns)
            self._conns.clear()
        for c in conns:
            try:
                c.close()
            # ptlint: disable=silent-failure -- teardown: peer may already be gone
            except Exception:
                pass
        t, self._accept_thread = self._accept_thread, None
        if t is not None:
            t.join(timeout=5.0)
        _ROUTERS.discard(self)

    def __enter__(self) -> "Router":
        return self

    def __exit__(self, *exc) -> None:
        self.stop()

    # -- flag knobs (read lazily, per decision) ---------------------------

    def _failover_budget(self) -> int:
        return max(0, int(_flag("router_failover_budget")))

    def _retry_budget(self) -> int:
        return max(0, int(_flag("router_retry_budget")))

    def _retry_backoff_s(self) -> float:
        return float(_flag("router_retry_backoff_s"))

    def _backend_deadline_s(self) -> float:
        return float(_flag("router_backend_deadline_s"))

    @staticmethod
    def _sat_persistence(cls: str) -> int:
        """Extra full-pool sweeps a stream gets once every backend has
        answered "saturated": premium persists for the full retry
        budget, everyone else sheds after the single exhausted pass.
        Together with the bulk early-shed in ``_serve_stream`` (bulk
        gives up on the FIRST saturated answer, before sweeping the
        rest of the pool) this is the router half of the shed order —
        the door turns away bulk before standard before premium."""
        from . import tenancy
        if tenancy.class_rank(cls) >= tenancy.class_rank("premium"):
            return max(0, int(_flag("router_retry_budget")))
        return 0

    # -- backend selection (prefix affinity + class-weighted load) --------

    def _pick_backend(self, burned: List[Backend], prompt: np.ndarray,
                      cls: str) -> Optional[Backend]:
        """One backend for the next attempt. With
        ``FLAGS_router_prefix_affinity`` off this is the PR-19
        round-robin pick. With it on: route to the backend that
        already holds the longest recorded prompt-block prefix (its
        prefix cache turns the prompt into ``kv_prefix_hit_tokens``
        instead of recompute); on a miss fall back to a
        class-weighted load pick — premium takes the least-loaded
        backend, bulk bin-packs onto the most-loaded one so the quiet
        backend stays free for premium, standard keeps round-robin.
        The chosen backend is recorded for the prompt's prefixes
        either way, so concurrent same-prefix streams converge."""
        if not bool(_flag("router_prefix_affinity")):
            return self.pool.pick(exclude=burned)
        from . import tenancy
        keys = self._prefix_keys(prompt)
        with self._lock:
            name = next((self._affinity[k] for k in keys
                         if k in self._affinity), None)
        b = None
        if name is not None:
            b = next((x for x in self.pool.backends
                      if x.name == name and x.in_rotation()
                      and x not in burned), None)
        if b is None:
            cands = [x for x in self.pool.backends
                     if x.in_rotation() and x not in burned]
            if not cands:
                return None
            rank = tenancy.class_rank(cls)
            if rank >= tenancy.class_rank("premium"):
                b = min(cands, key=lambda x: x.stream_delta(0))
            elif rank <= tenancy.class_rank("bulk"):
                b = max(cands, key=lambda x: x.stream_delta(0))
            else:
                b = self.pool.pick(exclude=burned)
        return self._record_affinity(keys, b)

    def _prefix_keys(self, prompt: np.ndarray) -> List[int]:
        """crc32 keys of the leading full KV blocks of ``prompt``,
        longest prefix first (capped at ``_AFFINITY_BLOCKS`` blocks).
        Block size mirrors the backends' paged KV allocator, so a key
        hit means the backend's prefix cache can reuse exactly those
        blocks."""
        try:
            bs = int(_flag("kv_block_size"))
        # ptlint: disable=silent-failure -- affinity is advisory; an
        # unreadable flag just disables the prefix keys
        except Exception:
            bs = 0
        if bs <= 0:
            return []
        nb = min(len(prompt) // bs, _AFFINITY_BLOCKS)
        if nb <= 0:
            return []
        raw = np.asarray(prompt[:nb * bs], np.int32).tobytes()
        return [zlib.crc32(raw[:j * bs * 4])
                for j in range(nb, 0, -1)]

    def _record_affinity(self, keys: List[int],
                         b: Optional[Backend]) -> Optional[Backend]:
        if b is None or not keys:
            return b
        with self._lock:
            for k in keys:
                self._affinity[k] = b.name
                self._affinity.move_to_end(k)
            while len(self._affinity) > _AFFINITY_CAP:
                self._affinity.popitem(last=False)
        return b

    # -- accept / frame loop ----------------------------------------------

    def _accept_loop(self) -> None:
        while not self._stop.is_set():
            try:
                conn, _ = self._sock.accept()
            except OSError:
                return  # listener closed by stop()
            conn.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
            with self._lock:
                self._conns.add(conn)
            threading.Thread(target=self._serve_conn, args=(conn,),
                             name="router-conn", daemon=True).start()

    def _serve_conn(self, conn: socket.socket) -> None:
        wlock = threading.Lock()
        try:
            while not self._stop.is_set():
                hdr = _recv_exact(conn, _HDR.size)
                if hdr is None:
                    return
                magic, tag, ln = _HDR.unpack(hdr)
                if ln > _MAX_PAYLOAD:
                    _discard_exact(conn, ln)
                    self._reply(conn, wlock, tag, -2, b"payload too large")
                    continue
                payload = _recv_exact(conn, ln)
                if payload is None and ln:
                    return
                self._dispatch(conn, wlock, magic, tag, payload or b"")
        except (OSError, _ClientGone):
            return  # client went away; nothing to answer
        finally:
            with self._lock:
                self._conns.discard(conn)
            try:
                conn.close()
            # ptlint: disable=silent-failure -- teardown: peer may already be gone
            except Exception:
                pass

    def _dispatch(self, conn, wlock, magic: int, tag: int,
                  payload: bytes) -> None:
        if magic == _MAGIC_CTL:
            (op,) = struct.unpack_from("<I", payload, 0)
            if op == _OP_STATS:
                self._reply(conn, wlock, tag, 0,
                            self._stats_text().encode())
            else:
                self._reply(conn, wlock, tag, -4,
                            f"unknown control op {op}".encode())
        elif magic in (_MAGIC, _MAGIC_TRACE):
            trace_id = 0
            if magic == _MAGIC_TRACE:
                (trace_id,) = struct.unpack_from("<Q", payload, 0)
                payload = payload[8:]
            self._proxy_infer(conn, wlock, tag, trace_id, payload)
        elif magic == _MAGIC_STREAM:
            (trace_id,) = struct.unpack_from("<Q", payload, 0)
            max_new, eos_raw, temp, seed = _GEN_HDR.unpack_from(payload, 8)
            from ..inference import decode_tensors
            from . import tenancy
            try:
                arrs = decode_tensors(payload[8 + _GEN_HDR.size:])
                prompt = np.asarray(arrs[0], np.int32).reshape(-1)
                # optional tails, dtype-disambiguated like the bridge:
                # int32 [1] resume offset, uint8 tenant descriptor
                base_offset = 0
                tenant_cls: Optional[Tuple[str, str]] = None
                for arr in arrs[1:]:
                    if arr.dtype == np.int32 and arr.size == 1:
                        base_offset = int(arr.reshape(-1)[0])
                    elif arr.dtype == np.uint8:
                        tenant_cls = tenancy.decode_descriptor(arr)
            except Exception as e:  # noqa: BLE001 — fail ONE request
                self._reply(conn, wlock, tag, -1,
                            f"router: bad generate body: {e}".encode())
                return
            threading.Thread(
                target=self._serve_stream,
                args=(conn, wlock, tag, trace_id, prompt, int(max_new),
                      None if eos_raw == _EOS_NONE else int(eos_raw),
                      float(temp), int(seed), base_offset, tenant_cls),
                name="router-stream", daemon=True).start()
        else:
            self._reply(conn, wlock, tag, -4,
                        f"unknown magic 0x{magic:08x}".encode())

    def _reply(self, conn, wlock, tag: int, status: int,
               payload: bytes = b"") -> None:
        with wlock:
            conn.sendall(_REPLY.pack(tag, status, len(payload)) + payload)

    # -- PTSV/PTSR proxy (idempotent → retry discipline) ------------------

    def _proxy_infer(self, conn, wlock, tag: int, trace_id: int,
                     body: bytes) -> None:
        from .. import inference as _inf
        with self._lock:
            self._counts["proxied"] += 1
        tried: List[Backend] = []
        last_err = "no backend available"
        while True:
            b = self.pool.pick(exclude=tried)
            if b is None or len(tried) > self._retry_budget():
                self._reply(conn, wlock, tag, -1,
                            f"router: no backend available: "
                            f"{last_err}".encode())
                return
            if tried:
                # a second-or-later attempt IS the retry (idempotent
                # tensor requests may re-send; streams never do this)
                self._count_retry(trace_id, b)
                self._sleep_jittered(len(tried))
            tried.append(b)
            try:
                cli = _inf.Client(b.host, b.port,
                                  timeout_s=self._backend_deadline_s(),
                                  connect_timeout_s=_CONNECT_TIMEOUT_S,
                                  deadline_s=self._backend_deadline_s(),
                                  max_reconnects=0, traced=False)
                try:
                    arrays = _inf.decode_tensors(body)
                    outs = cli.infer(arrays,
                                     trace_id=trace_id or None)
                finally:
                    cli.close()
            except (ConnectionError, TimeoutError, OSError) as e:
                last_err = str(e)
                self.pool.note_failure(b, error=last_err)
                continue
            except RuntimeError as e:
                msg = str(e)
                if _is_drain_refusal(msg):
                    self.pool.note_draining(b)
                    last_err = msg
                    continue  # orderly refusal: next backend, no penalty
                self._reply(conn, wlock, tag, -1, msg.encode())
                return
            self.pool.note_success(b)
            self._reply(conn, wlock, tag, 0, _inf.encode_tensors(outs))
            return

    # -- PTST stream failover state machine -------------------------------

    def _serve_stream(self, conn, wlock, tag: int, trace_id: int,
                      prompt: np.ndarray, max_new: int,
                      eos: Optional[int], temp: float, seed: int,
                      base_offset: int,
                      tenant_cls: Optional[Tuple[str, str]] = None) -> None:
        from . import tenancy
        tenant, cls = tenant_cls if tenant_cls is not None else (
            tenancy.DEFAULT_TENANT, tenancy.DEFAULT_CLASS)
        t_ingress = time.time()
        delivered: List[int] = []
        burned: List[Backend] = []
        hints: List[int] = []
        retries = failovers = sat_rounds = 0
        last_err = "no backend available"
        last_backend = ""
        dispatch_unix: Optional[float] = None
        with self._lock:
            self._counts["streams"] += 1
            self._streams_active += 1
            self._set_streams_gauge()
        try:
            while True:
                b = self._pick_backend(burned, prompt, cls)
                if b is None:
                    if hints and not delivered:
                        # every backend answered "saturated": how hard
                        # we push back depends on the stream's class —
                        # bulk sheds on the first exhausted pass,
                        # standard re-sweeps the pool once, premium
                        # persists to the full retry budget
                        sat_rounds += 1
                        if sat_rounds <= self._sat_persistence(cls):
                            burned.clear()
                            self._sleep_jittered(sat_rounds)
                            continue
                        self._shed(conn, wlock, tag, trace_id, hints,
                                   tenant)
                        outcome = "shed"
                    else:
                        self._reply(
                            conn, wlock, tag, -1,
                            f"router: no backend available after "
                            f"{len(delivered)} token(s): "
                            f"{last_err}".encode())
                        outcome = "error"
                    self._trace(trace_id, t_ingress, dispatch_unix,
                                last_backend, delivered, retries,
                                failovers, outcome, tenant, cls)
                    return
                burned.append(b)
                last_backend = b.name
                n_before = len(delivered)
                if dispatch_unix is None:
                    dispatch_unix = time.time()
                try:
                    self._run_attempt(b, conn, wlock, tag, trace_id,
                                      prompt, delivered, max_new, eos,
                                      temp, seed, base_offset,
                                      tenant_cls)
                except _ClientGone:
                    return  # downstream client gone; backend conn is
                    # closed, its dead-write path cancels the sequence
                except (ConnectionError, TimeoutError, OSError) as e:
                    # connect/deadline/mid-stream transport failure:
                    # breaker food (StreamInterrupted lands here too —
                    # its subclasses are ConnectionError/TimeoutError)
                    last_err = str(e)
                    self.pool.note_failure(b, error=last_err)
                except RuntimeError as e:
                    msg = str(e)
                    if _is_drain_refusal(msg):
                        # orderly drain refusal: out of rotation
                        # without breaker penalty, try a survivor
                        last_err = msg
                        self.pool.note_draining(b)
                    elif _retry_hint(msg) is not None:
                        # saturated: collect the hint, try the next
                        # backend immediately (no backoff — the shed
                        # decision needs every backend's answer).
                        # Bulk streams don't even finish the sweep:
                        # one saturated answer is their shed signal,
                        # leaving the rest of the pool's headroom to
                        # the classes above them.
                        hints.append(_retry_hint(msg))
                        last_err = msg
                        from . import tenancy as _tn
                        if (not delivered and _tn.class_rank(cls)
                                <= _tn.class_rank("bulk")):
                            self._shed(conn, wlock, tag, trace_id,
                                       hints, tenant)
                            self._trace(trace_id, t_ingress,
                                        dispatch_unix, last_backend,
                                        delivered, retries, failovers,
                                        "shed", tenant, cls)
                            return
                        continue
                    else:
                        # application error (bad params, execute
                        # error): the backend ANSWERED — propagate
                        # verbatim, nothing to retry
                        self._reply(conn, wlock, tag, -1,
                                    _strip_client_prefix(msg).encode())
                        self._trace(trace_id, t_ingress, dispatch_unix,
                                    last_backend, delivered, retries,
                                    failovers, "backend_error",
                                    tenant, cls)
                        return
                else:
                    # backend finished cleanly: close the stream
                    self._reply(conn, wlock, tag, 0, b"")
                    self._trace(trace_id, t_ingress, dispatch_unix,
                                last_backend, delivered, retries,
                                failovers, "ok", tenant, cls)
                    return
                # infra failure: started streams fail over (resume
                # with the offset), unstarted ones retry with backoff
                if len(delivered) > n_before or delivered:
                    failovers += 1
                    if failovers > self._failover_budget():
                        self._reply(
                            conn, wlock, tag, -1,
                            f"router: failover budget exhausted after "
                            f"{len(delivered)} token(s): "
                            f"{last_err}".encode())
                        self._trace(trace_id, t_ingress, dispatch_unix,
                                    last_backend, delivered, retries,
                                    failovers, "failover_exhausted",
                                    tenant, cls)
                        return
                    self._count_failover(trace_id, b, delivered)
                else:
                    retries += 1
                    if retries > self._retry_budget():
                        self._reply(
                            conn, wlock, tag, -1,
                            f"router: retry budget exhausted: "
                            f"{last_err}".encode())
                        self._trace(trace_id, t_ingress, dispatch_unix,
                                    last_backend, delivered, retries,
                                    failovers, "retry_exhausted",
                                    tenant, cls)
                        return
                    self._count_retry(trace_id, b)
                    self._sleep_jittered(retries)
        except OSError:
            return  # reply write failed: client is gone
        finally:
            with self._lock:
                self._streams_active -= 1
                self._set_streams_gauge()

    def _run_attempt(self, b: Backend, conn, wlock, tag: int,
                     trace_id: int, prompt: np.ndarray,
                     delivered: List[int], max_new: int,
                     eos: Optional[int], temp: float, seed: int,
                     base_offset: int,
                     tenant_cls: Optional[Tuple[str, str]] = None
                     ) -> None:
        """One backend attempt. Forwards chunks as they arrive and
        appends them to ``delivered`` (the failover resume state).
        Raises the attempt's infra/application error; returns on the
        backend's clean terminal frame. Resumption: the prompt grows
        by the delivered tokens and the sampler offset moves past
        them, so the continuation is bitwise the original."""
        from .. import inference as _inf
        remaining = max_new - len(delivered)
        if remaining <= 0:
            return
        full_prompt = np.concatenate(
            [prompt, np.asarray(delivered, np.int32)]) \
            if delivered else prompt
        offset = base_offset + len(delivered)
        b.stream_delta(+1)
        cli = None
        try:
            # fast connect failure, patient per-chunk reads: a cold
            # backend's first-request compile must not read as a wedge
            cli = _inf.Client(b.host, b.port,
                              timeout_s=self._backend_deadline_s(),
                              connect_timeout_s=_CONNECT_TIMEOUT_S,
                              deadline_s=self._backend_deadline_s(),
                              max_reconnects=0, traced=False)
            # forward the tenant descriptor only when the inbound
            # frame carried one, so tenant-less traffic stays
            # byte-identical end to end
            tkw = {} if tenant_cls is None else {
                "tenant": tenant_cls[0],
                "priority_class": tenant_cls[1]}
            for chunk in cli.generate_stream(
                    full_prompt, max_new_tokens=remaining,
                    eos_token_id=eos, temperature=temp, seed=seed,
                    trace_id=trace_id or None, sample_offset=offset,
                    **tkw):
                toks = [int(t) for t in np.asarray(chunk).reshape(-1)]
                try:
                    self._reply(conn, wlock, tag, 1,
                                _inf.encode_tensors(
                                    [np.asarray(toks, np.int32)]))
                except OSError as e:
                    raise _ClientGone() from e
                delivered.extend(toks)
        finally:
            b.stream_delta(-1)
            if cli is not None:
                cli.close()
        self.pool.note_success(b)

    # -- shed / counters / tracing ----------------------------------------

    def _shed(self, conn, wlock, tag: int, trace_id: int,
              hints: List[int], tenant: str = "") -> None:
        from . import tenancy
        hint = max(hints)
        label = tenancy.tenant_label(tenant or tenancy.DEFAULT_TENANT)
        with self._lock:
            self._counts["shed"] += 1
        from .. import observability as obs
        from ..observability import flight as _flight
        if obs.enabled():
            obs.counter("router_shed_total",
                        "streams refused at the router door because "
                        "every backend was saturated (the reply "
                        "carries the max retry_after_ms hint); "
                        "tenant= is the bounded tenant label, "
                        "default for tenant-less frames"
                        ).inc(tenant=label)
        _flight.record("router_shed", trace_id=trace_id,
                       retry_after_ms=hint, tenant=label)
        self._reply(conn, wlock, tag, -1,
                    f"router: all backends saturated: "
                    f"retry_after_ms={hint}".encode())

    def _count_retry(self, trace_id: int, b: Backend) -> None:
        with self._lock:
            self._counts["retries"] += 1
        from .. import observability as obs
        if obs.enabled():
            obs.counter("router_retries_total",
                        "zero-token requests re-sent to another "
                        "backend after a connect/deadline failure "
                        "(started streams fail over instead)").inc()

    def _count_failover(self, trace_id: int, b: Backend,
                        delivered: List[int]) -> None:
        with self._lock:
            self._counts["failovers"] += 1
        from .. import observability as obs
        from ..observability import flight as _flight
        if obs.enabled():
            obs.counter("router_failovers_total",
                        "started streams resumed on a surviving "
                        "backend after backend death (prompt+"
                        "delivered re-issued with the sample offset; "
                        "continuation is bitwise-exact)").inc()
        _flight.record("router_failover", trace_id=trace_id,
                       dead_backend=b.name, delivered=len(delivered))

    def _sleep_jittered(self, attempt: int) -> None:
        base = self._retry_backoff_s()
        if base <= 0:
            return
        span = base * (2 ** max(0, attempt - 1))
        time.sleep(span * (0.5 + random.random() / 2.0))

    # holds-lock: self._lock
    def _set_streams_gauge(self) -> None:
        from .. import observability as obs
        if obs.enabled():
            obs.gauge("router_streams_active",
                      "client streams currently held open by the "
                      "router (across all backends)"
                      ).set(self._streams_active)

    def _trace(self, trace_id: int, ingress_unix: float,
               dispatch_unix: Optional[float], backend: str,
               delivered: List[int], retries: int, failovers: int,
               outcome: str, tenant: str = "",
               cls: str = "") -> None:
        """Per-hop reqtrace span riding the client's trace id: joins
        against the backend's own span for the same id, making the
        router hop visible in tools/serving_report.py."""
        from ..observability import reqtrace as _reqtrace
        _reqtrace.record({
            "trace_id": trace_id, "kind": "router_stream",
            "ingress_unix": ingress_unix,
            "dispatch_unix": dispatch_unix,
            "reply_unix": time.time(),
            "backend": backend, "tokens": len(delivered),
            "retries": retries, "failovers": failovers,
            "outcome": outcome, "tenant": tenant, "cls": cls})

    # -- stats / snapshot -------------------------------------------------

    def _stats_text(self) -> str:
        with self._lock:
            c = dict(self._counts)
            active = self._streams_active
        lines = [
            "router.proto_version=1",
            f"router.uptime_ms={int((time.monotonic() - self._t0) * 1e3)}",
            f"router.backends={len(self.pool.backends)}",
            f"router.available={self.pool.available()}",
            f"router.streams_active={active}",
            f"router.streams_total={c['streams']}",
            f"router.proxied_total={c['proxied']}",
            f"router.failovers_total={c['failovers']}",
            f"router.retries_total={c['retries']}",
            f"router.shed_total={c['shed']}",
        ]
        for i, b in enumerate(self.pool.backends):
            lines.append(
                f"router.backend.{i}.state={STATE_CODES[b.state()]}")
        return "\n".join(lines) + "\n"

    def snapshot(self) -> Dict[str, Any]:
        """JSON-ready state for ``GET /router`` on the exporter."""
        with self._lock:
            c = dict(self._counts)
            active = self._streams_active
        return {"addr": self.addr,
                "streams_active": active,
                "streams_total": c["streams"],
                "proxied_total": c["proxied"],
                "failovers_total": c["failovers"],
                "retries_total": c["retries"],
                "shed_total": c["shed"],
                "available": self.pool.available(),
                "backends": self.pool.snapshot()}


# -- wire helpers ---------------------------------------------------------


def _is_drain_refusal(msg: str) -> bool:
    return "draining" in msg or "server stopping" in msg


def _strip_client_prefix(msg: str) -> str:
    """The backend Client wraps error payloads as
    ``server error: '<payload>'`` — unwrap so the router forwards the
    backend's payload (e.g. an AdmissionRejected message) verbatim."""
    prefix = "server error: "
    if msg.startswith(prefix):
        body = msg[len(prefix):]
        if len(body) >= 2 and body[0] == body[-1] == "'":
            return body[1:-1]
    return msg


def _recv_exact(sock: socket.socket, n: int) -> Optional[bytes]:
    """Read exactly ``n`` bytes; None on clean EOF before the first
    byte; ConnectionError on EOF mid-object."""
    if n == 0:
        return b""
    chunks: List[bytes] = []
    got = 0
    while got < n:
        part = sock.recv(min(n - got, 1 << 20))
        if not part:
            if got == 0:
                return None
            raise ConnectionError("peer closed mid-frame")
        chunks.append(part)
        got += len(part)
    return b"".join(chunks)


def _discard_exact(sock: socket.socket, n: int) -> None:
    while n > 0:
        part = sock.recv(min(n, 1 << 20))
        if not part:
            raise ConnectionError("peer closed mid-frame")
        n -= len(part)
