"""Tenant identity and SLO classes for the serving plane.

One serving fleet, many tenants: every request may carry a **tenant
id** (who pays for the tokens) and a **priority class** (what the
tenant bought). Three classes exist, ordered — ``bulk`` < ``standard``
< ``premium`` — and the whole policy layer keys off that order:

* the scheduler preempts the lowest class first and never lets a
  lower-class grower evict a higher-class resident
  (scheduler.py, "preempt-lowest-class"),
* the admission gate charges each tenant against its own KV-block
  budget (``FLAGS_tenant_kv_budget``) before the global watermark,
* the front door sheds bulk before standard before premium
  (router.py, class-aware door-shed).

Identity travels **on the wire** as one optional trailing uint8
tensor in the PTST generate body (docs/serving_protocol.md, "Tenant
descriptor"): the UTF-8 bytes ``tenant \\x00 class``. Old frames omit
it and every layer defaults to ``tenant=default / class=standard`` —
a pre-tenancy client talks to a tenancy-aware server unchanged. The
descriptor is distinguished from the optional resume-offset tensor by
dtype alone (offset: int32, descriptor: uint8), so the two optional
tails compose in any order.

Metric cardinality is bounded here, once, for every caller:
``tenant_label`` passes the first ``FLAGS_tenant_label_max`` distinct
tenant ids through verbatim and hash-buckets the rest into 16 stable
overflow labels (crc32, not Python ``hash`` — label identity must
survive interpreter restarts).
"""

from __future__ import annotations

import threading
import zlib
from typing import Dict, Optional, Tuple

import numpy as np

__all__ = ["CLASSES", "DEFAULT_TENANT", "DEFAULT_CLASS", "class_rank",
           "normalize_class", "parse_spec", "tenant_weight",
           "tenant_budget_frac", "tenant_label", "encode_descriptor",
           "decode_descriptor", "reset_labels"]

# priority classes in shed order: bulk degrades first, premium last
CLASSES = ("bulk", "standard", "premium")
_RANK = {name: i for i, name in enumerate(CLASSES)}

DEFAULT_TENANT = "default"
DEFAULT_CLASS = "standard"

# tenant ids are operator-facing strings; keep them printable and
# short so they can ride metric labels and log lines unescaped
_MAX_NAME = 64

# overflow hash buckets once FLAGS_tenant_label_max distinct tenants
# have claimed verbatim labels
_N_BUCKETS = 16

_label_lock = threading.Lock()
# tenant ids that hold a verbatim label   # guarded-by: _label_lock
_label_claimed: Dict[str, str] = {}


def normalize_class(name: Optional[str]) -> str:
    """Map any wire/API value onto a known class; unknown strings
    degrade to ``standard`` (never an error: a newer client's class
    name must not kill its request on an older server)."""
    if isinstance(name, str) and name in _RANK:
        return name
    return DEFAULT_CLASS


def class_rank(name: Optional[str]) -> int:
    """Shed/preemption order of a class: bulk=0 < standard=1 <
    premium=2. Unknown names rank as ``standard``."""
    return _RANK[normalize_class(name)]


def sanitize_tenant(name: Optional[str]) -> str:
    """Clamp a tenant id to a printable, bounded string (empty or
    non-string degrades to ``default``)."""
    if not isinstance(name, str) or not name:
        return DEFAULT_TENANT
    clean = "".join(c if c.isprintable() and c not in ",= " else "_"
                    for c in name[:_MAX_NAME])
    return clean or DEFAULT_TENANT


def parse_spec(spec: str) -> Dict[str, float]:
    """Parse a ``tenant=value,tenant=value`` flag string (weights or
    budget fractions). Malformed entries are skipped, not fatal — a
    typo in an operator flag must degrade to the default policy for
    that tenant, never take the serving loop down."""
    out: Dict[str, float] = {}
    for part in (spec or "").split(","):
        part = part.strip()
        if not part or "=" not in part:
            continue
        name, _, val = part.partition("=")
        try:
            out[sanitize_tenant(name.strip())] = float(val)
        except ValueError:
            continue
    return out


def _flag(name: str) -> str:
    try:
        from ..flags import GLOBAL_FLAGS
        return str(GLOBAL_FLAGS.get(name))
    except Exception:  # ptlint: disable=silent-failure -- flags unavailable during teardown; defaults apply
        return ""


def tenant_weight(tenant: str) -> float:
    """Fair-share weight from ``FLAGS_tenant_weights``; tenants not in
    the spec weigh 1.0. Weight 0 is legal: the tenant only runs when
    every weighted tenant is idle (the starvation floor keeps it
    progressing then)."""
    return parse_spec(_flag("tenant_weights")).get(tenant, 1.0)


def tenant_budget_frac(tenant: str) -> Optional[float]:
    """Per-tenant KV budget from ``FLAGS_tenant_kv_budget`` as a
    fraction of the pool, or None when the tenant is uncapped."""
    frac = parse_spec(_flag("tenant_kv_budget")).get(tenant)
    if frac is None:
        return None
    return min(max(frac, 0.0), 1.0)


def _label_max() -> int:
    try:
        from ..flags import GLOBAL_FLAGS
        return max(1, int(GLOBAL_FLAGS.get("tenant_label_max")))
    except Exception:  # ptlint: disable=silent-failure -- flags unavailable during teardown; defaults apply
        return 16


def tenant_label(tenant: str) -> str:
    """Bounded-cardinality metric label for a tenant id: verbatim for
    the first ``FLAGS_tenant_label_max`` distinct tenants seen by this
    process, then a stable crc32 overflow bucket. Deterministic across
    restarts for the verbatim set AND the buckets (crc32, not
    ``hash``), so dashboards keyed on the label survive redeploys."""
    tenant = sanitize_tenant(tenant)
    with _label_lock:
        got = _label_claimed.get(tenant)
        if got is not None:
            return got
        if len(_label_claimed) < _label_max():
            _label_claimed[tenant] = tenant
            return tenant
    bucket = zlib.crc32(tenant.encode("utf-8")) % _N_BUCKETS
    return f"overflow-{bucket:02d}"


def reset_labels() -> None:
    """Drop the verbatim-label claims (tests only — production label
    identity is append-only by design)."""
    with _label_lock:
        _label_claimed.clear()


# -- wire descriptor ----------------------------------------------------

def encode_descriptor(tenant: str, priority_class: str) -> np.ndarray:
    """The optional PTST trailing tensor: uint8 bytes of
    ``tenant \\x00 class``. Callers append it to the generate body's
    tensor list; absence means default/standard."""
    tenant = sanitize_tenant(tenant)
    cls = normalize_class(priority_class)
    raw = tenant.encode("utf-8") + b"\x00" + cls.encode("utf-8")
    return np.frombuffer(raw, dtype=np.uint8).copy()


def decode_descriptor(arr: np.ndarray) -> Tuple[str, str]:
    """Inverse of :func:`encode_descriptor`; anything malformed
    degrades to ``(default, standard)`` rather than failing the
    request — tenancy is routing metadata, not payload."""
    try:
        raw = bytes(np.asarray(arr, dtype=np.uint8).reshape(-1))
        tenant_b, _, cls_b = raw.partition(b"\x00")
        return (sanitize_tenant(tenant_b.decode("utf-8")),
                normalize_class(cls_b.decode("utf-8")))
    except (ValueError, UnicodeDecodeError):
        return DEFAULT_TENANT, DEFAULT_CLASS
