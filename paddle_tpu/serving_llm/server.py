"""Bridge between LLMEngine events and the streaming serving wire.

``inference.Server`` hands every 'PTST' streaming-generate request to
an :class:`LLMStreamBridge`, which owns the request's serving-side
lifecycle:

* ``admit`` parses the generate body (``<IIfI`` header —
  max_new_tokens, eos id with ``0xFFFFFFFF`` meaning none,
  temperature, seed — followed by one int32 prompt tensor in the
  standard tensor codec, plus two dtype-disambiguated optional
  tails in any order: a single-int32 resume-offset tensor for
  streams resumed after a router failover and a uint8 tenant
  descriptor carrying ``tenant \\x00 class``;
  docs/serving_protocol.md "Streaming generation", "Stream
  failover & resume" and "Tenant descriptor") and registers the
  sequence with the engine;
* ``step`` runs one engine step and turns its token events into
  status-1 reply chunks on the request's tag, the finish event into
  the terminal status-0 frame, and a failed chunk write (client gone)
  into an engine ``cancel`` that frees the sequence's KV blocks —
  the property the disconnect chaos drill asserts;
* every token is stamped into the request's span record; at terminal
  time the record (5 reqtrace stamps + ``token_unix`` list + TTFT /
  mean-TPOT) enters the /requests ring, and ``serving_ttft_ms`` /
  ``serving_tpot_ms`` histograms are observed per token.

Only the serving thread calls into a bridge, mirroring the engine's
single-owner contract.
"""

from __future__ import annotations

import struct
import time
from typing import Any, Dict, List, Optional

import numpy as np

from . import tenancy
from .engine import LLMEngine

__all__ = ["LLMStreamBridge", "GENERATE_HEADER", "EOS_NONE"]

# body header after the u64 trace id: max_new_tokens, eos_token_id
# (EOS_NONE = no eos), temperature, seed — then the tensor codec
GENERATE_HEADER = "<IIfI"
EOS_NONE = 0xFFFFFFFF


class LLMStreamBridge:
    def __init__(self, server, engine: LLMEngine):
        self.server = server
        self.engine = engine
        # seq_id -> req span
        # guarded-by: single-owner (serving thread)
        self._reqs: Dict[int, Dict[str, Any]] = {}

    def active(self) -> bool:
        return self.engine.active()

    # -- request intake ---------------------------------------------------

    def admit(self, req: Dict[str, Any]) -> None:
        """Parse one streaming-generate request and hand it to the
        engine. Malformed bodies are answered immediately with a
        terminal error frame; nothing enters the scheduler."""
        from ..inference import decode_tensors
        req["assembly_unix"] = time.time()
        req["token_unix"] = []
        req.setdefault("token_mono", [])
        try:
            buf = req["payload"]
            hdr = struct.calcsize(GENERATE_HEADER)
            if len(buf) < hdr:
                raise ValueError("generate body shorter than header")
            max_new, eos_raw, temperature, seed = struct.unpack_from(
                GENERATE_HEADER, buf, 0)
            arrs = decode_tensors(buf[hdr:])
            if not arrs or arrs[0].ndim != 1 \
                    or arrs[0].dtype != np.int32:
                raise ValueError(
                    "generate body must carry an int32 [T] prompt "
                    "tensor first")
            sample_offset = 0
            offset_seen = descriptor_seen = False
            tenant, cls = (tenancy.DEFAULT_TENANT,
                           tenancy.DEFAULT_CLASS)
            # the two optional tails are disambiguated by dtype and
            # compose in any order: int32 [1] resume offset ("Stream
            # failover & resume"), uint8 tenant descriptor ("Tenant
            # descriptor"); old frames carry neither
            for arr in arrs[1:]:
                if arr.dtype == np.int32 and arr.size == 1 \
                        and not offset_seen:
                    # resumed stream: the prompt already carries the
                    # delivered tokens; this shifts the position-keyed
                    # sampler past them
                    sample_offset = int(arr.reshape(-1)[0])
                    offset_seen = True
                elif arr.dtype == np.uint8 and not descriptor_seen:
                    tenant, cls = tenancy.decode_descriptor(arr)
                    descriptor_seen = True
                else:
                    raise ValueError(
                        "generate body must carry one prompt tensor "
                        "plus at most one resume-offset tensor "
                        "(int32 [1]) and one tenant descriptor "
                        "(uint8)")
            req["tenant"], req["class"] = tenant, cls
            seq_id = self.engine.add_request(
                arrs[0], max_new_tokens=max_new,
                eos_token_id=None if eos_raw == EOS_NONE else int(eos_raw),
                temperature=temperature, seed=seed,
                trace_id=req.get("trace_id") or 0,
                sample_offset=sample_offset,
                tenant=tenant, priority_class=cls)
        except Exception as e:  # noqa: BLE001 — fail ONE request
            from .engine import AdmissionRejected
            outcome = "admission_rejected" \
                if isinstance(e, AdmissionRejected) else "decode_error"
            # AdmissionRejected's message carries the retry-after hint
            # (retry_after_ms=N) — it ships verbatim in the payload
            self.server.transport.reply_chunk(
                req["rid"], str(e).encode(), status=-1, final=True)
            self._record(req, status=-1, outcome=outcome,
                         error=str(e)[:200])
            return
        # the join key both ways: /requests records carry seq_id, and
        # the engine timeline at /llm/seqs carries this trace_id
        req["seq_id"] = seq_id
        self._reqs[seq_id] = req
        from .. import observability as obs
        if obs.enabled():
            obs.counter("serving_stream_requests_total",
                        "streaming generate (PTST) requests admitted "
                        "to the LLM engine").inc()

    # -- one serving step -------------------------------------------------

    def step(self) -> None:
        """One engine step; fan its events out to the wire. Waiting
        sequences past the queue deadline are shed first — a stream
        that never reached prefill is refused exactly like an aged
        tensor request (requests_shed_total{kind=stream})."""
        from ..inference import encode_tensors
        from ..testing import faults as _faults
        self._shed_expired()
        for ev in self.engine.step():
            req = self._reqs.get(ev["seq_id"])
            if req is None:
                continue  # cancelled earlier this step
            if ev["type"] == "token":
                req.setdefault("dispatch_unix", ev["dispatch_unix"])
                now = time.time()
                now_mono = time.monotonic()
                try:
                    _faults.hit("llm_chunk_write")
                    rc = self.server.transport.reply_chunk(
                        req["rid"],
                        encode_tensors([np.asarray([ev["token"]],
                                                   np.int32)]),
                        status=1, final=False)
                except Exception:  # noqa: BLE001 — treat as client gone
                    rc = -3
                if rc != 0:
                    self._cancel(ev["seq_id"], req, now)
                    continue
                self._note_token(req, now, now_mono)
            elif ev["type"] == "finished":
                self.server.transport.reply_chunk(
                    req["rid"], b"", status=0, final=True)
                del self._reqs[ev["seq_id"]]
                self._record(req, status=0, outcome="ok",
                             reason=ev["reason"])
            elif ev["type"] == "error":
                self.server.transport.reply_chunk(
                    req["rid"], ev["error"].encode(), status=-1,
                    final=True)
                del self._reqs[ev["seq_id"]]
                from .. import observability as obs
                if obs.enabled():
                    obs.counter(
                        "serving_stream_errors_total",
                        "admitted streams terminated by an engine "
                        "execute error (a bad event in the "
                        "serving_availability SLO)").inc()
                self._record(req, status=-1, outcome="execute_error",
                             error=ev["error"][:200])

    def _shed_expired(self) -> None:
        """Queue-deadline shedding for streams that have not started:
        a sequence still waiting for prefill (no tokens generated,
        never preempted) older than FLAGS_serving_queue_deadline_ms is
        cancelled and answered with a terminal shed frame. Sequences
        that already streamed tokens are never shed — ending those is
        a cancel or a drain, not a shed."""
        ddl = self.server._queue_deadline_s()
        if ddl <= 0:
            return
        now = time.time()
        now_mono = time.monotonic()
        for seq in list(self.engine.scheduler.waiting):
            req = self._reqs.get(seq.seq_id)
            if req is None or seq.generated or seq.preemptions:
                continue
            mono0 = req.get("dequeue_mono")
            if mono0 is not None:
                age = now_mono - mono0
            else:
                # ptlint: disable=clock-hygiene -- fallback for spans injected without a dequeue_mono stamp (tests); production requests are stamped in _mk_req
                age = now - (req.get("dequeue_unix") or now)
            if age > ddl:
                self.engine.cancel(seq.seq_id, outcome="shed")
                self._reqs.pop(seq.seq_id, None)
                self.server._shed(req, age, ddl)

    def _note_token(self, req: Dict[str, Any], now: float,
                    now_mono: float) -> None:
        stamps: List[float] = req["token_unix"]
        mono: List[float] = req.setdefault("token_mono", [])
        from .. import observability as obs
        if obs.enabled():
            from ..observability import metrics as _m
            obs.counter("serving_stream_tokens_total",
                        "tokens streamed to clients as status-1 "
                        "chunks").inc()
            if not stamps and req.get("ingress_unix") is not None:
                obs.histogram(
                    "serving_ttft_ms",
                    "time to first token: request ingress to first "
                    "streamed chunk",
                    buckets=_m.LATENCY_MS_BUCKETS).observe(
                        # ptlint: disable=clock-hygiene -- ingress_unix is the C++ wire-ingress wall stamp; TTFT necessarily crosses the process boundary
                        max(0.0, (now - req["ingress_unix"]) * 1e3))
            elif stamps and mono:
                obs.histogram(
                    "serving_tpot_ms",
                    "time per output token: gap between consecutive "
                    "streamed chunks of one request",
                    buckets=_m.LATENCY_MS_BUCKETS).observe(
                        max(0.0, (now_mono - mono[-1]) * 1e3))
        stamps.append(now)
        mono.append(now_mono)

    def _cancel(self, seq_id: int, req: Dict[str, Any],
                now: float) -> None:
        """Chunk write failed (client gone): drop the sequence so its
        KV blocks return to the pool. NOT a shed — the request was
        being served; requests_shed_total stays untouched."""
        self.engine.cancel(seq_id)
        self._reqs.pop(seq_id, None)
        from ..observability import flight as _flight
        _flight.record("serving_stream_cancelled", force=True,
                       trace_id=req.get("trace_id"), seq_id=seq_id,
                       tokens_streamed=len(req["token_unix"]))
        from .. import observability as obs
        if obs.enabled():
            obs.counter("serving_stream_cancelled_total",
                        "streaming requests cancelled mid-generation "
                        "because the client connection died (KV "
                        "blocks freed)").inc()
        self._record(req, status=-3, outcome="cancelled",
                     reply_unix=now)

    def close(self, message: bytes = b"server stopping",
              outcome: str = "server_stop") -> None:
        """Terminal sweep (server stop, or drain deadline expiry):
        every still-open stream gets a terminal negative-status frame
        BEFORE its sequence is cancelled and the socket goes away —
        clients see an explicit error, never a bare TCP reset."""
        for seq_id, req in list(self._reqs.items()):
            try:
                self.server.transport.reply_chunk(
                    req["rid"], message, status=-1, final=True)
            # ptlint: disable=silent-failure -- terminal sweep: the client is likely already gone; the record below still logs the outcome
            except Exception:  # noqa: BLE001 — client may be gone
                pass
            self.engine.cancel(seq_id)
            self._record(req, status=-1, outcome=outcome)
        self._reqs.clear()

    # -- span records -----------------------------------------------------

    def _record(self, req: Dict[str, Any], status: int, outcome: str,
                reply_unix: Optional[float] = None,
                reason: Optional[str] = None,
                error: Optional[str] = None) -> None:
        """Terminal span record for one streaming request: the 5
        reqtrace stamps plus the per-token timeline and derived
        TTFT / mean-TPOT. Never raises."""
        from .. import observability as obs
        if not obs.enabled():
            return
        try:
            from ..observability import reqtrace as _reqtrace
            toks: List[float] = req.get("token_unix") or []
            rec = {"trace_id": req.get("trace_id") or 0,
                   "req_id": req.get("rid"),
                   "seq_id": req.get("seq_id"),
                   "status": status, "outcome": outcome,
                   "stream": True,
                   "ingress_unix": req.get("ingress_unix"),
                   "dequeue_unix": req.get("dequeue_unix"),
                   "assembly_unix": req.get("assembly_unix"),
                   "dispatch_unix": req.get("dispatch_unix"),
                   "reply_unix": reply_unix
                   if reply_unix is not None else time.time(),
                   "token_unix": list(toks),
                   "tokens": len(toks)}
            if reason is not None:
                rec["finish_reason"] = reason
            if error is not None:
                rec["error"] = error
            if "tenant" in req:  # per-tenant gap attribution
                rec["tenant"] = req["tenant"]
                rec["cls"] = req.get("class")
            ing = rec["ingress_unix"]
            if toks and ing is not None:
                rec["ttft_ms"] = max(0.0, (toks[0] - ing) * 1e3)
            if len(toks) > 1:
                rec["tpot_ms"] = (toks[-1] - toks[0]) * 1e3 \
                    / (len(toks) - 1)
            if ing is not None:
                rec["e2e_ms"] = max(0.0,
                                    (rec["reply_unix"] - ing) * 1e3)
            _reqtrace.record(rec)
        # ptlint: disable=silent-failure -- span records are best-effort by contract: a reply must never fail on telemetry
        except Exception:  # noqa: BLE001 — never fail a reply on spans
            pass
