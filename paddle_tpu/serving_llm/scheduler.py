"""Continuous-batching scheduler for the LLM serving engine.

Sequences JOIN and LEAVE the decode batch every step instead of
waiting for a static batch to drain (the reference's serving stack
behavior this rebuilds; PAPERS.md arxiv 2605.25645 describes the
fleet-scale lifecycle on TPU). Policy, deliberately simple and fully
tested:

* **Admission** is FCFS off the waiting queue: a prefill is admitted
  when the running set is below ``FLAGS_max_decode_batch`` AND the
  paged allocator can cover its whole prompt (plus any tokens
  generated before a preemption). A short prompt arriving mid-decode
  of a long one is therefore in the batch on the very next step —
  the interleaving property the tests assert. Under
  ``FLAGS_kv_prefix_sharing`` the allocator satisfies the already-
  resident prefix by refcount bumps, so admission passes the token
  timeline and records the shared-token count on the sequence
  (prefill resumes from there).
* **Growth** happens one token per decode step. When the pool is
  exhausted the scheduler preempts the YOUNGEST running sequence
  (LIFO): its blocks are freed and it returns to the FRONT of the
  waiting queue to be re-prefilled later (recompute-on-readmit, the
  vLLM recovery model — generated tokens are kept, only the cache is
  recomputed). Oldest work is protected, so progress is monotone and
  a sequence that fits alone can never starve.
"""

from __future__ import annotations

from collections import deque
from dataclasses import dataclass, field
from typing import Deque, Dict, List, Optional

from .kv_cache import KVBlockAllocator

__all__ = ["Sequence", "ContinuousBatchingScheduler"]


@dataclass
class Sequence:
    """One generate request's decoding state. ``prompt`` is the token
    id list; ``generated`` accumulates sampled ids (kept across
    preemptions); ``ctx_len`` counts tokens whose K/V currently sit in
    the pool (0 while waiting). ``cached_tokens`` is the leading-token
    count satisfied by prefix sharing at admission — prefill starts
    there instead of position 0. ``prefill_done`` flips when the last
    prefill chunk lands; only then does the sequence join the decode
    batch (chunked prefill advances one chunk per step).
    ``sample_offset`` shifts the position-keyed sampler: a stream
    resumed after a router failover re-sends prompt+delivered as the
    prompt and sets this to the delivered count, so token ``i`` of the
    resumed stream draws the RNG key of generated-index ``offset + i``
    — bitwise the token the dead backend would have produced next
    (docs/serving_protocol.md, "Stream failover & resume")."""
    seq_id: int
    prompt: List[int]
    max_new_tokens: int = 16
    eos_token_id: Optional[int] = None
    temperature: float = 0.0
    seed: int = 0
    sample_offset: int = 0
    generated: List[int] = field(default_factory=list)
    ctx_len: int = 0
    cached_tokens: int = 0
    prefill_done: bool = False
    admit_order: int = -1   # admission stamp; youngest = max
    preemptions: int = 0
    dispatch_unix: Optional[float] = None  # first prefill wall time

    @property
    def total_tokens(self) -> int:
        """Tokens the cache must cover for a (re-)prefill: prompt
        plus everything generated before any preemption reset."""
        return len(self.prompt) + len(self.generated)


class ContinuousBatchingScheduler:
    def __init__(self, allocator: KVBlockAllocator,
                 max_decode_batch: Optional[int] = None):
        self.allocator = allocator
        self._max_decode_batch = max_decode_batch
        self.waiting: Deque[Sequence] = deque()
        self.running: List[Sequence] = []
        self._admit_n = 0
        self.preemptions_total = 0

    def max_decode_batch(self) -> int:
        if self._max_decode_batch is not None:
            return int(self._max_decode_batch)
        from ..flags import GLOBAL_FLAGS
        return max(1, int(GLOBAL_FLAGS.get("max_decode_batch")))

    # -- lifecycle --------------------------------------------------------

    def add(self, seq: Sequence) -> None:
        self.waiting.append(seq)

    def admit(self) -> List[Sequence]:
        """FCFS admission pass: move waiting sequences into the
        running set while there is batch room and the pool covers
        their prefill (+1 headroom is NOT reserved — growth is handled
        per-step with preemption as the backstop). Returns the newly
        admitted sequences, which the engine must prefill."""
        admitted: List[Sequence] = []
        cap = self.max_decode_batch()
        while self.waiting and len(self.running) < cap:
            seq = self.waiting[0]
            tokens = seq.prompt + seq.generated
            if not self.allocator.allocate(seq.seq_id, len(tokens),
                                           tokens=tokens):
                break  # FCFS: never skip the queue head
            self.waiting.popleft()
            # the shared prefix (if any) is already resident: prefill
            # starts at cached_tokens instead of position 0
            seq.cached_tokens = self.allocator.shared_tokens(seq.seq_id)
            seq.ctx_len = seq.cached_tokens
            seq.prefill_done = False
            self._admit_n += 1
            seq.admit_order = self._admit_n
            self.running.append(seq)
            admitted.append(seq)
        return admitted

    def grow(self, seq: Sequence, n_tokens: int) -> bool:
        """Extend ``seq``'s cache to ``n_tokens`` slots, preempting
        YOUNGER running sequences one at a time if the pool is short.
        False only when the pool cannot cover it even with ``seq``
        alone (caller should fail the request: it can never fit)."""
        while True:
            if self.allocator.extend_to(seq.seq_id, n_tokens):
                return True
            victim = self._youngest(exclude=seq)
            if victim is None:
                return False
            self.preempt(victim)

    def make_writable(self, seq: Sequence, block_idx: int):
        """Copy-on-write backstop: make the block at ``seq``'s table
        position ``block_idx`` private, preempting YOUNGER running
        sequences one at a time if the pool cannot supply the copy
        target. Returns what allocator.make_private returns — None
        (already private), an (old, new) pair the engine must copy
        in-pool, or False when it can never fit. Preempting the very
        sequence the block is shared with drops its refcount to 1, so
        the retry then needs no copy at all."""
        while True:
            r = self.allocator.make_private(seq.seq_id, block_idx)
            if r is not False:
                return r
            victim = self._youngest(exclude=seq)
            if victim is None:
                return False
            self.preempt(victim)

    def _youngest(self, exclude: Sequence) -> Optional[Sequence]:
        cands = [s for s in self.running if s is not exclude]
        return max(cands, key=lambda s: s.admit_order) if cands else None

    def preempt(self, seq: Sequence) -> None:
        """Evict ``seq`` from the running set back to the FRONT of the
        waiting queue: blocks freed, generated tokens kept, cache
        recomputed at readmission."""
        self.allocator.free(seq.seq_id)
        self.running.remove(seq)
        seq.ctx_len = 0
        seq.cached_tokens = 0
        seq.prefill_done = False
        seq.preemptions += 1
        self.preemptions_total += 1
        self.waiting.appendleft(seq)
        from .. import observability as obs
        from ..observability import seqtrace as _seqtrace
        _seqtrace.event(seq.seq_id, "preempted",
                        preemptions=seq.preemptions,
                        tokens=len(seq.generated))
        if obs.enabled():
            obs.counter("kv_blocks_preempted_total",
                        "running sequences preempted back to the "
                        "waiting queue because the KV pool was "
                        "exhausted (recompute-on-readmit)").inc()

    def finish(self, seq: Sequence) -> None:
        self.allocator.free(seq.seq_id)
        if seq in self.running:
            self.running.remove(seq)

    def cancel(self, seq_id: int) -> Optional[Sequence]:
        """Remove a sequence wherever it lives (client disconnect).
        Frees its blocks; returns the sequence or None if unknown."""
        for seq in list(self.running):
            if seq.seq_id == seq_id:
                self.allocator.free(seq_id)
                self.running.remove(seq)
                return seq
        for seq in list(self.waiting):
            if seq.seq_id == seq_id:
                self.allocator.free(seq_id)  # no-op: waiting holds none
                self.waiting.remove(seq)
                return seq
        return None

    def active(self) -> bool:
        return bool(self.waiting or self.running)
