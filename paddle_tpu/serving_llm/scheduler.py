"""Continuous-batching scheduler for the LLM serving engine.

Sequences JOIN and LEAVE the decode batch every step instead of
waiting for a static batch to drain (the reference's serving stack
behavior this rebuilds; PAPERS.md arxiv 2605.25645 describes the
fleet-scale lifecycle on TPU). Policy, deliberately simple and fully
tested:

* **Admission** is FCFS off the waiting queue: a prefill is admitted
  when the running set is below ``FLAGS_max_decode_batch`` AND the
  paged allocator can cover its whole prompt (plus any tokens
  generated before a preemption). A short prompt arriving mid-decode
  of a long one is therefore in the batch on the very next step —
  the interleaving property the tests assert. Under
  ``FLAGS_kv_prefix_sharing`` the allocator satisfies the already-
  resident prefix by refcount bumps, so admission passes the token
  timeline and records the shared-token count on the sequence
  (prefill resumes from there).
* **Growth** happens one token per decode step. When the pool is
  exhausted the scheduler preempts a victim in a TOTAL order:
  lowest priority class first, youngest (max admission seq) within a
  class — with every sequence at the default class this is exactly
  preempt-youngest (LIFO), and the total order makes tie-breaks
  deterministic across runs. The victim's blocks are freed and it
  returns to the FRONT of the waiting queue to be re-prefilled later
  (recompute-on-readmit, the vLLM recovery model — generated tokens
  are kept, only the cache is recomputed). Oldest work is protected,
  so progress is monotone and a sequence that fits alone can never
  starve. A grower never evicts a sequence of a HIGHER class than
  its own: when only higher-class victims remain it preempts itself
  back to the queue instead (a bulk stream can stall under premium
  load; a premium stream never loses blocks to bulk).
* **Fair share** (``FLAGS_tenant_fair_share``): admission stops
  being globally FCFS and becomes weighted fair queueing over the
  per-tenant queue heads — each slot goes to the tenant with the
  lowest weight-normalized token-second service, FCFS *within* the
  tenant (tenancy.py). A tenant whose head cannot allocate is set
  aside for the pass and the next-best tenant is tried, so a bulk
  prompt too big for the current pool never head-of-line-blocks
  premium admission.
"""

from __future__ import annotations

from collections import deque
from dataclasses import dataclass, field
from typing import Deque, Dict, List, Optional

from . import tenancy
from .kv_cache import KVBlockAllocator

__all__ = ["Sequence", "ContinuousBatchingScheduler"]


@dataclass
class Sequence:
    """One generate request's decoding state. ``prompt`` is the token
    id list; ``generated`` accumulates sampled ids (kept across
    preemptions); ``ctx_len`` counts tokens whose K/V currently sit in
    the pool (0 while waiting). ``cached_tokens`` is the leading-token
    count satisfied by prefix sharing at admission — prefill starts
    there instead of position 0. ``prefill_done`` flips when the last
    prefill chunk lands; only then does the sequence join the decode
    batch (chunked prefill advances one chunk per step).
    ``sample_offset`` shifts the position-keyed sampler: a stream
    resumed after a router failover re-sends prompt+delivered as the
    prompt and sets this to the delivered count, so token ``i`` of the
    resumed stream draws the RNG key of generated-index ``offset + i``
    — bitwise the token the dead backend would have produced next
    (docs/serving_protocol.md, "Stream failover & resume").
    ``tenant``/``priority_class`` are the wire identity (tenancy.py):
    fair-share accounting keys on the tenant, victim selection and
    shed order key on the class."""
    seq_id: int
    prompt: List[int]
    max_new_tokens: int = 16
    eos_token_id: Optional[int] = None
    temperature: float = 0.0
    seed: int = 0
    sample_offset: int = 0
    tenant: str = tenancy.DEFAULT_TENANT
    priority_class: str = tenancy.DEFAULT_CLASS
    generated: List[int] = field(default_factory=list)
    ctx_len: int = 0
    cached_tokens: int = 0
    prefill_done: bool = False
    admit_order: int = -1   # admission stamp; youngest = max
    preemptions: int = 0
    dispatch_unix: Optional[float] = None  # first prefill wall time

    @property
    def total_tokens(self) -> int:
        """Tokens the cache must cover for a (re-)prefill: prompt
        plus everything generated before any preemption reset."""
        return len(self.prompt) + len(self.generated)

    @property
    def class_rank(self) -> int:
        """Preemption/shed order of this sequence's priority class
        (bulk=0 < standard=1 < premium=2)."""
        return tenancy.class_rank(self.priority_class)


class ContinuousBatchingScheduler:
    def __init__(self, allocator: KVBlockAllocator,
                 max_decode_batch: Optional[int] = None):
        self.allocator = allocator
        self._max_decode_batch = max_decode_batch
        self.waiting: Deque[Sequence] = deque()
        self.running: List[Sequence] = []
        self._admit_n = 0
        self.preemptions_total = 0
        # cumulative token-second service per tenant (resident
        # context-length x wall-seconds, charged by the engine step);
        # single-threaded with the engine step loop like every other
        # scheduler field
        self._service: Dict[str, float] = {}
        # monotonic WFQ virtual clock: tracks the lowest weight-
        # normalized service among running tenants as they charge.
        # Idle tenants re-enter floored to it, so a tenant that ran
        # alone earlier doesn't carry "debt" into a later contention
        # (and an idle one doesn't bank credit)
        self._vclock = 0.0

    def max_decode_batch(self) -> int:
        if self._max_decode_batch is not None:
            return int(self._max_decode_batch)
        from ..flags import GLOBAL_FLAGS
        return max(1, int(GLOBAL_FLAGS.get("max_decode_batch")))

    # -- lifecycle --------------------------------------------------------

    def add(self, seq: Sequence) -> None:
        if self._fair_share_on():
            self._floor_service(seq.tenant)
        self.waiting.append(seq)

    def admit(self) -> List[Sequence]:
        """Admission pass: move waiting sequences into the running set
        while there is batch room and the pool covers their prefill
        (+1 headroom is NOT reserved — growth is handled per-step with
        preemption as the backstop). FCFS off the queue by default;
        under ``FLAGS_tenant_fair_share`` each slot goes to the head
        of the least-served tenant queue instead (FCFS within a
        tenant), with allocation-blocked tenants set aside for the
        pass. Returns the newly admitted sequences, which the engine
        must prefill."""
        admitted: List[Sequence] = []
        cap = self.max_decode_batch()
        fair = self._fair_share_on()
        blocked: set = set()  # tenants whose head cannot allocate
        while self.waiting and len(self.running) < cap:
            seq = (self._pick_fair(blocked) if fair
                   else self.waiting[0])
            if seq is None:
                break  # every tenant head is allocation-blocked
            tokens = seq.prompt + seq.generated
            if not self.allocator.allocate(seq.seq_id, len(tokens),
                                           tokens=tokens):
                if not fair:
                    break  # FCFS: never skip the queue head
                # fair share: this tenant's head stays the head (no
                # within-tenant skip) but other tenants may still fit
                blocked.add(seq.tenant)
                continue
            self.waiting.remove(seq)
            # the shared prefix (if any) is already resident: prefill
            # starts at cached_tokens instead of position 0
            seq.cached_tokens = self.allocator.shared_tokens(seq.seq_id)
            seq.ctx_len = seq.cached_tokens
            seq.prefill_done = False
            self._admit_n += 1
            seq.admit_order = self._admit_n
            self.running.append(seq)
            admitted.append(seq)
        return admitted

    @staticmethod
    def _fair_share_on() -> bool:
        try:
            from ..flags import GLOBAL_FLAGS
            return bool(GLOBAL_FLAGS.get("tenant_fair_share"))
        # ptlint: disable=silent-failure -- flag may not be defined under direct submodule import; fair share stays off
        except Exception:  # noqa: BLE001
            return False

    def _pick_fair(self, blocked: set) -> Optional[Sequence]:
        """Weighted fair queueing over the per-tenant queue heads:
        the first waiting sequence of the tenant with the lowest
        weight-normalized token-second service wins; queue position
        breaks ties (equal-service tenants admit FCFS, so a single
        tenant under fair share behaves exactly like FCFS). Weight
        <= 0 sorts last but still admits when nothing weighted wants
        the slot — the starvation floor."""
        best = None
        best_key = None
        seen: set = set()
        for pos, seq in enumerate(self.waiting):
            t = seq.tenant
            if t in seen or t in blocked:
                continue
            seen.add(t)
            w = tenancy.tenant_weight(t)
            norm = (self._service.get(t, 0.0) / w) if w > 0 \
                else float("inf")
            key = (norm, pos)
            if best_key is None or key < best_key:
                best, best_key = seq, key
        return best

    def _floor_service(self, tenant: str) -> None:
        """Idle-tenant re-entry floor, applied when a tenant ARRIVES
        into a new backlogged period (no waiting or running work):
        its service is lifted to the virtual clock so idle time never
        converts into a catch-up monopoly, and a tenant that ran
        alone earlier doesn't drag catch-up debt into a later
        contention (the WFQ virtual-start-time rule:
        start = max(own finish, virtual now)). A tenant with work in
        the system keeps its raw ledger — flooring mid-backlog would
        erase the weight differentiation fair share exists for."""
        if any(s.tenant == tenant for s in self.running) or \
                any(s.tenant == tenant for s in self.waiting):
            return
        w = tenancy.tenant_weight(tenant)
        if w > 0:
            self._service[tenant] = max(
                self._service.get(tenant, 0.0), self._vclock * w)

    def charge(self, dt_s: float) -> None:
        """Accrue token-second service: each resident sequence
        charges its tenant ctx_len x dt. Called once per engine step
        with the measured step duration. Advances the virtual clock
        to the lowest normalized service among the tenants that just
        charged (virtual time moves at the pace of the most-starved
        backlogged flow)."""
        if dt_s <= 0:
            return
        for s in self.running:
            if s.ctx_len > 0:
                self._service[s.tenant] = (
                    self._service.get(s.tenant, 0.0)
                    + s.ctx_len * dt_s)
        norms = []
        for t in {s.tenant for s in self.running}:
            w = tenancy.tenant_weight(t)
            if w > 0:
                norms.append(self._service.get(t, 0.0) / w)
        if norms:
            self._vclock = max(self._vclock, min(norms))

    def service_snapshot(self) -> Dict[str, float]:
        """Per-tenant cumulative token-seconds (fair-share ledger)."""
        return dict(self._service)

    def grow(self, seq: Sequence, n_tokens: int) -> bool:
        """Extend ``seq``'s cache to ``n_tokens`` slots, preempting
        victims one at a time — lowest class first, youngest within a
        class, never a class above ``seq``'s own — if the pool is
        short. When only higher-class victims remain, ``seq`` preempts
        ITSELF back to the waiting queue (check membership after a
        False). False with ``seq`` still running only when the pool
        cannot cover it even with ``seq`` alone (caller should fail
        the request: it can never fit)."""
        while True:
            if self.allocator.extend_to(seq.seq_id, n_tokens):
                return True
            victim = self._victim(exclude=seq)
            if victim is None:
                if any(s is not seq for s in self.running):
                    # residents it may not touch hold the pool: yield
                    # rather than die — readmission recomputes
                    self.preempt(seq)
                return False
            self.preempt(victim)

    def make_writable(self, seq: Sequence, block_idx: int):
        """Copy-on-write backstop: make the block at ``seq``'s table
        position ``block_idx`` private, preempting victims (same
        total order and class gate as ``grow``) if the pool cannot
        supply the copy target. Returns what allocator.make_private
        returns — None (already private), an (old, new) pair the
        engine must copy in-pool, or False when it can never fit;
        as in ``grow``, a False with ``seq`` gone from the running
        set means it preempted itself and will retry after
        readmission. Preempting the very sequence the block is
        shared with drops its refcount to 1, so the retry then needs
        no copy at all."""
        while True:
            r = self.allocator.make_private(seq.seq_id, block_idx)
            if r is not False:
                return r
            victim = self._victim(exclude=seq)
            if victim is None:
                if any(s is not seq for s in self.running):
                    self.preempt(seq)
                return False
            self.preempt(victim)

    def _victim(self, exclude: Sequence) -> Optional[Sequence]:
        """Preemption victim in a TOTAL order: (class rank asc,
        admission seq desc) — deterministic where preempt-youngest
        tied on dict order — restricted to classes at or below the
        grower's (bulk pressure must never evict premium blocks).
        With every sequence at the default class this is exactly
        preempt-youngest."""
        cap = exclude.class_rank
        cands = [s for s in self.running
                 if s is not exclude and s.class_rank <= cap]
        if not cands:
            return None
        return min(cands,
                   key=lambda s: (s.class_rank, -s.admit_order))

    def preempt(self, seq: Sequence) -> None:
        """Evict ``seq`` from the running set back to the FRONT of the
        waiting queue: blocks freed, generated tokens kept, cache
        recomputed at readmission."""
        self.allocator.free(seq.seq_id)
        self.running.remove(seq)
        seq.ctx_len = 0
        seq.cached_tokens = 0
        seq.prefill_done = False
        seq.preemptions += 1
        self.preemptions_total += 1
        self.waiting.appendleft(seq)
        from .. import observability as obs
        from ..observability import seqtrace as _seqtrace
        _seqtrace.event(seq.seq_id, "preempted",
                        preemptions=seq.preemptions,
                        tokens=len(seq.generated),
                        tenant=seq.tenant, cls=seq.priority_class)
        if obs.enabled():
            obs.counter("kv_blocks_preempted_total",
                        "running sequences preempted back to the "
                        "waiting queue because the KV pool was "
                        "exhausted (recompute-on-readmit), by "
                        "priority class — {class=premium} staying at "
                        "zero under bulk load is the tenant-isolation "
                        "contract (docs/fault_tolerance.md, 'Tenant "
                        "isolation')").inc(
                            **{"class": seq.priority_class})

    def finish(self, seq: Sequence) -> None:
        self.allocator.free(seq.seq_id)
        if seq in self.running:
            self.running.remove(seq)

    def cancel(self, seq_id: int) -> Optional[Sequence]:
        """Remove a sequence wherever it lives (client disconnect).
        Frees its blocks; returns the sequence or None if unknown."""
        for seq in list(self.running):
            if seq.seq_id == seq_id:
                self.allocator.free(seq_id)
                self.running.remove(seq)
                return seq
        for seq in list(self.waiting):
            if seq.seq_id == seq_id:
                self.allocator.free(seq_id)  # no-op: waiting holds none
                self.waiting.remove(seq)
                return seq
        return None

    def active(self) -> bool:
        return bool(self.waiting or self.running)
