"""LLM serving subsystem: paged KV cache, continuous batching, and
streaming token generation.

Layering (each piece is independently testable):

* :mod:`.kv_cache` — ``KVBlockAllocator``: fixed-size token blocks in
  a preallocated pool, per-sequence block tables, free-list with
  alloc/eviction accounting.
* :mod:`.scheduler` — ``ContinuousBatchingScheduler``: FCFS admission
  of prefills into the running decode batch, youngest-first
  preemption (recompute-on-readmit) when the pool runs dry.
* :mod:`.engine` — ``LLMEngine``: owns the per-layer K/V pools,
  prefills via a dense causal forward that scatters into the pool,
  decodes via the Pallas ragged paged attention kernel, emits token
  events.
* :mod:`.server` — ``LLMStreamBridge``: glues engine events to
  ``inference.Server``'s streaming (PTST) reply frames, TTFT/TPOT
  histograms, and the reqtrace ring.
* :mod:`.router` — ``Router``: stdlib front-door over N backends —
  health-gated rotation with per-backend circuit breakers,
  deterministic mid-stream failover (resume via the sample offset),
  retry/shed discipline, and a ``GET /router`` exporter snapshot.
"""

from .kv_cache import KVBlockAllocator
from .scheduler import ContinuousBatchingScheduler, Sequence
from .engine import AdmissionRejected, LLMEngine, health_snapshot
from .server import LLMStreamBridge
from .router import Backend, BackendPool, CircuitBreaker, Router

__all__ = ["KVBlockAllocator", "ContinuousBatchingScheduler",
           "Sequence", "LLMEngine", "LLMStreamBridge",
           "AdmissionRejected", "health_snapshot",
           "Backend", "BackendPool", "CircuitBreaker", "Router"]
