"""Paged KV-cache block allocator.

vLLM-style cache management for the LLM serving subsystem: KV memory
is a preallocated pool of fixed-size token blocks
(``FLAGS_kv_pool_blocks`` blocks of ``FLAGS_kv_block_size`` token
slots), and each running sequence owns a BLOCK TABLE — an ordered list
of pool indices — instead of a contiguous [T_max] cache slab. The
allocator is pure bookkeeping over block INDICES; the tensors
themselves live in LLMEngine's per-layer pools, and the ragged paged
attention kernel consumes the tables directly
(kernels/paged_attention.py).

Accounting is load-bearing, not decorative: the chaos disconnect
drill asserts zero leaked blocks through the ``kv_blocks_used``/
``kv_blocks_free`` gauges, and the scheduler's preemption decisions
read ``num_free``. Single-owner object (the engine's serving thread);
no internal locking.
"""

from __future__ import annotations

import itertools
from typing import Dict, List, Optional

__all__ = ["KVBlockAllocator"]

# last allocator to publish the kv_blocks_* gauges (engines audit
# gauge-vs-allocator agreement only when their own allocator wrote the
# gauge last — several engines in one test process share the registry)
_pub_tokens = itertools.count(1)
_last_pub_token: Optional[int] = None


class KVBlockAllocator:
    def __init__(self, num_blocks: int, block_size: int):
        if num_blocks <= 0 or block_size <= 0:
            raise ValueError("num_blocks and block_size must be >= 1")
        self.num_blocks = int(num_blocks)
        self.block_size = int(block_size)
        # LIFO free list: recently-freed blocks are re-issued first,
        # which keeps the hot pool region small
        self._free: List[int] = list(range(self.num_blocks - 1, -1, -1))
        self._tables: Dict[int, List[int]] = {}
        self._tokens: Dict[int, int] = {}
        self.allocs_total = 0
        self.freed_total = 0
        self.alloc_failures_total = 0
        self._pub_token = next(_pub_tokens)
        self._publish()

    # -- queries ----------------------------------------------------------

    @property
    def num_free(self) -> int:
        return len(self._free)

    @property
    def num_used(self) -> int:
        return self.num_blocks - len(self._free)

    def blocks_for(self, n_tokens: int) -> int:
        """Blocks needed to hold ``n_tokens`` token slots."""
        return -(-max(0, int(n_tokens)) // self.block_size)

    def table(self, seq_id: int) -> List[int]:
        return list(self._tables.get(seq_id, ()))

    def tokens(self, seq_id: int) -> int:
        return self._tokens.get(seq_id, 0)

    def owners(self) -> List[int]:
        return list(self._tables.keys())

    # -- mutations --------------------------------------------------------

    def allocate(self, seq_id: int, n_tokens: int) -> bool:
        """Give ``seq_id`` (no existing table) blocks for ``n_tokens``
        token slots. All-or-nothing: on a short pool nothing is
        assigned and the failure is counted."""
        if seq_id in self._tables:
            raise ValueError(f"seq {seq_id} already has a block table")
        from ..testing import faults as _faults
        _faults.hit("kv_alloc")
        need = self.blocks_for(n_tokens)
        if need > len(self._free):
            self.alloc_failures_total += 1
            self._count("kv_alloc_failures_total")
            return False
        self._tables[seq_id] = [self._free.pop() for _ in range(need)]
        self._tokens[seq_id] = int(n_tokens)
        self.allocs_total += need
        self._count("kv_blocks_alloc_total", need)
        self._publish()
        return True

    def extend_to(self, seq_id: int, n_tokens: int) -> bool:
        """Grow ``seq_id``'s table to cover ``n_tokens`` total slots
        (typically +1 per decode step; most steps need no new block).
        False — with the table untouched — when the pool is short."""
        if seq_id not in self._tables:
            raise KeyError(f"seq {seq_id} has no block table")
        if n_tokens <= self._tokens[seq_id]:
            return True
        from ..testing import faults as _faults
        _faults.hit("kv_alloc")
        need = self.blocks_for(n_tokens) - len(self._tables[seq_id])
        if need > len(self._free):
            self.alloc_failures_total += 1
            self._count("kv_alloc_failures_total")
            return False
        if need > 0:
            self._tables[seq_id] += [self._free.pop()
                                     for _ in range(need)]
            self.allocs_total += need
            self._count("kv_blocks_alloc_total", need)
        self._tokens[seq_id] = int(n_tokens)
        self._publish()
        return True

    def free(self, seq_id: int) -> int:
        """Return every block of ``seq_id`` to the free list (finish,
        cancel, or preemption). Unknown ids are a no-op returning 0 so
        teardown paths can free unconditionally."""
        blocks = self._tables.pop(seq_id, None)
        self._tokens.pop(seq_id, None)
        if not blocks:
            self._publish()
            return 0
        self._free.extend(reversed(blocks))
        self.freed_total += len(blocks)
        self._count("kv_blocks_freed_total", len(blocks))
        self._publish()
        return len(blocks)

    # -- accounting -------------------------------------------------------

    def check(self) -> None:
        """Invariant audit (tests + drills): every block is either free
        or in exactly one table."""
        owned = [b for t in self._tables.values() for b in t]
        seen = set(owned) | set(self._free)
        if len(owned) + len(self._free) != self.num_blocks \
                or seen != set(range(self.num_blocks)):
            raise AssertionError(
                f"block accounting broken: {len(self._free)} free + "
                f"{len(owned)} owned != {self.num_blocks} "
                f"(or duplicates)")

    def _count(self, name: str, n: int = 1) -> None:
        from .. import observability as obs
        if not obs.enabled():
            return
        help_ = {
            "kv_blocks_alloc_total":
                "KV cache blocks handed to sequences by the paged "
                "allocator",
            "kv_blocks_freed_total":
                "KV cache blocks returned to the paged allocator's "
                "free list",
            "kv_alloc_failures_total":
                "KV block allocations refused because the pool was "
                "exhausted (triggers scheduler preemption)",
        }[name]
        obs.counter(name, help_).inc(n)

    def gauges_agree(self) -> Optional[bool]:
        """Do the kv_blocks_* gauges match this allocator's counts?
        None when unjudgeable (metrics off, or another allocator wrote
        the gauges last); the engine's post-step audit consumes this."""
        from .. import observability as obs
        if not obs.enabled() or _last_pub_token != self._pub_token:
            return None
        used = obs.gauge("kv_blocks_used").value()
        free = obs.gauge("kv_blocks_free").value()
        if used is None or free is None:
            return None
        return int(used) == self.num_used and int(free) == self.num_free

    def _publish(self) -> None:
        global _last_pub_token
        from .. import observability as obs
        if not obs.enabled():
            return
        _last_pub_token = self._pub_token
        obs.gauge("kv_blocks_used",
                  "KV cache blocks currently owned by sequences "
                  "(paged allocator)").set(float(self.num_used))
        obs.gauge("kv_blocks_free",
                  "KV cache blocks on the paged allocator's free "
                  "list").set(float(self.num_free))
