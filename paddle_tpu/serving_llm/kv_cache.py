"""Paged KV-cache block allocator with copy-on-write prefix sharing.

vLLM-style cache management for the LLM serving subsystem: KV memory
is a preallocated pool of fixed-size token blocks
(``FLAGS_kv_pool_blocks`` blocks of ``FLAGS_kv_block_size`` token
slots), and each running sequence owns a BLOCK TABLE — an ordered list
of pool indices — instead of a contiguous [T_max] cache slab. The
allocator is pure bookkeeping over block INDICES; the tensors
themselves live in LLMEngine's per-layer pools, and the ragged paged
attention kernel consumes the tables directly
(kernels/paged_attention.py).

**Prefix sharing (FLAGS_kv_prefix_sharing):** every physical block
carries a REFCOUNT. ``allocate()`` satisfies the already-resident
prefix of a new sequence's token timeline by bumping refcounts on
another sequence's blocks instead of popping the free list — full
blocks through a hash-of-full-blocks index (token-prefix tuple →
block), plus at most one partial tail block matched against a live
sequence's written timeline. ``free()`` decrements and only returns
refcount-0 blocks to the free list. A shared block is READ-ONLY: the
first divergent write goes through :meth:`make_private` (copy-on-
write — the engine copies the K/V rows in-pool). The decode kernel
needs zero changes; block tables are already indirect.

Accounting is load-bearing, not decorative: the chaos disconnect
drill asserts zero leaked blocks through the ``kv_blocks_used``/
``kv_blocks_free`` gauges, ``check()`` audits refcounts (per-table
reference counts must equal the refcount map; no refcount-0 block
outside the free list), and the scheduler's preemption decisions
read ``num_free``. Single-owner object (the engine's serving thread);
no internal locking.
"""

from __future__ import annotations

import itertools
from collections import Counter
from typing import Dict, List, Optional, Sequence as Seq, Tuple

__all__ = ["KVBlockAllocator"]

# last allocator to publish the kv_blocks_* gauges (engines audit
# gauge-vs-allocator agreement only when their own allocator wrote the
# gauge last — several engines in one test process share the registry)
_pub_tokens = itertools.count(1)
_last_pub_token: Optional[int] = None


class KVBlockAllocator:
    def __init__(self, num_blocks: int, block_size: int):
        if num_blocks <= 0 or block_size <= 0:
            raise ValueError("num_blocks and block_size must be >= 1")
        self.num_blocks = int(num_blocks)
        self.block_size = int(block_size)
        # LIFO free list: recently-freed blocks are re-issued first,
        # which keeps the hot pool region small
        self._free: List[int] = list(range(self.num_blocks - 1, -1, -1))
        self._tables: Dict[int, List[int]] = {}
        self._tokens: Dict[int, int] = {}
        # per-block refcount, used blocks only (a block with refcount
        # >= 2 is shared and read-only; COW via make_private)
        # guarded-by: single-owner (engine serving thread)
        self._refs: Dict[int, int] = {}
        # prefix-sharing index: token-prefix tuple (length = a whole
        # number of blocks) -> the physical block holding that
        # prefix's LAST block of K/V rows, plus the reverse map so a
        # freed block drops its entry. Content-addressed by the exact
        # token prefix — block j's K/V rows depend on every token
        # before them, so the key must cover positions [0, (j+1)*bs).
        # guarded-by: single-owner (engine serving thread)
        self._full_index: Dict[Tuple[int, ...], int] = {}
        self._index_key: Dict[int, Tuple[int, ...]] = {}
        # written token timeline per live sequence (only maintained
        # while FLAGS_kv_prefix_sharing is on): the partial-tail match
        # and the full-block registration both read it
        # guarded-by: single-owner (engine serving thread)
        self._timelines: Dict[int, List[int]] = {}
        # leading tokens satisfied by sharing at allocate() time
        self._shared_tokens: Dict[int, int] = {}
        self.allocs_total = 0
        self.freed_total = 0
        self.alloc_failures_total = 0
        self.cow_copies_total = 0
        self.prefix_hit_tokens_total = 0
        self._pub_token = next(_pub_tokens)
        self._publish()

    # -- queries ----------------------------------------------------------

    @property
    def num_free(self) -> int:
        return len(self._free)

    @property
    def num_used(self) -> int:
        return self.num_blocks - len(self._free)

    @property
    def num_shared(self) -> int:
        """Blocks referenced by two or more block tables."""
        return sum(1 for r in self._refs.values() if r >= 2)

    def blocks_for(self, n_tokens: int) -> int:
        """Blocks needed to hold ``n_tokens`` token slots."""
        return -(-max(0, int(n_tokens)) // self.block_size)

    def table(self, seq_id: int) -> List[int]:
        return list(self._tables.get(seq_id, ()))

    def tokens(self, seq_id: int) -> int:
        return self._tokens.get(seq_id, 0)

    def shared_tokens(self, seq_id: int) -> int:
        """Leading tokens of ``seq_id`` whose K/V were already
        resident when it was allocated (prefill may skip them)."""
        return self._shared_tokens.get(seq_id, 0)

    def refcount(self, block: int) -> int:
        return self._refs.get(block, 0)

    def owners(self) -> List[int]:
        return list(self._tables.keys())

    @staticmethod
    def _sharing() -> bool:
        from ..flags import GLOBAL_FLAGS
        try:
            return bool(GLOBAL_FLAGS.get("kv_prefix_sharing"))
        # ptlint: disable=silent-failure -- flag may not be defined under direct submodule import; sharing simply stays off
        except Exception:  # noqa: BLE001
            return False

    def _match_prefix(self, tokens: Seq[int],
                      limit: int) -> Tuple[List[int], int]:
        """Longest already-resident prefix of ``tokens`` (at most
        ``limit`` tokens): whole blocks through the hash-of-full-
        blocks index, then at most one partial tail block from a live
        sequence's written timeline. Returns (shared blocks, matched
        token count). The caller caps ``limit`` below len(tokens) so
        a fully-cached prompt still computes its final position."""
        bs = self.block_size
        blocks: List[int] = []
        j = 0
        while (j + 1) * bs <= limit:
            b = self._full_index.get(tuple(tokens[:(j + 1) * bs]))
            if b is None:
                break
            blocks.append(b)
            j += 1
        m = j * bs
        # partial tail: continue into block j of a live sequence whose
        # written timeline extends this prefix (COW on first write)
        best: Optional[Tuple[int, int]] = None
        for sid, tl in self._timelines.items():
            tbl = self._tables.get(sid)
            if tbl is None or len(tbl) <= j or len(tl) <= m:
                continue
            if list(tl[:m]) != list(tokens[:m]):
                continue
            stop = min(limit, m + bs, len(tl))
            extra = 0
            while m + extra < stop and tl[m + extra] == tokens[m + extra]:
                extra += 1
            if extra > 0 and (best is None or extra > best[0]):
                best = (extra, tbl[j])
        if best is not None:
            m += best[0]
            blocks.append(best[1])
        return blocks, m

    def probe_shared_tokens(self, tokens: Seq[int]) -> int:
        """How many leading tokens of ``tokens`` an allocate() issued
        right now would satisfy from resident blocks (0 when sharing
        is off). Read-only — the admission watermark projects
        post-sharing demand with it."""
        if not self._sharing() or not tokens:
            return 0
        return self._match_prefix(list(tokens), len(tokens) - 1)[1]

    # -- mutations --------------------------------------------------------

    def allocate(self, seq_id: int, n_tokens: int,
                 tokens: Optional[Seq[int]] = None) -> bool:
        """Give ``seq_id`` (no existing table) blocks for ``n_tokens``
        token slots. All-or-nothing: on a short pool nothing is
        assigned and the failure is counted. When
        FLAGS_kv_prefix_sharing is on and ``tokens`` (the sequence's
        token timeline, len == n_tokens) is passed, the already-
        resident prefix is satisfied by refcount bumps on shared
        blocks instead of free-list pops; ``shared_tokens()`` then
        reports how many leading tokens prefill may skip."""
        if seq_id in self._tables:
            raise ValueError(f"seq {seq_id} already has a block table")
        from ..testing import faults as _faults
        _faults.hit("kv_alloc")
        shared: List[int] = []
        m = 0
        sharing = tokens is not None and self._sharing()
        if sharing and len(tokens) > 0:
            # cap below n_tokens: the final position is always
            # recomputed so the engine has logits to sample from
            limit = min(len(tokens), int(n_tokens)) - 1
            if limit > 0:
                shared, m = self._match_prefix(list(tokens), limit)
        need = self.blocks_for(n_tokens) - len(shared)
        if need > len(self._free):
            self.alloc_failures_total += 1
            self._count("kv_alloc_failures_total")
            return False
        for b in shared:
            self._refs[b] += 1
        fresh = [self._free.pop() for _ in range(need)]
        for b in fresh:
            self._refs[b] = 1
        self._tables[seq_id] = shared + fresh
        self._tokens[seq_id] = int(n_tokens)
        self._shared_tokens[seq_id] = m
        if sharing:
            # the shared prefix is already-written content
            self._timelines[seq_id] = list(tokens[:m])
        self.allocs_total += need
        if need:
            self._count("kv_blocks_alloc_total", need)
        if m:
            self.prefix_hit_tokens_total += m
            self._count("kv_prefix_hit_tokens_total", m)
        self._publish()
        return True

    def extend_to(self, seq_id: int, n_tokens: int) -> bool:
        """Grow ``seq_id``'s table to cover ``n_tokens`` total slots
        (typically +1 per decode step; most steps need no new block).
        False — with the table untouched — when the pool is short."""
        if seq_id not in self._tables:
            raise KeyError(f"seq {seq_id} has no block table")
        if n_tokens <= self._tokens[seq_id]:
            return True
        from ..testing import faults as _faults
        _faults.hit("kv_alloc")
        need = self.blocks_for(n_tokens) - len(self._tables[seq_id])
        if need > len(self._free):
            self.alloc_failures_total += 1
            self._count("kv_alloc_failures_total")
            return False
        if need > 0:
            fresh = [self._free.pop() for _ in range(need)]
            for b in fresh:
                self._refs[b] = 1
            self._tables[seq_id] += fresh
            self.allocs_total += need
            self._count("kv_blocks_alloc_total", need)
        self._tokens[seq_id] = int(n_tokens)
        self._publish()
        return True

    def make_private(self, seq_id: int, block_idx: int):
        """Copy-on-write: make the block at table position
        ``block_idx`` exclusive to ``seq_id`` before a write.
        Returns None when the block is already private (refcount 1 —
        nothing to do), an ``(old, new)`` block pair when a copy
        target was allocated (the CALLER must copy the K/V rows
        old → new in-pool before writing), or False when the free
        list is empty (caller preempts a victim and retries)."""
        table = self._tables[seq_id]
        old = table[block_idx]
        if self._refs.get(old, 0) <= 1:
            return None
        if not self._free:
            self.alloc_failures_total += 1
            self._count("kv_alloc_failures_total")
            return False
        new = self._free.pop()
        self._refs[old] -= 1
        self._refs[new] = 1
        table[block_idx] = new
        self.allocs_total += 1
        self.cow_copies_total += 1
        self._count("kv_blocks_alloc_total", 1)
        self._count("kv_cow_copies_total")
        self._publish()
        return (old, new)

    def note_written(self, seq_id: int, tokens: Seq[int]) -> None:
        """Record the token timeline whose K/V now sit in ``seq_id``'s
        blocks (the engine calls this after each prefill chunk and
        decode write). Full blocks enter the hash-of-full-blocks
        index so later allocations can share them. No-op while
        sharing is off."""
        if seq_id not in self._tables or not self._sharing():
            return
        tl = list(int(t) for t in tokens)
        self._timelines[seq_id] = tl
        table = self._tables[seq_id]
        bs = self.block_size
        for j in range(len(tl) // bs):
            b = table[j]
            if b in self._index_key:
                continue
            key = tuple(tl[:(j + 1) * bs])
            if key not in self._full_index:
                self._full_index[key] = b
                self._index_key[b] = key

    def truncate_to(self, seq_id: int, n_tokens: int) -> int:
        """Rewind ``seq_id``'s table to cover exactly ``n_tokens``
        token slots — the speculative-decode rollback: draft-window
        K/V written past the accepted point must stop being part of
        the sequence's cache. Trailing blocks beyond
        ``blocks_for(n_tokens)`` are dereferenced exactly like
        :meth:`free` (refcount decrement; only refcount-0 blocks
        return to the free list, preserving LIFO order — a block
        still shared with another sequence is never recycled), and
        the written timeline is cut back so the rolled-back tokens
        can no longer be prefix-matched. A retained boundary block
        whose full-block index key extends past ``n_tokens`` drops
        its index entry: once this sequence holds it privately its
        tail rows get scribbled by future writes with no COW gate, so
        the content address would go stale (a co-owner's legitimate
        full block is simply re-registered by its next
        note_written). No-op returning 0 when ``n_tokens`` already
        covers the table. Returns blocks returned to the free list.
        """
        if seq_id not in self._tables:
            raise KeyError(f"seq {seq_id} has no block table")
        n_tokens = max(0, int(n_tokens))
        if n_tokens >= self._tokens[seq_id]:
            return 0
        table = self._tables[seq_id]
        keep = self.blocks_for(n_tokens)
        dropped = table[keep:]
        del table[keep:]
        self._tokens[seq_id] = n_tokens
        if seq_id in self._shared_tokens:
            self._shared_tokens[seq_id] = min(
                self._shared_tokens[seq_id], n_tokens)
        tl = self._timelines.get(seq_id)
        if tl is not None and len(tl) > n_tokens:
            del tl[n_tokens:]
        returned: List[int] = []
        for b in reversed(dropped):
            self._refs[b] -= 1
            if self._refs[b] == 0:
                del self._refs[b]
                key = self._index_key.pop(b, None)
                if key is not None:
                    self._full_index.pop(key, None)
                returned.append(b)
        # the retained boundary block: an index key that now extends
        # past the truncation point is (or can silently become) stale
        # content-addressing — drop it
        if table:
            key = self._index_key.get(table[-1])
            if key is not None and len(key) > n_tokens:
                del self._index_key[table[-1]]
                self._full_index.pop(key, None)
        self._free.extend(returned)
        if returned:
            self.freed_total += len(returned)
            self._count("kv_blocks_freed_total", len(returned))
        self._publish()
        return len(returned)

    def free(self, seq_id: int) -> int:
        """Drop every block reference of ``seq_id`` (finish, cancel,
        or preemption); blocks whose refcount hits 0 return to the
        free list (and leave the prefix index — their content is no
        longer addressable). Unknown ids are a no-op returning 0 so
        teardown paths can free unconditionally. Returns the number
        of blocks actually returned to the free list."""
        blocks = self._tables.pop(seq_id, None)
        self._tokens.pop(seq_id, None)
        self._shared_tokens.pop(seq_id, None)
        self._timelines.pop(seq_id, None)
        if not blocks:
            self._publish()
            return 0
        returned: List[int] = []
        for b in reversed(blocks):
            self._refs[b] -= 1
            if self._refs[b] == 0:
                del self._refs[b]
                key = self._index_key.pop(b, None)
                if key is not None:
                    self._full_index.pop(key, None)
                returned.append(b)
        self._free.extend(returned)
        if returned:
            self.freed_total += len(returned)
            self._count("kv_blocks_freed_total", len(returned))
        self._publish()
        return len(returned)

    # -- accounting -------------------------------------------------------

    def check(self) -> None:
        """Invariant audit (tests + drills): every block is either
        free or referenced by at least one table; the refcount map
        equals the per-table reference counts exactly (so no
        refcount-0 block lives outside the free list, and no free
        block carries a refcount); index entries only point at live
        blocks; every table is exactly sized for its token count and
        no written timeline overhangs it (the truncate/rewind
        contract — rolled-back draft tokens must be gone from BOTH)."""
        counts = Counter(b for t in self._tables.values() for b in t)
        distinct = set(counts)
        free_set = set(self._free)
        if distinct & free_set \
                or len(distinct) + len(self._free) != self.num_blocks \
                or (distinct | free_set) != set(range(self.num_blocks)):
            raise AssertionError(
                f"block accounting broken: {len(self._free)} free + "
                f"{len(distinct)} owned != {self.num_blocks} "
                f"(or duplicates)")
        if dict(counts) != self._refs:
            raise AssertionError(
                f"refcount accounting broken: per-table references "
                f"{dict(counts)} != refcount map {self._refs}")
        stale = [b for b in self._index_key if b not in self._refs]
        if stale:
            raise AssertionError(
                f"prefix index points at free blocks: {stale}")
        for sid, table in self._tables.items():
            if len(table) != self.blocks_for(self._tokens.get(sid, 0)):
                raise AssertionError(
                    f"seq {sid} table holds {len(table)} blocks but "
                    f"covers {self._tokens.get(sid, 0)} tokens "
                    f"(truncate/extend accounting broken)")
            tl = self._timelines.get(sid)
            if tl is not None and len(tl) > self._tokens.get(sid, 0):
                raise AssertionError(
                    f"seq {sid} written timeline ({len(tl)} tokens) "
                    f"overhangs its table "
                    f"({self._tokens.get(sid, 0)} tokens) — "
                    f"rolled-back tokens still prefix-matchable")

    def _count(self, name: str, n: int = 1) -> None:
        from .. import observability as obs
        if not obs.enabled():
            return
        help_ = {
            "kv_blocks_alloc_total":
                "KV cache blocks handed to sequences by the paged "
                "allocator",
            "kv_blocks_freed_total":
                "KV cache blocks returned to the paged allocator's "
                "free list",
            "kv_alloc_failures_total":
                "KV block allocations refused because the pool was "
                "exhausted (triggers scheduler preemption)",
            "kv_cow_copies_total":
                "copy-on-write block copies: a sequence's first "
                "divergent write to a shared block allocated a "
                "private copy (kv_prefix_sharing)",
            "kv_prefix_hit_tokens_total":
                "prompt tokens satisfied from already-resident "
                "shared blocks at allocate() time — prefill skips "
                "recomputing them (kv_prefix_sharing)",
        }[name]
        obs.counter(name, help_).inc(n)

    def gauges_agree(self) -> Optional[bool]:
        """Do the kv_blocks_* gauges match this allocator's counts?
        None when unjudgeable (metrics off, or another allocator wrote
        the gauges last); the engine's post-step audit consumes this."""
        from .. import observability as obs
        if not obs.enabled() or _last_pub_token != self._pub_token:
            return None
        used = obs.gauge("kv_blocks_used").value()
        free = obs.gauge("kv_blocks_free").value()
        shared = obs.gauge("kv_blocks_shared").value()
        if used is None or free is None or shared is None:
            return None
        return int(used) == self.num_used \
            and int(free) == self.num_free \
            and int(shared) == self.num_shared

    def _publish(self) -> None:
        global _last_pub_token
        from .. import observability as obs
        if not obs.enabled():
            return
        _last_pub_token = self._pub_token
        obs.gauge("kv_blocks_used",
                  "KV cache blocks currently owned by sequences "
                  "(paged allocator)").set(float(self.num_used))
        obs.gauge("kv_blocks_free",
                  "KV cache blocks on the paged allocator's free "
                  "list").set(float(self.num_free))
        obs.gauge("kv_blocks_shared",
                  "KV cache blocks referenced by two or more block "
                  "tables (prefix sharing; read-only until "
                  "copy-on-write)").set(float(self.num_shared))
