"""Mixture-of-Experts layer with expert parallelism.

The reference predates MoE (SURVEY §2.8 marks EP "absent in this
reference; cheap extension under pjit"), but the capability class it
covers — sharding a huge parameter space across devices, the role its
PS sharded embeddings play — is idiomatic on TPU as an expert-parallel
einsum: experts live stacked on a leading [E, ...] axis sharded over
the mesh's "ep" axis, tokens are dispatched densely with a capacity
limit (one-hot einsum — static shapes, MXU-friendly), and XLA inserts
the all-to-alls from the sharding annotations (the same mechanism the
reference's NCCL graph passes hand-build).
"""

from __future__ import annotations

import math
from typing import Optional

import jax
import jax.numpy as jnp

from ...core.dtype import get_default_dtype
from .. import initializer as I
from ..layer import Layer, Parameter

__all__ = ["MoELayer", "moe_param_rule"]


class MoELayer(Layer):
    """Top-k gated MoE FFN (Switch/GShard style).

    x [B, T, D] → gate picks top_k of num_experts per token; each
    expert is a 2-layer FFN with stacked weights [E, D, H]/[E, H, D].
    Dense dispatch with ``capacity_factor``: each expert processes at
    most ceil(tokens/E * cf) tokens, overflow tokens are dropped
    (standard GShard semantics; keeps every shape static for XLA).
    """

    def __init__(self, d_model: int, d_hidden: int, num_experts: int,
                 top_k: int = 2, capacity_factor: float = 1.25,
                 activation: str = "gelu") -> None:
        super().__init__()
        self.d_model = d_model
        self.d_hidden = d_hidden
        self.num_experts = num_experts
        self.top_k = min(top_k, num_experts)
        self.capacity_factor = capacity_factor
        dtype = get_default_dtype()
        init = I.XavierUniform()
        self.gate_weight = Parameter(
            init((d_model, num_experts), dtype))
        self.w_in = Parameter(init((num_experts, d_model, d_hidden),
                                   dtype))
        self.b_in = Parameter(jnp.zeros((num_experts, d_hidden), dtype))
        self.w_out = Parameter(init((num_experts, d_hidden, d_model),
                                    dtype))
        self.b_out = Parameter(jnp.zeros((num_experts, d_model), dtype))
        # threaded out through functional_call's buffer capture (a plain
        # attribute would leak a tracer under jit); to TRAIN with it,
        # return it from your model and add weight*aux in loss_fn
        self.register_buffer("aux_loss", jnp.zeros((), jnp.float32))
        from ...ops import activation as A
        self._act = getattr(A, activation)

    def forward(self, x):
        b, t, d = x.shape
        n_tok = b * t
        e = self.num_experts
        cap = max(1, math.ceil(
            self.capacity_factor * n_tok * self.top_k / e))
        tokens = x.reshape(n_tok, d)

        logits = tokens @ self.gate_weight  # [N, E]
        probs = jax.nn.softmax(logits.astype(jnp.float32), axis=-1)
        top_p, top_e = jax.lax.top_k(probs, self.top_k)  # [N, k]

        # position of each (token, choice) within its expert's queue:
        # count prior assignments to the same expert (GShard cumsum)
        choice_onehot = jax.nn.one_hot(top_e, e, dtype=jnp.float32)
        # flatten choices in priority order: all k=0 choices first
        flat = choice_onehot.transpose(1, 0, 2).reshape(
            self.top_k * n_tok, e)
        pos_flat = jnp.cumsum(flat, axis=0) - flat  # prior count
        position = (pos_flat * flat).sum(-1).reshape(
            self.top_k, n_tok).transpose(1, 0)  # [N, k]
        keep = position < cap

        pos_onehot = jax.nn.one_hot(position, cap,
                                    dtype=jnp.float32)  # [N, k, C]
        # dispatch[n, e, c] = Σ_k choice[n,k,e]·keep[n,k]·pos[n,k,c]
        dispatch = jnp.einsum("nke,nk,nkc->nec", choice_onehot,
                              keep.astype(jnp.float32), pos_onehot)

        expert_in = jnp.einsum("nec,nd->ecd", dispatch,
                               tokens.astype(jnp.float32))
        expert_in = expert_in.astype(x.dtype)  # [E, C, D]
        h = self._act(jnp.einsum("ecd,edh->ech", expert_in, self.w_in)
                      + self.b_in[:, None])
        out = jnp.einsum("ech,ehd->ecd", h, self.w_out) \
            + self.b_out[:, None]  # [E, C, D]

        gates = (top_p * keep).astype(jnp.float32)  # [N, k]
        combine = jnp.einsum("nke,nk,nkc->nec", choice_onehot, gates,
                             pos_onehot)
        y = jnp.einsum("nec,ecd->nd", combine,
                       out.astype(jnp.float32)).astype(x.dtype)

        # load-balance auxiliary loss (GShard): mean gate prob x mean
        # assignment fraction per expert, scaled by E
        frac_tokens = choice_onehot[:, 0].mean(axis=0)  # top-1 fraction
        mean_prob = probs.mean(axis=0)
        self.aux_loss = e * jnp.sum(frac_tokens * mean_prob)
        return y.reshape(b, t, d)


def moe_param_rule(ep_axis: str = "ep"):
    """param_rule for ShardedTrainStep: shard the stacked expert
    dimension over the ep mesh axis (XLA turns the dispatch/combine
    einsums into all-to-alls across it)."""
    from jax.sharding import PartitionSpec as P

    def rule(name: str, v) -> P:
        shape = getattr(v, "shape", ())
        leaf = name.split(".")[-1]
        if leaf in ("w_in", "w_out", "b_in", "b_out") \
                and len(shape) >= 2:
            return P(ep_axis, *([None] * (len(shape) - 1)))
        return P()

    return rule
