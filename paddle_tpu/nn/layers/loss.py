"""Loss layers (class wrappers over ops.loss).

Reference: python/paddle/nn/layer/loss.py (CrossEntropyLoss, MSELoss,
L1Loss, NLLLoss, BCELoss, BCEWithLogitsLoss, KLDivLoss, SmoothL1Loss,
MarginRankingLoss, CTCLoss).
"""

from __future__ import annotations

from ...ops import loss as L
from ..layer import Layer


class CrossEntropyLoss(Layer):
    def __init__(self, weight=None, ignore_index: int = -100,
                 reduction: str = "mean", soft_label: bool = False,
                 axis: int = -1, use_softmax: bool = True) -> None:
        super().__init__()
        self.weight = weight
        self.ignore_index = ignore_index
        self.reduction = reduction
        self.soft_label = soft_label
        self.axis = axis
        self.use_softmax = use_softmax

    def forward(self, input, label):
        return L.cross_entropy(input, label, self.soft_label,
                               self.ignore_index, self.reduction, self.axis,
                               self.use_softmax, self.weight)


class FusedLinearCrossEntropy(Layer):
    """Linear projection + softmax cross-entropy as ONE loss-region op:
    ``loss = xent(hidden @ weight.T + bias, label)`` without ever
    materializing the [..., V] logits when the Pallas fused kernel is
    routed (FLAGS_fused_softmax_xent; falls back to the composed
    projection + ops.loss path with identical semantics otherwise).
    The class-level entry point for tied-embedding LM heads — BERT's
    pretraining_loss uses the same kernels.maybe_fused_linear_xent."""

    def __init__(self, ignore_index: int = -100,
                 reduction: str = "mean") -> None:
        super().__init__()
        self.ignore_index = ignore_index
        self.reduction = reduction

    def forward(self, hidden, weight, label, bias=None):
        from ...kernels import maybe_fused_linear_xent
        loss = maybe_fused_linear_xent(hidden, weight, bias, label,
                                       ignore_index=self.ignore_index)
        return L._reduce(loss, self.reduction)


class MSELoss(Layer):
    def __init__(self, reduction: str = "mean") -> None:
        super().__init__()
        self.reduction = reduction

    def forward(self, input, label):
        return L.mse_loss(input, label, self.reduction)


class L1Loss(Layer):
    def __init__(self, reduction: str = "mean") -> None:
        super().__init__()
        self.reduction = reduction

    def forward(self, input, label):
        return L.l1_loss(input, label, self.reduction)


class NLLLoss(Layer):
    def __init__(self, weight=None, ignore_index: int = -100,
                 reduction: str = "mean") -> None:
        super().__init__()
        self.weight = weight
        self.ignore_index = ignore_index
        self.reduction = reduction

    def forward(self, input, label):
        return L.nll_loss(input, label, self.weight, self.ignore_index,
                          self.reduction)


class BCELoss(Layer):
    def __init__(self, weight=None, reduction: str = "mean") -> None:
        super().__init__()
        self.weight = weight
        self.reduction = reduction

    def forward(self, input, label):
        return L.bce_loss(input, label, self.weight, self.reduction)


class BCEWithLogitsLoss(Layer):
    def __init__(self, weight=None, reduction: str = "mean",
                 pos_weight=None) -> None:
        super().__init__()
        self.weight = weight
        self.reduction = reduction
        self.pos_weight = pos_weight

    def forward(self, logit, label):
        return L.binary_cross_entropy_with_logits(
            logit, label, self.weight, self.pos_weight, self.reduction)


class KLDivLoss(Layer):
    def __init__(self, reduction: str = "mean") -> None:
        super().__init__()
        self.reduction = reduction

    def forward(self, input, label):
        return L.kl_div(input, label, self.reduction)


class SmoothL1Loss(Layer):
    def __init__(self, reduction: str = "mean", delta: float = 1.0) -> None:
        super().__init__()
        self.reduction = reduction
        self.delta = delta

    def forward(self, input, label):
        return L.smooth_l1_loss(input, label, self.delta, self.reduction)


class MarginRankingLoss(Layer):
    def __init__(self, margin: float = 0.0,
                 reduction: str = "mean") -> None:
        super().__init__()
        self.margin = margin
        self.reduction = reduction

    def forward(self, input, other, label):
        return L.margin_ranking_loss(input, other, label, self.margin,
                                     self.reduction)


class CTCLoss(Layer):
    def __init__(self, blank: int = 0, reduction: str = "mean") -> None:
        super().__init__()
        self.blank = blank
        self.reduction = reduction

    def forward(self, log_probs, labels, input_lengths, label_lengths):
        return L.ctc_loss(log_probs, labels, input_lengths, label_lengths,
                          self.blank, self.reduction)


class CosineEmbeddingLoss(Layer):
    def __init__(self, margin: float = 0.0,
                 reduction: str = "mean") -> None:
        super().__init__()
        self.margin = margin
        self.reduction = reduction

    def forward(self, input1, input2, label):
        return L.cosine_embedding_loss(input1, input2, label, self.margin,
                                       self.reduction)


class TripletMarginLoss(Layer):
    def __init__(self, margin: float = 1.0, p: float = 2.0,
                 reduction: str = "mean") -> None:
        super().__init__()
        self.margin = margin
        self.p = p
        self.reduction = reduction

    def forward(self, anchor, positive, negative):
        return L.triplet_margin_loss(anchor, positive, negative,
                                     self.margin, self.p, self.reduction)
