"""Common layers: Linear, Embedding, Dropout, activations-as-layers, Flatten.

TPU-native layer wrappers over ops/ (reference:
python/paddle/fluid/dygraph/nn.py Linear/Embedding/Dropout and
python/paddle/nn/layer/common.py). Each stores Parameters and calls the
functional op, so the same code runs eagerly and under jit via Layer.bind.
"""

from __future__ import annotations

from typing import Optional, Sequence

import jax.numpy as jnp

from ...core.dtype import get_default_dtype
from ...ops import activation as A
from ...ops import nn_functional as F
from .. import initializer as I
from ..layer import Layer, Parameter


class Linear(Layer):
    """y = x W + b with W [in, out] (reference fc convention)."""

    def __init__(self, in_features: int, out_features: int,
                 weight_attr=None, bias_attr=None, name=None) -> None:
        super().__init__()
        self.in_features = in_features
        self.out_features = out_features
        self.weight = I.make_param(weight_attr, I.XavierUniform(),
                                   (in_features, out_features),
                                   get_default_dtype())
        if bias_attr is False:
            self.bias = None
        else:
            self.bias = I.make_param(bias_attr, I.Constant(0.0),
                                     (out_features,),
                                     get_default_dtype())

    def forward(self, x):
        return F.linear(x, self.weight,
                        self.bias if "bias" in self._parameters else None)


class Embedding(Layer):
    """(ref: lookup_table_v2_op.cc; dygraph/nn.py Embedding).

    ``sparse`` is accepted for API parity and intentionally does not
    change the gradient representation: on TPU a dense scatter-add
    embedding gradient is the efficient XLA lowering (the reference's
    selected-rows path optimizes CPU/PS training — that capability
    lives in the lazy-mode optimizers over ops.sparse.RowSlices and the
    parameter-server sparse tables instead)."""

    def __init__(self, num_embeddings: int, embedding_dim: int,
                 padding_idx: Optional[int] = None, sparse: bool = False,
                 weight_attr=None, name=None) -> None:
        super().__init__()
        self.num_embeddings = num_embeddings
        self.embedding_dim = embedding_dim
        self.padding_idx = padding_idx
        self.sparse = sparse
        self.weight = I.make_param(weight_attr, I.XavierNormal(),
                                   (num_embeddings, embedding_dim),
                                   get_default_dtype())

    def forward(self, x):
        return F.embedding(x, self.weight, self.padding_idx)


class Dropout(Layer):
    def __init__(self, p: float = 0.5,
                 mode: str = "upscale_in_train") -> None:
        super().__init__()
        self.p = p
        self.mode = mode

    def forward(self, x):
        return F.dropout(x, self.p, training=self.training, mode=self.mode)


class Dropout2D(Layer):
    def __init__(self, p: float = 0.5) -> None:
        super().__init__()
        self.p = p

    def forward(self, x):
        return F.dropout2d(x, self.p, training=self.training)


class AlphaDropout(Layer):
    def __init__(self, p: float = 0.5) -> None:
        super().__init__()
        self.p = p

    def forward(self, x):
        return F.alpha_dropout(x, self.p, training=self.training)


class Flatten(Layer):
    def __init__(self, start_axis: int = 1, stop_axis: int = -1) -> None:
        super().__init__()
        self.start_axis = start_axis
        self.stop_axis = stop_axis

    def forward(self, x):
        from ...ops.manipulation import flatten
        return flatten(x, self.start_axis, self.stop_axis)


class Identity(Layer):
    def forward(self, x):
        return x


class Upsample(Layer):
    def __init__(self, size=None, scale_factor=None, mode: str = "nearest",
                 align_corners: bool = False) -> None:
        super().__init__()
        self.size = size
        self.scale_factor = scale_factor
        self.mode = mode
        self.align_corners = align_corners

    def forward(self, x):
        return F.interpolate(x, self.size, self.scale_factor, self.mode,
                             self.align_corners)


class Pad2D(Layer):
    def __init__(self, padding, mode: str = "constant",
                 value: float = 0.0, data_format: str = "NCHW") -> None:
        super().__init__()
        self.padding = padding
        self.mode = mode
        self.value = value
        self.data_format = data_format

    def forward(self, x):
        return F.pad2d(x, self.padding, self.mode, self.value,
                       self.data_format)


class CosineSimilarity(Layer):
    def __init__(self, axis: int = 1, eps: float = 1e-8) -> None:
        super().__init__()
        self.axis = axis
        self.eps = eps

    def forward(self, x1, x2):
        return F.cosine_similarity(x1, x2, self.axis, self.eps)


class Bilinear(Layer):
    """(ref: bilinear_tensor_product_op.cc)."""

    def __init__(self, in1_features: int, in2_features: int,
                 out_features: int, weight_attr=None,
                 bias_attr=None) -> None:
        super().__init__()
        self.weight = I.make_param(
            weight_attr, I.XavierUniform(),
            (out_features, in1_features, in2_features),
            get_default_dtype())
        if bias_attr is False:
            pass
        else:
            self.bias = I.make_param(bias_attr, I.Constant(0.0),
                                     (out_features,),
                                     get_default_dtype())

    def forward(self, x1, x2):
        from ...ops.math import bilinear_tensor_product
        bias = self.bias if "bias" in self._parameters else None
        return bilinear_tensor_product(x1, x2, self.weight, bias)


def _activation_layer(fn_name: str, **defaults):
    fn = getattr(A, fn_name)

    class _Act(Layer):
        def __init__(self, **kwargs) -> None:
            super().__init__()
            self.kwargs = {**defaults, **kwargs}

        def forward(self, x):
            return fn(x, **self.kwargs)

    _Act.__name__ = "".join(s.capitalize() for s in fn_name.split("_"))
    return _Act


ReLU = _activation_layer("relu")
ReLU6 = _activation_layer("relu6")
LeakyReLU = _activation_layer("leaky_relu")
ELU = _activation_layer("elu")
SELU = _activation_layer("selu")
CELU = _activation_layer("celu")
GELU = _activation_layer("gelu")
Sigmoid = _activation_layer("sigmoid")
LogSigmoid = _activation_layer("logsigmoid")
Hardsigmoid = _activation_layer("hard_sigmoid")
Hardswish = _activation_layer("hard_swish")
Hardshrink = _activation_layer("hard_shrink")
Softshrink = _activation_layer("soft_shrink")
Hardtanh = _activation_layer("hard_tanh")
Tanh = _activation_layer("tanh")
Tanhshrink = _activation_layer("tanh_shrink")
Softplus = _activation_layer("softplus")
Softsign = _activation_layer("softsign")
Swish = _activation_layer("swish")
Silu = _activation_layer("swish")
Mish = _activation_layer("mish")
ThresholdedReLU = _activation_layer("thresholded_relu")
LogSoftmax = _activation_layer("log_softmax")
Softmax = _activation_layer("softmax")
GLU = _activation_layer("glu")


class PReLU(Layer):
    def __init__(self, num_parameters: int = 1, init: float = 0.25) -> None:
        super().__init__()
        self.weight = Parameter(jnp.full((num_parameters,), init,
                                         get_default_dtype()))

    def forward(self, x):
        w = self.weight
        if w.shape[0] > 1:
            w = w.reshape((1, -1) + (1,) * (x.ndim - 2))
        return A.prelu(x, w)


class Maxout(Layer):
    def __init__(self, groups: int, axis: int = 1) -> None:
        super().__init__()
        self.groups = groups
        self.axis = axis

    def forward(self, x):
        return A.maxout(x, self.groups, self.axis)
