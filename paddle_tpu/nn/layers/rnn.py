"""Recurrent layers.

TPU-native redesign of the reference's RNN stack (reference:
paddle/fluid/operators/lstm_op.cc, gru_op.cc, cudnn_lstm_op.cu,
rnn layers in python/paddle/fluid/layers/rnn.py). cuDNN's fused RNN has no
TPU analogue; instead cells are expressed as matmul-heavy step functions and
the time loop is ``lax.scan`` — XLA pipelines the per-step matmuls onto the
MXU and the scan keeps compile time flat in sequence length.
"""

from __future__ import annotations

from typing import Optional, Tuple

import jax
import jax.numpy as jnp
from jax import lax

from ...core.dtype import get_default_dtype
from .. import initializer as I
from ..layer import Layer, Parameter


class RNNCell(Layer):
    """Single-step recurrent cell protocol (ref: fluid/layers/rnn.py
    RNNCell): ``forward(inputs, states) -> (outputs, new_states)`` plus
    ``get_initial_states``. The Decoder API (nn/decode.py) and the RNN
    driver both consume this protocol."""

    def get_initial_states(self, batch_size: int):
        raise NotImplementedError


class LSTMCell(RNNCell):
    """(ref: lstm_unit_op.cc gate math: i,f,c,o with forget bias)."""

    def __init__(self, input_size: int, hidden_size: int,
                 weight_ih_attr=None, weight_hh_attr=None,
                 bias_ih_attr=None, bias_hh_attr=None) -> None:
        super().__init__()
        self.input_size = input_size
        self.hidden_size = hidden_size
        dt = get_default_dtype()
        k = 1.0 / (hidden_size ** 0.5)
        init = I.Uniform(-k, k)
        self.weight_ih = I.make_param(
            weight_ih_attr, init, (input_size, 4 * hidden_size), dt)
        self.weight_hh = I.make_param(
            weight_hh_attr, init, (hidden_size, 4 * hidden_size), dt)
        self.bias_ih = I.make_param(bias_ih_attr, init,
                                    (4 * hidden_size,), dt)
        self.bias_hh = I.make_param(bias_hh_attr, init,
                                    (4 * hidden_size,), dt)

    def forward(self, x, states: Optional[Tuple] = None):
        if states is None:
            b = x.shape[0]
            states = self.get_initial_states(b)
        h, c = states
        gates = x @ self.weight_ih + self.bias_ih \
            + h @ self.weight_hh + self.bias_hh
        i, f, g, o = jnp.split(gates, 4, axis=-1)
        i = jax.nn.sigmoid(i)
        f = jax.nn.sigmoid(f)
        g = jnp.tanh(g)
        o = jax.nn.sigmoid(o)
        new_c = f * c + i * g
        new_h = o * jnp.tanh(new_c)
        return new_h, (new_h, new_c)

    def get_initial_states(self, batch_size: int):
        z = jnp.zeros((batch_size, self.hidden_size), get_default_dtype())
        return (z, z)


class GRUCell(RNNCell):
    """(ref: gru_unit_op.cc)."""

    def __init__(self, input_size: int, hidden_size: int) -> None:
        super().__init__()
        self.input_size = input_size
        self.hidden_size = hidden_size
        dt = get_default_dtype()
        k = 1.0 / (hidden_size ** 0.5)
        init = I.Uniform(-k, k)
        self.weight_ih = Parameter(init((input_size, 3 * hidden_size), dt))
        self.weight_hh = Parameter(init((hidden_size, 3 * hidden_size), dt))
        self.bias_ih = Parameter(init((3 * hidden_size,), dt))
        self.bias_hh = Parameter(init((3 * hidden_size,), dt))

    def forward(self, x, states=None):
        if states is None:
            states = self.get_initial_states(x.shape[0])
        h = states
        x_g = x @ self.weight_ih + self.bias_ih
        h_g = h @ self.weight_hh + self.bias_hh
        xr, xz, xn = jnp.split(x_g, 3, axis=-1)
        hr, hz, hn = jnp.split(h_g, 3, axis=-1)
        r = jax.nn.sigmoid(xr + hr)
        z = jax.nn.sigmoid(xz + hz)
        n = jnp.tanh(xn + r * hn)
        new_h = (1.0 - z) * n + z * h
        return new_h, new_h

    def get_initial_states(self, batch_size: int):
        return jnp.zeros((batch_size, self.hidden_size), get_default_dtype())


class SimpleRNNCell(RNNCell):
    def __init__(self, input_size: int, hidden_size: int,
                 activation: str = "tanh") -> None:
        super().__init__()
        self.input_size = input_size
        self.hidden_size = hidden_size
        self.activation = activation
        dt = get_default_dtype()
        k = 1.0 / (hidden_size ** 0.5)
        init = I.Uniform(-k, k)
        self.weight_ih = Parameter(init((input_size, hidden_size), dt))
        self.weight_hh = Parameter(init((hidden_size, hidden_size), dt))
        self.bias_ih = Parameter(init((hidden_size,), dt))
        self.bias_hh = Parameter(init((hidden_size,), dt))

    def forward(self, x, states=None):
        if states is None:
            states = self.get_initial_states(x.shape[0])
        h = states
        pre = x @ self.weight_ih + self.bias_ih \
            + h @ self.weight_hh + self.bias_hh
        new_h = jnp.tanh(pre) if self.activation == "tanh" \
            else jax.nn.relu(pre)
        return new_h, new_h

    def get_initial_states(self, batch_size: int):
        return jnp.zeros((batch_size, self.hidden_size), get_default_dtype())


class RNN(Layer):
    """Run a cell over time with lax.scan (ref: layers/rnn.py RNN)."""

    def __init__(self, cell: Layer, is_reverse: bool = False,
                 time_major: bool = False) -> None:
        super().__init__()
        self.cell = cell
        self.is_reverse = is_reverse
        self.time_major = time_major

    def forward(self, inputs, initial_states=None,
                sequence_length=None):
        xs = inputs if self.time_major else jnp.swapaxes(inputs, 0, 1)
        ts = jnp.arange(xs.shape[0])
        if self.is_reverse:
            xs = jnp.flip(xs, axis=0)
            ts = jnp.flip(ts, axis=0)
        batch = xs.shape[1]
        if initial_states is None:
            initial_states = self.cell.get_initial_states(batch)

        cell = self.cell
        seq_len = None if sequence_length is None \
            else jnp.asarray(sequence_length)

        def step(states, inp):
            x_t, t = inp
            out_t, new_states = cell(x_t, states)
            if seq_len is not None:
                # padded steps: state frozen, output zeroed. In reverse
                # the scan starts on the padding, where the state simply
                # stays initial until the first valid position — the
                # correct ragged-reverse semantics.
                alive = t < seq_len
                new_states = jax.tree.map(
                    lambda new, old: jnp.where(
                        alive.reshape((-1,) + (1,) * (new.ndim - 1)),
                        new, old), new_states, states)
                out_t = jnp.where(
                    alive.reshape((-1,) + (1,) * (out_t.ndim - 1)),
                    out_t, jnp.zeros_like(out_t))
            return new_states, out_t

        final, outs = lax.scan(step, initial_states, (xs, ts))
        if self.is_reverse:
            outs = jnp.flip(outs, axis=0)
        if not self.time_major:
            outs = jnp.swapaxes(outs, 0, 1)
        return outs, final


class _StackedRNNBase(Layer):
    _cell_cls = None

    def __init__(self, input_size: int, hidden_size: int,
                 num_layers: int = 1, direction: str = "forward",
                 dropout: float = 0.0, time_major: bool = False) -> None:
        super().__init__()
        self.num_layers = num_layers
        self.direction = direction
        self.dropout = dropout
        self.time_major = time_major
        self.hidden_size = hidden_size
        bidirect = direction in ("bidirect", "bidirectional")
        self.bidirect = bidirect
        from ..layer import LayerList
        self.fw = LayerList()
        self.bw = LayerList() if bidirect else None
        for i in range(num_layers):
            in_size = input_size if i == 0 else \
                hidden_size * (2 if bidirect else 1)
            self.fw.append(RNN(self._make_cell(in_size, hidden_size)))
            if bidirect:
                self.bw.append(RNN(self._make_cell(in_size, hidden_size),
                                   is_reverse=True))

    def _make_cell(self, in_size, hidden):
        raise NotImplementedError

    def forward(self, inputs, initial_states=None, sequence_length=None):
        x = inputs if not self.time_major else jnp.swapaxes(inputs, 0, 1)
        finals_f = []
        finals_b = []
        from ...ops.nn_functional import dropout as dropout_fn
        for i in range(self.num_layers):
            out_f, fin_f = self.fw[i](
                x, initial_states=self._slice_initial(initial_states, i,
                                                      backward=False),
                sequence_length=sequence_length)
            finals_f.append(fin_f)
            if self.bidirect:
                out_b, fin_b = self.bw[i](
                    x, initial_states=self._slice_initial(
                        initial_states, i, backward=True),
                    sequence_length=sequence_length)
                finals_b.append(fin_b)
                x = jnp.concatenate([out_f, out_b], axis=-1)
            else:
                x = out_f
            if self.dropout > 0 and i < self.num_layers - 1:
                x = dropout_fn(x, self.dropout, training=self.training)
        if self.time_major:
            x = jnp.swapaxes(x, 0, 1)
        if self.bidirect:
            # layer-major interleave [l0fw, l0bw, l1fw, l1bw, ...] —
            # the reference's (num_layers*2, B, H) layout reshapable to
            # (num_layers, 2, ...) (contrib/layers/rnn_impl.py:196)
            finals = [f for pair in zip(finals_f, finals_b)
                      for f in pair]
        else:
            finals = finals_f
        return x, self._merge_finals(finals)

    def _slice_initial(self, initial_states, layer: int, backward: bool):
        """Pick layer/direction states out of the stacked initial-state
        layout — the SAME layer-major layout _merge_finals emits
        ((num_layers*dirs, B, H), reshapable to (num_layers, dirs, ...)),
        so `out, st = rnn(x); rnn(y, st)` carries state across segments
        (truncated BPTT) and reference-layout states route correctly."""
        if initial_states is None:
            return None
        n_dirs = 2 if self.bidirect else 1
        idx = layer * n_dirs + (1 if backward else 0)
        if isinstance(initial_states, tuple):
            return tuple(s[idx] for s in initial_states)
        return initial_states[idx]

    def _merge_finals(self, finals):
        if isinstance(finals[0], tuple):
            hs = jnp.stack([f[0] for f in finals], axis=0)
            cs = jnp.stack([f[1] for f in finals], axis=0)
            return (hs, cs)
        return jnp.stack(finals, axis=0)


class LSTM(_StackedRNNBase):
    """(ref: cudnn_lstm_op.cu capability)."""

    def _make_cell(self, in_size, hidden):
        return LSTMCell(in_size, hidden)


class GRU(_StackedRNNBase):
    def _make_cell(self, in_size, hidden):
        return GRUCell(in_size, hidden)


class SimpleRNN(_StackedRNNBase):
    def _make_cell(self, in_size, hidden):
        return SimpleRNNCell(in_size, hidden)
