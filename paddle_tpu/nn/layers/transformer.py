"""Transformer layers.

TPU-native transformer stack. The reference's transformer support is
op-level fusions (fused/multihead_matmul_op.cu,
fused_embedding_eltwise_layernorm_op.cu, ir skip_layernorm_fuse_pass) used
by its BERT/ERNIE models; here the same capability is a first-class layer
family whose attention core routes through kernels.maybe_flash_attention
(Pallas on TPU). Shapes are [batch, seq, hidden] throughout; bf16-friendly.
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp

from ...core.dtype import get_default_dtype
from ...ops import activation as A
from ...ops import nn_functional as F
from .. import initializer as I
from ..layer import Layer, LayerList, Parameter
from .common import Dropout, Linear
from .norm import LayerNorm


class MultiHeadAttention(Layer):
    """(capability ref: multihead_matmul_op.cu fused attention)."""

    def __init__(self, embed_dim: int, num_heads: int,
                 dropout: float = 0.0, kdim: Optional[int] = None,
                 vdim: Optional[int] = None, need_weights: bool = False,
                 weight_attr=None, bias_attr=None) -> None:
        super().__init__()
        self.embed_dim = embed_dim
        self.num_heads = num_heads
        self.head_dim = embed_dim // num_heads
        self.dropout = dropout
        self.need_weights = need_weights
        kdim = kdim or embed_dim
        vdim = vdim or embed_dim
        self.q_proj = Linear(embed_dim, embed_dim, weight_attr, bias_attr)
        self.k_proj = Linear(kdim, embed_dim, weight_attr, bias_attr)
        self.v_proj = Linear(vdim, embed_dim, weight_attr, bias_attr)
        self.out_proj = Linear(embed_dim, embed_dim, weight_attr, bias_attr)

    def _split(self, x):
        b, t, _ = x.shape
        return jnp.moveaxis(
            x.reshape(b, t, self.num_heads, self.head_dim), 2, 1)

    def _qkv_self(self, x):
        """Self-attention projections as ONE [d, 3d] matmul: the q/k/v
        weights are concatenated at trace time (XLA folds the concat of
        constants-at-step-scope into the dot operand), so the MXU sees a
        single large GEMM instead of three d×d ones — the same shape the
        reference's fused multihead_matmul_op.cu feeds cuBLAS. Parameter
        structure (q_proj/k_proj/v_proj) and checkpoints are unchanged;
        per-column math is identical (test_fused_qkv)."""
        w = jnp.concatenate([self.q_proj.weight, self.k_proj.weight,
                             self.v_proj.weight], axis=1)
        biases = [self.q_proj.bias, self.k_proj.bias, self.v_proj.bias]
        b = jnp.concatenate(biases) if all(
            bb is not None for bb in biases) else None
        qkv = F.linear(x, w, b)
        return jnp.split(qkv, 3, axis=-1)

    def forward(self, query, key=None, value=None, attn_mask=None,
                causal: bool = False):
        # Layout note: the main path hands the projections to attention
        # in their NATIVE [B, T, H, D] layout (layout="bthd") — the
        # flash kernel gathers heads inside its block DMA, so the
        # routed path runs zero physical head transposes (the r5 BERT
        # b8 profile measured ~2.2 ms/step of transpose_jvp around
        # attention). The XLA fallback transposes to BHTD internally,
        # costing exactly what the old caller-side split did. An
        # earlier transpose-free attempt (ops.attention.attention_bthd)
        # targeted the XLA composition, where dot_general re-transposes
        # anyway — that objection does not apply to the Pallas path.
        from ...flags import GLOBAL_FLAGS
        fusable = (GLOBAL_FLAGS.get("fused_qkv_projection")
                   and key is None and value is None
                   and self.q_proj.in_features == self.k_proj.in_features
                   == self.v_proj.in_features
                   and ((self.q_proj.bias is None)
                        == (self.k_proj.bias is None)
                        == (self.v_proj.bias is None)))
        key = query if key is None else key
        value = key if value is None else value
        if fusable:
            qp, kp, vp = self._qkv_self(query)
        else:
            qp = self.q_proj(query)
            kp = self.k_proj(key)
            vp = self.v_proj(value)
        if self.need_weights:
            # the reference returns (out, attention weights); weights
            # require materializing the [B, H, Tq, Tk] probs, so this
            # path stays on the XLA composition by construction
            from ...ops.attention import scaled_dot_product_attention
            out, weights = scaled_dot_product_attention(
                self._split(qp), self._split(kp), self._split(vp),
                mask=attn_mask, causal=causal,
                dropout_p=self.dropout, training=self.training,
                return_weights=True)
            b, h, t, d = out.shape
            out = jnp.moveaxis(out, 1, 2).reshape(b, t, h * d)
            out = self.out_proj(out)
            return out, weights
        from ...kernels import maybe_flash_attention
        if not GLOBAL_FLAGS.get("attention_bthd_layout"):
            # transpose layout (the measured A/B partner + escape hatch)
            out = maybe_flash_attention(
                self._split(qp), self._split(kp), self._split(vp),
                mask=attn_mask, causal=causal, dropout_p=self.dropout,
                training=self.training)
            b, h, t, d = out.shape
            return self.out_proj(
                jnp.moveaxis(out, 1, 2).reshape(b, t, h * d))

        def heads(x):
            b_, t_, _ = x.shape
            return x.reshape(b_, t_, self.num_heads, self.head_dim)

        out = maybe_flash_attention(
            heads(qp), heads(kp), heads(vp), mask=attn_mask,
            causal=causal, dropout_p=self.dropout,
            training=self.training, layout="bthd")
        b, t, h, d = out.shape
        return self.out_proj(out.reshape(b, t, h * d))


class TransformerEncoderLayer(Layer):
    def __init__(self, d_model: int, nhead: int, dim_feedforward: int,
                 dropout: float = 0.1, activation: str = "relu",
                 attn_dropout: Optional[float] = None,
                 act_dropout: Optional[float] = None,
                 normalize_before: bool = False) -> None:
        super().__init__()
        self.self_attn = MultiHeadAttention(
            d_model, nhead,
            dropout=attn_dropout if attn_dropout is not None else dropout)
        self.linear1 = Linear(d_model, dim_feedforward)
        self.linear2 = Linear(dim_feedforward, d_model)
        self.norm1 = LayerNorm(d_model)
        self.norm2 = LayerNorm(d_model)
        self.dropout1 = Dropout(dropout)
        self.dropout2 = Dropout(dropout)
        self.act_dropout = Dropout(
            act_dropout if act_dropout is not None else dropout)
        self.activation = getattr(A, activation)
        self.normalize_before = normalize_before

    def forward(self, src, src_mask=None):
        residual = src
        if self.normalize_before:
            src = self.norm1(src)
        src = self.self_attn(src, attn_mask=src_mask)
        src = residual + self.dropout1(src)
        if not self.normalize_before:
            src = self.norm1(src)
        residual = src
        if self.normalize_before:
            src = self.norm2(src)
        src = self.linear2(self.act_dropout(self.activation(
            self.linear1(src))))
        src = residual + self.dropout2(src)
        if not self.normalize_before:
            src = self.norm2(src)
        return src


class TransformerEncoder(Layer):
    def __init__(self, encoder_layer_ctor, num_layers: int,
                 norm: Optional[Layer] = None) -> None:
        super().__init__()
        self.layers = LayerList([encoder_layer_ctor()
                                 for _ in range(num_layers)])
        if norm is not None:
            self.norm = norm
        self.has_norm = norm is not None

    def forward(self, src, src_mask=None):
        from ...flags import GLOBAL_FLAGS
        out = src
        remat = (GLOBAL_FLAGS.get("transformer_remat")
                 and self.training)
        for layer in self.layers:
            if remat:
                # per-layer rematerialization: the backward recomputes
                # this layer's activations instead of keeping them —
                # trades ~1/3 more FLOPs for O(layers) less activation
                # HBM (jax.checkpoint; traced RNG replays identically,
                # so dropout masks match between fwd and recompute)
                out = jax.checkpoint(
                    lambda s, m, _l=layer: _l(s, src_mask=m))(out, src_mask)
            else:
                out = layer(out, src_mask=src_mask)
        if self.has_norm:
            out = self.norm(out)
        return out


class TransformerDecoderLayer(Layer):
    def __init__(self, d_model: int, nhead: int, dim_feedforward: int,
                 dropout: float = 0.1, activation: str = "relu",
                 normalize_before: bool = False) -> None:
        super().__init__()
        self.self_attn = MultiHeadAttention(d_model, nhead, dropout=dropout)
        self.cross_attn = MultiHeadAttention(d_model, nhead, dropout=dropout)
        self.linear1 = Linear(d_model, dim_feedforward)
        self.linear2 = Linear(dim_feedforward, d_model)
        self.norm1 = LayerNorm(d_model)
        self.norm2 = LayerNorm(d_model)
        self.norm3 = LayerNorm(d_model)
        self.dropout1 = Dropout(dropout)
        self.dropout2 = Dropout(dropout)
        self.dropout3 = Dropout(dropout)
        self.activation = getattr(A, activation)
        self.normalize_before = normalize_before

    def forward(self, tgt, memory, tgt_mask=None, memory_mask=None):
        residual = tgt
        if self.normalize_before:
            tgt = self.norm1(tgt)
        tgt = self.self_attn(tgt, attn_mask=tgt_mask, causal=tgt_mask is None)
        tgt = residual + self.dropout1(tgt)
        if not self.normalize_before:
            tgt = self.norm1(tgt)
        residual = tgt
        if self.normalize_before:
            tgt = self.norm2(tgt)
        tgt = self.cross_attn(tgt, memory, memory, attn_mask=memory_mask)
        tgt = residual + self.dropout2(tgt)
        if not self.normalize_before:
            tgt = self.norm2(tgt)
        residual = tgt
        if self.normalize_before:
            tgt = self.norm3(tgt)
        tgt = self.linear2(self.activation(self.linear1(tgt)))
        tgt = residual + self.dropout3(tgt)
        if not self.normalize_before:
            tgt = self.norm3(tgt)
        return tgt


class TransformerDecoder(Layer):
    def __init__(self, decoder_layer_ctor, num_layers: int,
                 norm: Optional[Layer] = None) -> None:
        super().__init__()
        self.layers = LayerList([decoder_layer_ctor()
                                 for _ in range(num_layers)])
        if norm is not None:
            self.norm = norm
        self.has_norm = norm is not None

    def forward(self, tgt, memory, tgt_mask=None, memory_mask=None):
        out = tgt
        for layer in self.layers:
            out = layer(out, memory, tgt_mask, memory_mask)
        if self.has_norm:
            out = self.norm(out)
        return out


class Transformer(Layer):
    def __init__(self, d_model: int = 512, nhead: int = 8,
                 num_encoder_layers: int = 6, num_decoder_layers: int = 6,
                 dim_feedforward: int = 2048, dropout: float = 0.1,
                 activation: str = "relu",
                 normalize_before: bool = False) -> None:
        super().__init__()
        self.encoder = TransformerEncoder(
            lambda: TransformerEncoderLayer(
                d_model, nhead, dim_feedforward, dropout, activation,
                normalize_before=normalize_before), num_encoder_layers,
            LayerNorm(d_model) if normalize_before else None)
        self.decoder = TransformerDecoder(
            lambda: TransformerDecoderLayer(
                d_model, nhead, dim_feedforward, dropout, activation,
                normalize_before), num_decoder_layers,
            LayerNorm(d_model) if normalize_before else None)

    def forward(self, src, tgt, src_mask=None, tgt_mask=None,
                memory_mask=None):
        memory = self.encoder(src, src_mask)
        return self.decoder(tgt, memory, tgt_mask, memory_mask)
