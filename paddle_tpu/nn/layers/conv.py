"""Convolution & pooling layers.

TPU-native wrappers (reference: python/paddle/fluid/dygraph/nn.py Conv2D /
Pool2D and python/paddle/nn/layer/conv.py, pooling.py; kernels in
paddle/fluid/operators/conv_op.cc and pool_op.cc). Weight layout is OIHW to
match the reference; XLA re-layouts for the MXU internally.
"""

from __future__ import annotations

from typing import Optional, Sequence, Union

from ...core.dtype import get_default_dtype
from ...ops import nn_functional as F
from .. import initializer as I
from ..layer import Layer, Parameter

IntOrPair = Union[int, Sequence[int]]


def _pair(v, n=2):
    return (v,) * n if isinstance(v, int) else tuple(v)


class _ConvNd(Layer):
    def __init__(self, in_channels: int, out_channels: int,
                 kernel_size: IntOrPair, stride: IntOrPair, padding,
                 dilation: IntOrPair, groups: int, weight_attr, bias_attr,
                 spatial: int, transpose: bool = False,
                 output_padding: IntOrPair = 0,
                 data_format: str = "NCHW") -> None:
        super().__init__()
        self.in_channels = in_channels
        self.out_channels = out_channels
        self.kernel_size = _pair(kernel_size, spatial)
        self.stride = stride
        self.padding = padding
        self.output_padding = output_padding
        self.dilation = dilation
        self.groups = groups
        self.data_format = data_format
        if transpose:
            w_shape = (in_channels, out_channels // groups) \
                + self.kernel_size
        else:
            w_shape = (out_channels, in_channels // groups) \
                + self.kernel_size
        self.weight = I.make_param(weight_attr, I.KaimingUniform(),
                                   w_shape, get_default_dtype())
        if bias_attr is False:
            self.bias = None
        else:
            self.bias = I.make_param(bias_attr, I.Constant(0.0),
                                     (out_channels,),
                                     get_default_dtype())

    def _bias(self):
        return self.bias if "bias" in self._parameters else None


class Conv1D(_ConvNd):
    def __init__(self, in_channels, out_channels, kernel_size, stride=1,
                 padding=0, dilation=1, groups=1, weight_attr=None,
                 bias_attr=None, data_format="NCL") -> None:
        super().__init__(in_channels, out_channels, kernel_size, stride,
                         padding, dilation, groups, weight_attr, bias_attr,
                         spatial=1)

    def forward(self, x):
        k = self.kernel_size[0]
        s = self.stride if isinstance(self.stride, int) else self.stride[0]
        d = self.dilation if isinstance(self.dilation, int) \
            else self.dilation[0]
        p = self.padding if isinstance(self.padding, (int, str)) \
            else self.padding[0]
        return F.conv1d(x, self.weight, self._bias(), s, p, d, self.groups)


class Conv2D(_ConvNd):
    def __init__(self, in_channels, out_channels, kernel_size, stride=1,
                 padding=0, dilation=1, groups=1, weight_attr=None,
                 bias_attr=None, data_format="NCHW") -> None:
        super().__init__(in_channels, out_channels, kernel_size, stride,
                         padding, dilation, groups, weight_attr, bias_attr,
                         spatial=2, data_format=data_format)

    def forward(self, x):
        # weights are stored OIHW whatever the activation layout, so
        # checkpoints are layout-independent (NHWC transposes the small
        # filter inside XLA, never the activations)
        return F.conv2d(x, self.weight, self._bias(), self.stride,
                        self.padding, self.dilation, self.groups,
                        self.data_format, weight_format="OIHW")


class Conv3D(_ConvNd):
    def __init__(self, in_channels, out_channels, kernel_size, stride=1,
                 padding=0, dilation=1, groups=1, weight_attr=None,
                 bias_attr=None, data_format="NCDHW") -> None:
        super().__init__(in_channels, out_channels, kernel_size, stride,
                         padding, dilation, groups, weight_attr, bias_attr,
                         spatial=3, data_format=data_format)

    def forward(self, x):
        return F.conv3d(x, self.weight, self._bias(), self.stride,
                        self.padding, self.dilation, self.groups,
                        self.data_format)


class Conv2DTranspose(_ConvNd):
    def __init__(self, in_channels, out_channels, kernel_size, stride=1,
                 padding=0, output_padding=0, dilation=1, groups=1,
                 weight_attr=None, bias_attr=None,
                 data_format="NCHW") -> None:
        super().__init__(in_channels, out_channels, kernel_size, stride,
                         padding, dilation, groups, weight_attr, bias_attr,
                         spatial=2, transpose=True,
                         output_padding=output_padding,
                         data_format=data_format)

    def forward(self, x):
        return F.conv2d_transpose(x, self.weight, self._bias(), self.stride,
                                  self.padding, self.output_padding,
                                  self.dilation, self.groups,
                                  data_format=self.data_format)


class MaxPool2D(Layer):
    def __init__(self, kernel_size, stride=None, padding=0,
                 ceil_mode: bool = False,
                 data_format: str = "NCHW") -> None:
        super().__init__()
        self.kernel_size = kernel_size
        self.stride = stride
        self.padding = padding
        self.ceil_mode = ceil_mode
        self.data_format = data_format

    def forward(self, x):
        return F.max_pool2d(x, self.kernel_size, self.stride, self.padding,
                            self.ceil_mode, self.data_format)


class AvgPool2D(Layer):
    def __init__(self, kernel_size, stride=None, padding=0,
                 ceil_mode: bool = False, exclusive: bool = True,
                 data_format: str = "NCHW") -> None:
        super().__init__()
        self.kernel_size = kernel_size
        self.stride = stride
        self.padding = padding
        self.ceil_mode = ceil_mode
        self.exclusive = exclusive
        self.data_format = data_format

    def forward(self, x):
        return F.avg_pool2d(x, self.kernel_size, self.stride, self.padding,
                            self.ceil_mode, self.exclusive,
                            self.data_format)


class MaxPool3D(Layer):
    def __init__(self, kernel_size, stride=None, padding=0,
                 ceil_mode: bool = False) -> None:
        super().__init__()
        self.kernel_size = kernel_size
        self.stride = stride
        self.padding = padding
        self.ceil_mode = ceil_mode

    def forward(self, x):
        return F.max_pool3d(x, self.kernel_size, self.stride, self.padding,
                            self.ceil_mode)


class AvgPool3D(Layer):
    def __init__(self, kernel_size, stride=None, padding=0,
                 ceil_mode: bool = False) -> None:
        super().__init__()
        self.kernel_size = kernel_size
        self.stride = stride
        self.padding = padding
        self.ceil_mode = ceil_mode

    def forward(self, x):
        return F.avg_pool3d(x, self.kernel_size, self.stride, self.padding,
                            self.ceil_mode)


class AdaptiveAvgPool2D(Layer):
    def __init__(self, output_size, data_format: str = "NCHW") -> None:
        super().__init__()
        self.output_size = output_size
        self.data_format = data_format

    def forward(self, x):
        return F.adaptive_avg_pool2d(x, self.output_size,
                                     self.data_format)


class AdaptiveMaxPool2D(Layer):
    def __init__(self, output_size) -> None:
        super().__init__()
        self.output_size = output_size

    def forward(self, x):
        return F.adaptive_max_pool2d(x, self.output_size)


class Unfold(Layer):
    def __init__(self, kernel_sizes, strides=1, paddings=0,
                 dilations=1) -> None:
        super().__init__()
        self.kernel_sizes = kernel_sizes
        self.strides = strides
        self.paddings = paddings
        self.dilations = dilations

    def forward(self, x):
        return F.unfold(x, self.kernel_sizes, self.strides, self.paddings,
                        self.dilations)


class Fold(Layer):
    def __init__(self, output_sizes, kernel_sizes, strides=1, paddings=0,
                 dilations=1) -> None:
        super().__init__()
        self.output_sizes = output_sizes
        self.kernel_sizes = kernel_sizes
        self.strides = strides
        self.paddings = paddings
        self.dilations = dilations

    def forward(self, x):
        return F.fold(x, self.output_sizes, self.kernel_sizes, self.strides,
                      self.paddings, self.dilations)


class PixelShuffle(Layer):
    def __init__(self, upscale_factor: int,
                 data_format: str = "NCHW") -> None:
        super().__init__()
        self.upscale_factor = upscale_factor
        self.data_format = data_format

    def forward(self, x):
        from ...ops.manipulation import pixel_shuffle
        return pixel_shuffle(x, self.upscale_factor, self.data_format)
