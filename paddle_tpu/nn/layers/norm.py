"""Normalization layers.

TPU-native wrappers (reference: python/paddle/fluid/dygraph/nn.py BatchNorm
:1035, LayerNorm, GroupNorm, SpectralNorm; kernels batch_norm_op.cc,
layer_norm_op.cc, instance_norm_op.cc, group_norm_op.cc,
sync_batch_norm_op.cc). BatchNorm running stats are registered buffers;
under jit they are captured by Layer.bind and threaded through step state
(the reference instead mutates scope variables in-place).
"""

from __future__ import annotations

from typing import Optional

import jax.numpy as jnp

from ...core.dtype import get_default_dtype
from ...ops import nn_functional as F
from .. import initializer as I
from ..layer import Layer, Parameter


class _BatchNormBase(Layer):
    def __init__(self, num_features: int, momentum: float = 0.9,
                 epsilon: float = 1e-5, weight_attr=None, bias_attr=None,
                 data_format: str = "NCHW",
                 use_global_stats: Optional[bool] = None,
                 sync_axis: Optional[str] = None) -> None:
        super().__init__()
        self.num_features = num_features
        self.momentum = momentum
        self.epsilon = epsilon
        self.data_format = data_format
        self.use_global_stats = use_global_stats
        self.sync_axis = sync_axis
        dt = get_default_dtype()
        if weight_attr is False:
            self.weight = None
        else:
            self.weight = I.make_param(weight_attr, I.Constant(1.0),
                             (num_features,), dt)
        if bias_attr is False:
            self.bias = None
        else:
            self.bias = I.make_param(bias_attr, I.Constant(0.0),
                             (num_features,), dt)
        self.register_buffer("_mean", jnp.zeros((num_features,), dt))
        self.register_buffer("_variance", jnp.ones((num_features,), dt))

    def forward(self, x):
        training = self.training and not (self.use_global_stats is True)
        w = self.weight if "weight" in self._parameters else None
        b = self.bias if "bias" in self._parameters else None
        if self.sync_axis is not None:
            out, new_mean, new_var = F.sync_batch_norm(
                x, self._mean, self._variance, w, b, training,
                self.momentum, self.epsilon, self.data_format,
                axis_name=self.sync_axis)
        else:
            out, new_mean, new_var = F.batch_norm(
                x, self._mean, self._variance, w, b, training,
                self.momentum, self.epsilon, self.data_format)
        if training:
            self._mean = new_mean
            self._variance = new_var
        return out


class BatchNorm(_BatchNormBase):
    """Fluid-style BatchNorm (dygraph/nn.py:1035)."""


class BatchNorm1D(_BatchNormBase):
    def __init__(self, num_features, **kw):
        kw.setdefault("data_format", "NCL")
        super().__init__(num_features, **kw)


class BatchNorm2D(_BatchNormBase):
    pass


class BatchNorm3D(_BatchNormBase):
    def __init__(self, num_features, **kw):
        kw.setdefault("data_format", "NCDHW")
        super().__init__(num_features, **kw)


class SyncBatchNorm(_BatchNormBase):
    """(ref: sync_batch_norm_op.cc) — set ``sync_axis`` to the data-parallel
    mesh axis name; stats are pmean-reduced when run under shard_map."""

    def __init__(self, num_features, sync_axis: str = "dp", **kw):
        super().__init__(num_features, sync_axis=sync_axis, **kw)

    @classmethod
    def convert_sync_batchnorm(cls, layer: Layer,
                               sync_axis: str = "dp") -> Layer:
        for _, sub in layer.named_sublayers(include_self=True):
            if isinstance(sub, _BatchNormBase):
                object.__setattr__(sub, "sync_axis", sync_axis)
        return layer


class LayerNorm(Layer):
    """(ref: layer_norm_op.cc). normalized_shape covers trailing dims."""

    def __init__(self, normalized_shape, epsilon: float = 1e-5,
                 weight_attr=None, bias_attr=None) -> None:
        super().__init__()
        if isinstance(normalized_shape, int):
            normalized_shape = (normalized_shape,)
        self.normalized_shape = tuple(normalized_shape)
        self.epsilon = epsilon
        dt = get_default_dtype()
        if weight_attr is False:
            self.weight = None
        else:
            self.weight = I.make_param(weight_attr, I.Constant(1.0),
                             self.normalized_shape, dt)
        if bias_attr is False:
            self.bias = None
        else:
            self.bias = I.make_param(bias_attr, I.Constant(0.0),
                             self.normalized_shape, dt)

    def forward(self, x):
        w = self.weight if "weight" in self._parameters else None
        b = self.bias if "bias" in self._parameters else None
        begin = x.ndim - len(self.normalized_shape)
        from ...kernels import maybe_layer_norm
        return maybe_layer_norm(x, w, b, self.epsilon, begin)


class InstanceNorm2D(Layer):
    def __init__(self, num_features: int, epsilon: float = 1e-5,
                 weight_attr=None, bias_attr=None) -> None:
        super().__init__()
        dt = get_default_dtype()
        if weight_attr is False:
            self.weight = None
        else:
            self.weight = I.make_param(weight_attr, I.Constant(1.0),
                             (num_features,), dt)
        if bias_attr is False:
            self.bias = None
        else:
            self.bias = I.make_param(bias_attr, I.Constant(0.0),
                             (num_features,), dt)
        self.epsilon = epsilon

    def forward(self, x):
        w = self.weight if "weight" in self._parameters else None
        b = self.bias if "bias" in self._parameters else None
        return F.instance_norm(x, w, b, self.epsilon)


InstanceNorm1D = InstanceNorm2D
InstanceNorm3D = InstanceNorm2D


class GroupNorm(Layer):
    def __init__(self, num_groups: int, num_channels: int,
                 epsilon: float = 1e-5, weight_attr=None,
                 bias_attr=None) -> None:
        super().__init__()
        self.num_groups = num_groups
        self.epsilon = epsilon
        dt = get_default_dtype()
        if weight_attr is False:
            self.weight = None
        else:
            self.weight = I.make_param(weight_attr, I.Constant(1.0),
                             (num_channels,), dt)
        if bias_attr is False:
            self.bias = None
        else:
            self.bias = I.make_param(bias_attr, I.Constant(0.0),
                             (num_channels,), dt)

    def forward(self, x):
        w = self.weight if "weight" in self._parameters else None
        b = self.bias if "bias" in self._parameters else None
        return F.group_norm(x, self.num_groups, w, b, self.epsilon)


class SpectralNorm(Layer):
    """(ref: spectral_norm_op.cc)."""

    def __init__(self, weight_shape, dim: int = 0,
                 power_iters: int = 1, epsilon: float = 1e-12) -> None:
        super().__init__()
        self.dim = dim
        self.power_iters = power_iters
        self.epsilon = epsilon
        import numpy as np
        h = weight_shape[dim]
        w = int(np.prod(weight_shape)) // h
        from ...core import random as _random
        import jax
        self.register_buffer("weight_u", jax.random.normal(
            _random.next_key("init"), (h,)))
        self.register_buffer("weight_v", jax.random.normal(
            _random.next_key("init"), (w,)))

    def forward(self, weight):
        return F.spectral_norm(weight, self.weight_u, self.weight_v,
                               self.power_iters, self.epsilon, self.dim)


class LocalResponseNorm(Layer):
    def __init__(self, size: int = 5, alpha: float = 1e-4,
                 beta: float = 0.75, k: float = 1.0) -> None:
        super().__init__()
        self.size = size
        self.alpha = alpha
        self.beta = beta
        self.k = k

    def forward(self, x):
        return F.local_response_norm(x, self.size, self.alpha, self.beta,
                                     self.k)
