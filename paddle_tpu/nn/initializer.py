"""Parameter initializers.

TPU-native analogue of /root/reference/python/paddle/fluid/initializer.py
(ConstantInitializer, UniformInitializer, NormalInitializer,
TruncatedNormalInitializer, XavierInitializer :366, MSRAInitializer :516,
BilinearInitializer, NumpyArrayInitializer). Each initializer is a callable
``(key, shape, dtype) -> array`` built on jax.random — deterministic given
the global seed, independent per parameter via key folding.
"""

from __future__ import annotations

import math
from typing import Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from ..core import random as _random
from ..core.dtype import convert_dtype


def _fans(shape: Sequence[int]):
    if len(shape) == 0:
        return 1, 1
    if len(shape) == 1:
        return shape[0], shape[0]
    if len(shape) == 2:
        return shape[0], shape[1]
    # conv kernels [out, in, *spatial] (OIHW)
    receptive = int(np.prod(shape[2:]))
    return shape[1] * receptive, shape[0] * receptive


class Initializer:
    def __call__(self, shape, dtype="float32", key=None):
        raise NotImplementedError


class Constant(Initializer):
    def __init__(self, value: float = 0.0) -> None:
        self.value = value

    def __call__(self, shape, dtype="float32", key=None):
        return jnp.full(tuple(shape), self.value, convert_dtype(dtype))


class Uniform(Initializer):
    def __init__(self, low: float = -1.0, high: float = 1.0) -> None:
        self.low, self.high = low, high

    def __call__(self, shape, dtype="float32", key=None):
        key = key if key is not None else _random.next_key("init")
        return jax.random.uniform(key, tuple(shape), convert_dtype(dtype),
                                  self.low, self.high)


class Normal(Initializer):
    def __init__(self, mean: float = 0.0, std: float = 1.0) -> None:
        self.mean, self.std = mean, std

    def __call__(self, shape, dtype="float32", key=None):
        key = key if key is not None else _random.next_key("init")
        return self.mean + self.std * jax.random.normal(
            key, tuple(shape), convert_dtype(dtype))


class TruncatedNormal(Initializer):
    def __init__(self, mean: float = 0.0, std: float = 1.0) -> None:
        self.mean, self.std = mean, std

    def __call__(self, shape, dtype="float32", key=None):
        key = key if key is not None else _random.next_key("init")
        return self.mean + self.std * jax.random.truncated_normal(
            key, -2.0, 2.0, tuple(shape), convert_dtype(dtype))


class XavierUniform(Initializer):
    """(ref: initializer.py:366 XavierInitializer uniform branch)."""

    def __init__(self, gain: float = 1.0) -> None:
        self.gain = gain

    def __call__(self, shape, dtype="float32", key=None):
        key = key if key is not None else _random.next_key("init")
        fan_in, fan_out = _fans(shape)
        limit = self.gain * math.sqrt(6.0 / (fan_in + fan_out))
        return jax.random.uniform(key, tuple(shape), convert_dtype(dtype),
                                  -limit, limit)


class XavierNormal(Initializer):
    def __init__(self, gain: float = 1.0) -> None:
        self.gain = gain

    def __call__(self, shape, dtype="float32", key=None):
        key = key if key is not None else _random.next_key("init")
        fan_in, fan_out = _fans(shape)
        std = self.gain * math.sqrt(2.0 / (fan_in + fan_out))
        return std * jax.random.normal(key, tuple(shape),
                                       convert_dtype(dtype))


class KaimingUniform(Initializer):
    """(ref: initializer.py:516 MSRAInitializer uniform branch)."""

    def __init__(self, negative_slope: float = 0.0,
                 nonlinearity: str = "relu", fan_mode: str = "fan_in") -> None:
        self.negative_slope = negative_slope
        self.nonlinearity = nonlinearity
        self.fan_mode = fan_mode

    def _gain(self) -> float:
        if self.nonlinearity == "relu":
            return math.sqrt(2.0)
        if self.nonlinearity == "leaky_relu":
            return math.sqrt(2.0 / (1 + self.negative_slope ** 2))
        if self.nonlinearity == "tanh":
            return 5.0 / 3.0
        return 1.0

    def __call__(self, shape, dtype="float32", key=None):
        key = key if key is not None else _random.next_key("init")
        fan_in, fan_out = _fans(shape)
        fan = fan_in if self.fan_mode == "fan_in" else fan_out
        limit = self._gain() * math.sqrt(3.0 / fan)
        return jax.random.uniform(key, tuple(shape), convert_dtype(dtype),
                                  -limit, limit)


class KaimingNormal(KaimingUniform):
    def __call__(self, shape, dtype="float32", key=None):
        key = key if key is not None else _random.next_key("init")
        fan_in, fan_out = _fans(shape)
        fan = fan_in if self.fan_mode == "fan_in" else fan_out
        std = self._gain() / math.sqrt(fan)
        return std * jax.random.normal(key, tuple(shape),
                                       convert_dtype(dtype))


class Bilinear(Initializer):
    """(ref: initializer.py BilinearInitializer — for upsample deconv)."""

    def __call__(self, shape, dtype="float32", key=None):
        if len(shape) != 4:
            raise ValueError("Bilinear init expects conv kernel rank 4")
        out_c, in_c, kh, kw = shape
        f_h = math.ceil(kh / 2.0)
        f_w = math.ceil(kw / 2.0)
        c_h = (2 * f_h - 1 - f_h % 2) / (2.0 * f_h)
        c_w = (2 * f_w - 1 - f_w % 2) / (2.0 * f_w)
        og = np.ogrid[:kh, :kw]
        filt = (1 - abs(og[0] / f_h - c_h)) * (1 - abs(og[1] / f_w - c_w))
        weight = np.zeros(shape, dtype=np.float32)
        for i in range(min(out_c, in_c)):
            weight[i, i] = filt
        return jnp.asarray(weight, dtype=convert_dtype(dtype))


class Assign(Initializer):
    """(ref: NumpyArrayInitializer)."""

    def __init__(self, value) -> None:
        self.value = np.asarray(value)

    def __call__(self, shape, dtype="float32", key=None):
        if tuple(self.value.shape) != tuple(shape):
            raise ValueError(
                f"Assign init shape {self.value.shape} != {tuple(shape)}")
        return jnp.asarray(self.value, dtype=convert_dtype(dtype))


class Orthogonal(Initializer):
    def __init__(self, gain: float = 1.0) -> None:
        self.gain = gain

    def __call__(self, shape, dtype="float32", key=None):
        key = key if key is not None else _random.next_key("init")
        return self.gain * jax.nn.initializers.orthogonal()(
            key, tuple(shape), convert_dtype(dtype))


def make_param(attr, default: "Initializer", shape, dtype):
    """Resolve ``attr`` (initializer / number / callable / str name /
    ParamAttr) and build the Parameter, honoring ParamAttr's
    per-parameter metadata (trainable / name / regularizer /
    need_clip) — a frozen ``ParamAttr(trainable=False)`` must actually
    freeze the weight. A bare string is fluid's name-only shorthand
    (ref: ParamAttr._to_attr accepts str)."""
    from .layer import Parameter
    if isinstance(attr, str):
        from ..param_attr import ParamAttr
        attr = ParamAttr(name=attr)
    value = _resolve(attr, default)(shape, dtype)
    if hasattr(attr, "initializer"):  # ParamAttr-like
        if getattr(attr, "learning_rate", 1.0) != 1.0:
            import warnings
            warnings.warn(
                "ParamAttr.learning_rate multipliers are not applied in "
                "this framework (the optimizer uses one LR schedule); "
                f"parameter {getattr(attr, 'name', None)!r} will train "
                "at the global rate", UserWarning, stacklevel=3)
        return Parameter(value,
                         trainable=getattr(attr, "trainable", True),
                         name=getattr(attr, "name", None),
                         regularizer=getattr(attr, "regularizer", None),
                         need_clip=getattr(attr, "need_clip", True))
    return Parameter(value)


def _resolve(init, default: Initializer) -> Initializer:
    if init is None:
        return default
    if isinstance(init, str):  # fluid name-only shorthand
        return default
    if hasattr(init, "initializer"):  # ParamAttr / WeightNormParamAttr
        return _resolve(init.initializer, default)
    if isinstance(init, Initializer):
        return init
    if isinstance(init, (int, float)):
        return Constant(float(init))
    if callable(init):
        return init
    raise TypeError(f"bad initializer {init!r}")


# ----------------------------------------------------------------- aliases
# Reference long-name spellings (ref: fluid/initializer.py:1004-1011;
# XavierInitializer/MSRAInitializer default to uniform=True there, so
# the aliases bind the uniform variants).
ConstantInitializer = Constant
UniformInitializer = Uniform
NormalInitializer = Normal
TruncatedNormalInitializer = TruncatedNormal
XavierInitializer = XavierUniform
Xavier = XavierUniform
MSRAInitializer = KaimingUniform
MSRA = KaimingUniform
BilinearInitializer = Bilinear
NumpyArrayInitializer = Assign
