"""Layer library (paddle.nn analogue).

Reference surface: python/paddle/nn/ (9.5k LoC of re-exports over fluid
dygraph layers) + python/paddle/fluid/dygraph/nn.py. See SURVEY.md §2.7.
"""

from . import functional, initializer
from .layer import (HookRemoveHelper, Layer, LayerList, Parameter,
                    ParameterList, Sequential, functional_call)
from .layers.common import (GLU, AlphaDropout, Bilinear, CosineSimilarity,
                            Dropout, Dropout2D, ELU, Embedding, Flatten,
                            GELU, Hardshrink, Hardsigmoid, Hardswish,
                            Hardtanh, Identity, LeakyReLU, Linear,
                            LogSigmoid, LogSoftmax, Maxout, Mish, PReLU,
                            Pad2D, ReLU, ReLU6, SELU, CELU, Sigmoid, Silu,
                            Softmax, Softplus, Softshrink, Softsign, Swish,
                            Tanh, Tanhshrink, ThresholdedReLU, Upsample)
from .layers.conv import (AdaptiveAvgPool2D, AdaptiveMaxPool2D, AvgPool2D,
                          AvgPool3D, Conv1D, Conv2D, Conv2DTranspose,
                          Conv3D, Fold, MaxPool2D, MaxPool3D, PixelShuffle,
                          Unfold)
from .layers.norm import (BatchNorm, BatchNorm1D, BatchNorm2D, BatchNorm3D,
                          GroupNorm, InstanceNorm1D, InstanceNorm2D,
                          InstanceNorm3D, LayerNorm, LocalResponseNorm,
                          SpectralNorm, SyncBatchNorm)
from .layers.loss import (BCELoss, BCEWithLogitsLoss, CTCLoss,
                          CosineEmbeddingLoss, CrossEntropyLoss,
                          FusedLinearCrossEntropy, KLDivLoss,
                          L1Loss, MSELoss, MarginRankingLoss, NLLLoss,
                          SmoothL1Loss, TripletMarginLoss)
from .layers.moe import MoELayer, moe_param_rule  # noqa: F401
from .decode import (BasicDecoder, BeamSearchDecoder,  # noqa: F401
                     DecodeHelper, Decoder, dynamic_decode,
                     GreedyEmbeddingHelper, SampleEmbeddingHelper,
                     TrainingHelper)
from .layers.rnn import (GRU, GRUCell, LSTM, LSTMCell, RNN, RNNCell,  # noqa
                         SimpleRNN,
                         SimpleRNNCell)
from .layers.transformer import (MultiHeadAttention, Transformer,
                                 TransformerDecoder,
                                 TransformerDecoderLayer,
                                 TransformerEncoder,
                                 TransformerEncoderLayer)
