"""paddle.nn.functional analogue — re-export of the functional ops."""

from ..ops.activation import *  # noqa: F401,F403
from ..ops.attention import (multihead_matmul,  # noqa: F401
                             scaled_dot_product_attention)
from ..ops.loss import *  # noqa: F401,F403
from ..ops.nn_functional import *  # noqa: F401,F403
from ..ops.sequence import (sequence_mask, sequence_pool,  # noqa: F401
                            sequence_softmax)
