"""Seq2seq decoding API — the reference's RNNCell/Decoder family
(/root/reference/python/paddle/fluid/layers/rnn.py: RNNCell, Decoder,
BasicDecoder, DecodeHelper, TrainingHelper, GreedyEmbeddingHelper,
SampleEmbeddingHelper, BeamSearchDecoder, dynamic_decode).

TPU-native redesign: the reference drives decoding with a while_op over
LoD tensors; here ``dynamic_decode`` is ONE ``lax.scan`` over a static
``max_step_num`` with a ``finished`` mask (XLA unrolls nothing, pads
nothing, and the whole decode jits). The cell protocol is the framework's
existing one — ``cell(inputs, states) -> (outputs, new_states)`` — so
``nn.LSTMCell``/``nn.GRUCell`` plug in directly as the reference's
RNNCell subclasses do. Beam search routes to the static-shape beam
machinery in ops/beam.py (beam_search_op.cc analogue).
"""

from __future__ import annotations

from typing import Any, Callable, Optional, Tuple

import jax
import jax.numpy as jnp

from ..core import random as _random

__all__ = ["Decoder", "BasicDecoder", "DecodeHelper", "TrainingHelper",
           "GreedyEmbeddingHelper", "SampleEmbeddingHelper",
           "BeamSearchDecoder", "dynamic_decode"]


class DecodeHelper:
    """Sampling/feeding policy for BasicDecoder (ref: rnn.py
    DecodeHelper): provides initial inputs, and how to sample + produce
    the next step's inputs."""

    def initialize(self, batch_size: int):
        raise NotImplementedError

    def sample(self, time, outputs):
        raise NotImplementedError

    def next_inputs(self, time, outputs, sample_ids):
        """returns (finished [B] bool, next_inputs)."""
        raise NotImplementedError


class TrainingHelper(DecodeHelper):
    """Teacher forcing: feed the ground-truth sequence
    (ref: rnn.py TrainingHelper). inputs: [B, T, ...]."""

    def __init__(self, inputs, sequence_length=None, time_major=False):
        self.inputs = jnp.swapaxes(inputs, 0, 1) if not time_major \
            else inputs                           # [T, B, ...]
        self.sequence_length = sequence_length
        self.t_max = self.inputs.shape[0]

    def initialize(self, batch_size: int):
        fin = jnp.zeros((batch_size,), bool) if self.sequence_length is \
            None else (jnp.asarray(self.sequence_length) <= 0)
        return fin, self.inputs[0]

    def sample(self, time, outputs):
        return jnp.argmax(outputs, axis=-1).astype(jnp.int32)

    def next_inputs(self, time, outputs, sample_ids):
        nxt = jnp.clip(time + 1, 0, self.t_max - 1)
        if self.sequence_length is not None:
            finished = (time + 1) >= jnp.asarray(self.sequence_length)
        else:
            finished = jnp.broadcast_to(time + 1 >= self.t_max,
                                        (outputs.shape[0],))
        return finished, self.inputs[nxt]


class GreedyEmbeddingHelper(DecodeHelper):
    """Inference: feed back argmax through an embedding
    (ref: rnn.py GreedyEmbeddingHelper)."""

    def __init__(self, embedding_fn: Callable, start_tokens, end_token):
        self.embedding_fn = embedding_fn
        self.start_tokens = jnp.asarray(start_tokens, jnp.int32)
        self.end_token = int(end_token)

    def initialize(self, batch_size: int):
        fin = jnp.zeros((batch_size,), bool)
        return fin, self.embedding_fn(self.start_tokens)

    def sample(self, time, outputs):
        return jnp.argmax(outputs, axis=-1).astype(jnp.int32)

    def next_inputs(self, time, outputs, sample_ids):
        return sample_ids == self.end_token, self.embedding_fn(sample_ids)


class SampleEmbeddingHelper(GreedyEmbeddingHelper):
    """Inference with sampling instead of argmax
    (ref: rnn.py SampleEmbeddingHelper)."""

    def __init__(self, embedding_fn, start_tokens, end_token,
                 softmax_temperature: Optional[float] = None, key=None):
        super().__init__(embedding_fn, start_tokens, end_token)
        self.temperature = softmax_temperature
        self.key = key

    def sample(self, time, outputs):
        logits = outputs if self.temperature is None \
            else outputs / self.temperature
        key = self.key if self.key is not None \
            else _random.next_key("random")
        # fold in the step so every timestep draws fresh randomness
        # while the scan stays side-effect free
        key = jax.random.fold_in(key, time)
        return jax.random.categorical(key, logits).astype(jnp.int32)


class Decoder:
    """One-step decode interface (ref: rnn.py Decoder)."""

    def initialize(self, inits, batch_size: int):
        raise NotImplementedError

    def step(self, time, inputs, states):
        """returns (outputs, next_states, next_inputs, finished)."""
        raise NotImplementedError


class BasicDecoder(Decoder):
    """cell + helper + optional output layer (ref: rnn.py BasicDecoder).
    outputs per step: (cell_outputs, sample_ids)."""

    def __init__(self, cell, helper: DecodeHelper,
                 output_fn: Optional[Callable] = None):
        self.cell = cell
        self.helper = helper
        self.output_fn = output_fn

    def initialize(self, inits, batch_size: int):
        finished, first_inputs = self.helper.initialize(batch_size)
        return first_inputs, inits, finished

    def step(self, time, inputs, states):
        cell_out, next_states = self.cell(inputs, states)
        if self.output_fn is not None:
            cell_out = self.output_fn(cell_out)
        sample_ids = self.helper.sample(time, cell_out)
        finished, next_inputs = self.helper.next_inputs(time, cell_out,
                                                        sample_ids)
        return (cell_out, sample_ids), next_states, next_inputs, finished


class BeamSearchDecoder:
    """Beam-search decoding (ref: rnn.py BeamSearchDecoder). Wraps the
    static-shape beam machinery (ops/beam.py — beam_search_op.cc
    analogue); consumed by :func:`dynamic_decode`."""

    def __init__(self, cell, start_token: int, end_token: int,
                 beam_size: int, embedding_fn: Callable,
                 output_fn: Optional[Callable] = None,
                 length_penalty: float = 0.0):
        self.cell = cell
        self.start_token = int(start_token)
        self.end_token = int(end_token)
        self.beam_size = beam_size
        self.embedding_fn = embedding_fn
        self.output_fn = output_fn
        self.length_penalty = length_penalty

    def decode(self, inits, batch_size: int, max_step_num: int):
        from ..ops.beam import beam_search
        k = self.beam_size

        # cell state pytree must be [batch, beam, ...]
        tiled = jax.tree.map(
            lambda leaf: jnp.broadcast_to(
                leaf[:, None], (batch_size, k) + leaf.shape[1:]), inits)

        def step_fn(tokens, cell_state):
            # flatten beams into the batch for the cell
            emb = self.embedding_fn(tokens.reshape(-1))
            flat_state = jax.tree.map(
                lambda leaf: leaf.reshape((-1,) + leaf.shape[2:]),
                cell_state)
            out, new_state = self.cell(emb, flat_state)
            if self.output_fn is not None:
                out = self.output_fn(out)
            log_probs = jax.nn.log_softmax(out, axis=-1)
            log_probs = log_probs.reshape(batch_size, k, -1)
            new_state = jax.tree.map(
                lambda leaf: leaf.reshape((batch_size, k)
                                          + leaf.shape[1:]), new_state)
            return log_probs, new_state

        return beam_search(step_fn, tiled, batch_size, k, max_step_num,
                           self.start_token, self.end_token,
                           self.length_penalty)


def dynamic_decode(decoder, inits=None, max_step_num: int = 100,
                   batch_size: Optional[int] = None,
                   output_time_major: bool = False,
                   impute_finished: bool = True):
    """Run a decoder to completion (ref: rnn.py dynamic_decode).

    For a :class:`BeamSearchDecoder` returns (sequences [B, beam, T],
    scores [B, beam]). For step decoders returns
    (outputs pytree stacked over time, final_states, sequence_lengths)
    — one lax.scan over ``max_step_num`` with finished masking (the
    reference's while_op + array-write loop).
    """
    if isinstance(decoder, BeamSearchDecoder):
        if batch_size is None:
            leaf = jax.tree.leaves(inits)[0]
            batch_size = leaf.shape[0]
        return decoder.decode(inits, batch_size, max_step_num)

    if batch_size is None:
        leaf = jax.tree.leaves(inits)[0]
        batch_size = leaf.shape[0]
    first_inputs, states0, finished0 = decoder.initialize(inits,
                                                          batch_size)

    def one_step(carry, time):
        inputs, states, finished, seq_len = carry
        outputs, next_states, next_inputs, step_fin = decoder.step(
            time, inputs, states)
        if impute_finished:
            # frozen state once finished (reference impute_finished)
            next_states = jax.tree.map(
                lambda new, old: jnp.where(
                    finished.reshape((-1,) + (1,) * (new.ndim - 1)),
                    old, new), next_states, states)
        seq_len = jnp.where(finished, seq_len, time + 1)
        new_finished = finished | step_fin
        return ((next_inputs, next_states, new_finished, seq_len),
                outputs)

    carry0 = (first_inputs, states0, finished0,
              jnp.zeros((batch_size,), jnp.int32))
    (_, final_states, _, seq_len), outputs = jax.lax.scan(
        one_step, carry0, jnp.arange(max_step_num))
    if not output_time_major:
        outputs = jax.tree.map(
            lambda leaf: jnp.swapaxes(leaf, 0, 1), outputs)
    return outputs, final_states, seq_len
