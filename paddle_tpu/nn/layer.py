"""Layer: the module system.

TPU-native redesign of the reference's dygraph Layer
(/root/reference/python/paddle/fluid/dygraph/layers.py and
paddle/fluid/imperative/layer.h): named parameters/buffers/sublayers with
eager execution — but built so the SAME object compiles under jit:

- Eagerly, a Layer holds concrete jax arrays and ``layer(x)`` dispatches ops
  immediately (the imperative Tracer path, tracer.cc:46, is simply jax eager).
- For compiled training, :meth:`state_dict` extracts the param/buffer pytree
  and :func:`functional_call` temporarily binds a (possibly traced) state
  into the layer tree, runs forward, and captures mutated buffers (BN
  running stats) — giving a pure function XLA can compile and donate
  buffers through. This replaces the reference's scope/variable mutation
  model (framework/scope.h:46) with state threading.
"""

from __future__ import annotations

import contextlib
import contextvars
from collections import OrderedDict
from typing import Any, Callable, Dict, Iterator, List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ..errors import InvalidArgumentError, NotFoundError


class Parameter:
    """Trainable leaf. Holds the array plus attributes the reference keeps
    on framework.Parameter (framework.py:5018): trainable flag, name,
    regularizer, and optimizer metadata hooks."""

    __slots__ = ("value", "trainable", "name", "regularizer", "need_clip")

    def __init__(self, value, trainable: bool = True,
                 name: Optional[str] = None, regularizer=None,
                 need_clip: bool = True) -> None:
        self.value = jnp.asarray(value)
        self.trainable = trainable
        self.name = name
        self.regularizer = regularizer
        self.need_clip = need_clip

    @property
    def shape(self):
        return self.value.shape

    @property
    def dtype(self):
        return self.value.dtype

    def numpy(self):
        return np.asarray(self.value)

    def set_value(self, value) -> None:
        """In-place value replacement (ref: VarBase.set_value,
        imperative/layer.h). Shape must match; dtype follows the new value
        if jax-compatible, else keeps the old dtype."""
        new = jnp.asarray(value)
        if tuple(new.shape) != tuple(self.value.shape):
            raise InvalidArgumentError(
                f"set_value shape mismatch: parameter has "
                f"{tuple(self.value.shape)}, got {tuple(new.shape)}")
        self.value = new

    def __repr__(self) -> str:
        return (f"Parameter(shape={tuple(self.value.shape)}, "
                f"dtype={self.value.dtype}, trainable={self.trainable})")


# Training-mode override: None = per-layer flags apply; a bool forces
# every Layer's .training during the with-block. Lets code that only
# holds a traced function (Program.clone(for_test=True)) flip the whole
# model to eval for one trace — the reference's is_test pass
# (ir is_test_pass, framework.py clone(for_test)). A ContextVar so a
# concurrent trace on another thread (hapi's async loops) can't have
# eval semantics leak into its cached executable.
_TRAINING_OVERRIDE: "contextvars.ContextVar[Optional[bool]]" = \
    contextvars.ContextVar("pt_training_override", default=None)


@contextlib.contextmanager
def eval_mode():
    """Force eval-mode (dropout off, BN running stats) for every Layer
    called inside the block, regardless of per-layer flags."""
    token = _TRAINING_OVERRIDE.set(False)
    try:
        yield
    finally:
        _TRAINING_OVERRIDE.reset(token)


class Layer:
    """Base class for all layers."""

    def __init__(self) -> None:
        object.__setattr__(self, "_parameters", OrderedDict())
        object.__setattr__(self, "_buffers", OrderedDict())
        object.__setattr__(self, "_non_persistable_buffers", set())
        object.__setattr__(self, "_sub_layers", OrderedDict())
        object.__setattr__(self, "training", True)
        object.__setattr__(self, "_forward_pre_hooks", OrderedDict())
        object.__setattr__(self, "_forward_post_hooks", OrderedDict())

    @property
    def training(self) -> bool:
        ov = _TRAINING_OVERRIDE.get()
        if ov is not None:
            return ov
        return self.__dict__.get("_training", True)

    @training.setter
    def training(self, value: bool) -> None:
        self.__dict__["_training"] = bool(value)

    # ------------------------------------------------------------------
    # attribute plumbing
    # ------------------------------------------------------------------
    def __setattr__(self, name: str, value: Any) -> None:
        params = self.__dict__.get("_parameters")
        buffers = self.__dict__.get("_buffers")
        subs = self.__dict__.get("_sub_layers")
        if isinstance(value, Parameter):
            if params is None:
                raise InvalidArgumentError(
                    "call Layer.__init__ before assigning parameters")
            params[name] = value
            self.__dict__.pop(name, None)
        elif isinstance(value, Layer):
            subs[name] = value
            self.__dict__.pop(name, None)
        elif params is not None and name in params:
            # assigning an array to an existing parameter name updates it
            params[name].value = jnp.asarray(value)
        elif buffers is not None and name in buffers:
            buffers[name] = jnp.asarray(value)
        else:
            object.__setattr__(self, name, value)

    def __getattr__(self, name: str) -> Any:
        # only called when normal lookup fails
        for store in ("_parameters", "_buffers", "_sub_layers"):
            d = self.__dict__.get(store)
            if d is not None and name in d:
                v = d[name]
                return v.value if isinstance(v, Parameter) else v
        raise AttributeError(
            f"'{type(self).__name__}' object has no attribute '{name}'")

    def __delattr__(self, name: str) -> None:
        for store in ("_parameters", "_buffers", "_sub_layers"):
            d = self.__dict__.get(store)
            if d is not None and name in d:
                del d[name]
                return
        object.__delattr__(self, name)

    # ------------------------------------------------------------------
    # registration
    # ------------------------------------------------------------------
    def add_parameter(self, name: str, param: Optional[Parameter]) -> \
            Optional[Parameter]:
        if param is not None and not isinstance(param, Parameter):
            param = Parameter(param)
        if param is None:
            self._parameters.pop(name, None)
        else:
            self._parameters[name] = param
        return param

    def register_buffer(self, name: str, value, persistable: bool = True):
        self._buffers[name] = jnp.asarray(value) if value is not None \
            else None
        if not persistable:
            # reference parity: non-persistable buffers still thread
            # through the functional step but stay out of state_dict
            self._non_persistable_buffers.add(name)
        else:
            self._non_persistable_buffers.discard(name)
        return self._buffers[name]

    def _persistable_buffer(self, name: str) -> bool:
        return name not in self._non_persistable_buffers

    def add_sublayer(self, name: str, sublayer: "Layer") -> "Layer":
        self._sub_layers[name] = sublayer
        return sublayer

    def get_parameter(self, name: str) -> Parameter:
        obj: Layer = self
        parts = name.split(".")
        for p in parts[:-1]:
            obj = obj._sub_layers[p]
        if parts[-1] not in obj._parameters:
            raise NotFoundError(f"parameter '{name}' not found")
        return obj._parameters[parts[-1]]

    # ------------------------------------------------------------------
    # traversal
    # ------------------------------------------------------------------
    def named_sublayers(self, prefix: str = "", include_self: bool = False) \
            -> Iterator[Tuple[str, "Layer"]]:
        if include_self:
            yield prefix, self
        for name, sub in self._sub_layers.items():
            sub_prefix = f"{prefix}.{name}" if prefix else name
            yield sub_prefix, sub
            yield from sub.named_sublayers(prefix=sub_prefix)

    def sublayers(self, include_self: bool = False) -> List["Layer"]:
        return [l for _, l in self.named_sublayers(include_self=include_self)]

    def named_parameters(self, prefix: str = "") \
            -> Iterator[Tuple[str, Parameter]]:
        for name, p in self._parameters.items():
            yield (f"{prefix}.{name}" if prefix else name), p
        for name, sub in self._sub_layers.items():
            sub_prefix = f"{prefix}.{name}" if prefix else name
            yield from sub.named_parameters(prefix=sub_prefix)

    def parameters(self) -> List[Parameter]:
        return [p for _, p in self.named_parameters()]

    def named_buffers(self, prefix: str = "") \
            -> Iterator[Tuple[str, jax.Array]]:
        for name, b in self._buffers.items():
            yield (f"{prefix}.{name}" if prefix else name), b
        for name, sub in self._sub_layers.items():
            sub_prefix = f"{prefix}.{name}" if prefix else name
            yield from sub.named_buffers(prefix=sub_prefix)

    def buffers(self) -> List[jax.Array]:
        return [b for _, b in self.named_buffers()]

    def apply(self, fn: Callable[["Layer"], None]) -> "Layer":
        for layer in self.sublayers(include_self=True):
            fn(layer)
        return self

    # ------------------------------------------------------------------
    # train / eval
    # ------------------------------------------------------------------
    def train(self) -> "Layer":
        for layer in self.sublayers(include_self=True):
            object.__setattr__(layer, "training", True)
        return self

    def eval(self) -> "Layer":
        for layer in self.sublayers(include_self=True):
            object.__setattr__(layer, "training", False)
        return self

    # ------------------------------------------------------------------
    # state dict
    # ------------------------------------------------------------------
    def state_dict(self, include_buffers: bool = True,
                   trainable_only: bool = False) -> Dict[str, jax.Array]:
        out: Dict[str, jax.Array] = {}
        for name, p in self.named_parameters():
            if trainable_only and not p.trainable:
                continue
            out[name] = p.value
        if include_buffers:
            # resolve buffer owners via the sublayer store (immune to
            # attribute shadowing), shared with set_state_dict/bind
            slots = self._named_buffer_slots()
            for name, b in self.named_buffers():
                owner, leaf = slots[name]
                if b is not None and owner._persistable_buffer(leaf):
                    out[name] = b
        return out

    def set_state_dict(self, state: Dict[str, Any],
                       strict: bool = True) -> None:
        own_params = dict(self.named_parameters())
        own_buffers = self._named_buffer_slots()
        for name, value in state.items():
            if name in own_params:
                own_params[name].value = jnp.asarray(value)
            elif name in own_buffers:
                layer, bname = own_buffers[name]
                layer._buffers[bname] = jnp.asarray(value)
            elif strict:
                raise NotFoundError(f"state key '{name}' not found in layer")

    load_dict = set_state_dict

    def _named_buffer_slots(self) -> Dict[str, Tuple["Layer", str]]:
        out: Dict[str, Tuple[Layer, str]] = {}

        def walk(layer: "Layer", prefix: str) -> None:
            for bname in layer._buffers:
                out[f"{prefix}.{bname}" if prefix else bname] = (layer, bname)
            for sname, sub in layer._sub_layers.items():
                walk(sub, f"{prefix}.{sname}" if prefix else sname)

        walk(self, "")
        return out

    # split state: params vs buffers — the functional step threads both
    def param_dict(self, trainable_only: bool = True) -> Dict[str, jax.Array]:
        return {n: p.value for n, p in self.named_parameters()
                if p.trainable or not trainable_only}

    def buffer_dict(self) -> Dict[str, jax.Array]:
        return {n: b for n, b in self.named_buffers() if b is not None}

    # ------------------------------------------------------------------
    # functional binding (see module docstring)
    # ------------------------------------------------------------------
    @contextlib.contextmanager
    def bind(self, params: Optional[Dict[str, Any]] = None,
             buffers: Optional[Dict[str, Any]] = None):
        """Temporarily substitute leaves; on exit, restore originals. The
        yielded capture object exposes mutated buffers after the block."""
        saved_params = {n: p.value for n, p in self.named_parameters()}
        saved_buffers = {}
        slots = self._named_buffer_slots()
        for n, (layer, bname) in slots.items():
            saved_buffers[n] = layer._buffers[bname]

        capture = _BindCapture()
        try:
            if params:
                own = dict(self.named_parameters())
                for n, v in params.items():
                    own[n].value = v
            if buffers:
                for n, v in buffers.items():
                    layer, bname = slots[n]
                    layer._buffers[bname] = v
            yield capture
            capture.buffers = {
                n: layer._buffers[bname]
                for n, (layer, bname) in slots.items()
                if layer._buffers[bname] is not None}
        finally:
            own = dict(self.named_parameters())
            for n, v in saved_params.items():
                own[n].value = v
            for n, (layer, bname) in slots.items():
                layer._buffers[bname] = saved_buffers[n]

    # ------------------------------------------------------------------
    # call
    # ------------------------------------------------------------------
    def forward(self, *args, **kwargs):
        raise NotImplementedError

    def __call__(self, *args, **kwargs):
        for hook in self._forward_pre_hooks.values():
            result = hook(self, args)
            if result is not None:
                args = result if isinstance(result, tuple) else (result,)
        out = self.forward(*args, **kwargs)
        for hook in self._forward_post_hooks.values():
            result = hook(self, args, out)
            if result is not None:
                out = result
        return out

    def register_forward_pre_hook(self, hook) -> "HookRemoveHelper":
        handle = HookRemoveHelper(self._forward_pre_hooks)
        self._forward_pre_hooks[handle.id] = hook
        return handle

    def register_forward_post_hook(self, hook) -> "HookRemoveHelper":
        handle = HookRemoveHelper(self._forward_post_hooks)
        self._forward_post_hooks[handle.id] = hook
        return handle

    # ------------------------------------------------------------------
    # dtype conversion
    # ------------------------------------------------------------------
    def to(self, dtype=None) -> "Layer":
        if dtype is not None:
            from ..core.dtype import convert_dtype
            dt = convert_dtype(dtype)
            for p in self.parameters():
                if jnp.issubdtype(p.value.dtype, jnp.floating):
                    p.value = p.value.astype(dt)
        return self

    def astype(self, dtype) -> "Layer":
        return self.to(dtype=dtype)

    def __repr__(self) -> str:
        lines = [type(self).__name__ + "("]
        for name, sub in self._sub_layers.items():
            sub_repr = repr(sub).replace("\n", "\n  ")
            lines.append(f"  ({name}): {sub_repr}")
        lines.append(")")
        return "\n".join(lines) if len(lines) > 2 else \
            type(self).__name__ + "()"


class _BindCapture:
    def __init__(self) -> None:
        self.buffers: Dict[str, jax.Array] = {}


class HookRemoveHelper:
    _next_id = 0

    def __init__(self, store: Dict) -> None:
        self._store = store
        self.id = HookRemoveHelper._next_id
        HookRemoveHelper._next_id += 1

    def remove(self) -> None:
        self._store.pop(self.id, None)


def functional_call(layer: Layer, params: Dict[str, Any],
                    buffers: Optional[Dict[str, Any]], *args,
                    capture_buffers: bool = False, **kwargs):
    """Pure-function view of ``layer``: run forward with the given state.

    Returns ``out`` or ``(out, new_buffers)`` when capture_buffers is set.
    """
    with layer.bind(params, buffers) as cap:
        out = layer(*args, **kwargs)
    if capture_buffers:
        return out, cap.buffers
    return out


class LayerList(Layer):
    def __init__(self, sublayers=None) -> None:
        super().__init__()
        if sublayers is not None:
            for i, l in enumerate(sublayers):
                self.add_sublayer(str(i), l)

    def append(self, sublayer: Layer) -> "LayerList":
        self.add_sublayer(str(len(self._sub_layers)), sublayer)
        return self

    def __getitem__(self, idx):
        if isinstance(idx, slice):
            return list(self._sub_layers.values())[idx]
        if idx < 0:
            idx += len(self._sub_layers)
        return self._sub_layers[str(idx)]

    def __len__(self) -> int:
        return len(self._sub_layers)

    def __iter__(self):
        return iter(self._sub_layers.values())


class ParameterList(Layer):
    def __init__(self, parameters=None) -> None:
        super().__init__()
        if parameters is not None:
            for i, p in enumerate(parameters):
                self.add_parameter(str(i), p)

    def append(self, p) -> "ParameterList":
        self.add_parameter(str(len(self._parameters)), p)
        return self

    def __getitem__(self, idx: int):
        return self._parameters[str(idx)].value

    def __len__(self) -> int:
        return len(self._parameters)


class Sequential(Layer):
    def __init__(self, *layers) -> None:
        super().__init__()
        if len(layers) == 1 and isinstance(layers[0], (list, tuple)) and \
                layers[0] and isinstance(layers[0][0], (list, tuple)):
            for name, layer in layers[0]:
                self.add_sublayer(name, layer)
        else:
            for i, layer in enumerate(layers):
                self.add_sublayer(str(i), layer)

    def forward(self, x):
        for layer in self._sub_layers.values():
            x = layer(x)
        return x

    def __getitem__(self, idx: int) -> Layer:
        return list(self._sub_layers.values())[idx]

    def __len__(self) -> int:
        return len(self._sub_layers)
