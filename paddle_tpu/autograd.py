"""Autograd surface.

The reference implements reverse-mode AD twice: statically
(/root/reference/python/paddle/fluid/backward.py:1215 append_backward walks
ops in reverse emitting grad ops) and eagerly
(/root/reference/paddle/fluid/imperative/basic_engine.cc:161 tape replay).
On TPU both collapse into jax's functional transforms: ``grad`` /
``value_and_grad`` ARE append_backward and BasicEngine — the jaxpr trace is
the tape, XLA emits the fused backward program. This module provides the
reference-shaped entry points plus double-grad (PartialGradEngine parity via
nested grad) and ``no_grad``.
"""

from __future__ import annotations

import contextlib
import functools
from typing import Any, Callable, Sequence, Union

import jax


def grad(fn_or_outputs, inputs=None, argnums: Union[int, Sequence[int]] = 0,
         has_aux: bool = False, create_graph: bool = False):
    """Two call styles:

    - transform style (idiomatic): ``grad(f)(x)`` — jax.grad semantics.
    - paddle.grad style is served by :func:`grad_values` below.
    """
    if callable(fn_or_outputs):
        return jax.grad(fn_or_outputs, argnums=argnums, has_aux=has_aux)
    raise TypeError(
        "grad(outputs, inputs) tape-style is not supported: TPU autograd is "
        "functional. Wrap the computation in a function and use "
        "grad(fn)(args) or value_and_grad.")


def value_and_grad(fn: Callable, argnums: Union[int, Sequence[int]] = 0,
                   has_aux: bool = False):
    return jax.value_and_grad(fn, argnums=argnums, has_aux=has_aux)


def jacobian(fn: Callable, argnums: int = 0, mode: str = "reverse"):
    return jax.jacrev(fn, argnums) if mode == "reverse" \
        else jax.jacfwd(fn, argnums)


def hessian(fn: Callable, argnums: int = 0):
    return jax.hessian(fn, argnums)


def vjp(fn: Callable, *primals, has_aux: bool = False):
    return jax.vjp(fn, *primals, has_aux=has_aux)


def jvp(fn: Callable, primals, tangents):
    return jax.jvp(fn, primals, tangents)


class _NoGradState:
    enabled = False


_no_grad_state = _NoGradState()


@contextlib.contextmanager
def no_grad():
    """Advisory in functional autograd; provided for API parity. Inside the
    context, ``stop_gradient`` is applied by layers that consult it."""
    prev = _no_grad_state.enabled
    _no_grad_state.enabled = True
    try:
        yield
    finally:
        _no_grad_state.enabled = prev


def in_no_grad() -> bool:
    return _no_grad_state.enabled


def stop_gradient(x):
    return jax.lax.stop_gradient(x)
