"""Profiling & observability.

TPU-native redesign of the reference's three-part tracing stack
(/root/reference/paddle/fluid/platform/profiler.h:126 RecordEvent spans,
profiler.h:208 Enable/DisableProfiler + chrome-trace output;
device_tracer.cc:61 CUPTI device timelines; monitor.h:33 global stat
registry). Mapping:

- CUPTI device tracing → **jax.profiler / XPlane**: start_profiler writes
  TensorBoard-loadable traces with real TPU kernel timelines.
- RecordEvent host spans → :class:`RecordEvent` (times host code AND
  forwards to jax.profiler.TraceAnnotation so spans land in the xplane).
- monitor.h STAT registry → :class:`StatRegistry` (monotonic counters).
- FLAGS_benchmark per-op sync → ``benchmark_sync()`` helper that
  block_until_ready()s a pytree (operator.cc:1022 analogue).
"""

from __future__ import annotations

import contextlib
import threading
import time
from collections import defaultdict
from typing import Any, Dict, List, Optional

import jax

from .flags import GLOBAL_FLAGS


class _ProfilerState:
    def __init__(self) -> None:
        self.active = False
        self.log_dir: Optional[str] = None
        self.events: List[Dict[str, Any]] = []
        self.lock = threading.Lock()


_state = _ProfilerState()


def start_profiler(log_dir: Optional[str] = None) -> None:
    """(ref: EnableProfiler, profiler.h:208)."""
    log_dir = log_dir or GLOBAL_FLAGS.get("profile_dir") or "/tmp/pt_prof"
    jax.profiler.start_trace(log_dir)
    _state.active = True
    _state.log_dir = log_dir


def stop_profiler() -> Optional[str]:
    """(ref: DisableProfiler) — returns the trace directory."""
    if _state.active:
        jax.profiler.stop_trace()
        _state.active = False
    return _state.log_dir


@contextlib.contextmanager
def profiler(log_dir: Optional[str] = None):
    """Context manager parity with fluid.profiler.profiler()."""
    start_profiler(log_dir)
    try:
        yield
    finally:
        stop_profiler()


class RecordEvent:
    """Host-side span that also annotates the device trace
    (ref: platform::RecordEvent RAII, profiler.h:126)."""

    def __init__(self, name: str) -> None:
        self.name = name
        self._trace_ctx = None
        self._t0 = 0.0

    def __enter__(self) -> "RecordEvent":
        self._t0 = time.perf_counter()
        self._trace_ctx = jax.profiler.TraceAnnotation(self.name)
        self._trace_ctx.__enter__()
        return self

    def __exit__(self, *exc) -> None:
        self._trace_ctx.__exit__(*exc)
        dt = time.perf_counter() - self._t0
        with _state.lock:
            _state.events.append({"name": self.name, "dur_s": dt,
                                  "ts": self._t0})


def get_host_events() -> List[Dict[str, Any]]:
    with _state.lock:
        return list(_state.events)


def reset_host_events() -> None:
    with _state.lock:
        _state.events.clear()


def event_summary() -> Dict[str, Dict[str, float]]:
    """Aggregated table like the reference's profiler summary printer."""
    agg: Dict[str, Dict[str, float]] = defaultdict(
        lambda: {"calls": 0, "total_s": 0.0, "max_s": 0.0})
    for e in get_host_events():
        a = agg[e["name"]]
        a["calls"] += 1
        a["total_s"] += e["dur_s"]
        a["max_s"] = max(a["max_s"], e["dur_s"])
    for a in agg.values():
        a["avg_s"] = a["total_s"] / max(a["calls"], 1)
    return dict(agg)


class StatRegistry:
    """(ref: monitor.h:33 StatRegistry, STAT_ADD :129)."""

    def __init__(self) -> None:
        self._stats: Dict[str, int] = defaultdict(int)
        self._lock = threading.Lock()

    def add(self, name: str, value: int = 1) -> None:
        with self._lock:
            self._stats[name] += value

    def get(self, name: str) -> int:
        with self._lock:
            return self._stats[name]

    def set(self, name: str, value: int) -> None:
        with self._lock:
            self._stats[name] = value

    def snapshot(self) -> Dict[str, int]:
        with self._lock:
            return dict(self._stats)


stats = StatRegistry()


def stat_add(name: str, value: int = 1) -> None:
    stats.add(name, value)


def benchmark_sync(tree) -> Any:
    """Block on device work for accurate timing
    (ref: FLAGS_benchmark sync, operator.cc:1022)."""
    return jax.block_until_ready(tree)


def device_memory_stats() -> Dict[str, int]:
    """Allocator stats analogue (ref: memory/stats + gpu_info mem flags)."""
    out: Dict[str, int] = {}
    for d in jax.local_devices():
        try:
            ms = d.memory_stats()
            if ms:
                out[str(d)] = int(ms.get("bytes_in_use", 0))
        except Exception:
            pass
    return out


class StepTimer:
    """Per-step timing hook with throughput accounting — the
    trainer-loop observability the reference gets from DeviceWorker
    PrintFetchVars/monitor stats."""

    def __init__(self, items_per_step: int = 0) -> None:
        self.items_per_step = items_per_step
        self.times: List[float] = []
        self._t0: Optional[float] = None

    def start(self) -> None:
        self._t0 = time.perf_counter()

    def stop(self, result=None) -> float:
        if GLOBAL_FLAGS.get("benchmark") and result is not None:
            benchmark_sync(result)
        dt = time.perf_counter() - (self._t0 or time.perf_counter())
        self.times.append(dt)
        return dt

    def throughput(self, skip_first: int = 1) -> float:
        ts = self.times[skip_first:] or self.times
        if not ts:
            return 0.0
        return self.items_per_step * len(ts) / sum(ts)
