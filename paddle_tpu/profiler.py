"""Profiling compat shim over :mod:`paddle_tpu.observability`.

The real implementation lives in ``paddle_tpu/observability/`` (metrics
registry, span tracer, recompile tracker, trace aggregation); this
module keeps the original surface working:

- ``start_profiler``/``stop_profiler``/``profiler`` — jax xplane device
  capture (ref: Enable/DisableProfiler, profiler.h:208).
- ``RecordEvent`` — host span + TraceAnnotation (ref: profiler.h:126);
  records regardless of FLAGS_enable_metrics (an explicit call is its
  own opt-in), now also landing in the exported chrome trace.
- ``stats``/``stat_add``/``StatRegistry`` — absorbed by the metrics
  registry (ref: monitor.h:33); names share the registry namespace.
- ``event_summary``/``get_host_events`` — served from the span tracer.
- ``benchmark_sync``, ``device_memory_stats``, ``StepTimer`` — as
  before, with the silent-failure fixes.
"""

from __future__ import annotations

import contextlib
import time
from typing import Any, Dict, List, Optional

import jax

from .flags import GLOBAL_FLAGS
from . import observability as _obs
from .observability import device_memory_stats  # noqa: F401  (public)


class _ProfilerState:
    def __init__(self) -> None:
        self.active = False
        self.log_dir: Optional[str] = None


_state = _ProfilerState()


def start_profiler(log_dir: Optional[str] = None) -> None:
    """(ref: EnableProfiler, profiler.h:208)."""
    log_dir = log_dir or GLOBAL_FLAGS.get("profile_dir") or "/tmp/pt_prof"
    jax.profiler.start_trace(log_dir)
    _state.active = True
    _state.log_dir = log_dir


def stop_profiler() -> Optional[str]:
    """(ref: DisableProfiler) — returns the trace directory."""
    if _state.active:
        jax.profiler.stop_trace()
        _state.active = False
    return _state.log_dir


@contextlib.contextmanager
def profiler(log_dir: Optional[str] = None):
    """Context manager parity with fluid.profiler.profiler()."""
    start_profiler(log_dir)
    try:
        yield
    finally:
        stop_profiler()


class RecordEvent:
    """Host-side span that also annotates the device trace
    (ref: platform::RecordEvent RAII, profiler.h:126)."""

    def __init__(self, name: str) -> None:
        self.name = name
        self._cm = None

    def __enter__(self) -> "RecordEvent":
        self._cm = _obs.span(self.name, force=True)
        self._cm.__enter__()
        return self

    def __exit__(self, *exc) -> None:
        self._cm.__exit__(*exc)


def get_host_events() -> List[Dict[str, Any]]:
    """Old event format: name / dur_s / ts (seconds)."""
    return [{"name": e["name"], "dur_s": e["dur"] / 1e6,
             "ts": e["ts"] / 1e6}
            for e in _obs.get_tracer().events() if e.get("ph") == "X"]


def reset_host_events() -> None:
    _obs.get_tracer().reset()


def event_summary() -> Dict[str, Dict[str, float]]:
    """Aggregated table like the reference's profiler summary printer."""
    return _obs.get_tracer().summary()


class StatRegistry:
    """(ref: monitor.h:33) — a view over the observability metrics
    registry; add/get/set keep their old int semantics and the counters
    they create are always-on (explicit user API)."""

    def __init__(self) -> None:
        self._names: Dict[str, bool] = {}

    def _c(self, name: str):
        self._names[name] = True
        return _obs.counter(name, always=True)

    def add(self, name: str, value: int = 1) -> None:
        self._c(name).inc(value)

    def get(self, name: str) -> int:
        return int(self._c(name).value())

    def set(self, name: str, value: int) -> None:
        self._c(name).set_total(value)

    def snapshot(self) -> Dict[str, int]:
        return {n: self.get(n) for n in self._names}


stats = StatRegistry()


def stat_add(name: str, value: int = 1) -> None:
    stats.add(name, value)


def benchmark_sync(tree) -> Any:
    """Block on device work for accurate timing
    (ref: FLAGS_benchmark sync, operator.cc:1022)."""
    return jax.block_until_ready(tree)


class StepTimer:
    """Per-step timing hook with throughput accounting — the
    trainer-loop observability the reference gets from DeviceWorker
    PrintFetchVars/monitor stats."""

    def __init__(self, items_per_step: int = 0) -> None:
        self.items_per_step = items_per_step
        self.times: List[float] = []
        self._t0: Optional[float] = None

    def start(self) -> None:
        self._t0 = time.perf_counter()

    def stop(self, result=None) -> float:
        if self._t0 is None:
            # stop() without start() used to silently time against
            # "now" and record a ~0 sample that skewed throughput
            return 0.0
        if GLOBAL_FLAGS.get("benchmark") and result is not None:
            benchmark_sync(result)
        dt = time.perf_counter() - self._t0
        self._t0 = None
        self.times.append(dt)
        return dt

    def throughput(self, skip_first: int = 1) -> float:
        # drop warmup samples, but never fall back to re-using the
        # skipped (compile-inflated) sample when it is the only one —
        # that reported a number dominated by compile time
        ts = self.times[skip_first:]
        if not ts:
            return 0.0
        return self.items_per_step * len(ts) / sum(ts)
