"""Host-boundary LoD <-> dense-padded conversion.

The reference's LoD (level-of-detail) layout packs ragged sequences into
one flat buffer plus recursive offset tables
(/root/reference/paddle/fluid/framework/lod_tensor.h:104;
python/paddle/fluid/lod_tensor.py:24 ``create_lod_tensor``). XLA needs
static shapes, so on TPU the ragged layout exists ONLY at the host
boundary: :class:`RaggedBatch` converts packed LoD data to the dense
padded ``[batch, max_len, ...] + lengths [batch]`` layout every op in
``ops/sequence.py`` consumes, and back.

Multi-level LoD: the innermost level segments tokens into sequences and
becomes the dense batch; every OUTER level groups sequences and is kept
as a plain lengths vector (``outer_lengths``). Hierarchical ops (e.g.
pool over level 0 of a 2-level tensor) are then two dense calls: pool
the inner batch, regroup with the outer lengths — the same
decomposition the reference performs internally over its offset tables.
"""

from __future__ import annotations

from typing import List, Optional, Sequence

import numpy as np


class RaggedBatch:
    """Dense padded view of a ragged batch: ``data`` [B, T, ...] with
    rows zero-padded past their length, ``lengths`` [B] int32, and, for
    multi-level LoD sources, ``outer_lengths`` — one lengths vector per
    collapsed outer level, outermost first."""

    def __init__(self, data, lengths, outer_lengths=None):
        self.data = np.asarray(data)
        self.lengths = np.asarray(lengths, dtype=np.int32).reshape(-1)
        if self.data.shape[0] != self.lengths.shape[0]:
            raise ValueError(
                f"data batch {self.data.shape[0]} != lengths batch "
                f"{self.lengths.shape[0]}")
        if self.data.ndim >= 2 and self.lengths.size and \
                int(self.lengths.max(initial=0)) > self.data.shape[1]:
            raise ValueError(
                f"length {int(self.lengths.max())} exceeds padded time "
                f"dim {self.data.shape[1]}")
        self.outer_lengths = [
            np.asarray(o, dtype=np.int32).reshape(-1)
            for o in (outer_lengths or [])]

    # -- constructors -------------------------------------------------
    @classmethod
    def from_list(cls, seqs: Sequence,
                  max_len: Optional[int] = None) -> "RaggedBatch":
        """From per-row arrays (each [Ti, ...])."""
        seqs = [np.asarray(s) for s in seqs]
        lengths = np.asarray([s.shape[0] for s in seqs], np.int32)
        t = max_len if max_len is not None else \
            (int(lengths.max()) if len(seqs) else 0)
        feat = seqs[0].shape[1:] if seqs else ()
        dtype = seqs[0].dtype if seqs else np.float32
        out = np.zeros((len(seqs), t) + feat, dtype=dtype)
        for i, s in enumerate(seqs):
            if s.shape[1:] != feat:
                raise ValueError(
                    f"row {i} feature shape {s.shape[1:]} != {feat}")
            if s.shape[0] > t:
                raise ValueError(
                    f"row {i} length {s.shape[0]} exceeds max_len {t}")
            out[i, :s.shape[0]] = s
        return cls(out, lengths)

    @classmethod
    def from_lod(cls, flat, recursive_seq_lens: List[List[int]],
                 max_len: Optional[int] = None) -> "RaggedBatch":
        """From the reference's packed layout: ``flat`` [sum(lens), ...]
        plus per-level lengths (the reference's recursive_seq_lens —
        lengths-based LoD, outermost level first). The innermost level
        becomes the dense batch; outer levels ride along as
        ``outer_lengths``."""
        flat = np.asarray(flat)
        if not recursive_seq_lens:
            raise ValueError("recursive_seq_lens must have >= 1 level")
        for lv, lens in enumerate(recursive_seq_lens[:-1]):
            if int(np.sum(lens)) != len(recursive_seq_lens[lv + 1]):
                raise ValueError(
                    f"level {lv} lengths sum {int(np.sum(lens))} != "
                    f"level {lv + 1} count "
                    f"{len(recursive_seq_lens[lv + 1])} (each outer "
                    f"entry must cover the next level's sequences)")
        inner = np.asarray(recursive_seq_lens[-1], np.int64)
        if int(inner.sum()) != flat.shape[0]:
            raise ValueError(
                f"innermost lengths sum {int(inner.sum())} != flat rows "
                f"{flat.shape[0]}")
        offsets = np.concatenate([[0], np.cumsum(inner)])
        rows = [flat[offsets[i]:offsets[i + 1]]
                for i in range(len(inner))]
        rb = cls.from_list(rows, max_len=max_len)
        rb.outer_lengths = [np.asarray(o, np.int32)
                            for o in recursive_seq_lens[:-1]]
        return rb

    # -- exporters ----------------------------------------------------
    def to_list(self) -> List[np.ndarray]:
        return [self.data[i, :int(n)] for i, n in enumerate(self.lengths)]

    def flat(self) -> np.ndarray:
        """Packed [sum(lengths), ...] buffer (the reference's layout)."""
        rows = self.to_list()
        return np.concatenate(rows, axis=0) if rows else \
            self.data.reshape((0,) + self.data.shape[2:])

    def recursive_seq_lens(self) -> List[List[int]]:
        return [o.tolist() for o in self.outer_lengths] + \
            [self.lengths.tolist()]

    def regroup_outer(self) -> "RaggedBatch":
        """Collapse the innermost grouping one level up: rows become the
        per-outer-group concatenations (lengths in tokens), using the
        last ``outer_lengths`` vector. This is how a hierarchical op
        walks outward after pooling the inner level."""
        if not self.outer_lengths:
            raise ValueError("no outer level to regroup by")
        group = self.outer_lengths[-1]
        rows = self.to_list()
        out_rows, i = [], 0
        for g in group:
            g = int(g)
            chunk = rows[i:i + g]
            out_rows.append(np.concatenate(chunk, axis=0) if chunk else
                            np.zeros((0,) + self.data.shape[2:],
                                     self.data.dtype))
            i += g
        rb = RaggedBatch.from_list(out_rows)
        rb.outer_lengths = list(self.outer_lengths[:-1])
        return rb

    def __repr__(self) -> str:
        return (f"RaggedBatch(data={self.data.shape}, "
                f"lengths={self.lengths.tolist()}, "
                f"outer_levels={len(self.outer_lengths)})")


def create_lod_tensor(data, recursive_seq_lens, place=None) -> RaggedBatch:
    """Reference-compatible constructor
    (ref: python/paddle/fluid/lod_tensor.py:24). ``data`` may be a
    packed ndarray, a (possibly nested) list of sequences, or an
    existing RaggedBatch (re-segmented). ``place`` is accepted for
    signature parity; host conversion is place-independent and the
    arrays move to device when an op consumes them."""
    if isinstance(data, RaggedBatch):
        return RaggedBatch.from_lod(data.flat(), recursive_seq_lens)
    if isinstance(data, (list, tuple)):
        # reference semantics: a list of sequences is packed along the
        # token axis; rows of scalar tokens become a [N, 1] column (the
        # reference appends a trailing unit dim to nested lists), rows
        # with feature dims concatenate unchanged
        rows = [np.asarray(r) for r in data]
        flat = np.concatenate(
            [r.reshape(-1, 1) if r.ndim <= 1 else r for r in rows],
            axis=0)
        return RaggedBatch.from_lod(flat, recursive_seq_lens)
    data = np.asarray(data)
    if data.ndim == 1:
        data = data.reshape(-1, 1)
    return RaggedBatch.from_lod(data, recursive_seq_lens)


def create_random_int_lodtensor(recursive_seq_lens, base_shape,
                                place=None, low=0, high=10,
                                seed=None) -> RaggedBatch:
    """(ref: python/paddle/fluid/lod_tensor.py:102)."""
    total = int(np.sum(recursive_seq_lens[-1]))
    rng = np.random.default_rng(seed)
    flat = rng.integers(low, high + 1,
                        (total,) + tuple(base_shape)).astype(np.int64)
    return RaggedBatch.from_lod(flat, recursive_seq_lens)
