"""Host staging arena: recycled page-aligned buffers for device feeds.

TPU half of the reference's allocator subsystem
(/root/reference/paddle/fluid/memory/allocation/
auto_growth_best_fit_allocator.cc growth-by-chunk reuse, pinned staging
allocation/pinned_allocator.cc, and the allocator_strategy flag
flags.cc). On TPU, XLA owns device HBM outright (SURVEY §2.3 plan), so
the allocator capability that remains meaningful is the HOST side of
every feed: per-batch collate/transfer buffers. The arena hands out
numpy views over a small ring of large reused blocks — steady-state
feeding does zero host mallocs, keeps pages warm for DMA, and exposes
the reference-style stats counters (monitor.h STAT registry role).

Generational safety: ``stage()`` copies a batch into views of the
current generation's blocks; the caller ``advance()``s once per step
and views from ``depth`` generations ago are recycled — matching the
in-flight window of DeviceLoader's prefetch ring.
"""

from __future__ import annotations

from typing import Any, Dict, List

import numpy as np

__all__ = ["HostStagingArena"]

_ALIGN = 4096  # page alignment for DMA-friendly staging


class _Block:
    __slots__ = ("buf", "offset")

    def __init__(self, nbytes: int) -> None:
        # over-allocate to guarantee a page-aligned window
        raw = np.empty(nbytes + _ALIGN, np.uint8)
        shift = (-raw.ctypes.data) % _ALIGN
        self.buf = raw[shift:shift + nbytes]
        self.offset = 0


class HostStagingArena:
    def __init__(self, block_bytes: int = 64 << 20,
                 depth: int = 3) -> None:
        self.block_bytes = int(block_bytes)
        self.depth = max(2, int(depth))
        # one generation = list of blocks being bump-allocated, plus the
        # device arrays produced from them (synced before recycling)
        self._generations: List[List[_Block]] = [[] for _ in
                                                 range(self.depth)]
        self._inflight: List[Any] = [None] * self.depth
        self._free: List[_Block] = []
        self._gen = 0
        self.stats: Dict[str, int] = {
            "blocks_allocated": 0, "blocks_reused": 0,
            "bytes_staged": 0, "oversize_passthrough": 0,
            "blocks_released": 0,
        }

    def _alloc_view(self, nbytes: int) -> np.ndarray:
        if nbytes > self.block_bytes:
            # huge single tensors bypass the arena (same policy as the
            # reference's huge-chunk path in auto_growth)
            self.stats["oversize_passthrough"] += 1
            return np.empty(nbytes, np.uint8)
        gen = self._generations[self._gen % self.depth]
        aligned = -(-nbytes // _ALIGN) * _ALIGN
        for blk in gen:
            if blk.offset + aligned <= len(blk.buf):
                view = blk.buf[blk.offset:blk.offset + nbytes]
                blk.offset += aligned
                return view
        if self._free:
            blk = self._free.pop()
            blk.offset = 0
            self.stats["blocks_reused"] += 1
        else:
            blk = _Block(self.block_bytes)
            self.stats["blocks_allocated"] += 1
        gen.append(blk)
        view = blk.buf[:nbytes]
        blk.offset = aligned
        return view

    def stage(self, tree: Any) -> Any:
        """Copy every ndarray leaf into arena-backed views (same
        shapes/dtypes/values; contiguous)."""
        import jax

        def put(x):
            if not isinstance(x, np.ndarray):
                return x
            flat = self._alloc_view(x.nbytes)
            out = flat.view(x.dtype).reshape(x.shape)
            np.copyto(out, x)
            self.stats["bytes_staged"] += x.nbytes
            return out

        return jax.tree.map(put, tree)

    def advance(self, live_refs: Any = None) -> None:
        """End of step. ``live_refs``: the device arrays produced from
        this generation's staged views — before the generation's blocks
        are recycled ``depth`` steps later, those transfers are synced
        (device_put returns before the host→device DMA completes;
        reusing the buffer mid-flight would silently corrupt the device
        batch)."""
        import jax

        self._inflight[self._gen % self.depth] = live_refs
        self._gen += 1
        slot = self._gen % self.depth
        old_refs = self._inflight[slot]
        if old_refs is not None:
            jax.block_until_ready(old_refs)
            self._inflight[slot] = None
        self._free.extend(self._generations[slot])
        self._generations[slot] = []
        self._trim_free()

    def _trim_free(self) -> None:
        """Bound the retained free list by FLAGS_eager_delete_tensor_gb
        (the reference's retained-buffer GC threshold, flags.cc): keep a
        working set of `depth` blocks regardless, release the rest once
        the free list exceeds the flag's byte budget."""
        try:
            from ..flags import GLOBAL_FLAGS
            budget = float(GLOBAL_FLAGS.get("eager_delete_tensor_gb"))
        except Exception:
            budget = 0.0
        keep = max(self.depth,
                   int(budget * (1 << 30)) // max(self.block_bytes, 1))
        while len(self._free) > keep:
            self._free.pop(0)
            self.stats["blocks_released"] += 1
