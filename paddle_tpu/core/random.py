"""RNG state management.

Analogue of the reference's Generator (/root/reference/paddle/fluid/
framework/generator.cc — global per-device RNG state) redesigned for JAX's
functional, key-based PRNG:

- Eager mode keeps a global stateful :class:`Generator` whose ``split()``
  advances an internal key — matching the reference's "global seed" UX.
- Under ``jit`` tracing, stateful splitting would bake one fixed key into the
  compiled program. Traced code must instead draw keys from a *bound stream*
  (:func:`rng_scope`), which the Layer/executor machinery seeds per step with
  a key threaded through the step's functional state. ``split()`` inside a
  scope folds a trace-time counter into the bound key, so every dropout call
  site gets a distinct, step-varying key without retracing.
"""

from __future__ import annotations

import contextlib
import threading
from typing import Dict, Iterator, Optional

import jax


class Generator:
    """Stateful PRNG-key source for eager mode."""

    def __init__(self, seed: int = 0) -> None:
        self._seed = seed
        self._key = jax.random.key(seed)
        self._lock = threading.Lock()

    def manual_seed(self, seed: int) -> "Generator":
        with self._lock:
            self._seed = seed
            self._key = jax.random.key(seed)
        return self

    @property
    def initial_seed(self) -> int:
        return self._seed

    def split(self) -> jax.Array:
        with self._lock:
            self._key, sub = jax.random.split(self._key)
            return sub


_default_generator = Generator(0)


def default_generator() -> Generator:
    return _default_generator


def seed(value: int) -> Generator:
    """Global seed — mirrors ``paddle.seed``."""
    return _default_generator.manual_seed(value)


class _RngStream:
    """A bound key plus a trace-time call counter."""

    def __init__(self, key: jax.Array) -> None:
        self.key = key
        self.count = 0

    def next(self) -> jax.Array:
        sub = jax.random.fold_in(self.key, self.count)
        self.count += 1
        return sub


class _ScopeState(threading.local):
    def __init__(self) -> None:
        self.streams: Optional[Dict[str, _RngStream]] = None


_scope = _ScopeState()


@contextlib.contextmanager
def rng_scope(**keys: jax.Array) -> Iterator[None]:
    """Bind named key streams (e.g. ``dropout=key``) for traced code."""
    prev = _scope.streams
    _scope.streams = {name: _RngStream(k) for name, k in keys.items()}
    try:
        yield
    finally:
        _scope.streams = prev


def next_key(stream: str = "default") -> jax.Array:
    """Draw the next key: from the bound scope if present, else eagerly."""
    if _scope.streams is not None:
        if stream in _scope.streams:
            return _scope.streams[stream].next()
        if "default" in _scope.streams:
            return _scope.streams["default"].next()
    return _default_generator.split()


def in_rng_scope() -> bool:
    return _scope.streams is not None
