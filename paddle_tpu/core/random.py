"""RNG state management.

Analogue of the reference's Generator (/root/reference/paddle/fluid/
framework/generator.cc — global per-device RNG state) redesigned for JAX's
functional, key-based PRNG:

- Eager mode keeps a global stateful :class:`Generator` whose ``split()``
  advances an internal key — matching the reference's "global seed" UX.
- Under ``jit`` tracing, stateful splitting would bake one fixed key into the
  compiled program. Traced code must instead draw keys from a *bound stream*
  (:func:`rng_scope`), which the Layer/executor machinery seeds per step with
  a key threaded through the step's functional state. ``split()`` inside a
  scope folds a trace-time counter into the bound key, so every dropout call
  site gets a distinct, step-varying key without retracing.
"""

from __future__ import annotations

import contextlib
import threading
from typing import Dict, Iterator, Optional

import jax

_fast_rng_configured = False
_fast_rng_lock = threading.Lock()


def _configure_fast_rng_once() -> None:
    """Switch to the hardware RngBitGenerator PRNG on TPU (FLAGS_use_fast_rng).

    Must run before the FIRST jax.random key is created anywhere in the
    package — threefry dropout-mask generation costs ~35% of a BERT-base
    train step on v5e. Called lazily from Generator key creation so that
    ``import paddle_tpu`` never initializes the PJRT backend (a slow or
    contended accelerator plugin would hang the import otherwise).
    """
    global _fast_rng_configured
    with _fast_rng_lock:
        if _fast_rng_configured:
            return
        from .. import flags

        if flags.GLOBAL_FLAGS.get("use_fast_rng"):
            try:
                backend = jax.default_backend()
            except Exception:
                return  # backend unavailable — retry on next key creation
            from .place import ACCEL_PLATFORMS
            if backend in ACCEL_PLATFORMS:
                jax.config.update("jax_default_prng_impl", "rbg")
        _fast_rng_configured = True


def make_key(seed) -> jax.Array:
    """Create a PRNG key, applying the fast-RNG backend config first.

    Every key creation in the package must go through here (or through
    ``Generator.split``) so the FLAGS_use_fast_rng switch to the TPU
    RngBitGenerator impl lands before the first key exists — mixing PRNG
    impls in one process breaks stream reproducibility.
    """
    _configure_fast_rng_once()
    return jax.random.key(seed)


class Generator:
    """Stateful PRNG-key source for eager mode.

    Key creation is lazy: no JAX backend is touched until the first
    ``split()`` — keeping ``import paddle_tpu`` accelerator-free.
    """

    def __init__(self, seed: int = 0) -> None:
        self._seed = seed
        self._key = None
        self._lock = threading.Lock()

    def manual_seed(self, seed: int) -> "Generator":
        with self._lock:
            self._seed = seed
            self._key = None
        return self

    @property
    def initial_seed(self) -> int:
        return self._seed

    def split(self) -> jax.Array:
        with self._lock:
            if self._key is None:
                self._key = make_key(self._seed)
            self._key, sub = jax.random.split(self._key)
            return sub


_default_generator = Generator(0)


def default_generator() -> Generator:
    return _default_generator


def seed(value: int) -> Generator:
    """Global seed — mirrors ``paddle.seed``."""
    return _default_generator.manual_seed(value)


class _RngStream:
    """A bound key plus a trace-time call counter."""

    def __init__(self, key: jax.Array) -> None:
        self.key = key
        self.count = 0

    def next(self) -> jax.Array:
        sub = jax.random.fold_in(self.key, self.count)
        self.count += 1
        return sub


class _ScopeState(threading.local):
    def __init__(self) -> None:
        self.streams: Optional[Dict[str, _RngStream]] = None


_scope = _ScopeState()


@contextlib.contextmanager
def rng_scope(**keys: jax.Array) -> Iterator[None]:
    """Bind named key streams (e.g. ``dropout=key``) for traced code."""
    prev = _scope.streams
    _scope.streams = {name: _RngStream(k) for name, k in keys.items()}
    try:
        yield
    finally:
        _scope.streams = prev


def next_key(stream: str = "default") -> jax.Array:
    """Draw the next key: from the bound scope if present, else eagerly."""
    if _scope.streams is not None:
        if stream in _scope.streams:
            return _scope.streams[stream].next()
        if "default" in _scope.streams:
            return _scope.streams["default"].next()
    return _default_generator.split()


def in_rng_scope() -> bool:
    return _scope.streams is not None
