"""Device identity ("Place") layer.

TPU-native analogue of the reference's Place/DeviceContext/DeviceContextPool
(/root/reference/paddle/fluid/platform/place.h, device_context.h, and
init.cc:141 InitDevices). PJRT owns streams/contexts, so the layer reduces
to: tagged device identity objects (CPUPlace/TPUPlace), device enumeration,
and a default-device selector that maps onto ``jax.default_device``. The
``selected_devices`` flag mirrors FLAGS_selected_gpus.
"""

from __future__ import annotations

import functools
from typing import List, Optional, Union

import jax

from ..flags import GLOBAL_FLAGS


class Place:
    device_type = "unspecified"

    def __init__(self, device_id: int = 0) -> None:
        self.device_id = device_id

    def __eq__(self, other) -> bool:
        return (type(self) is type(other)
                and self.device_id == other.device_id)

    def __hash__(self) -> int:
        return hash((type(self).__name__, self.device_id))

    def __repr__(self) -> str:
        return f"{type(self).__name__}({self.device_id})"

    def jax_device(self):
        devs = [d for d in jax.devices()
                if d.platform == self.device_type] or jax.devices("cpu")
        return devs[self.device_id % len(devs)]


class CPUPlace(Place):
    device_type = "cpu"

    def jax_device(self):
        return jax.devices("cpu")[0]


class TPUPlace(Place):
    """The accelerator place. On this runtime the platform may register as
    'tpu' or (tunneled) 'axon'; both are accelerator-backed."""

    device_type = "tpu"

    def jax_device(self):
        for platform in ACCEL_PLATFORMS:
            try:
                devs = jax.devices(platform)
                if devs:
                    return devs[self.device_id % len(devs)]
            except RuntimeError:
                continue
        return jax.devices()[self.device_id % len(jax.devices())]


# API parity alias: reference code says CUDAPlace for the accelerator.
CUDAPlace = TPUPlace
XPUPlace = TPUPlace


# THE canonical accelerator-platform list. On this runtime the chip
# registers as 'tpu' or (tunneled) 'axon'; every accel check in the
# package, bench, and tools imports this tuple — a new platform name
# is added HERE, once.
ACCEL_PLATFORMS = ("tpu", "axon")


@functools.lru_cache(maxsize=None)
def _accelerator_available() -> bool:
    return any(d.platform in ACCEL_PLATFORMS for d in jax.devices())


def accelerator_available() -> bool:
    """THE public accelerator predicate (initializes the backend; use
    accelerator_configured() where a wedged tunnel must not block).
    Every in-package/bench/tool accel check calls this so platform
    semantics live in one place. False (not an exception) when backend
    init fails."""
    try:
        return _accelerator_available()
    except Exception:  # noqa: BLE001
        return False


def accelerator_configured() -> bool:
    """Cheap, NON-BLOCKING accelerator check for device-selection code:
    never initializes the backend (a wedged accelerator tunnel must not
    hang ``is_compiled_with_cuda()``-style probes). If a backend is
    already live, answer from its devices; otherwise answer from the
    configured platform list (env/config) without touching PJRT."""
    from jax._src import xla_bridge
    if getattr(xla_bridge, "_backends", None):
        try:
            return _accelerator_available()
        except Exception:  # noqa: BLE001 — init raced and failed
            return False
    import os
    plats = (os.environ.get("JAX_PLATFORMS") or "")
    try:
        cfg = jax.config.read("jax_platforms")
        if cfg:
            plats = cfg
    # ptlint: disable=silent-failure -- jax.config.read is a best-effort probe for a config key older jax builds lack; the env fallback above stands
    except Exception:  # noqa: BLE001
        pass
    return any(p in plats.lower()
               for p in ACCEL_PLATFORMS + ("cuda", "gpu"))


def is_compiled_with_tpu() -> bool:
    return _accelerator_available()


# reference-parity spelling
def is_compiled_with_cuda() -> bool:
    return _accelerator_available()


_current_place: Optional[Place] = None


def set_device(device: Union[str, Place]) -> Place:
    """'tpu', 'tpu:0', 'cpu' — mirrors paddle.set_device."""
    global _current_place
    if isinstance(device, Place):
        _current_place = device
    else:
        name, _, idx = device.partition(":")
        idx = int(idx) if idx else 0
        if name in ACCEL_PLATFORMS + ("gpu", "cuda", "xpu"):
            _current_place = TPUPlace(idx)
        elif name == "cpu":
            _current_place = CPUPlace(idx)
        else:
            raise ValueError(f"unknown device '{device}'")
    jax.config.update("jax_default_device",
                      _current_place.jax_device())
    return _current_place


def get_device() -> Place:
    global _current_place
    if _current_place is None:
        _current_place = TPUPlace(0) if _accelerator_available() \
            else CPUPlace(0)
    return _current_place


def device_count() -> int:
    sel = GLOBAL_FLAGS.get("selected_devices")
    if sel:
        return len([s for s in sel.split(",") if s.strip() != ""])
    return jax.device_count()


def local_devices() -> List:
    devs = jax.local_devices()
    sel = GLOBAL_FLAGS.get("selected_devices")
    if sel:
        wanted = {int(s) for s in sel.split(",") if s.strip() != ""}
        devs = [d for d in devs if d.id in wanted]
    return devs
