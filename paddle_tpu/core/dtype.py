"""Dtype registry.

Analogue of the reference's VarType dtype enum
(/root/reference/paddle/fluid/framework/framework.proto:104-135) and
platform/float16.h. On TPU the canonical compute dtype is bfloat16 (MXU
native); fp16 is retained for API parity. Dtypes are plain jnp dtypes plus
string aliases, with promotion rules delegated to jax.
"""

from __future__ import annotations

from typing import Any, Union

import jax.numpy as jnp
import numpy as np

bool_ = jnp.bool_
uint8 = jnp.uint8
int8 = jnp.int8
int16 = jnp.int16
int32 = jnp.int32
int64 = jnp.int64
float16 = jnp.float16
bfloat16 = jnp.bfloat16
float32 = jnp.float32
float64 = jnp.float64
complex64 = jnp.complex64
complex128 = jnp.complex128

_ALIASES = {
    "bool": bool_,
    "uint8": uint8,
    "int8": int8,
    "int16": int16,
    "int32": int32,
    "int64": int64,
    "float16": float16,
    "fp16": float16,
    "half": float16,
    "bfloat16": bfloat16,
    "bf16": bfloat16,
    "float32": float32,
    "fp32": float32,
    "float": float32,
    "float64": float64,
    "fp64": float64,
    "double": float64,
    "complex64": complex64,
    "complex128": complex128,
}

DTypeLike = Union[str, type, np.dtype, Any]


def convert_dtype(dtype: DTypeLike):
    """Normalize any dtype spec to a numpy/jnp dtype object."""
    if dtype is None:
        return None
    if isinstance(dtype, str):
        key = dtype.lower()
        if key not in _ALIASES:
            raise ValueError(f"unknown dtype '{dtype}'")
        return jnp.dtype(_ALIASES[key])
    return jnp.dtype(dtype)


def is_floating(dtype: DTypeLike) -> bool:
    return jnp.issubdtype(convert_dtype(dtype), jnp.floating)


def is_integer(dtype: DTypeLike) -> bool:
    return jnp.issubdtype(convert_dtype(dtype), jnp.integer)


def is_complex(dtype: DTypeLike) -> bool:
    return jnp.issubdtype(convert_dtype(dtype), jnp.complexfloating)


# Default dtype management (ref: fluid get_default_dtype/set_default_dtype)
_default_dtype = jnp.float32


def set_default_dtype(dtype: DTypeLike) -> None:
    global _default_dtype
    d = convert_dtype(dtype)
    if not jnp.issubdtype(d, jnp.floating):
        raise ValueError("default dtype must be floating point")
    _default_dtype = d


def get_default_dtype():
    return _default_dtype
