"""Core substrate: dtypes, RNG state, device/place abstraction."""

from . import dtype, random
from .dtype import convert_dtype, get_default_dtype, set_default_dtype
from .place import (CPUPlace, Place, TPUPlace, get_device, is_compiled_with_tpu,
                    set_device)
from .random import Generator, default_generator, next_key, rng_scope, seed


def as_label_tuple(labels):
    """Normalize a ``labels=`` argument to a tuple of arrays.

    A bare array is ONE label, not a sequence to unpack — ``tuple(arr)``
    would shred it into per-row scalars and break batch sharding.
    """
    if isinstance(labels, (tuple, list)):
        return tuple(labels)
    return (labels,)
