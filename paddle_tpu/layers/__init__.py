"""``paddle_tpu.layers`` — the reference's ``fluid.layers`` surface.

Migration shim with real implementations behind every name
(ref: /root/reference/python/paddle/fluid/layers/__init__.py — nn.py,
tensor.py, control_flow.py, detection.py, learning_rate_scheduler.py,
sequence_lod.py, distributions.py). A fluid user's op spellings
(``elementwise_add``, ``reduce_sum(dim=...)``, ``resize_bilinear``,
``cosine_decay`` ...) resolve here to the framework's TPU-native ops.
Names with fluid-specific semantics are defined in this module (with
signature adapters); the rest of the reference's aggregated ``__all__``
delegates via module ``__getattr__`` to ``nn.functional`` / the root
namespace. Every name in the reference list resolves to working code —
``tests/test_layers_compat.py::test_every_reference_layers_name_resolves``
sweeps the full list mechanically (the only exceptions, DynamicRNN/
StaticRNN, raise a documented redirect naming the working equivalent).

Graph-construction-only constructs translate per SURVEY §7's inversion:
- lr schedules return :class:`~paddle_tpu.optimizer.lr.LRScheduler`
  objects (the reference emits ops computing lr-as-a-Variable; our
  optimizers consume schedulers directly).
- ``create_parameter``/``create_global_var`` return live arrays/
  Parameters (no Scope to register into; the Layer system owns naming).
- ``Print``/``Assert`` map to ``jax.debug`` (side effects under jit).
- ``py_reader`` returns a DataLoader-backed adapter.
"""

from __future__ import annotations

from typing import Any, Callable, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from .. import ops as _ops
from ..ops import (activation as _act, attention as _attn, beam as _beam,
                   control_flow as _cf, conv_extra as _convx, crf as _crf,
                   detection as _det, loss as _loss,
                   manipulation as _manip, math as _math,
                   metrics_ops as _mops, nn_functional as _F,
                   random_ops as _rand, reduction as _red,
                   rnn_functional as _rnn, sampling as _samp,
                   search as _search, sequence as _seq)
from ..optimizer import lr as _lr

# ---------------------------------------------------------------- elementwise
# (ref: python/paddle/fluid/layers/nn.py elementwise_* family; axis-based
# broadcast collapses into numpy broadcasting on TPU)


def _elementwise(fn):
    def op(x, y, axis: int = -1, act: Optional[str] = None, name=None):
        if axis != -1 and jnp.ndim(y) < jnp.ndim(x):
            # fluid's axis semantics: y's dims align with x starting at
            # `axis` (so axis=0 pads trailing ones — numpy's default
            # right-alignment only matches fluid's axis=-1)
            y = jnp.reshape(
                y, tuple(jnp.shape(y))
                + (1,) * (jnp.ndim(x) - axis - jnp.ndim(y)))
        out = fn(x, y)
        if act is not None:
            out = getattr(_act, act)(out)
        return out
    return op


elementwise_add = _elementwise(jnp.add)
elementwise_sub = _elementwise(jnp.subtract)
elementwise_mul = _elementwise(jnp.multiply)
elementwise_div = _elementwise(jnp.divide)
elementwise_max = _elementwise(jnp.maximum)
elementwise_min = _elementwise(jnp.minimum)
elementwise_mod = _elementwise(jnp.mod)
elementwise_floordiv = _elementwise(jnp.floor_divide)
elementwise_pow = _elementwise(jnp.power)

# ------------------------------------------------------------------ reductions
# (ref: layers/nn.py reduce_*: `dim` / `keep_dim` spellings)


def _reduce(fn):
    def op(input, dim=None, keep_dim: bool = False, name=None):
        axis = tuple(dim) if isinstance(dim, (list, tuple)) else dim
        return fn(input, axis=axis, keepdims=keep_dim)
    return op


reduce_sum = _reduce(jnp.sum)
reduce_mean = _reduce(jnp.mean)
reduce_max = _reduce(jnp.max)
reduce_min = _reduce(jnp.min)
reduce_prod = _reduce(jnp.prod)
reduce_all = _reduce(jnp.all)
reduce_any = _reduce(jnp.any)

# ------------------------------------------------------------------- resizing
# (ref: layers/nn.py image_resize / resize_bilinear / resize_nearest ...)


def image_resize(input, out_shape=None, scale=None, resample: str = "BILINEAR",
                 align_corners: bool = True, align_mode: int = 1,
                 data_format: str = "NCHW", name=None):
    mode = {"BILINEAR": "bilinear", "NEAREST": "nearest",
            "TRILINEAR": "trilinear", "LINEAR": "linear",
            "BICUBIC": "bicubic"}[resample.upper()]
    return _F.interpolate(input, size=out_shape, scale_factor=scale,
                          mode=mode, align_corners=align_corners,
                          data_format=data_format)


def resize_bilinear(input, out_shape=None, scale=None, align_corners=True,
                    align_mode=1, name=None):
    return image_resize(input, out_shape, scale, "BILINEAR", align_corners,
                        align_mode)


def resize_nearest(input, out_shape=None, scale=None, align_corners=True,
                   name=None):
    return image_resize(input, out_shape, scale, "NEAREST", align_corners)


def resize_trilinear(input, out_shape=None, scale=None, align_corners=True,
                     name=None):
    return image_resize(input, out_shape, scale, "TRILINEAR", align_corners)


def resize_linear(input, out_shape=None, scale=None, align_corners=True,
                  name=None):
    return image_resize(input, out_shape, scale, "LINEAR", align_corners,
                        data_format="NCW")


def image_resize_short(input, out_short_len: int, resample="BILINEAR"):
    h, w = input.shape[2], input.shape[3]
    short, long_ = (h, w) if h < w else (w, h)
    scaled = int(round(long_ * out_short_len / short))
    out = (out_short_len, scaled) if h < w else (scaled, out_short_len)
    return image_resize(input, out_shape=out, resample=resample)


grid_sampler = _F.grid_sample

# -------------------------------------------------------------- lr schedules
# (ref: layers/learning_rate_scheduler.py — these returned lr Variables;
# here they return scheduler objects our optimizers consume directly)


def noam_decay(d_model: int, warmup_steps: int, learning_rate: float = 1.0):
    return _lr.NoamDecay(d_model, warmup_steps, learning_rate)


def exponential_decay(learning_rate: float, decay_steps: int,
                      decay_rate: float, staircase: bool = False):
    return _DecayEvery(_lr.ExponentialDecay(learning_rate, decay_rate),
                       decay_steps, staircase)


def natural_exp_decay(learning_rate: float, decay_steps: int,
                      decay_rate: float, staircase: bool = False):
    return _DecayEvery(_lr.NaturalExpDecay(learning_rate, decay_rate),
                       decay_steps, staircase)


def inverse_time_decay(learning_rate: float, decay_steps: int,
                       decay_rate: float, staircase: bool = False):
    return _DecayEvery(_lr.InverseTimeDecay(learning_rate, decay_rate),
                       decay_steps, staircase)


def polynomial_decay(learning_rate: float, decay_steps: int,
                     end_learning_rate: float = 0.0001, power: float = 1.0,
                     cycle: bool = False):
    return _lr.PolynomialDecay(learning_rate, decay_steps,
                               end_lr=end_learning_rate, power=power,
                               cycle=cycle)


def piecewise_decay(boundaries: Sequence[int], values: Sequence[float]):
    return _lr.PiecewiseDecay(boundaries, values)


def cosine_decay(learning_rate: float, step_each_epoch: int, epochs: int):
    return _lr.CosineAnnealingDecay(learning_rate,
                                    T_max=step_each_epoch * epochs)


def linear_lr_warmup(learning_rate, warmup_steps: int, start_lr: float,
                     end_lr: float):
    return _lr.LinearWarmup(learning_rate, warmup_steps, start_lr, end_lr)


class _DecayEvery(_lr.LRScheduler):
    """fluid's decay_steps/staircase semantics over a per-step scheduler:
    the inner scheduler sees t/decay_steps (floored when staircase)."""

    def __init__(self, inner, decay_steps: int, staircase: bool):
        self.inner = inner
        self.decay_steps = decay_steps
        self.staircase = staircase
        super().__init__(inner.base_lr)

    def lr_at(self, step):
        t = step / self.decay_steps
        if self.staircase:
            t = jnp.floor(t) if hasattr(t, "dtype") else int(t)
        return self.inner.lr_at(t)


# ------------------------------------------------------------- control flow
# (ref: layers/control_flow.py; lax is the TPU lowering)

While = _cf.while_loop
while_loop = _cf.while_loop
cond = _cf.cond
case = _cf.case
switch_case = _cf.switch_case
Switch = _cf.switch_case
IfElse = _cf.cond


def Print(input, message: str = "", summarize: int = 20, name=None,
          **kwargs):
    """(ref: control_flow.py Print) debug-print that survives jit."""
    jax.debug.print(message + " {x}", x=input)
    return input


def Assert(cond_value, data=None, summarize: int = 20, name=None):
    """(ref: control_flow.py Assert) checked under jit via checkify-style
    where; eagerly raises."""
    import numpy as _np
    if isinstance(cond_value, (bool, _np.bool_)):
        if not cond_value:
            raise AssertionError(f"layers.Assert failed: {data}")
        return
    def _chk(v):
        if not bool(v):
            raise AssertionError(f"layers.Assert failed: {data}")
    jax.debug.callback(_chk, cond_value)


def is_empty(x, name=None):
    return _manip.is_empty(x)


# -------------------------------------------------------- tensor constructors
# (ref: layers/tensor.py; Scope-registered Variables become live arrays)


def create_tensor(dtype, name=None, persistable: bool = False):
    from ..core.dtype import convert_dtype
    return jnp.zeros((), convert_dtype(dtype))


def create_parameter(shape, dtype, name=None, attr=None,
                     is_bias: bool = False, default_initializer=None):
    from ..nn.layer import Parameter
    from ..nn import initializer as I
    init = default_initializer or (I.Constant(0.0) if is_bias
                                   else I.XavierNormal())
    from ..core.dtype import convert_dtype
    return Parameter(init(tuple(shape), convert_dtype(dtype)), name=name)


def create_global_var(shape, value, dtype, persistable: bool = False,
                      force_cpu: bool = False, name=None):
    from ..core.dtype import convert_dtype
    return jnp.full(tuple(shape), value, convert_dtype(dtype))


def autoincreased_step_counter(counter_name=None, begin: int = 1,
                               step: int = 1):
    """(ref: layers/tensor.py) host-side monotonic counter; under the
    TrainStep design the step lives in optimizer state, so this is for
    eager orchestration code."""
    return _StepCounter(begin, step)


class _StepCounter:
    def __init__(self, begin: int, step: int):
        self.value = begin
        self.step = step

    def __call__(self) -> int:
        v = self.value
        self.value += self.step
        return v


def fill_constant(shape, dtype, value, force_cpu: bool = False, out=None):
    from ..core.dtype import convert_dtype
    return jnp.full(tuple(shape), value, convert_dtype(dtype))


# ------------------------------------------------------------------ data feed
# (ref: layers/io.py py_reader / create_py_reader_by_data / double_buffer;
# the DataLoader already prefetches — these adapt the call pattern)


class _PyReader:
    def __init__(self, capacity: int, shapes, dtypes):
        self.capacity = capacity
        self.shapes = shapes
        self.dtypes = dtypes
        self._gen = None

    def decorate_paddle_reader(self, reader: Callable):
        self._gen = reader

    decorate_sample_list_generator = decorate_paddle_reader
    decorate_batch_generator = decorate_paddle_reader

    def start(self):
        if self._gen is None:
            raise ValueError("py_reader: call decorate_paddle_reader first")
        self._it = iter(self._gen())

    def reset(self):
        # fluid's per-epoch pattern: reset() then start() re-arms it
        self._it = None

    def __iter__(self):
        if getattr(self, "_it", None) is None:
            raise ValueError("py_reader: call start() before iterating "
                             "(and after each reset())")
        return self._it

    def __next__(self):
        return next(iter(self))


def py_reader(capacity: int, shapes, dtypes, lod_levels=None,
              name=None, use_double_buffer: bool = True):
    return _PyReader(capacity, shapes, dtypes)


def create_py_reader_by_data(capacity: int, feed_list, name=None,
                             use_double_buffer: bool = True):
    return _PyReader(capacity, [getattr(f, "shape", None)
                                for f in feed_list], None)


def double_buffer(reader, place=None, name=None):
    return reader  # DeviceLoader prefetch covers this; see data/__init__


def read_file(reader):
    return next(iter(reader))


# ------------------------------------------------------------------- the rest
# direct re-exports under their fluid spellings

# nn.py
def fc(input, size: int, num_flatten_dims: int = 1, weight=None, bias=None,
       act: Optional[str] = None, name=None):
    """(ref: layers/nn.py fc) flatten trailing dims then affine; pass
    weight/bias explicitly (the functional world has no LayerHelper —
    use nn.Linear for parameter-owning layers)."""
    lead = input.shape[:num_flatten_dims]
    flat = input.reshape((int(np.prod(lead)), -1))
    if weight is None:
        raise ValueError("layers.fc in the functional API needs an "
                         "explicit weight (or use nn.Linear)")
    out = flat @ weight
    if bias is not None:
        out = out + bias
    if act is not None:
        out = getattr(_act, act)(out)
    return out.reshape(lead + (size,))
adaptive_pool2d = (lambda input, pool_size, pool_type="avg", name=None:
                   _F.adaptive_avg_pool2d(input, pool_size)
                   if pool_type == "avg"
                   else _F.adaptive_max_pool2d(input, pool_size))
adaptive_pool3d = (lambda input, pool_size, pool_type="avg", name=None:
                   _F.adaptive_pool3d(input, pool_size, pool_type))
pool2d = _F.pool2d
pool3d = _F.pool3d
add_position_encoding = _F.add_position_encoding
similarity_focus = _F.similarity_focus
random_crop = _F.random_crop
inplace_abn = _F.inplace_abn
dice_loss = _loss.dice_loss
kldiv_loss = _loss.kl_div
smooth_l1 = _loss.smooth_l1_loss
warpctc = _loss.warpctc
edit_distance = _seq.edit_distance
ctc_greedy_decoder = _seq.ctc_greedy_decoder
mean_iou = _mops.mean_iou
def auc(input, label, num_thresholds: int = 2048, curve: str = "ROC"):
    """(ref: layers/nn.py auc) single-batch AUC; for streaming
    accumulation use paddle_tpu.metric.Auc."""
    pos = input[:, 1] if input.ndim == 2 else input
    tp, fp = _mops.auc_stats(pos, label, num_thresholds)
    return _mops.auc_from_stats(tp, fp)
hash = _samp.hash_bucket
has_inf = _math.has_inf
has_nan = _math.has_nan
isfinite = _math.isfinite_all
sums = _math.sums
fill_constant_batch_size_like = _math.fill_constant_batch_size_like
uniform_random_batch_size_like = _math.uniform_random_batch_size_like
gaussian_random_batch_size_like = _math.gaussian_random_batch_size_like
uniform_random = _rand.uniform_random
reverse = _manip.reverse
unique_with_counts = _manip.unique_with_counts
crop_tensor = _manip.crop_tensor
size = _manip.numel
range = _manip.arange

# rnn
dynamic_lstm = _rnn.dynamic_lstm
dynamic_lstmp = _rnn.dynamic_lstmp
dynamic_gru = _rnn.dynamic_gru
lstm = _rnn.lstm
lstm_unit = _rnn.lstm_unit
gru_unit = _rnn.gru_unit

# detection.py
iou_similarity = _det.iou_similarity
box_coder = _det.box_coder
box_clip = _det.box_clip
prior_box = _det.prior_box
density_prior_box = _det.density_prior_box
anchor_generator = _det.anchor_generator
yolo_box = _det.yolo_box
yolov3_loss = _det.yolov3_loss
multiclass_nms = _det.multiclass_nms
matrix_nms = _det.matrix_nms
locality_aware_nms = _det.locality_aware_nms
bipartite_match = _det.bipartite_match
target_assign = _det.target_assign
ssd_loss = _det.ssd_loss
roi_align = _det.roi_align
roi_pool = _det.roi_pool
psroi_pool = _det.psroi_pool
prroi_pool = _det.prroi_pool
roi_perspective_transform = _det.roi_perspective_transform
deformable_conv = _convx.deformable_conv
deformable_psroi_pooling = _F.deformable_roi_pooling  # reference op name
generate_proposals = _det.generate_proposals
rpn_target_assign = _det.rpn_target_assign
retinanet_target_assign = _det.retinanet_target_assign
retinanet_detection_output = _det.retinanet_detection_output
sigmoid_focal_loss = _det.sigmoid_focal_loss
generate_proposal_labels = _det.generate_proposal_labels
generate_mask_labels = _det.generate_mask_labels
distribute_fpn_proposals = _det.distribute_fpn_proposals
collect_fpn_proposals = _det.collect_fpn_proposals
box_decoder_and_assign = _det.box_decoder_and_assign
polygon_box_transform = _det.polygon_box_transform

# sampling / search
nce = _samp.nce_loss
hsigmoid = _samp.hsigmoid_loss
beam_search = _beam.beam_search
from ..nn.decode import (BasicDecoder, BeamSearchDecoder,  # noqa: E402
                         DecodeHelper, Decoder, dynamic_decode,
                         GreedyEmbeddingHelper, SampleEmbeddingHelper,
                         TrainingHelper)
beam_search_decode = _beam.beam_search_decode
gather_tree = _beam.gather_tree

# crf
linear_chain_crf = _crf.linear_chain_crf
crf_decoding = _crf.crf_decoding

# distributions (layers.distributions re-export)
from ..distribution import (Categorical, MultivariateNormalDiag, Normal,  # noqa: E402
                            Uniform)


def sampling_id(x, min: float = 0.0, max: float = 1.0, seed: int = 0,
                dtype="int64", key=None):
    """(ref: sampling_id_op.cc) sample a category index per row of a
    probability matrix."""
    from ..core import random as _random
    if key is None:
        key = _random.next_key("random")
    return jax.random.categorical(
        key, jnp.log(jnp.maximum(x, 1e-20)), axis=-1)


def detection_output(loc, scores, prior_box, prior_box_var,
                     background_label: int = 0, nms_threshold: float = 0.3,
                     nms_top_k: int = 400, keep_top_k: int = 200,
                     score_threshold: float = 0.01, nms_eta: float = 1.0):
    """SSD inference head (ref: layers/detection.py detection_output =
    box_coder(decode) + multiclass_nms). loc: [B, P, 4]; scores:
    [B, P, C]."""
    pw = prior_box[:, 2] - prior_box[:, 0]
    ph = prior_box[:, 3] - prior_box[:, 1]
    pcx = prior_box[:, 0] + 0.5 * pw
    pcy = prior_box[:, 1] + 0.5 * ph
    var = (prior_box_var if prior_box_var is not None
           else jnp.ones((4,), loc.dtype))

    def one(loc_i, sc_i):
        # per-prior diagonal decode (the [G,P] pairwise box_coder would
        # materialize P^2 boxes at SSD scale)
        d = loc_i * var
        cx = d[:, 0] * pw + pcx
        cy = d[:, 1] * ph + pcy
        w = jnp.exp(d[:, 2]) * pw
        h = jnp.exp(d[:, 3]) * ph
        dec = jnp.stack([cx - w / 2, cy - h / 2,
                         cx + w / 2, cy + h / 2], axis=-1)
        return _det.multiclass_nms(
            dec, sc_i.T, score_threshold=score_threshold,
            nms_threshold=nms_threshold, nms_top_k=nms_top_k,
            keep_top_k=keep_top_k, background_label=background_label)
    outs = [one(loc[i], scores[i]) for i in builtins_range(loc.shape[0])]
    return outs


import builtins as _builtins  # noqa: E402
builtins_range = _builtins.range


# Graph-recording block APIs with no tracing analogue: the `with
# rnn.step():` protocol records ops into a sub-block, which has no
# meaning when tracing IS compilation. The working equivalents:
_REDIRECTED = {
    "DynamicRNN": "nn.RNN / ops.control_flow.static_rnn over dense "
                  "padded sequences (+ lengths)",
    "StaticRNN": "ops.control_flow.static_rnn (lax.scan)",
}


def __getattr__(name):
    if name in _REDIRECTED:
        raise NotImplementedError(
            f"fluid.layers.{name} is a graph-recording block API; use "
            f"{_REDIRECTED[name]} instead")
    # The rest of the reference's aggregated ``fluid.layers.__all__``
    # (ref: python/paddle/fluid/layers/__init__.py sums the __all__ of
    # nn/io/tensor/control_flow/ops/device/detection/metric_op/
    # learning_rate_scheduler/distributions/sequence_lod/loss/rnn)
    # delegates to the framework's modern spellings: ``nn.functional``
    # first (fluid's functional semantics), then the root namespace.
    # tests/test_layers_compat.py sweeps the full reference list and
    # asserts zero plain AttributeErrors.
    if not name.startswith("_"):
        obj = getattr(_F, name, None)
        if obj is None:
            from .. import __dict__ as _root
            obj = _root.get(name)
        if obj is not None:
            globals()[name] = obj  # cache for subsequent lookups
            return obj
    raise AttributeError(f"module 'paddle_tpu.layers' has no attribute "
                         f"{name!r}")


# ----------------------------------------------------- remaining fills
def argmax(x, axis: int = 0):
    """(ref: fluid/layers/tensor.py:881 — fluid defaults to axis=0,
    unlike the root namespace's axis=-1). Index dtype follows the JAX
    default (int32 unless x64 is enabled; the reference emits int64)."""
    return jnp.argmax(x, axis=axis)


def argmin(x, axis: int = 0):
    """(ref: fluid/layers/tensor.py:920 — fluid defaults to axis=0)."""
    return jnp.argmin(x, axis=axis)


def expand(x, expand_times: Sequence[int], name=None):
    """(ref: fluid/layers/nn.py:10142 expand) — TILES each dim by
    ``expand_times`` (paddle 2.x ``expand`` broadcasts instead)."""
    if len(expand_times) != x.ndim:
        raise ValueError(
            f"expand: expand_times has {len(expand_times)} entries for "
            f"rank-{x.ndim} input (fluid requires one per dim)")
    return jnp.tile(x, tuple(int(t) for t in expand_times))


def expand_as(x, target_tensor, name=None):
    """(ref: fluid/layers/nn.py:10219 expand_as) — tile x so its shape
    matches ``target_tensor`` (each target dim must be a multiple)."""
    tshape = tuple(target_tensor.shape)
    if len(tshape) != x.ndim:
        raise ValueError(
            f"expand_as: rank mismatch {x.ndim} vs {len(tshape)}")
    reps = []
    for i, (s, t) in enumerate(zip(x.shape, tshape)):
        if t % s != 0:
            raise ValueError(
                f"expand_as: target dim {i} ({t}) is not a multiple of "
                f"input dim ({s})")
        reps.append(t // s)
    return jnp.tile(x, tuple(reps))


def flatten(x, axis: int = 1, name=None):
    """(ref: fluid/layers/nn.py:9817 flatten) — reshape to a 2-D matrix
    [prod(shape[:axis]), prod(shape[axis:])] (paddle 2.x flatten uses
    start/stop axes instead)."""
    lead = int(np.prod(x.shape[:axis])) if axis > 0 else 1
    return jnp.reshape(x, (lead, -1))


def split(input, num_or_sections, dim: int = -1, name=None):
    """(ref: fluid/layers/nn.py:4792 split) — fluid defaults to the
    LAST axis (``dim=-1``), unlike the root namespace's axis=0."""
    return _manip.split(input, num_or_sections, axis=dim)


def unique(x, dtype="int32"):
    """(ref: fluid/layers/nn.py:14024 unique) — returns ``(out, index)``
    with ``out`` in FIRST-OCCURRENCE order and ``index`` the inverse map
    recovering x (``out[index] == x``); fluid's second positional arg is
    the index dtype. Eager-only (dynamic output shape; under jit use
    ops.manipulation.unique with a static ``size``)."""
    flat = jnp.reshape(x, (-1,))
    out_sorted, first_idx, inv_sorted = jnp.unique(
        flat, return_index=True, return_inverse=True)
    order = jnp.argsort(first_idx)       # sorted-unique -> occurrence order
    rank = jnp.argsort(order)            # sorted-unique idx -> new position
    return out_sorted[order], rank[inv_sorted].astype(dtype)


def sum(x):
    """(ref: fluid/layers/nn.py:10661 sum == sum_op/add_n) — elementwise
    sum over a LIST of same-shaped tensors (a reduce-sum lives at
    ``reduce_sum``; the root namespace's ``sum`` reduces one tensor)."""
    if isinstance(x, (list, tuple)):
        out = x[0]
        for t in x[1:]:
            out = out + t
        return out
    return jnp.asarray(x)


def cross_entropy(input, label, soft_label: bool = False,
                  ignore_index: int = -100):
    """(ref: fluid/layers/loss.py:206 cross_entropy) — fluid's op takes
    PROBABILITY inputs (no softmax applied) and returns PER-SAMPLE
    losses shaped like the label (the root/nn.functional cross_entropy
    is the 2.x logits+mean-reduction op; do not confuse the two when
    migrating). ``ignore_index`` zeroes those samples (hard labels)."""
    logp = jnp.log(jnp.clip(input, 1e-20))
    if soft_label:
        return -(label * logp).sum(-1, keepdims=True)
    lab = jnp.asarray(label)
    squeeze_back = lab.ndim == input.ndim  # fluid's [N, 1] hard labels
    if squeeze_back:
        lab = jnp.squeeze(lab, -1)
    safe = jnp.where(lab == ignore_index, 0, lab).astype(jnp.int32)
    picked = jnp.take_along_axis(logp, safe[..., None], axis=-1)
    out = jnp.where((lab != ignore_index)[..., None], -picked, 0.0)
    return out  # label-shaped: trailing singleton kept, fluid-style


def dropout(x, dropout_prob: float, is_test: bool = False, seed=None,
            name=None, dropout_implementation: str = "downgrade_in_infer"):
    """(ref: fluid/layers/nn.py:1364 dropout) — fluid's default
    implementation is ``downgrade_in_infer`` (train: mask only, no
    1/(1-p) upscale; infer: scale by (1-p)); 2.x/nn.functional defaults
    to ``upscale_in_train``. Both spellings accepted here."""
    mode = {"downgrade_in_infer": "downscale_in_infer",
            "downscale_in_infer": "downscale_in_infer",
            "upscale_in_train": "upscale_in_train"}.get(
        dropout_implementation)
    if mode is None:
        raise ValueError(
            f"dropout: unknown dropout_implementation "
            f"{dropout_implementation!r} (expected 'downgrade_in_infer' "
            f"or 'upscale_in_train')")
    # fluid's fixed seed => deterministic mask (reproducible runs);
    # None => fresh key from the global stream each call
    key = jax.random.key(seed) if seed is not None else None
    return _F.dropout(x, dropout_prob, training=not is_test, mode=mode,
                      key=key)


def embedding(input, size, is_sparse: bool = False,
              is_distributed: bool = False, padding_idx=None,
              param_attr=None, dtype="float32", weight=None):
    """(ref: fluid/layers/nn.py:380 embedding) — fluid's layer creates
    its own table via LayerHelper; the functional world has no
    parameter registry, so pass the table as ``weight`` explicitly (or
    use nn.Embedding for a parameter-owning layer, same as layers.fc)."""
    if weight is None:
        raise ValueError(
            "layers.embedding in the functional API needs an explicit "
            "weight table (shape `size`); use nn.Embedding for a "
            "parameter-owning layer")
    if tuple(weight.shape) != tuple(size):
        raise ValueError(
            f"layers.embedding: weight shape {tuple(weight.shape)} != "
            f"size {tuple(size)}")
    return _F.embedding(input, weight, padding_idx=padding_idx)


def pad(x, paddings: Sequence[int], pad_value: float = 0.0, name=None):
    """(ref: fluid/layers/nn.py:6546 pad) — flat ``paddings`` list
    [before_0, after_0, before_1, after_1, ...] and fluid's
    ``pad_value`` keyword spelling."""
    if len(paddings) != 2 * x.ndim:
        raise ValueError(
            f"pad: expected {2 * x.ndim} padding entries for rank "
            f"{x.ndim}, got {len(paddings)}")
    widths = [(int(paddings[2 * i]), int(paddings[2 * i + 1]))
              for i in builtins_range(x.ndim)]
    return jnp.pad(x, widths, constant_values=pad_value)


continuous_value_model = _F.continuous_value_model
deformable_roi_pooling = _F.deformable_roi_pooling
lod_append = _seq.lod_append
lod_reset = _seq.lod_reset
reorder_lod_tensor_by_rank = _seq.reorder_lod_tensor_by_rank
def rnn(cell, inputs, initial_states=None, sequence_length=None,
        time_major: bool = False, is_reverse: bool = False):
    """(ref: fluid/layers/rnn.py rnn) drive any RNNCell over a dense
    padded sequence — delegates to nn.RNN (one lax.scan with length
    masking: finished rows keep their last state, outputs zeroed).
    Returns (outputs, final_states)."""
    from ..nn.layers.rnn import RNN as _RNN
    driver = _RNN(cell, is_reverse=is_reverse, time_major=time_major)
    return driver(inputs, initial_states=initial_states,
                  sequence_length=sequence_length)


from ..nn.layers.rnn import GRUCell, LSTMCell, RNNCell  # noqa: E402
from ..ops.sparse import (RowSlices, merge_rows, to_dense)  # noqa: E402


def merge_selected_rows(x: "RowSlices"):
    """(ref: merge_selected_rows_op.cc) sum duplicate rows of a
    SelectedRows-analogue RowSlices gradient."""
    return merge_rows(x)


def get_tensor_from_selected_rows(x: "RowSlices"):
    """(ref: get_tensor_from_selected_rows_op.cc) densify RowSlices."""
    return to_dense(x)


def load(out=None, file_path: str = "", load_as_fp16: bool = False):
    """(ref: layers/io.py load — load one persistable tensor INTO a
    variable). When ``out`` is a Parameter its value is replaced
    in-place (the fluid calling pattern, which discards the return);
    the loaded array is also returned."""
    from .. import io as _io
    data = _io.load(file_path)
    if isinstance(data, dict) and len(data) == 1:
        data = next(iter(data.values()))
    if load_as_fp16:
        import jax.numpy as _jnp
        cast = lambda v: _jnp.asarray(v, _jnp.float16)  # noqa: E731
        data = jax.tree.map(cast, data)
    if out is not None:
        if not hasattr(out, "set_value"):
            raise TypeError(
                "layers.load: out must be a Parameter (has set_value); "
                f"got {type(out).__name__}")
        out.set_value(data)
        return out
    return data


def multi_box_head(inputs, image_hw, num_classes: int,
                   min_sizes, max_sizes, aspect_ratios,
                   loc_weights, conf_weights, loc_biases=None,
                   conf_biases=None, flip: bool = True,
                   clip: bool = False):
    """SSD multi-scale head (ref: layers/detection.py multi_box_head):
    per-feature-map loc/conf convs + prior boxes, concatenated.

    inputs: list of [B, C_i, H_i, W_i] feature maps; *_weights: per-map
    conv kernels [A_i*4, C_i, 3, 3] / [A_i*(num_classes), C_i, 3, 3]
    (functional API — nn-layer users should build heads as in
    models/ssd.py SSDLite). Returns (loc [B, P, 4],
    conf [B, P, num_classes], priors [P, 4], variances [P, 4]).
    """
    locs, confs, priors, pvars = [], [], [], []
    for i, feat in enumerate(inputs):
        b, c, fh, fw = feat.shape
        boxes, variances = _det.prior_box(
            (fh, fw), tuple(image_hw), min_sizes=[min_sizes[i]],
            max_sizes=[max_sizes[i]] if max_sizes else (),
            aspect_ratios=aspect_ratios[i]
            if isinstance(aspect_ratios[i], (list, tuple))
            else (aspect_ratios[i],), flip=flip, clip=clip)
        a = boxes.shape[2]
        if loc_weights[i].shape[0] != a * 4 or \
                conf_weights[i].shape[0] != a * num_classes:
            raise ValueError(
                f"multi_box_head: feature map {i} has {a} priors/cell; "
                f"loc/conf weights must have {a * 4}/{a * num_classes} "
                f"output channels, got {loc_weights[i].shape[0]}/"
                f"{conf_weights[i].shape[0]}")
        lo = _F.conv2d(feat, loc_weights[i],
                       loc_biases[i] if loc_biases else None, padding=1)
        co = _F.conv2d(feat, conf_weights[i],
                       conf_biases[i] if conf_biases else None, padding=1)
        locs.append(jnp.transpose(lo, (0, 2, 3, 1)).reshape(b, -1, 4))
        confs.append(jnp.transpose(co, (0, 2, 3, 1)).reshape(
            b, -1, num_classes))
        priors.append(boxes.reshape(-1, 4))
        pvars.append(variances.reshape(-1, 4))
    return (jnp.concatenate(locs, 1), jnp.concatenate(confs, 1),
            jnp.concatenate(priors, 0), jnp.concatenate(pvars, 0))
