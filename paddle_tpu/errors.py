"""Typed error layer.

TPU-native analogue of the reference's enforce machinery
(/root/reference/paddle/fluid/platform/enforce.h, errors.cc and
error_codes.proto): typed exception classes plus ``enforce_*`` check helpers
that raise with file:line context. Where the reference wraps CUDA/NCCL status
codes, here the native error domain is XLA/jax; those surface as ordinary
exceptions and are wrapped by :func:`convert_external_error` at runtime
boundaries (executor, checkpoint IO, data pipeline).
"""

from __future__ import annotations

import inspect
from typing import Any, NoReturn, Sequence


class EnforceError(RuntimeError):
    """Base class; mirrors the reference's EnforceNotMet."""

    code = "LEGACY"


class InvalidArgumentError(EnforceError):
    code = "INVALID_ARGUMENT"


class NotFoundError(EnforceError):
    code = "NOT_FOUND"


class OutOfRangeError(EnforceError):
    code = "OUT_OF_RANGE"


class AlreadyExistsError(EnforceError):
    code = "ALREADY_EXISTS"


class ResourceExhaustedError(EnforceError):
    code = "RESOURCE_EXHAUSTED"


class PreconditionNotMetError(EnforceError):
    code = "PRECONDITION_NOT_MET"


class PermissionDeniedError(EnforceError):
    code = "PERMISSION_DENIED"


class ExecutionTimeoutError(EnforceError):
    code = "EXECUTION_TIMEOUT"


class UnimplementedError(EnforceError):
    code = "UNIMPLEMENTED"


class UnavailableError(EnforceError):
    code = "UNAVAILABLE"


class FatalError(EnforceError):
    code = "FATAL"


class ExternalError(EnforceError):
    """Wraps errors raised by jax/XLA/IO libraries (ref: EXTERNAL)."""

    code = "EXTERNAL"


def _caller() -> str:
    frame = inspect.stack()[2]
    return f"{frame.filename}:{frame.lineno}"


def _raise(cls, msg: str, *args: Any) -> NoReturn:
    if args:
        msg = msg % args
    raise cls(f"{msg}\n  [Hint: raised at {_caller()}]")


def enforce(cond: Any, msg: str = "enforce failed", *args: Any,
            exc: type = PreconditionNotMetError) -> None:
    if not cond:
        _raise(exc, msg, *args)


def enforce_eq(a: Any, b: Any, msg: str = "", *args: Any) -> None:
    if a != b:
        _raise(InvalidArgumentError,
               f"expected {a!r} == {b!r}. {msg}", *args)


def enforce_ne(a: Any, b: Any, msg: str = "", *args: Any) -> None:
    if a == b:
        _raise(InvalidArgumentError,
               f"expected {a!r} != {b!r}. {msg}", *args)


def enforce_gt(a: Any, b: Any, msg: str = "", *args: Any) -> None:
    if not a > b:
        _raise(InvalidArgumentError, f"expected {a!r} > {b!r}. {msg}", *args)


def enforce_ge(a: Any, b: Any, msg: str = "", *args: Any) -> None:
    if not a >= b:
        _raise(InvalidArgumentError, f"expected {a!r} >= {b!r}. {msg}", *args)


def enforce_lt(a: Any, b: Any, msg: str = "", *args: Any) -> None:
    if not a < b:
        _raise(InvalidArgumentError, f"expected {a!r} < {b!r}. {msg}", *args)


def enforce_le(a: Any, b: Any, msg: str = "", *args: Any) -> None:
    if not a <= b:
        _raise(InvalidArgumentError, f"expected {a!r} <= {b!r}. {msg}", *args)


def enforce_in(value: Any, allowed: Sequence[Any], what: str = "value") -> None:
    if value not in allowed:
        _raise(InvalidArgumentError,
               f"{what} must be one of {list(allowed)!r}, got {value!r}")


def enforce_shape_rank(shape: Sequence[int], rank: int,
                       what: str = "tensor") -> None:
    if len(shape) != rank:
        _raise(InvalidArgumentError,
               f"{what} expects rank {rank}, got shape {tuple(shape)}")


def convert_external_error(err: Exception, context: str = "") -> ExternalError:
    prefix = f"{context}: " if context else ""
    return ExternalError(f"{prefix}{type(err).__name__}: {err}")
