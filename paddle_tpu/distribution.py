"""Probability distributions (ref: /root/reference/python/paddle/
fluid/layers/distributions.py:1 — Uniform/Normal/Categorical/
MultivariateNormalDiag — re-exported as paddle.distribution).

TPU-native redesign: the reference emits graph ops per method call
(sample builds uniform_random ops etc.); here every method is a pure
jnp computation, so distributions compose under jit/grad/vmap — log_prob
of a sampled trajectory differentiates through reparameterized samples
for free (the reference has no reparameterization story).

Broadcasting follows the loc/scale convention: all parameters broadcast
against each other, and ``sample(shape)`` prepends ``shape``.
"""

from __future__ import annotations

from typing import Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from .core import random as _random

__all__ = ["Distribution", "Uniform", "Normal", "Categorical",
           "MultivariateNormalDiag", "kl_divergence"]


def _asarray(x, dtype=jnp.float32):
    return jnp.asarray(x, dtype)


def _key(key):
    return key if key is not None else _random.next_key("random")


class Distribution:
    """Abstract base (ref: distributions.py Distribution)."""

    def sample(self, shape: Sequence[int] = (), key=None):
        raise NotImplementedError

    def log_prob(self, value):
        raise NotImplementedError

    def entropy(self):
        raise NotImplementedError

    def kl_divergence(self, other: "Distribution"):
        raise NotImplementedError

    def probs(self, value):
        return jnp.exp(self.log_prob(value))


class Uniform(Distribution):
    """U(low, high) (ref: distributions.py Uniform).

    sample uses reparameterization (low + (high-low)*u) so gradients flow
    to the bounds.
    """

    def __init__(self, low, high):
        self.low = _asarray(low)
        self.high = _asarray(high)

    def sample(self, shape: Sequence[int] = (), key=None):
        shape = tuple(shape) + jnp.broadcast_shapes(self.low.shape,
                                                    self.high.shape)
        u = jax.random.uniform(_key(key), shape)
        return self.low + (self.high - self.low) * u

    def log_prob(self, value):
        value = _asarray(value)
        inside = (value >= self.low) & (value < self.high)
        lp = -jnp.log(self.high - self.low)
        return jnp.where(inside, lp, -jnp.inf)

    def entropy(self):
        return jnp.log(self.high - self.low)

    def kl_divergence(self, other: "Distribution"):
        return kl_divergence(self, other)


class Normal(Distribution):
    """N(loc, scale) (ref: distributions.py Normal)."""

    def __init__(self, loc, scale):
        self.loc = _asarray(loc)
        self.scale = _asarray(scale)

    def sample(self, shape: Sequence[int] = (), key=None):
        shape = tuple(shape) + jnp.broadcast_shapes(self.loc.shape,
                                                    self.scale.shape)
        eps = jax.random.normal(_key(key), shape)
        return self.loc + self.scale * eps

    def log_prob(self, value):
        value = _asarray(value)
        var = self.scale ** 2
        return (-((value - self.loc) ** 2) / (2 * var)
                - jnp.log(self.scale) - 0.5 * np.log(2 * np.pi))

    def entropy(self):
        return 0.5 + 0.5 * np.log(2 * np.pi) + jnp.log(
            jnp.broadcast_to(self.scale,
                             jnp.broadcast_shapes(self.loc.shape,
                                                  self.scale.shape)))

    def kl_divergence(self, other: "Distribution"):
        return kl_divergence(self, other)


class Categorical(Distribution):
    """Categorical over the last axis of ``logits``
    (ref: distributions.py Categorical)."""

    def __init__(self, logits):
        self.logits = _asarray(logits)
        self._log_p = jax.nn.log_softmax(self.logits, axis=-1)

    @property
    def probs_param(self):
        return jnp.exp(self._log_p)

    def sample(self, shape: Sequence[int] = (), key=None):
        return jax.random.categorical(_key(key), self.logits,
                                      shape=tuple(shape)
                                      + self.logits.shape[:-1])

    def log_prob(self, value):
        value = jnp.asarray(value, jnp.int32)
        return jnp.take_along_axis(self._log_p, value[..., None],
                                   axis=-1)[..., 0]

    def entropy(self):
        p = jnp.exp(self._log_p)
        return -jnp.sum(p * self._log_p, axis=-1)

    def kl_divergence(self, other: "Distribution"):
        return kl_divergence(self, other)


class MultivariateNormalDiag(Distribution):
    """N(loc, diag(scale)) with event dim = last axis
    (ref: distributions.py MultivariateNormalDiag)."""

    def __init__(self, loc, scale):
        self.loc = _asarray(loc)
        self.scale = _asarray(scale)  # diagonal std, same shape as loc

    @property
    def _dim(self):
        return self.loc.shape[-1]

    def sample(self, shape: Sequence[int] = (), key=None):
        shape = tuple(shape) + jnp.broadcast_shapes(self.loc.shape,
                                                    self.scale.shape)
        eps = jax.random.normal(_key(key), shape)
        return self.loc + self.scale * eps

    def log_prob(self, value):
        value = _asarray(value)
        z = (value - self.loc) / self.scale
        return (-0.5 * jnp.sum(z ** 2, axis=-1)
                - jnp.sum(jnp.log(self.scale), axis=-1)
                - 0.5 * self._dim * np.log(2 * np.pi))

    def entropy(self):
        return (0.5 * self._dim * (1 + np.log(2 * np.pi))
                + jnp.sum(jnp.log(
                    jnp.broadcast_to(self.scale, jnp.broadcast_shapes(
                        self.loc.shape, self.scale.shape))), axis=-1))

    def kl_divergence(self, other: "Distribution"):
        return kl_divergence(self, other)


def kl_divergence(p: Distribution, q: Distribution):
    """KL(p||q) for matched families (ref: distributions.py kl pairs)."""
    if isinstance(p, Normal) and isinstance(q, Normal):
        var_ratio = (p.scale / q.scale) ** 2
        t1 = ((p.loc - q.loc) / q.scale) ** 2
        return 0.5 * (var_ratio + t1 - 1 - jnp.log(var_ratio))
    if (isinstance(p, MultivariateNormalDiag)
            and isinstance(q, MultivariateNormalDiag)):
        var_ratio = (p.scale / q.scale) ** 2
        t1 = ((p.loc - q.loc) / q.scale) ** 2
        return 0.5 * jnp.sum(var_ratio + t1 - 1 - jnp.log(var_ratio),
                             axis=-1)
    if isinstance(p, Categorical) and isinstance(q, Categorical):
        pp = jnp.exp(p._log_p)
        return jnp.sum(pp * (p._log_p - q._log_p), axis=-1)
    if isinstance(p, Uniform) and isinstance(q, Uniform):
        # supp(p) must lie inside supp(q) for finite KL
        inside = (q.low <= p.low) & (p.high <= q.high)
        kl = jnp.log((q.high - q.low) / (p.high - p.low))
        return jnp.where(inside, kl, jnp.inf)
    if isinstance(p, Uniform) and isinstance(q, Normal):
        # E_p[log p] - E_p[log q], closed form over [a,b]
        a, b = p.low, p.high
        m2 = (b ** 3 - a ** 3) / (3 * (b - a))  # E[x^2]
        mean = (a + b) / 2
        elogq = (-0.5 * np.log(2 * np.pi) - jnp.log(q.scale)
                 - (m2 - 2 * q.loc * mean + q.loc ** 2)
                 / (2 * q.scale ** 2))
        return -p.entropy() - elogq
    raise NotImplementedError(
        f"kl_divergence({type(p).__name__}, {type(q).__name__})")
