"""metric-hygiene: instrument kinds must match their naming contract.

The registry's naming conventions are load-bearing, not cosmetic: the
SLO engine treats ``*_total`` as monotonic counters (windowed
``increase()`` reset-clamps them), the fleet merge sums them across
hosts, and ``*_ms`` histograms are only bucket-wise mergeable — and
their SLO thresholds only exact — when every host declares the shared
``LATENCY_MS_BUCKETS`` boundaries.  This pass pins those contracts at
the registration site:

- a literal name ending ``_total`` must be registered with
  ``counter(...)`` — a gauge or histogram under that suffix would be
  silently mis-merged (summed as if monotonic) and mis-windowed;
- a literal name ending ``_ms`` registered with ``histogram(...)``
  must declare ``buckets=<…>LATENCY_MS_BUCKETS`` — defaulted
  boundaries (seconds-scale) put every millisecond sample in +Inf and
  break the cross-host merge the moment two sites disagree;
- a ``gauge(...)`` registration must not be used add/inc-only: a
  value that only ever accumulates is a counter (``inc()`` is not
  even in the Gauge API and fails at runtime); ``add()`` is legal
  only for gauges the same module also ``set()``/``set_max()``s.

Only string-literal names are judged — dynamically built names are a
different rule's problem (metrics-doc already forces literals into the
docs).  ``selftest_``-prefixed names are exempt: drill fixtures
deliberately fabricate odd instruments.
"""

from __future__ import annotations

import ast

from .base import Finding, Pass
from .jitgraph import attr_chain

_REGISTER_FUNCS = ("counter", "gauge", "histogram")


def _registration(node):
    """(kind, name, call) when ``node`` registers an instrument with a
    literal name: a call whose callee is ``counter``/``gauge``/
    ``histogram`` (bare or as the terminal attribute, catching
    ``obs.X`` / ``_metrics.X`` / ``registry().X``)."""
    if not isinstance(node, ast.Call):
        return None
    func = node.func
    if isinstance(func, ast.Name):
        kind = func.id
    elif isinstance(func, ast.Attribute):
        kind = func.attr
    else:
        return None
    if kind not in _REGISTER_FUNCS:
        return None
    if not (node.args and isinstance(node.args[0], ast.Constant)
            and isinstance(node.args[0].value, str)):
        return None
    name = node.args[0].value
    if name.startswith("selftest_"):
        return None
    return kind, name, node


def _buckets_kwarg(call):
    for kw in call.keywords:
        if kw.arg == "buckets":
            return kw.value
    return None


class MetricHygienePass(Pass):
    name = "metric-hygiene"
    help = ("instrument kind must match the name contract: *_total is "
            "a counter, *_ms histograms declare LATENCY_MS_BUCKETS, "
            "gauges are not add/inc-only")

    def run(self, modules, ctx):
        findings = []
        for mod in modules:
            findings.extend(self._scan(mod))
        return findings

    def _scan(self, mod):
        out = []
        # gauge usage survey first: which literal gauge names does this
        # module ever level-set vs only accumulate?
        gauge_setters, gauge_adders = set(), {}
        for n in ast.walk(mod.tree):
            if not (isinstance(n, ast.Attribute)
                    and isinstance(n.value, ast.Call)):
                continue
            reg = _registration(n.value)
            if reg is None or reg[0] != "gauge":
                continue
            if n.attr in ("set", "set_max"):
                gauge_setters.add(reg[1])
            elif n.attr in ("add", "inc"):
                gauge_adders.setdefault(reg[1], (n.value.lineno, n.attr))

        for n in ast.walk(mod.tree):
            reg = _registration(n)
            if reg is None:
                continue
            kind, name, call = reg
            if name.endswith("_total") and kind != "counter":
                out.append(Finding(
                    self.name, mod.rel, call.lineno,
                    f"`{name}` registered as a {kind} — the *_total "
                    "suffix promises a monotonic counter (SLO windowed "
                    "increase() and the fleet sum-merge rely on it); "
                    "rename it or register a counter"))
            if kind == "histogram" and name.endswith("_ms"):
                b = _buckets_kwarg(call)
                bucket_src = attr_chain(b) if b is not None else ""
                if not bucket_src.endswith("LATENCY_MS_BUCKETS"):
                    out.append(Finding(
                        self.name, mod.rel, call.lineno,
                        f"`{name}` histogram must declare "
                        "buckets=…LATENCY_MS_BUCKETS — default "
                        "boundaries are seconds-scale (every ms sample "
                        "lands in +Inf) and mismatched boundaries "
                        "break the fleet bucket-wise merge and exact "
                        "SLO thresholds"))
        for name, (lineno, meth) in sorted(gauge_adders.items()):
            if meth == "inc" or name not in gauge_setters:
                out.append(Finding(
                    self.name, mod.rel, lineno,
                    f"gauge `{name}` is {meth}()-only here — a value "
                    "that only accumulates is a counter (and Gauge has "
                    "no inc()); use counter(), or pair add() with a "
                    "set()/set_max() site in this module"))
        return out

    positive = (
        # *_total as a gauge
        """
        from paddle_tpu import observability as obs

        def publish(n):
            obs.gauge("worker_restarts_total", "h").set(n)
        """,
        # *_total as a histogram
        """
        from paddle_tpu.observability import metrics as _m

        def publish(v):
            _m.histogram("frames_dropped_total", "h").observe(v)
        """,
        # _ms histogram without the shared boundaries
        """
        from paddle_tpu import observability as obs

        def note(ms):
            obs.histogram("queue_wait_ms", "h").observe(ms)
        """,
        # _ms histogram with ad-hoc boundaries
        """
        from paddle_tpu import observability as obs

        MY_BUCKETS = (1.0, 10.0)

        def note(ms):
            obs.histogram("queue_wait_ms", "h",
                          buckets=MY_BUCKETS).observe(ms)
        """,
        # add()-only gauge: that's a counter in disguise
        """
        from paddle_tpu import observability as obs

        def bump():
            obs.gauge("bytes_seen", "h").add(4096)
        """,
    )
    negative = (
        # the contract followed: counter for _total, shared buckets
        """
        from paddle_tpu.observability import metrics as _m

        def note(ms):
            _m.counter("frames_total", "h").inc()
            _m.histogram("queue_wait_ms", "h",
                         buckets=_m.LATENCY_MS_BUCKETS).observe(ms)
        """,
        # add() is fine when the module also level-sets the gauge
        """
        from paddle_tpu import observability as obs

        def drain(n):
            obs.gauge("inflight", "h").add(-n)

        def reset():
            obs.gauge("inflight", "h").set(0.0)
        """,
        # selftest_ fixtures and dynamic names are exempt
        """
        from paddle_tpu import observability as obs

        def fabricate(name):
            obs.gauge("selftest_weird_total", "h").set(1.0)
            obs.histogram(name + "_ms", "h").observe(1.0)
        """,
    )
