"""flags-doc: every framework flag has help text and a docs mention.

Migrated from ``tools/check_flags_doc.py`` (now a thin shim over this
module): walks the ``define_flag`` calls in ``paddle_tpu/flags.py`` by
AST and fails when a flag's ``help`` is empty/missing or the flag is
not mentioned (as ``FLAGS_<name>``) anywhere under ``docs/``.
``docs/flags.md`` is the canonical index.  The module keeps the shim's
exact CLI output and public API (``collect_flags``/``docs_text``/
``cli_main``) so the existing tier-1 tests stay green.
"""

from __future__ import annotations

import ast
import os
import sys

from . import base
from .base import Context, Finding, Pass, fixture_self_test

ROOT = base.ROOT
FLAGS_PY = os.path.join(ROOT, "paddle_tpu", "flags.py")
DOCS_DIR = os.path.join(ROOT, "docs")


def collect_flags_detail(path: str = FLAGS_PY, tree=None):
    """[(name, has_help, lineno)] for every define_flag(...) call."""
    if tree is None:
        with open(path) as fh:
            tree = ast.parse(fh.read(), filename=path)
    out = []
    for node in ast.walk(tree):
        if not (isinstance(node, ast.Call)
                and isinstance(node.func, ast.Name)
                and node.func.id == "define_flag"):
            continue
        if not node.args or not isinstance(node.args[0], ast.Constant):
            continue
        name = node.args[0].value
        help_node = None
        if len(node.args) >= 3:
            help_node = node.args[2]
        for kw in node.keywords:
            if kw.arg == "help":
                help_node = kw.value
        has_help = (isinstance(help_node, ast.Constant)
                    and isinstance(help_node.value, str)
                    and bool(help_node.value.strip()))
        out.append((name, has_help, node.lineno))
    return out


def collect_flags(path: str = FLAGS_PY):
    """[(name, has_help)] for every define_flag(...) call."""
    return [(n, h) for n, h, _ in collect_flags_detail(path)]


def docs_text(docs_dir: str = DOCS_DIR) -> str:
    chunks = []
    for dirpath, _, files in os.walk(docs_dir):
        for f in files:
            if f.endswith((".md", ".rst", ".txt")):
                with open(os.path.join(dirpath, f)) as fh:
                    chunks.append(fh.read())
    return "\n".join(chunks)


class FlagsDocPass(Pass):
    name = "flags-doc"
    help = ("every define_flag(...) needs non-empty help= and a "
            "FLAGS_<name> mention under docs/")
    fixture_rel = "paddle_tpu/flags.py"

    def run(self, modules, ctx):
        docs = ctx.docs_text
        if docs is None:
            docs = docs_text() if ctx.root else ""
        out = []
        for mod in modules:
            if not mod.rel.endswith("flags.py"):
                continue
            for name, has_help, lineno in collect_flags_detail(
                    tree=mod.tree):
                if not has_help:
                    out.append(Finding(
                        self.name, mod.rel, lineno,
                        f"FLAGS_{name}: empty or missing help= — every "
                        "flag carries a descriptive string"))
                if f"FLAGS_{name}" not in docs:
                    out.append(Finding(
                        self.name, mod.rel, lineno,
                        f"FLAGS_{name}: not documented anywhere under "
                        "docs/ (add it to docs/flags.md)"))
        return out

    def self_test(self):
        ctx = Context(root=None,
                      docs_text="FLAGS_alpha — the documented one")
        return fixture_self_test(self, ctx)

    positive = (
        'define_flag("beta", 1, "")\n',            # empty help
        'define_flag("gamma", 1, "has help")\n',   # undocumented
    )
    negative = (
        'define_flag("alpha", 1, "help text")\n',  # documented + helped
        'x = 1\n',                                 # no flags at all
    )


def cli_main() -> int:
    """The original tools/check_flags_doc.py CLI, byte-identical."""
    flags = collect_flags()
    if not flags:
        print("check_flags_doc: no define_flag calls found "
              f"in {FLAGS_PY} — parser broken?", file=sys.stderr)
        return 1
    docs = docs_text()
    bad_help = [n for n, has_help in flags if not has_help]
    undocumented = [n for n, _ in flags if f"FLAGS_{n}" not in docs]
    for n in bad_help:
        print(f"FLAGS_{n}: empty or missing help= in flags.py",
              file=sys.stderr)
    for n in undocumented:
        print(f"FLAGS_{n}: not documented anywhere under docs/ "
              "(add it to docs/flags.md)", file=sys.stderr)
    if bad_help or undocumented:
        print(f"check_flags_doc: {len(bad_help)} empty-help, "
              f"{len(undocumented)} undocumented "
              f"(of {len(flags)} flags)", file=sys.stderr)
        return 1
    print(f"check_flags_doc: OK ({len(flags)} flags documented)")
    return 0
