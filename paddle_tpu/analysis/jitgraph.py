"""Shared jit-entry-point discovery and same-module call-graph walking.

Used by the trace-purity and callback-cache passes.  The model is
deliberately lexical and same-module only:

- **roots** are functions handed to ``jax.jit`` / ``instrumented_jit``
  / ``pl.pallas_call`` (first positional argument, unwrapping one level
  of ``functools.partial`` / ``shard_map``-style wrapper calls) or
  decorated with a jit wrapper (``@jax.jit``, ``@to_static``,
  ``@declarative``).
- **edges** resolve bare-name calls to same-module ``def``s (any
  nesting level; if several defs share the name, all are traversed —
  conservative) and ``self.m()`` calls to methods of the enclosing
  class.  Cross-module calls are out of scope: a known heuristic limit,
  documented in docs/static_analysis.md.
- ``jax.debug.callback`` / ``io_callback`` *arguments* are never
  traversed: the payload runs on the host, which is exactly the
  allowlisted probe pattern.
"""

from __future__ import annotations

import ast
from typing import Dict, List, Tuple

from .base import FUNC_NODES

#: callables whose first positional argument becomes traced code
JIT_WRAPPERS = {"jit", "instrumented_jit", "to_static", "declarative"}
PALLAS_WRAPPERS = {"pallas_call"}


def attr_chain(node: ast.AST) -> str:
    """Dotted name of an attribute chain rooted at a Name, else ''."""
    parts = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return ""


def is_callback_call(call: ast.Call) -> bool:
    chain = attr_chain(call.func)
    return (chain.endswith("debug.callback")
            or chain.split(".")[-1] == "io_callback")


def iter_scope(fn: ast.AST):
    """Nodes lexically in ``fn``'s own executed scope: nested ``def``s
    are skipped (they run only when called — the graph walks them as
    separate functions) and callback-call *arguments* are skipped
    (host-side payloads).  Lambda bodies are kept: traced control flow
    runs them."""
    stack = list(ast.iter_child_nodes(fn))
    while stack:
        node = stack.pop()
        yield node
        if isinstance(node, FUNC_NODES):
            continue
        if isinstance(node, ast.Call) and is_callback_call(node):
            stack.append(node.func)
            continue
        stack.extend(ast.iter_child_nodes(node))


class ModuleGraph:
    """Function index + jit-root discovery for one SourceModule."""

    def __init__(self, mod):
        self.mod = mod
        self.defs: Dict[str, List[ast.AST]] = {}
        self.methods: Dict[Tuple[str, str], ast.AST] = {}
        for node in ast.walk(mod.tree):
            if isinstance(node, FUNC_NODES):
                self.defs.setdefault(node.name, []).append(node)
                cls = mod.enclosing(node, (ast.ClassDef,))
                if cls is not None:
                    self.methods.setdefault((cls.name, node.name), node)

    def enclosing_class_name(self, node: ast.AST):
        cls = self.mod.enclosing(node, (ast.ClassDef,))
        return cls.name if cls is not None else None

    def resolve_target(self, expr: ast.AST, class_name) -> List[ast.AST]:
        """Resolve an expression handed to a jit wrapper to local
        function defs (unwraps one wrapper-call level for partial /
        shard_map shapes)."""
        if isinstance(expr, ast.Call):
            if expr.args:
                return self.resolve_target(expr.args[0], class_name)
            return []
        if isinstance(expr, ast.Lambda):
            return [expr]
        if isinstance(expr, ast.Name):
            return list(self.defs.get(expr.id, ()))
        if (isinstance(expr, ast.Attribute)
                and isinstance(expr.value, ast.Name)
                and expr.value.id == "self" and class_name):
            m = self.methods.get((class_name, expr.attr))
            return [m] if m is not None else []
        return []

    def resolve_call(self, call: ast.Call, class_name) -> List[ast.AST]:
        """Same-module callees of a direct call (no wrapper unwrap)."""
        f = call.func
        if isinstance(f, ast.Name):
            return list(self.defs.get(f.id, ()))
        if (isinstance(f, ast.Attribute)
                and isinstance(f.value, ast.Name)
                and f.value.id == "self" and class_name):
            m = self.methods.get((class_name, f.attr))
            return [m] if m is not None else []
        return []

    def jit_roots(self) -> List[Tuple[ast.AST, str]]:
        """[(fn_node, description)] for every traced entry point."""
        roots: List[Tuple[ast.AST, str]] = []
        for node in ast.walk(self.mod.tree):
            if isinstance(node, ast.Call):
                chain = attr_chain(node.func)
                last = chain.split(".")[-1] if chain else ""
                if (last in JIT_WRAPPERS or last in PALLAS_WRAPPERS) \
                        and node.args:
                    cls = self.enclosing_class_name(node)
                    for fn in self.resolve_target(node.args[0], cls):
                        roots.append(
                            (fn, f"`{chain}(…)` at line {node.lineno}"))
            elif isinstance(node, FUNC_NODES):
                for dec in node.decorator_list:
                    target = dec.func if isinstance(dec, ast.Call) else dec
                    chain = attr_chain(target)
                    if chain.split(".")[-1] in JIT_WRAPPERS:
                        roots.append((node, f"`@{chain}`"))
        seen, out = set(), []
        for fn, desc in roots:
            if id(fn) not in seen:
                seen.add(id(fn))
                out.append((fn, desc))
        return out

    def reachable(self, roots) -> Dict[int, Tuple[ast.AST, str]]:
        """{id(fn): (fn, root_description)} over same-module edges."""
        out: Dict[int, Tuple[ast.AST, str]] = {}
        stack = list(roots)
        while stack:
            fn, desc = stack.pop()
            if id(fn) in out:
                continue
            out[id(fn)] = (fn, desc)
            cls = self.enclosing_class_name(fn)
            for node in iter_scope(fn):
                if isinstance(node, ast.Call):
                    for callee in self.resolve_call(node, cls):
                        if id(callee) not in out:
                            stack.append((callee, desc))
        return out
