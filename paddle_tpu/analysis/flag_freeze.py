"""flag-freeze: flags are read at call time, never at module import.

``GLOBAL_FLAGS.get(...)`` at module scope freezes whatever the
environment held at *first import* — `FLAGS_*` env vars set afterwards
(tests, launchers exporting before spawn, `set_flags` at runtime)
silently never apply.  The whole point of the registry is late binding:
read the flag inside the function that needs it.

Deliberate import-time reads exist (arming the fault registry from an
env the drill exported before the trainer started) and carry inline
suppressions explaining exactly that.
"""

from __future__ import annotations

import ast

from .base import FUNC_NODES, Finding, Pass, flags_aliases


class FlagFreezePass(Pass):
    name = "flag-freeze"
    help = ("GLOBAL_FLAGS.get(...) at module import time freezes the "
            "env — read flags at call time")

    def run(self, modules, ctx):
        out = []
        for mod in modules:
            aliases = flags_aliases(mod.tree)
            for node in ast.walk(mod.tree):
                if not isinstance(node, ast.Call):
                    continue
                f = node.func
                if not (isinstance(f, ast.Attribute) and f.attr == "get"
                        and isinstance(f.value, ast.Name)
                        and f.value.id in aliases):
                    continue
                if mod.enclosing(node, FUNC_NODES + (ast.Lambda,)) \
                        is not None:
                    continue
                out.append(Finding(
                    self.name, mod.rel, node.lineno,
                    "flag read at module import time — the value "
                    "freezes whatever the env held at first import; "
                    "read the flag at call time (or suppress with the "
                    "reason the freeze is deliberate)"))
        return out

    positive = (
        """
        from paddle_tpu.flags import GLOBAL_FLAGS

        _DEBUG = GLOBAL_FLAGS.get("debug_mode")
        """,
        # aliased import, read inside a module-scope try
        """
        from paddle_tpu.flags import GLOBAL_FLAGS as _GF

        try:
            _SPEC = _GF.get("fault_spec")
        except Exception:
            _SPEC = None
        """,
    )
    negative = (
        # call-time read is the rule
        """
        from paddle_tpu.flags import GLOBAL_FLAGS

        def debug_enabled():
            return bool(GLOBAL_FLAGS.get("debug_mode"))
        """,
        # method read is also call time
        """
        from paddle_tpu.flags import GLOBAL_FLAGS as _GF

        class T:
            def tick(self):
                return _GF.get("interval")
        """,
    )
