"""metrics-doc: every registered metric name must be documented.

Migrated from ``tools/check_metrics_doc.py`` (now a thin shim over this
module): every literal-named ``counter(...)`` / ``gauge(...)`` /
``histogram(...)`` / ``stat_add(...)`` in the Python tree and every
literal ``pt_mon_add("...")`` in ``csrc/*.cc`` must appear in
``docs/observability.md`` — the canonical index scrapers and dashboards
are built from.  Dynamically-named instruments and ``selftest_*``
fixtures are out of scope.  The shim's exact CLI output and public API
(``collect_metrics``/``collect_native_metrics``/``cli_main``) are kept
so the existing tier-1 tests stay green.
"""

from __future__ import annotations

import ast
import os
import re
import sys

from . import base
from .base import Context, Finding, Pass, fixture_self_test

ROOT = base.ROOT
PKG_DIR = os.path.join(ROOT, "paddle_tpu")
CSRC_DIR = os.path.join(ROOT, "csrc")
DOC = os.path.join(ROOT, "docs", "observability.md")

_FACTORIES = {"counter", "gauge", "histogram"}
# native stat registrations: C++ pt_mon_add / Python native.stat_add
_NATIVE_FACTORIES = {"stat_add"}
_PT_MON_RE = re.compile(r'pt_mon_add\(\s*"([^"]+)"')


def _call_name(node: ast.Call) -> str:
    f = node.func
    if isinstance(f, ast.Name):
        return f.id
    if isinstance(f, ast.Attribute):
        return f.attr
    return ""


def _tree_metrics(tree):
    """[(name, lineno)] literal-named instruments in one parsed file."""
    out = []
    for node in ast.walk(tree):
        if not (isinstance(node, ast.Call)
                and (_call_name(node) in _FACTORIES
                     or _call_name(node) in _NATIVE_FACTORIES)
                and node.args
                and isinstance(node.args[0], ast.Constant)
                and isinstance(node.args[0].value, str)):
            continue
        name = node.args[0].value
        if not name or name.startswith("selftest_"):
            continue
        out.append((name, node.lineno))
    return out


def collect_metrics(pkg_dir: str = PKG_DIR):
    """{name: [file:line, ...]} for every literal-named instrument."""
    out = {}
    for dirpath, _, files in os.walk(pkg_dir):
        for fname in files:
            if not fname.endswith(".py"):
                continue
            path = os.path.join(dirpath, fname)
            try:
                with open(path) as fh:
                    tree = ast.parse(fh.read(), filename=path)
            except SyntaxError as e:  # pragma: no cover
                print(f"check_metrics_doc: cannot parse {path}: {e}",
                      file=sys.stderr)
                return None
            rel = os.path.relpath(path, ROOT)
            for name, lineno in _tree_metrics(tree):
                out.setdefault(name, []).append(f"{rel}:{lineno}")
    return out


def collect_native_metrics(csrc_dir: str = CSRC_DIR):
    """{name: [file:line, ...]} for every literal pt_mon_add() stat in
    the C++ sources (regex scan — no C++ parser needed for literal
    first arguments; dynamically-built names are out of scope like
    their Python counterparts)."""
    out = {}
    if not os.path.isdir(csrc_dir):
        return out
    for fname in sorted(os.listdir(csrc_dir)):
        if not fname.endswith((".cc", ".c", ".h")):
            continue
        path = os.path.join(csrc_dir, fname)
        try:
            with open(path) as fh:
                text = fh.read()
        except OSError:  # pragma: no cover
            continue
        for i, line in enumerate(text.splitlines(), 1):
            for m in _PT_MON_RE.finditer(line):
                rel = os.path.relpath(path, ROOT)
                out.setdefault(m.group(1), []).append(f"{rel}:{i}")
    return out


class MetricsDocPass(Pass):
    name = "metrics-doc"
    help = ("every literal metric name (Python factories + native "
            "pt_mon_add/stat_add) must appear in docs/observability.md")
    fixture_rel = "paddle_tpu/fixture_mod.py"

    def run(self, modules, ctx):
        doc = ctx.metrics_doc_text
        if doc is None:
            if not ctx.root:
                doc = ""
            else:
                try:
                    with open(DOC) as fh:
                        doc = fh.read()
                except OSError:
                    doc = ""
        out = []
        reported = set()
        for mod in modules:
            if not mod.rel.startswith("paddle_tpu/"):
                continue
            for name, lineno in _tree_metrics(mod.tree):
                if name in doc or name in reported:
                    continue
                reported.add(name)
                out.append(Finding(
                    self.name, mod.rel, lineno,
                    f"metric `{name}` is registered here but not "
                    "mentioned in docs/observability.md — add its row "
                    "to the canonical index"))
        if ctx.root:
            for name, sites in collect_native_metrics().items():
                if name in doc or name in reported:
                    continue
                # native findings anchor on the doc file (csrc isn't a
                # parsed module); the message carries the real site
                out.append(Finding(
                    self.name, "docs/observability.md", 1,
                    f"native stat `{name}` (registered at "
                    f"{', '.join(sites)}) is not mentioned in "
                    "docs/observability.md"))
        return out

    def self_test(self):
        ctx = Context(root=None,
                      metrics_doc_text="serving.documented_total — row")
        return fixture_self_test(self, ctx)

    positive = (
        'c = counter("m_undoc_total", "h")\n',
        'h = obs.histogram("lat_undoc_ms", "h")\n',
    )
    negative = (
        'c = counter("serving.documented_total", "h")\n',  # documented
        'c = counter("selftest_x", "h")\nd = counter(dyn_name, "h")\n',
    )


def cli_main() -> int:
    """The original tools/check_metrics_doc.py CLI, byte-identical."""
    metrics = collect_metrics()
    if metrics is None:
        return 1
    if not metrics:
        print("check_metrics_doc: no instrument registrations found "
              f"under {PKG_DIR} — parser broken?", file=sys.stderr)
        return 1
    for name, sites in collect_native_metrics().items():
        metrics.setdefault(name, []).extend(sites)
    try:
        with open(DOC) as fh:
            doc = fh.read()
    except OSError as e:
        print(f"check_metrics_doc: cannot read {DOC}: {e}",
              file=sys.stderr)
        return 1
    missing = {n: sites for n, sites in metrics.items() if n not in doc}
    for name in sorted(missing):
        print(f"{name}: registered at {', '.join(missing[name])} but "
              "not mentioned in docs/observability.md",
              file=sys.stderr)
    if missing:
        print(f"check_metrics_doc: {len(missing)} undocumented of "
              f"{len(metrics)} metric names", file=sys.stderr)
        return 1
    print(f"check_metrics_doc: OK ({len(metrics)} metric names "
          "documented)")
    return 0
