"""clock-hygiene: durations must come from monotonic clocks.

``time.time()`` is wall time: NTP step adjustments move it backwards or
forwards by whole seconds, so any latency/age computed by subtracting
two wall stamps (TTFT, TPOT, queue wait, watchdog ages) can jump or go
negative under clock discipline that is entirely outside the process.
Durations belong to ``time.monotonic()`` / ``time.perf_counter()``.

The pass runs a small local taint analysis per scope: a name assigned
from ``time.time()`` (propagated through simple assignments, tuple
unpacks, ``or``/conditional expressions) and any ``self.<attr>``
assigned from ``time.time()`` anywhere in the file are *wall-tainted*;
a subtraction with a wall-tainted operand (or a direct ``time.time()``
operand) is a finding.  Deadline *comparisons* (``time.time() <
deadline``) and record-dict arithmetic over stored stamps
(``req["b"] - req["a"]``) are deliberately not flagged.

Realtime is still legal where wall time is the point — wire-ingress
stamps crossing process boundaries (``ingress_unix`` from csrc),
exported heartbeat gauges, test-pinned watchdog fields — and those
sites carry `# ptlint: disable=clock-hygiene -- <why>` suppressions or
baseline entries.
"""

from __future__ import annotations

import ast

from .base import FUNC_NODES, Finding, Pass
from .jitgraph import attr_chain


def _is_wall_call(node):
    if not isinstance(node, ast.Call):
        return False
    chain = attr_chain(node.func)
    return chain == "time.time" or chain.endswith(".time.time")


def _scope_nodes(scope):
    """Nodes lexically in this scope, nested functions excluded (they
    get their own scan)."""
    out = []
    stack = list(ast.iter_child_nodes(scope))
    while stack:
        n = stack.pop()
        if isinstance(n, FUNC_NODES):
            continue
        out.append(n)
        stack.extend(ast.iter_child_nodes(n))
    return out


class ClockHygienePass(Pass):
    name = "clock-hygiene"
    help = ("time.time() flowing into a duration subtraction — use "
            "time.monotonic()/perf_counter(); wall time only at "
            "allowlisted wire-ingress stamps")

    def run(self, modules, ctx):
        findings = []
        for mod in modules:
            tainted_attrs = self._tainted_attrs(mod)
            scopes = [mod.tree] + [n for n in ast.walk(mod.tree)
                                   if isinstance(n, FUNC_NODES)]
            for scope in scopes:
                findings.extend(
                    self._scan_scope(mod, scope, tainted_attrs))
        return findings

    @staticmethod
    def _tainted_attrs(mod):
        """self.<attr> names assigned from time.time() anywhere."""
        tainted = set()
        assigns = [n for n in ast.walk(mod.tree)
                   if isinstance(n, (ast.Assign, ast.AnnAssign))]
        for _ in range(4):
            changed = False
            for n in assigns:
                value = n.value
                if value is None:
                    continue
                targets = n.targets if isinstance(n, ast.Assign) \
                    else [n.target]
                for t in targets:
                    pairs = []
                    if isinstance(t, ast.Tuple) \
                            and isinstance(value, ast.Tuple) \
                            and len(t.elts) == len(value.elts):
                        pairs = list(zip(t.elts, value.elts))
                    else:
                        pairs = [(t, value)]
                    for tgt, val in pairs:
                        if (isinstance(tgt, ast.Attribute)
                                and isinstance(tgt.value, ast.Name)
                                and tgt.value.id == "self"
                                and tgt.attr not in tainted
                                and (_is_wall_call(val)
                                     or (isinstance(val, ast.Attribute)
                                         and isinstance(val.value,
                                                        ast.Name)
                                         and val.value.id == "self"
                                         and val.attr in tainted))):
                            tainted.add(tgt.attr)
                            changed = True
            if not changed:
                break
        return tainted

    def _scan_scope(self, mod, scope, tainted_attrs):
        nodes = _scope_nodes(scope)
        tainted = set()
        for _ in range(8):
            changed = False
            for n in nodes:
                if isinstance(n, ast.Assign):
                    items = [(t, n.value) for t in n.targets]
                elif isinstance(n, ast.AnnAssign) and n.value is not None:
                    items = [(n.target, n.value)]
                else:
                    continue
                for tgt, val in items:
                    pairs = []
                    if isinstance(tgt, ast.Tuple) \
                            and isinstance(val, ast.Tuple) \
                            and len(tgt.elts) == len(val.elts):
                        pairs = list(zip(tgt.elts, val.elts))
                    else:
                        pairs = [(tgt, val)]
                    for t2, v2 in pairs:
                        if isinstance(t2, ast.Name) \
                                and t2.id not in tainted \
                                and self._tainted_expr(v2, tainted,
                                                       tainted_attrs):
                            tainted.add(t2.id)
                            changed = True
            if not changed:
                break
        out = []
        for n in nodes:
            if isinstance(n, ast.BinOp) and isinstance(n.op, ast.Sub):
                for side in (n.left, n.right):
                    if self._tainted_operand(side, tainted,
                                             tainted_attrs):
                        out.append(Finding(
                            self.name, mod.rel, n.lineno,
                            "wall-clock `time.time()` flows into a "
                            "duration subtraction — durations must use "
                            "time.monotonic()/time.perf_counter() (NTP "
                            "steps move wall time); realtime is only "
                            "legal at wire-ingress stamps (suppress "
                            "with a reason there)"))
                        break
        return out

    @classmethod
    def _tainted_operand(cls, node, tainted, tainted_attrs):
        if _is_wall_call(node):
            return True
        if isinstance(node, ast.Name) and node.id in tainted:
            return True
        if (isinstance(node, ast.Attribute)
                and isinstance(node.value, ast.Name)
                and node.value.id == "self"
                and node.attr in tainted_attrs):
            return True
        return False

    @classmethod
    def _tainted_expr(cls, node, tainted, tainted_attrs):
        if cls._tainted_operand(node, tainted, tainted_attrs):
            return True
        if isinstance(node, ast.BoolOp):
            return any(cls._tainted_expr(v, tainted, tainted_attrs)
                       for v in node.values)
        if isinstance(node, ast.IfExp):
            return (cls._tainted_expr(node.body, tainted, tainted_attrs)
                    or cls._tainted_expr(node.orelse, tainted,
                                         tainted_attrs))
        return False

    positive = (
        # the classic pair
        """
        import time

        def work():
            t0 = time.time()
            do_stuff()
            return time.time() - t0
        """,
        # wall stamp stored on self, subtracted in another method
        """
        import time

        class T:
            def start(self):
                self._t0 = time.time()

            def lap(self):
                now = time.time()
                return now - self._t0
        """,
    )
    negative = (
        # monotonic pair is the fix
        """
        import time

        def work():
            t0 = time.monotonic()
            do_stuff()
            return time.monotonic() - t0
        """,
        # deadline comparison and additive deadline are fine
        """
        import time

        def wait(grace_s):
            deadline = time.time() + grace_s
            while time.time() < deadline:
                pass
        """,
        # record-dict math over stored stamps is untainted by design
        """
        def span(req):
            return (req["dispatch_unix"] - req["ingress_unix"]) * 1e3
        """,
    )
