"""callback-cache: host callbacks must not silently kill cacheability.

XLA refuses to persist an executable whose HLO contains a host callback
— with ``FLAGS_compile_cache_dir`` set, one stray ``jax.debug.callback``
or ``io_callback`` in the traced program means every restart pays full
compile again (the bug PR 8 burned a root-cause cycle on dynamically).
The sanctioned pattern routes probe signals through reserved ``_pt_*``
metric leaves on the step outputs when deferring (see
``static/__init__.py`` ``_defer_probes``): the callback only appears in
branches controlled by a defer test, so the cached program is
callback-free.

This pass walks the same jit call graph as trace-purity and flags any
callback call reachable from a jit entry point that is not lexically
under an ``if`` whose test mentions ``defer``.
"""

from __future__ import annotations

import ast

from .base import FUNC_NODES, Finding, Pass
from .jitgraph import ModuleGraph, is_callback_call


class CallbackCachePass(Pass):
    name = "callback-cache"
    help = ("jax.debug.callback/io_callback reachable from a jit entry "
            "point outside a deferred-probe guard (disqualifies the "
            "persistent compile cache)")

    def run(self, modules, ctx):
        findings = []
        for mod in modules:
            graph = ModuleGraph(mod)
            roots = graph.jit_roots()
            if not roots:
                continue
            seen_sites = set()
            visited = set()
            stack = [(fn, desc, False) for fn, desc in roots]

            def scan(node, guarded, cls, desc):
                for child in ast.iter_child_nodes(node):
                    if isinstance(child, FUNC_NODES):
                        continue  # reached via calls, scanned separately
                    if isinstance(child, ast.If):
                        try:
                            test = ast.unparse(child.test)
                        except Exception:  # pragma: no cover
                            test = ""
                        scan(child, guarded or "defer" in test, cls, desc)
                        continue
                    if isinstance(child, ast.Call):
                        if is_callback_call(child):
                            if not guarded \
                                    and child.lineno not in seen_sites:
                                seen_sites.add(child.lineno)
                                findings.append(Finding(
                                    self.name, mod.rel, child.lineno,
                                    "host callback reachable from jit "
                                    f"entry point {desc} outside a "
                                    "deferred-probe guard — a callback "
                                    "in the HLO disqualifies the "
                                    "executable from the persistent "
                                    "compile cache "
                                    "(FLAGS_compile_cache_dir); route "
                                    "it through the `_pt_*` deferred "
                                    "path (static/__init__.py, "
                                    "`_defer_probes`) or suppress with "
                                    "a reason"))
                            # callback args are host-side: don't descend
                            continue
                        for callee in graph.resolve_call(child, cls):
                            stack.append((callee, desc, guarded))
                    scan(child, guarded, cls, desc)

            while stack:
                fn, desc, guarded = stack.pop()
                if (id(fn), guarded) in visited:
                    continue
                visited.add((id(fn), guarded))
                scan(fn, guarded, graph.enclosing_class_name(fn), desc)
        return findings

    positive = (
        # raw callback in a jitted function
        """
        import jax

        def step(x):
            jax.debug.callback(print, x)
            return x

        f = jax.jit(step)
        """,
        # transitive io_callback through a helper
        """
        import jax
        from jax.experimental import io_callback

        def emit(x):
            io_callback(print, None, x)

        def step(x):
            emit(x)
            return x

        f = jax.jit(step)
        """,
    )
    negative = (
        # the PR 8 pattern: callback only in the defer-guarded branch
        """
        import jax

        class T:
            def _step(self, x):
                if self._defer_probes:
                    x = x + 1
                else:
                    jax.debug.callback(print, x)
                return x

            def build(self):
                self._jitted = jax.jit(self._step)
        """,
        # callback in host-only code, never traced
        """
        import jax

        def host_only(x):
            jax.debug.callback(print, x)

        def step(x):
            return x * 2

        f = jax.jit(step)
        """,
    )
