"""paddle_tpu.analysis — the ptlint pass-based static-analysis layer.

The rebuild's answer to the reference framework's registered-graph-pass
system: small composable AST passes over the Python tree, driven by
``tools/ptlint.py`` and the tier-1 test suite.

**Import contract:** everything in this package is stdlib-only (ast /
json / os / re).  ``tools/ptlint.py`` loads it standalone via
``importlib`` *without* going through ``paddle_tpu/__init__.py`` (which
imports jax), so the linter keeps the doc checkers' milliseconds-fast,
jax-free property.  Never import from the parent package here.

Rule catalog (docs/static_analysis.md has the long form):

- ``trace-purity``     host effects in jit-reachable code
- ``callback-cache``   raw host callbacks vs the persistent compile cache
- ``lock-discipline``  `# guarded-by:` fields mutate only under their lock
- ``clock-hygiene``    wall-clock time.time() in duration subtractions
- ``silent-failure``   `except …: pass` without a counter or a reason
- ``flag-freeze``      GLOBAL_FLAGS.get(...) at module import time
- ``flags-doc``        flags need help= + docs (ex check_flags_doc.py)
- ``metrics-doc``      metric names need docs (ex check_metrics_doc.py)
- ``metric-hygiene``   instrument kind must match the name contract
"""

from . import base, jitgraph  # noqa: F401  (re-exported submodules)
from . import (callback_cache, clock_hygiene, flag_freeze, flags_doc,
               lock_discipline, metric_hygiene, metrics_doc,
               silent_failure, trace_purity)
from .base import Context, Finding, Pass, SourceModule  # noqa: F401

_PASSES = None


def all_passes():
    """One fresh registry instance list (stable order = report order)."""
    global _PASSES
    if _PASSES is None:
        _PASSES = [
            trace_purity.TracePurityPass(),
            callback_cache.CallbackCachePass(),
            lock_discipline.LockDisciplinePass(),
            clock_hygiene.ClockHygienePass(),
            silent_failure.SilentFailurePass(),
            flag_freeze.FlagFreezePass(),
            flags_doc.FlagsDocPass(),
            metrics_doc.MetricsDocPass(),
            metric_hygiene.MetricHygienePass(),
        ]
    return list(_PASSES)
