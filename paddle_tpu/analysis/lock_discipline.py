"""lock-discipline: annotated fields only mutate under their lock.

The serving plane spans ~10 threads (batcher, stream bridge, metric
reporter, watchdogs, HTTP handlers) whose discipline used to live only
in comments.  This pass makes those comments checkable:

- ``# guarded-by: <lockexpr>`` on a field's init line (or the line
  directly above) declares that every mutation of the field must be
  lexically inside ``with <lockexpr>:`` — or in ``__init__``, or in a
  method annotated ``# holds-lock: <lockexpr>`` (callers acquire it).
  Works for ``self._field`` class fields and module globals.
- ``# guarded-by: single-owner (<who>)`` declares a lock-free
  single-thread ownership contract instead: the declaring class may
  mutate the field freely, but any ``obj.<field>`` mutation from
  outside (a non-``self`` receiver, anywhere in the scanned tree) is a
  violation.

Mutations are assignments (incl. tuple/subscript targets and
augmented assigns), ``del``, and calls of mutating container methods
(``append``/``pop``/``update``/…).  Lock expressions match textually
against ``ast.unparse`` of the with-items, so write the annotation the
way the code writes the ``with`` (e.g. ``self._lock``).
"""

from __future__ import annotations

import ast
import re

from .base import FUNC_NODES, Finding, Pass

_GUARD_RE = re.compile(r"#\s*guarded-by:\s*([^#]+?)\s*$")
_HOLDS_RE = re.compile(r"#\s*holds-lock:\s*([^#]+?)\s*$")

_MUTATORS = {
    "append", "appendleft", "extend", "insert", "add", "update",
    "setdefault", "pop", "popleft", "popitem", "remove", "discard",
    "clear", "sort", "reverse",
}


def _assign_targets(node):
    out = []
    if isinstance(node, ast.Assign):
        raw = list(node.targets)
    elif isinstance(node, (ast.AnnAssign, ast.AugAssign)):
        raw = [node.target]
    elif isinstance(node, ast.Delete):
        raw = list(node.targets)
    else:
        return out
    stack = raw
    while stack:
        t = stack.pop()
        if isinstance(t, (ast.Tuple, ast.List)):
            stack.extend(t.elts)
        else:
            out.append(t)
    return out


def _mutated_slots(node):
    """Expressions whose binding/content this statement mutates."""
    slots = []
    for t in _assign_targets(node):
        if isinstance(t, ast.Subscript):
            slots.append(t.value)
        elif isinstance(t, (ast.Attribute, ast.Name)):
            slots.append(t)
    if isinstance(node, ast.Call) and isinstance(node.func, ast.Attribute) \
            and node.func.attr in _MUTATORS:
        slots.append(node.func.value)
    return slots


def _declarations(mod):
    """[(class_name|None, field|None, lock, anno_lineno)] — a None
    field marks a dangling annotation."""
    assigns = {}
    for node in ast.walk(mod.tree):
        if isinstance(node, (ast.Assign, ast.AnnAssign, ast.AugAssign)):
            assigns.setdefault(node.lineno, node)
    decls = []
    for i, comment in sorted(mod.comments.items()):
        m = _GUARD_RE.search(comment)
        if not m:
            continue
        lock = m.group(1).strip()
        # a comment-only line annotates the line below it
        own_line = mod.line(i).strip().startswith("#")
        target_line = i + 1 if own_line else i
        node = assigns.get(target_line)
        attached = False
        if node is not None:
            cls = mod.enclosing(node, (ast.ClassDef,))
            for t in _assign_targets(node):
                if (isinstance(t, ast.Attribute)
                        and isinstance(t.value, ast.Name)
                        and t.value.id == "self" and cls is not None):
                    decls.append((cls.name, t.attr, lock, i))
                    attached = True
                elif (isinstance(t, ast.Name)
                      and mod.enclosing(node, FUNC_NODES) is None):
                    decls.append((None, t.id, lock, i))
                    attached = True
        if not attached:
            decls.append((None, None, lock, i))
    return decls


def _holds_lock(mod, fn, lock):
    for ln in (fn.lineno, fn.lineno - 1):
        m = _HOLDS_RE.search(mod.comments.get(ln, ""))
        if m and m.group(1).strip() == lock:
            return True
    return False


def _under_with(mod, node, lock):
    n = mod.parents.get(node)
    while n is not None:
        if isinstance(n, (ast.With, ast.AsyncWith)):
            for item in n.items:
                try:
                    expr = ast.unparse(item.context_expr)
                except Exception:  # pragma: no cover
                    expr = ""
                if expr == lock:
                    return True
        n = mod.parents.get(n)
    return False


class LockDisciplinePass(Pass):
    name = "lock-discipline"
    help = ("fields annotated `# guarded-by: <lock>` mutate only under "
            "`with <lock>:` (or __init__/holds-lock); single-owner "
            "fields reject external mutation")

    def run(self, modules, ctx):
        findings = []
        per_mod = {}
        single_owner = {}  # field -> (class, rel, lock)
        for mod in modules:
            decls = _declarations(mod)
            per_mod[mod.rel] = decls
            for cls, field, lock, lineno in decls:
                if field is None:
                    findings.append(Finding(
                        self.name, mod.rel, lineno,
                        f"`# guarded-by: {lock}` is not attached to a "
                        "field assignment — put it on the field's init "
                        "line or the line directly above"))
                elif cls is not None and lock.startswith("single-owner"):
                    single_owner[field] = (cls, mod.rel, lock)

        for mod in modules:
            fields = {}
            globals_map = {}
            for cls, field, lock, _ in per_mod[mod.rel]:
                if field is None:
                    continue
                if cls is None:
                    globals_map[field] = lock
                else:
                    fields[(cls, field)] = lock
            for node in ast.walk(mod.tree):
                for slot in _mutated_slots(node):
                    findings.extend(self._check_slot(
                        mod, node, slot, fields, globals_map,
                        single_owner))
        return findings

    def _check_slot(self, mod, node, slot, fields, globals_map,
                    single_owner):
        out = []
        if isinstance(slot, ast.Attribute) \
                and isinstance(slot.value, ast.Name):
            field = slot.attr
            if slot.value.id == "self":
                cls = mod.enclosing(node, (ast.ClassDef,))
                if cls is None:
                    return out
                lock = fields.get((cls.name, field))
                if lock is None or lock.startswith("single-owner"):
                    return out  # single-owner: own-class mutation is fine
                if not self._legal(mod, node, lock):
                    out.append(Finding(
                        self.name, mod.rel, node.lineno,
                        f"`self.{field}` is declared `# guarded-by: "
                        f"{lock}` but is mutated outside `with {lock}:` "
                        "(and outside __init__) — take the lock, or "
                        f"annotate the method `# holds-lock: {lock}` if "
                        "every caller already holds it"))
            else:
                owner = single_owner.get(field)
                if owner is not None:
                    cls, rel, lock = owner
                    out.append(Finding(
                        self.name, mod.rel, node.lineno,
                        f"`.{field}` is declared `# guarded-by: {lock}` "
                        f"by {cls} ({rel}) — mutating it through an "
                        "external reference breaks the single-thread "
                        "ownership contract"))
        elif isinstance(slot, ast.Name):
            lock = globals_map.get(slot.id)
            if lock is None:
                return out
            if mod.enclosing(node, FUNC_NODES) is None:
                return out  # module-scope init (the declaration itself)
            if not self._legal(mod, node, lock, allow_init=False):
                out.append(Finding(
                    self.name, mod.rel, node.lineno,
                    f"module global `{slot.id}` is declared "
                    f"`# guarded-by: {lock}` but is mutated outside "
                    f"`with {lock}:`"))
        return out

    @staticmethod
    def _legal(mod, node, lock, allow_init=True):
        fn = mod.enclosing(node, FUNC_NODES)
        if fn is not None:
            if allow_init and fn.name == "__init__":
                return True
            if _holds_lock(mod, fn, lock):
                return True
        return _under_with(mod, node, lock)

    positive = (
        # class field mutated without the lock
        """
        import threading

        class S:
            def __init__(self):
                self._lock = threading.Lock()
                self._q = []  # guarded-by: self._lock

            def bad(self, x):
                self._q.append(x)
        """,
        # module global mutated without the lock
        """
        import threading

        _lock = threading.Lock()
        _server = None  # guarded-by: _lock

        def stop():
            global _server
            _server = None
        """,
        # single-owner field mutated through an external reference
        """
        class E:
            def __init__(self):
                self._seqs = {}  # guarded-by: single-owner (serving thread)

        class Other:
            def poke(self, e):
                e._seqs["x"] = 1
        """,
    )
    negative = (
        # every mutation under the lock (incl. subscript + del)
        """
        import threading

        class S:
            def __init__(self):
                self._lock = threading.Lock()
                self._q = {}  # guarded-by: self._lock

            def good(self, k, v):
                with self._lock:
                    self._q[k] = v
                    del self._q[k]
        """,
        # caller holds the lock; callee declares holds-lock
        """
        import threading

        class S:
            def __init__(self):
                self._lock = threading.Lock()
                self._n = 0  # guarded-by: self._lock

            def _bump(self):  # holds-lock: self._lock
                self._n += 1

            def bump(self):
                with self._lock:
                    self._bump()
        """,
        # single-owner class mutating its own field is fine
        """
        class E:
            def __init__(self):
                self._seqs = {}  # guarded-by: single-owner (serving thread)

            def emit(self, k, v):
                self._seqs[k] = v
                self._seqs.pop(k, None)
        """,
    )
