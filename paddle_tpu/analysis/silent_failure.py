"""silent-failure: `except …: pass` must be counted or justified.

A bare ``pass`` handler makes a failure class invisible forever: shm
decode errors leak segments, close() errors hide socket trouble, and
nobody ever learns.  The rule: either the handler increments a counter
/ flight event (any non-``pass`` body), or the site carries a
suppression **with a reason** —

    except OSError:  # ptlint: disable=silent-failure -- <why it's safe>
        pass

Reason-less suppressions are rejected (``requires_reason``).
"""

from __future__ import annotations

import ast

from .base import Finding, Pass


class SilentFailurePass(Pass):
    name = "silent-failure"
    help = ("`except …: pass` swallows failures invisibly — count it "
            "(metrics/flight) or suppress with a reason")
    requires_reason = True

    def run(self, modules, ctx):
        out = []
        for mod in modules:
            for node in ast.walk(mod.tree):
                if isinstance(node, ast.ExceptHandler) \
                        and len(node.body) == 1 \
                        and isinstance(node.body[0], ast.Pass):
                    out.append(Finding(
                        self.name, mod.rel, node.lineno,
                        "`except …: pass` swallows the failure "
                        "invisibly — increment a counter / flight "
                        "event, or suppress with a reason "
                        "(`# ptlint: disable=silent-failure -- <why>`)"))
        return out

    positive = (
        """
        def f():
            try:
                g()
            except ValueError:
                pass
        """,
        """
        def f():
            try:
                g()
            except Exception:  # noqa: BLE001
                pass
        """,
    )
    negative = (
        # counted handler: the failure stays observable
        """
        def f(metrics):
            try:
                g()
            except Exception:
                metrics.counter("g_errors_total", "g failures").inc()
        """,
        # suppressed WITH a reason (the round-trip case)
        """
        def f():
            try:
                g()
            except OSError:  # ptlint: disable=silent-failure -- interpreter may be tearing down
                pass
        """,
    )
