"""trace-purity: host effects must not reach jit-traced code.

A function reachable from a jit entry point (``jax.jit`` /
``instrumented_jit`` target, ``pallas_call`` kernel, ``@to_static``
body) runs at **trace time**: a ``time.time()``, ``random.*``,
``os.environ`` or flag read there bakes one host value into the
compiled program forever (or silently changes it across retraces), and
metric/flight writes fire once per trace instead of once per step.  The
deliberate escape hatch is ``jax.debug.callback`` — its payload runs on
the host per execution — so callback arguments are allowlisted and
never traversed (the callback-cache pass owns *their* hygiene).
"""

from __future__ import annotations

import ast

from .base import Finding, Pass, flags_aliases
from .jitgraph import ModuleGraph, attr_chain, is_callback_call, iter_scope

_ENV_CALLS = {"os.getenv", "os.environ.get", "os.putenv"}
_METRIC_FACTORIES = {"counter", "gauge", "histogram"}


def _effects(fn, aliases):
    """[(lineno, description)] host effects lexically in fn's scope."""
    out = []
    for node in iter_scope(fn):
        if isinstance(node, (ast.Global, ast.Nonlocal)):
            kind = "global" if isinstance(node, ast.Global) else "nonlocal"
            out.append((node.lineno,
                        f"`{kind} {', '.join(node.names)}` write"))
        elif isinstance(node, ast.Call):
            chain = attr_chain(node.func)
            if not chain or is_callback_call(node):
                continue
            parts = chain.split(".")
            root, last = parts[0], parts[-1]
            if root in ("time", "_time"):
                out.append((node.lineno, f"`{chain}()` host clock read"))
            elif root == "random" or chain.startswith(("np.random.",
                                                       "numpy.random.")):
                out.append((node.lineno, f"`{chain}()` host RNG"))
            elif chain in _ENV_CALLS:
                out.append((node.lineno, f"`{chain}()` environment read"))
            elif last == "get" and any(
                    "FLAGS" in p or p in aliases for p in parts[:-1]):
                out.append((node.lineno, f"`{chain}()` flag read"))
            elif (last in _METRIC_FACTORIES and len(parts) <= 2
                  and root not in ("self", "cls")):
                out.append((node.lineno,
                            f"`{chain}()` metric registration/mutation"))
            elif (last == "record" and len(parts) >= 2
                  and "flight" in parts[-2].lower()):
                out.append((node.lineno,
                            f"`{chain}()` flight-recorder write"))
        elif isinstance(node, ast.Attribute):
            if (node.attr == "environ" and isinstance(node.value, ast.Name)
                    and node.value.id == "os"):
                out.append((node.lineno, "`os.environ` access"))
    return out


class TracePurityPass(Pass):
    name = "trace-purity"
    help = ("host effects (time/random/os.environ/flag reads/metric "
            "writes/global writes) in functions reachable from jit "
            "entry points")

    def run(self, modules, ctx):
        findings = []
        for mod in modules:
            graph = ModuleGraph(mod)
            roots = graph.jit_roots()
            if not roots:
                continue
            aliases = flags_aliases(mod.tree)
            seen = set()
            for fn, desc in graph.reachable(roots).values():
                fname = getattr(fn, "name", "<lambda>")
                for lineno, what in _effects(fn, aliases):
                    key = (lineno, what)
                    if key in seen:
                        continue
                    seen.add(key)
                    findings.append(Finding(
                        self.name, mod.rel, lineno,
                        f"host effect {what} in `{fname}`, reachable "
                        f"from jit entry point {desc} — traced code must "
                        "be pure: the value is baked in at trace time "
                        "(or silently changes across retraces)"))
        return findings

    positive = (
        # direct host clock in a jitted function
        """
        import time
        import jax

        def step(x):
            t = time.time()
            return x + t

        f = jax.jit(step)
        """,
        # flag read in a method jitted via self-reference
        """
        import jax
        from paddle_tpu.flags import GLOBAL_FLAGS

        class T:
            def _step(self, x):
                if GLOBAL_FLAGS.get("debug"):
                    return x
                return x * 2

            def build(self):
                self._jitted = jax.jit(self._step)
        """,
        # transitive: global write in a helper called from the root
        """
        import jax

        _n = 0

        def _inner(x):
            global _n
            _n = 1
            return x

        def outer(x):
            return _inner(x)

        f = jax.jit(outer)
        """,
    )
    negative = (
        # host effects confined to never-traced functions
        """
        import time
        import jax

        def host_loop(x):
            t0 = time.monotonic()
            return x, t0

        def step(x):
            return x * 2

        f = jax.jit(step)
        """,
        # the allowlisted probe pattern: callback args are host-side
        """
        import time
        import jax

        def probe(v):
            jax.debug.callback(lambda x: time.time(), v)

        def step(x):
            probe(x)
            return x

        f = jax.jit(step)
        """,
    )
