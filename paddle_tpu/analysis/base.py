"""ptlint core — shared machinery for the AST-walking pass framework.

The analysis layer is the rebuild's answer to the reference framework's
static IR-pass system: a registry of small, composable passes that walk
the Python sources (and a little of ``csrc/``) without importing the
framework.  Everything in ``paddle_tpu/analysis/`` must stay
**stdlib-only** (ast/json/os/re/textwrap) so ``tools/ptlint.py`` runs in
milliseconds with no jax, exactly like the doc checkers it absorbed.

Shared pieces:

- :class:`Finding` — one diagnostic: rule id, ``path:line``, severity.
- :class:`SourceModule` — one parsed file (parse once, share across
  every pass), with raw source lines kept so passes can read comments
  (``# guarded-by:``, ``# ptlint: disable=``) that ast discards.
- suppressions — ``# ptlint: disable=<rule>[,<rule>…] -- <reason>`` on
  the finding line or the line directly above.  Passes with
  ``requires_reason = True`` reject reason-less suppressions.
- baseline — ``tools/ptlint_baseline.json`` holds deliberately deferred
  findings, each with a reason.  Entries are matched by
  (rule, path, stripped-source-line anchor) so they survive line drift;
  an entry that matches nothing is *stale* and errors, which is how the
  "baseline may only shrink" policy is enforced at runtime.

See docs/static_analysis.md for the rule catalog and policies.
"""

from __future__ import annotations

import ast
import io
import json
import os
import re
import textwrap
import tokenize
from dataclasses import dataclass
from typing import Dict, List, Optional, Sequence, Tuple

#: repo root (…/paddle_tpu/analysis/base.py -> repo)
ROOT = os.path.dirname(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

FUNC_NODES = (ast.FunctionDef, ast.AsyncFunctionDef)

_SUPPRESS_RE = re.compile(
    r"#\s*ptlint:\s*disable=\s*([A-Za-z0-9_,\-]+)"
    r"(?:\s+--\s*(\S.*?))?\s*$")


# ---------------------------------------------------------------------------
# findings and suppressions
# ---------------------------------------------------------------------------


@dataclass
class Finding:
    """One diagnostic, anchored at ``path:line``."""

    rule: str
    path: str            # repo-relative, '/'-separated
    line: int
    message: str
    severity: str = "error"

    def format(self) -> str:
        return f"{self.path}:{self.line}: [{self.rule}] {self.message}"


@dataclass
class Suppression:
    """A parsed ``# ptlint: disable=…`` comment."""

    line: int
    rules: Tuple[str, ...]
    reason: str


def comment_lines(text: str) -> Dict[int, str]:
    """{lineno: comment_text} for real COMMENT tokens only — a
    ``# guarded-by:`` inside a docstring or string literal is prose,
    not an annotation."""
    out: Dict[int, str] = {}
    try:
        for tok in tokenize.generate_tokens(io.StringIO(text).readline):
            if tok.type == tokenize.COMMENT:
                out[tok.start[0]] = tok.string
    except (tokenize.TokenError, IndentationError,
            SyntaxError):  # pragma: no cover - ast.parse catches first
        for i, line in enumerate(text.splitlines(), 1):
            if line.lstrip().startswith("#"):
                out[i] = line.strip()
    return out


def parse_suppressions(comments: Dict[int, str]) -> List[Suppression]:
    out = []
    for i, comment in sorted(comments.items()):
        m = _SUPPRESS_RE.search(comment)
        if m:
            rules = tuple(r.strip() for r in m.group(1).split(",")
                          if r.strip())
            out.append(Suppression(i, rules, (m.group(2) or "").strip()))
    return out


# ---------------------------------------------------------------------------
# source modules
# ---------------------------------------------------------------------------


class SourceModule:
    """One parsed source file, shared by every pass (parse once)."""

    def __init__(self, path: str, rel: str, text: str):
        self.path = path
        self.rel = rel.replace(os.sep, "/")
        self.text = text
        self.lines = text.splitlines()
        self.tree = ast.parse(text, filename=path)
        self.comments = comment_lines(text)
        self.suppressions = parse_suppressions(self.comments)
        self._parents: Optional[Dict[ast.AST, ast.AST]] = None

    @classmethod
    def from_source(cls, source: str, rel: str = "fixture.py"):
        return cls("<fixture>", rel, textwrap.dedent(source))

    def line(self, lineno: int) -> str:
        if 1 <= lineno <= len(self.lines):
            return self.lines[lineno - 1]
        return ""

    def suppression_for(self, rule: str, lineno: int):
        """The suppression covering (rule, line), if any — same line or
        the line directly above."""
        for s in self.suppressions:
            if rule in s.rules and s.line in (lineno, lineno - 1):
                return s
        return None

    @property
    def parents(self) -> Dict[ast.AST, ast.AST]:
        if self._parents is None:
            p: Dict[ast.AST, ast.AST] = {}
            for node in ast.walk(self.tree):
                for child in ast.iter_child_nodes(node):
                    p[child] = node
            self._parents = p
        return self._parents

    def enclosing(self, node: ast.AST, kinds) -> Optional[ast.AST]:
        """Nearest ancestor of ``node`` matching ``kinds`` (or None)."""
        n = self.parents.get(node)
        while n is not None:
            if isinstance(n, kinds):
                return n
            n = self.parents.get(n)
        return None


EXCLUDE_DIRS = {"__pycache__", ".git", "build", "dist", ".eggs"}


def load_modules(root: str, subdirs: Sequence[str] = ("paddle_tpu",),
                 on_error=None) -> List[SourceModule]:
    """Parse every ``.py`` under ``root/<subdir>`` (or a single file)."""
    mods: List[SourceModule] = []
    for sub in subdirs:
        top = os.path.join(root, sub)
        if os.path.isfile(top):
            paths = [top] if top.endswith(".py") else []
        else:
            paths = []
            for dirpath, dirnames, files in os.walk(top):
                dirnames[:] = sorted(d for d in dirnames
                                     if d not in EXCLUDE_DIRS)
                paths.extend(os.path.join(dirpath, f)
                             for f in sorted(files) if f.endswith(".py"))
        for path in paths:
            try:
                with open(path) as fh:
                    text = fh.read()
                mods.append(SourceModule(
                    path, os.path.relpath(path, root), text))
            except (OSError, SyntaxError) as e:
                if on_error is not None:
                    on_error(path, e)
    return mods


# ---------------------------------------------------------------------------
# pass base + fixture self-test
# ---------------------------------------------------------------------------


@dataclass
class Context:
    """Ambient inputs a pass may need beyond the parsed modules.

    ``root`` is None for fixture runs; doc passes take the text
    overrides so their self-tests need no filesystem."""

    root: Optional[str] = None
    docs_text: Optional[str] = None        # flags-doc override
    metrics_doc_text: Optional[str] = None  # metrics-doc override


class Pass:
    """Base class for ptlint passes.

    Subclasses set ``name`` (the rule id used in suppressions and the
    baseline), ``help`` (one-line catalog entry), optionally
    ``requires_reason`` (suppressions must carry ``-- <why>``), and the
    ``positive`` / ``negative`` fixture snippets the self-test runs."""

    name = "?"
    help = ""
    severity = "error"
    requires_reason = False
    #: rel path given to fixture modules (doc passes need a specific one)
    fixture_rel: Optional[str] = None
    positive: Sequence[str] = ()
    negative: Sequence[str] = ()

    def run(self, modules: List[SourceModule],
            ctx: Context) -> List[Finding]:
        raise NotImplementedError

    def self_test(self) -> List[str]:
        """Error strings ([] = healthy).  Default: every positive
        fixture must produce ≥1 unsuppressed finding, every negative
        fixture none."""
        return fixture_self_test(self)


def fixture_self_test(p: Pass, ctx: Optional[Context] = None) -> List[str]:
    ctx = ctx or Context(root=None)
    errs = []
    if not p.positive or not p.negative:
        errs.append(f"{p.name}: needs both positive and negative fixtures")
    for kind, snippets, want in (("positive", p.positive, True),
                                 ("negative", p.negative, False)):
        for i, src in enumerate(snippets):
            rel = p.fixture_rel or f"fixture_{p.name}_{kind}_{i}.py"
            mod = SourceModule.from_source(src, rel=rel)
            got = [f for f in p.run([mod], ctx)
                   if mod.suppression_for(f.rule, f.line) is None]
            if want and not got:
                errs.append(f"{p.name}: {kind} fixture #{i} "
                            "produced no finding")
            if not want and got:
                errs.append(f"{p.name}: {kind} fixture #{i} produced: "
                            + "; ".join(f.format() for f in got))
    return errs


# ---------------------------------------------------------------------------
# triage: suppressions then baseline
# ---------------------------------------------------------------------------


def apply_suppressions(findings: List[Finding],
                       modules_by_rel: Dict[str, SourceModule],
                       passes_by_rule: Dict[str, Pass]):
    """Split findings into (active, suppressed).  A reason-less
    suppression on a ``requires_reason`` rule stays active."""
    active, suppressed = [], []
    for f in findings:
        mod = modules_by_rel.get(f.path)
        s = mod.suppression_for(f.rule, f.line) if mod else None
        if s is None:
            active.append(f)
            continue
        p = passes_by_rule.get(f.rule)
        if p is not None and p.requires_reason and not s.reason:
            active.append(Finding(
                f.rule, f.path, f.line,
                f.message + f"  (suppression found but `{f.rule}` "
                "requires a reason: append ' -- <why>')", f.severity))
        else:
            suppressed.append(f)
    return active, suppressed


def load_baseline(path: str):
    """-> (entries, errors).  Malformed files error rather than hide."""
    if not os.path.exists(path):
        return [], []
    try:
        with open(path) as fh:
            data = json.load(fh)
    except (OSError, ValueError) as e:
        return [], [f"cannot read baseline {path}: {e}"]
    entries = data.get("entries", [])
    if not isinstance(entries, list):
        return [], [f"baseline {path}: 'entries' must be a list"]
    return entries, []


def apply_baseline(findings: List[Finding], entries: List[dict],
                   modules_by_rel: Dict[str, SourceModule],
                   check_stale: bool = True):
    """Split findings into (active, baselined, errors).

    Matching is by (rule, path, stripped-source-line anchor).  Every
    entry needs a reason; with ``check_stale`` an entry matching no
    live finding errors — the baseline may only shrink."""
    errors: List[str] = []
    used = [0] * len(entries)
    active, baselined = [], []
    for f in findings:
        mod = modules_by_rel.get(f.path)
        anchor = mod.line(f.line).strip() if mod else ""
        hit = None
        for i, e in enumerate(entries):
            if (e.get("rule") == f.rule and e.get("path") == f.path
                    and str(e.get("anchor", "")).strip() == anchor):
                hit = i
                break
        if hit is None:
            active.append(f)
        else:
            used[hit] += 1
            baselined.append(f)
    for i, e in enumerate(entries):
        where = f"{e.get('rule')} @ {e.get('path')}"
        if not str(e.get("reason", "")).strip():
            errors.append(f"baseline entry {i} ({where}) has no reason — "
                          "every deliberate deferral needs one")
        if check_stale and not used[i]:
            errors.append(
                f"stale baseline entry {i} ({where}): matches no current "
                "finding — delete it; the baseline may only shrink")
    return active, baselined, errors


# ---------------------------------------------------------------------------
# small shared helpers
# ---------------------------------------------------------------------------


def flags_aliases(tree: ast.AST) -> set:
    """Names the module binds to the flag registry (GLOBAL_FLAGS plus
    any ``from …flags import GLOBAL_FLAGS as X`` alias)."""
    out = {"GLOBAL_FLAGS"}
    for node in ast.walk(tree):
        if isinstance(node, ast.ImportFrom):
            for a in node.names:
                if a.name == "GLOBAL_FLAGS":
                    out.add(a.asname or a.name)
    return out
