"""Multiprocess DataLoader workers with shared-memory batch transport.

TPU-native equivalent of the reference's multiprocess dataloader
(/root/reference/python/paddle/fluid/dataloader/dataloader_iter.py:335
_DataLoaderIterMultiProcess, python/paddle/fluid/reader.py:123, and the
SIGCHLD-safe process management in
paddle/fluid/imperative/data_loader.cc). Design differences from the
reference, on purpose:

- Transport is ``multiprocessing.shared_memory`` segments carrying the
  *collated* numpy batch (one segment per large array), not a
  LoDTensorBlockingQueue: the consumer is ``jax.device_put``, so the
  parent only needs a contiguous host buffer, and collating in the
  worker keeps the parent's GIL free for dispatch.
- Worker death is detected by a liveness check on queue-get timeout
  (rather than a SIGCHLD handler, which a library should not own) and
  surfaces as a RuntimeError naming the dead worker and exit code.
- Batches are re-ordered by sequence number so ``num_workers`` never
  changes the stream the model sees.
"""

from __future__ import annotations

import itertools
import queue
import time
import traceback
from multiprocessing import get_context, resource_tracker
from multiprocessing.shared_memory import SharedMemory
from typing import Any, Callable, List, Optional

import numpy as np

# Arrays below this many bytes ride the pickle queue directly; above it
# they move through a shared-memory segment (one memcpy in the worker,
# one in the parent — no pickle of the payload).
_SHM_MIN_BYTES = 1 << 14


def _note_swallowed(where: str, exc: BaseException) -> None:
    """A teardown/decode-path error was deliberately swallowed: count
    it instead of losing it — a failed leftover decode is a leaked shm
    segment, and a string of them should be visible on a dashboard."""
    try:
        from ..observability import metrics as _metrics
        _metrics.counter(
            "dataloader_swallowed_errors_total",
            "errors swallowed on DataLoader teardown/decode paths "
            "(where: decode_sweep | decode_leftover | shutdown_put | "
            "shutdown_close)", always=True).inc(where=where)
        from ..observability import flight as _flight
        _flight.record("dataloader_swallowed_error", where=where,
                       error=repr(exc)[:200])
    # ptlint: disable=silent-failure -- telemetry about a swallowed error must never itself raise (interpreter may be tearing down)
    except Exception:  # noqa: BLE001
        pass


class WorkerInfo:
    """Per-worker shard info, available inside worker processes via
    :func:`get_worker_info` (ref: dataloader/worker.py get_worker_info)."""

    def __init__(self, id: int, num_workers: int, seed: int) -> None:
        self.id = id
        self.num_workers = num_workers
        self.seed = seed


_worker_info: Optional[WorkerInfo] = None


def get_worker_info() -> Optional[WorkerInfo]:
    """Inside a worker process: that worker's (id, num_workers, seed).
    In the main process: None."""
    return _worker_info


def _encode(obj, segments: List[SharedMemory]):
    """Replace large ndarrays in a batch pytree with shm descriptors."""
    if isinstance(obj, np.ndarray):
        if obj.nbytes >= _SHM_MIN_BYTES:
            shm = SharedMemory(create=True, size=max(obj.nbytes, 1))
            # Ownership transfers to the parent (which unlinks after the
            # copy-out); keep this process's resource tracker out of it.
            try:
                resource_tracker.unregister(shm._name, "shared_memory")
            # ptlint: disable=silent-failure -- resource_tracker unregistration is best-effort across Python versions; worst case is a spurious tracker warning at exit
            except Exception:
                pass
            dst = np.ndarray(obj.shape, obj.dtype, buffer=shm.buf)
            np.copyto(dst, obj)
            segments.append(shm)
            return ("__shm__", shm.name, obj.dtype.str, obj.shape)
        return obj
    if isinstance(obj, tuple):
        return tuple(_encode(o, segments) for o in obj)
    if isinstance(obj, list):
        return [_encode(o, segments) for o in obj]
    if isinstance(obj, dict):
        return {k: _encode(v, segments) for k, v in obj.items()}
    return obj


def _decode(obj):
    """Materialize shm descriptors back into ndarrays (copy + unlink)."""
    if isinstance(obj, tuple):
        if len(obj) == 4 and obj[0] == "__shm__":
            _, name, dtype, shape = obj
            shm = SharedMemory(name=name)
            try:
                src = np.ndarray(shape, np.dtype(dtype), buffer=shm.buf)
                out = np.array(src)  # own the data before unlinking
            finally:
                shm.close()
                shm.unlink()
            return out
        return tuple(_decode(o) for o in obj)
    if isinstance(obj, list):
        return [_decode(o) for o in obj]
    if isinstance(obj, dict):
        return {k: _decode(v) for k, v in obj.items()}
    return obj


def _drain_and_reap(result_qs, workers, leftovers, timeout: float = 10.0):
    """Decode (and so unlink) every in-flight shm payload, then reap the
    workers. Runs until the workers have exited AND the queues are empty,
    so a worker that was mid-batch at shutdown can't strand segments in
    /dev/shm."""
    if not isinstance(result_qs, (list, tuple)):
        result_qs = [result_qs]

    def sweep(block_s: float) -> bool:
        got = False
        for q in result_qs:
            try:
                item = q.get(timeout=block_s)
            except queue.Empty:
                continue
            got = True
            if item[2] is None:
                try:
                    _decode(item[1])
                except Exception as e:  # noqa: BLE001
                    _note_swallowed("decode_sweep", e)
        return got

    for payload in leftovers:
        try:
            _decode(payload)
        except Exception as e:  # noqa: BLE001
            _note_swallowed("decode_leftover", e)
    deadline = time.monotonic() + timeout
    while (any(w.is_alive() for w in workers)
           and time.monotonic() < deadline):
        sweep(0.1)
    for w in workers:
        w.join(timeout=2.0)
        if w.is_alive():
            w.terminate()
            w.join(timeout=1.0)
    # final sweeps: nothing can be producing anymore
    while sweep(0.05):
        pass


def _map_worker_loop(dataset, collate_fn, index_q, result_q,
                     worker_id: int, num_workers: int, seed: int) -> None:
    global _worker_info
    _worker_info = WorkerInfo(worker_id, num_workers, seed + worker_id)
    while True:
        item = index_q.get()
        if item is None:
            break
        seq, indices = item
        try:
            batch = collate_fn([dataset[i] for i in indices])
            segments: List[SharedMemory] = []
            payload = _encode(batch, segments)
            result_q.put((seq, payload, None))
            for shm in segments:
                shm.close()
        except Exception:
            result_q.put((seq, None, traceback.format_exc()))


def _iterable_worker_loop(dataset, collate_fn, batch_size: int,
                          drop_last: bool, result_q, worker_id: int,
                          num_workers: int, seed: int,
                          auto_shard: bool, stop_event) -> None:
    """Each worker reads the stream into its OWN bounded queue; with
    ``auto_shard`` the loop strides so worker w sees samples w, w+n,
    w+2n… The parent merges the queues round-robin, so order is
    deterministic and backpressure is per worker: a fast worker blocks
    once its own queue fills, it cannot race ahead on the others'
    slots or pile batches into parent memory. Datasets that shard
    themselves via :func:`get_worker_info` (the reference's convention)
    must be run with auto_shard=False or they'd be strided twice."""
    global _worker_info
    _worker_info = WorkerInfo(worker_id, num_workers, seed + worker_id)
    try:
        it = iter(dataset)
        if auto_shard and num_workers > 1:
            it = itertools.islice(it, worker_id, None, num_workers)
        while not stop_event.is_set():
            samples = list(itertools.islice(it, batch_size))
            if not samples or (len(samples) < batch_size and drop_last):
                break
            batch = collate_fn(samples)
            segments: List[SharedMemory] = []
            payload = _encode(batch, segments)
            posted = False
            while not stop_event.is_set():
                try:
                    result_q.put((None, payload, None), timeout=0.2)
                    posted = True
                    break
                except queue.Full:
                    continue
            if not posted:
                # parent never saw this payload: unlink it here
                for shm in segments:
                    shm.close()
                    try:
                        shm.unlink()
                    # ptlint: disable=silent-failure -- the parent may have unlinked first on a racing teardown; either side unlinking is enough
                    except Exception:
                        pass
                break
            for shm in segments:
                shm.close()
        result_q.put((None, None, "__done__"))
    except Exception:
        result_q.put((None, None, traceback.format_exc()))


class MultiprocessIter:
    """Order-preserving multiprocess iterator over a map-style dataset.

    Round-robins batch index lists to ``num_workers`` processes, bounded
    to ``num_workers * prefetch_factor`` batches in flight, and yields
    results strictly in sampler order.
    """

    _GET_TIMEOUT = 5.0

    def __init__(self, dataset, collate_fn: Callable, batch_indices,
                 num_workers: int, prefetch_factor: int = 2,
                 mp_start_method: str = "fork", seed: int = 0) -> None:
        ctx = get_context(mp_start_method)
        self._result_q = ctx.Queue()
        self._index_qs = [ctx.Queue() for _ in range(num_workers)]
        self._workers = []
        for wid in range(num_workers):
            w = ctx.Process(
                target=_map_worker_loop,
                args=(dataset, collate_fn, self._index_qs[wid],
                      self._result_q, wid, num_workers, seed),
                daemon=True)
            w.start()
            self._workers.append(w)
        self._batches = iter(enumerate(batch_indices))
        self._max_outstanding = max(1, num_workers * prefetch_factor)
        self._outstanding = 0
        self._next_dispatch_worker = 0
        self._next_yield = 0
        self._reorder: dict = {}
        self._finished = False

    def _dispatch_one(self) -> bool:
        try:
            seq, indices = next(self._batches)
        except StopIteration:
            return False
        self._index_qs[self._next_dispatch_worker].put((seq, indices))
        self._next_dispatch_worker = \
            (self._next_dispatch_worker + 1) % len(self._workers)
        self._outstanding += 1
        return True

    def _check_workers_alive(self) -> None:
        for w in self._workers:
            if not w.is_alive():
                code = w.exitcode
                self.shutdown()
                raise RuntimeError(
                    f"DataLoader worker pid={w.pid} died unexpectedly "
                    f"(exitcode={code}); batch stream is broken. "
                    "(ref capability: imperative/data_loader.cc SIGCHLD "
                    "handling)")

    def __iter__(self):
        return self

    def __next__(self):
        while self._outstanding < self._max_outstanding:
            if not self._dispatch_one():
                break
        if self._outstanding == 0:
            self.shutdown()
            raise StopIteration
        while self._next_yield not in self._reorder:
            try:
                seq, payload, err = self._result_q.get(
                    timeout=self._GET_TIMEOUT)
            except queue.Empty:
                self._check_workers_alive()
                continue
            if err is not None:
                self.shutdown()
                raise RuntimeError(f"DataLoader worker failed:\n{err}")
            self._reorder[seq] = payload
        payload = self._reorder.pop(self._next_yield)
        self._next_yield += 1
        self._outstanding -= 1
        self._dispatch_one()
        return _decode(payload)

    def shutdown(self) -> None:
        if self._finished:
            return
        self._finished = True
        for q in self._index_qs:
            try:
                q.put(None)
            except Exception as e:  # noqa: BLE001
                _note_swallowed("shutdown_put", e)
        leftovers = list(self._reorder.values())
        self._reorder.clear()
        _drain_and_reap(self._result_q, self._workers, leftovers)
        for q in self._index_qs + [self._result_q]:
            try:
                q.close()
            except Exception as e:  # noqa: BLE001
                _note_swallowed("shutdown_close", e)

    def __del__(self):
        try:
            self.shutdown()
        # ptlint: disable=silent-failure -- finalizer: shutdown() already counts its own swallowed errors; raising from __del__ only prints noise
        except Exception:
            pass


class IterableMultiprocessIter:
    """Multiprocess iterator over an IterableDataset.

    One bounded queue PER worker (maxsize=prefetch_factor): the parent
    pops the next batch from worker 0, then 1, … — a deterministic merge
    with hard per-worker backpressure and zero parent-side buffering (a
    slow shard stalls the merge at its turn instead of letting the fast
    workers fill /dev/shm behind it)."""

    _GET_TIMEOUT = 5.0

    def __init__(self, dataset, collate_fn: Callable, batch_size: int,
                 drop_last: bool, num_workers: int,
                 mp_start_method: str = "fork", seed: int = 0,
                 prefetch_factor: int = 2, auto_shard: bool = True) -> None:
        ctx = get_context(mp_start_method)
        self._result_qs = [ctx.Queue(maxsize=max(1, prefetch_factor))
                           for _ in range(num_workers)]
        self._stop_event = ctx.Event()
        self._workers = []
        for wid in range(num_workers):
            w = ctx.Process(
                target=_iterable_worker_loop,
                args=(dataset, collate_fn, batch_size, drop_last,
                      self._result_qs[wid], wid, num_workers, seed,
                      auto_shard, self._stop_event),
                daemon=True)
            w.start()
            self._workers.append(w)
        self._n = num_workers
        self._next_worker = 0
        self._done = [False] * num_workers
        self._finished = False

    def __iter__(self):
        return self

    def __next__(self):
        while True:
            if all(self._done):
                self.shutdown()
                raise StopIteration
            while self._done[self._next_worker]:
                self._next_worker = (self._next_worker + 1) % self._n
            wid = self._next_worker
            try:
                _, payload, err = self._result_qs[wid].get(
                    timeout=self._GET_TIMEOUT)
            except queue.Empty:
                w = self._workers[wid]
                if not w.is_alive() and self._result_qs[wid].empty():
                    code = w.exitcode
                    self.shutdown()
                    raise RuntimeError(
                        f"DataLoader worker pid={w.pid} died unexpectedly "
                        f"(exitcode={code}); batch stream is broken.")
                continue
            if err == "__done__":
                self._done[wid] = True
                self._next_worker = (wid + 1) % self._n
                continue
            if err is not None:
                self.shutdown()
                raise RuntimeError(f"DataLoader worker failed:\n{err}")
            self._next_worker = (wid + 1) % self._n
            return _decode(payload)

    def shutdown(self) -> None:
        if self._finished:
            return
        self._finished = True
        self._stop_event.set()
        _drain_and_reap(self._result_qs, self._workers, [])

    def __del__(self):
        try:
            self.shutdown()
        # ptlint: disable=silent-failure -- finalizer: shutdown() already counts its own swallowed errors; raising from __del__ only prints noise
        except Exception:
            pass
