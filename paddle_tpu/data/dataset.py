"""Industrial dataset pipeline: file-driven training datasets.

TPU-native rebuild of the reference's Dataset stack
(/root/reference/python/paddle/fluid/dataset.py DatasetFactory/
InMemoryDataset/QueueDataset; C++ side paddle/fluid/framework/data_set.h:43
DatasetImpl, data_feed.h:255 MultiSlotDataFeed). Parsing/shuffling/batching
runs in the C++ native feed (csrc/data_feed.cc) on reader threads; global
shuffle exchanges serialized record ranges through the control plane
(the reference ships records between nodes via FleetWrapper RPC,
data_set.h:111 GlobalShuffle).

Slot model: each line holds every slot in declaration order,
``<count> v...`` per slot — dense slots are fixed-width float vectors,
sparse slots variable-length int64 id lists (reference: MultiSlot format,
data_feed.proto).
"""

from __future__ import annotations

import math
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from .. import native


class _SlotDef:
    def __init__(self, name: str, kind: str, dim: int,
                 shape: Optional[Tuple[int, ...]] = None):
        self.name, self.kind, self.dim = name, kind, dim
        self.shape = shape  # optional reshape for dense slots


class DatasetBase:
    """Shared config surface (ref: dataset.py DatasetBase)."""

    def __init__(self) -> None:
        self._slots: List[_SlotDef] = []
        self._batch_size = 1
        self._thread = 1
        self._filelist: List[str] = []
        self._queue_capacity = 64
        self._feed: Optional[native.NativeDataFeed] = None

    # -- reference-parity setters (dataset.py set_batch_size/set_thread/...)
    def set_batch_size(self, batch_size: int) -> None:
        self._batch_size = int(batch_size)

    def set_thread(self, thread_num: int) -> None:
        self._thread = int(thread_num)

    def set_filelist(self, filelist: Sequence[str]) -> None:
        self._filelist = list(filelist)

    def set_queue_capacity(self, capacity: int) -> None:
        self._queue_capacity = int(capacity)

    def set_slots(self, slots: Sequence) -> None:
        """Declare input slots, in file order.

        Each slot: (name, kind, dim) tuple or dict with those keys plus
        optional 'shape' to reshape dense slots (e.g. (1, 28, 28)).
        This is the analogue of set_use_var (dataset.py): the reference
        derives slots from program variables; here they are declared.
        """
        defs = []
        for s in slots:
            if isinstance(s, dict):
                defs.append(_SlotDef(s["name"], s["kind"], int(s["dim"]),
                                     tuple(s["shape"]) if s.get("shape")
                                     else None))
            else:
                name, kind, dim = s
                defs.append(_SlotDef(name, kind, int(dim)))
        self._slots = defs

    # alias for reference drop-in style
    set_use_var = set_slots

    def slot_names(self) -> List[str]:
        return [s.name for s in self._slots]

    # -- feed lifecycle
    def _make_feed(self) -> native.NativeDataFeed:
        if not self._slots:
            raise ValueError("dataset has no slots; call set_slots first")
        specs = [native.SlotSpec(s.name, s.kind, s.dim) for s in self._slots]
        feed = native.NativeDataFeed(specs, batch_size=self._batch_size,
                                     num_threads=self._thread,
                                     queue_capacity=self._queue_capacity)
        feed.set_files(self._filelist)
        return feed

    def _feed_or_make(self) -> native.NativeDataFeed:
        if self._feed is None:
            self._feed = self._make_feed()
        return self._feed

    def _postprocess(self, batch: Dict[str, np.ndarray]) \
            -> Dict[str, np.ndarray]:
        for s in self._slots:
            if s.kind == "dense" and s.shape is not None:
                b = batch[s.name]
                batch[s.name] = b.reshape((b.shape[0],) + s.shape)
        return batch

    def release(self) -> None:
        if self._feed is not None:
            self._feed.close()
            self._feed = None


class QueueDataset(DatasetBase):
    """Streaming dataset: reader threads parse files straight into the
    batch queue each epoch (ref: dataset.py QueueDataset; C++
    MultiSlotDataFeed)."""

    def __iter__(self):
        feed = self._feed_or_make()
        feed.set_files(self._filelist)
        feed.start()
        for batch in feed:
            yield self._postprocess(batch)


class InMemoryDataset(DatasetBase):
    """Load-once dataset with local/global shuffle
    (ref: dataset.py InMemoryDataset; data_set.h:157 LocalShuffle,
    :111 GlobalShuffle)."""

    def __init__(self) -> None:
        super().__init__()
        self._epoch = 0
        self._shuffle_round = 0

    def load_into_memory(self) -> int:
        feed = self._feed_or_make()
        feed.set_files(self._filelist)
        return feed.load_into_memory()

    def get_memory_data_size(self) -> int:
        return self._feed_or_make().memory_size()

    def local_shuffle(self, seed: Optional[int] = None) -> None:
        if seed is None:
            seed = self._shuffle_round
        self._shuffle_round += 1
        self._feed_or_make().local_shuffle(seed)

    def global_shuffle(self, client: "native.ControlPlaneClient",
                       rank: int, world: int,
                       timeout_ms: int = 120000) -> int:
        """Shuffle records across `world` workers through the control plane.

        Every worker r: local-shuffles, splits its records into `world`
        contiguous chunks, publishes chunk d under key gshuf/<round>/<r>-><d>,
        barriers, then rebuilds its memory from all chunks destined to it.
        Returns the new local record count. (Reference routes this through
        FleetWrapper RPC: data_set.h:111; the capability is identical, the
        transport is the TPU framework's control plane.)
        """
        feed = self._feed_or_make()
        rnd = self._shuffle_round
        self._shuffle_round += 1
        feed.local_shuffle(seed=rnd * 1000003 + 17)
        n = feed.memory_size()
        bounds = [int(round(i * n / world)) for i in range(world + 1)]
        for dst in range(world):
            blob = feed.serialize_range(bounds[dst], bounds[dst + 1])
            client.set(f"gshuf/{rnd}/{rank}->{dst}", blob)
        client.barrier(f"gshuf/{rnd}/posted", world, timeout_ms)
        feed.clear_memory()
        total = 0
        for src in range(world):
            blob = client.get(f"gshuf/{rnd}/{src}->{rank}", block=True,
                              timeout_ms=timeout_ms)
            total += feed.deserialize_append(blob)
        client.barrier(f"gshuf/{rnd}/done", world, timeout_ms)
        feed.local_shuffle(seed=rnd * 7919 + rank)
        return total

    def release_memory(self) -> None:
        self._feed_or_make().clear_memory()

    def __iter__(self):
        feed = self._feed_or_make()
        feed.start_from_memory()
        self._epoch += 1
        for batch in feed:
            yield self._postprocess(batch)


class DatasetFactory:
    """(ref: dataset.py DatasetFactory.create_dataset)."""

    _KINDS = {"InMemoryDataset": InMemoryDataset,
              "QueueDataset": QueueDataset}

    def create_dataset(self, datafeed_class: str = "QueueDataset"):
        if datafeed_class not in self._KINDS:
            raise ValueError(
                f"unknown dataset class {datafeed_class!r}; "
                f"choose from {sorted(self._KINDS)}")
        return self._KINDS[datafeed_class]()
