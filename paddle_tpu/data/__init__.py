"""Data pipeline.

TPU-native redesign of the reference's reader stack
(/root/reference/python/paddle/fluid/reader.py:123 DataLoader,
python/paddle/fluid/dataloader/dataloader_iter.py:237,335 worker processes,
and the C++ BufferedReader async device prefetch
paddle/fluid/operators/reader/buffered_reader.h:46). v1 is a threaded
Python pipeline with device prefetch; the C++ industrial pipeline
(data_feed/Dataset parity) lands in csrc/ and plugs in behind the same
DataLoader API.

Key TPU-specific piece: :class:`DeviceLoader` overlaps host batch prep with
device compute by keeping ``buffer_size`` batches in flight via
jax.device_put (the BufferedReader.ReadAsync role).
"""

from __future__ import annotations

import itertools
import time
from typing import Any, Callable, Iterable, Iterator, List, Optional, Sequence

import jax
import numpy as np

from ..observability import metrics as _obs_metrics


class Dataset:
    """Map-style dataset (ref: dataloader/dataset.py)."""

    def __getitem__(self, idx: int):
        raise NotImplementedError

    def __len__(self) -> int:
        raise NotImplementedError


class IterableDataset:
    def __iter__(self):
        raise NotImplementedError


class TensorDataset(Dataset):
    """Dataset wrapping same-length arrays. Accepts both the reference's
    list form ``TensorDataset([x, y])`` and varargs ``TensorDataset(x, y)``.

    Note the list form follows the reference contract (a list OF
    tensors): ``TensorDataset([[1, 2], [3, 4]])`` is two length-2
    entries yielding samples ``(1, 3)`` and ``(2, 4)`` — to wrap a
    single 2-D array, pass it as one array: ``TensorDataset(arr)``."""

    def __init__(self, *arrays) -> None:
        if len(arrays) == 1 and isinstance(arrays[0], (list, tuple)):
            arrays = tuple(arrays[0])
        self.arrays = [np.asarray(a) for a in arrays]
        if any(a.ndim == 0 for a in self.arrays):
            raise ValueError(
                "TensorDataset entries must be indexable along a first "
                "dimension; got a scalar (pass arrays, e.g. "
                "TensorDataset([x, y]) or TensorDataset(x, y))")
        if any(len(a) != len(self.arrays[0]) for a in self.arrays[1:]):
            raise ValueError(
                "TensorDataset arrays must share their first dimension: "
                f"got lengths {[len(a) for a in self.arrays]}")

    def __getitem__(self, idx: int):
        return tuple(a[idx] for a in self.arrays)

    def __len__(self) -> int:
        return len(self.arrays[0])


class Sampler:
    def __init__(self, data_source=None) -> None:
        self.data_source = data_source

    def __iter__(self) -> Iterator[int]:
        raise NotImplementedError


class SequenceSampler(Sampler):
    def __iter__(self):
        return iter(range(len(self.data_source)))

    def __len__(self):
        return len(self.data_source)


class RandomSampler(Sampler):
    def __init__(self, data_source, replacement: bool = False,
                 seed: Optional[int] = None) -> None:
        super().__init__(data_source)
        self.replacement = replacement
        self.rng = np.random.default_rng(seed)

    def __iter__(self):
        n = len(self.data_source)
        if self.replacement:
            return iter(self.rng.integers(0, n, size=n).tolist())
        return iter(self.rng.permutation(n).tolist())

    def __len__(self):
        return len(self.data_source)


class DistributedBatchSampler(Sampler):
    """(ref: dataloader/batch_sampler.py DistributedBatchSampler) shards
    batches across data-parallel ranks."""

    def __init__(self, dataset, batch_size: int, num_replicas: int = 1,
                 rank: int = 0, shuffle: bool = False,
                 drop_last: bool = False, seed: int = 0) -> None:
        self.dataset = dataset
        self.batch_size = batch_size
        self.num_replicas = num_replicas
        self.rank = rank
        self.shuffle = shuffle
        self.drop_last = drop_last
        self.epoch = 0
        self.seed = seed

    def set_epoch(self, epoch: int) -> None:
        self.epoch = epoch

    def __iter__(self):
        n = len(self.dataset)
        idx = np.arange(n)
        if self.shuffle:
            rng = np.random.default_rng(self.seed + self.epoch)
            idx = rng.permutation(n)
        # pad so each replica sees the same number of samples
        per_replica = int(np.ceil(n / self.num_replicas))
        padded = np.concatenate([idx, idx[:per_replica * self.num_replicas
                                          - n]])
        local = padded[self.rank::self.num_replicas]
        batches = [local[i:i + self.batch_size].tolist()
                   for i in range(0, len(local), self.batch_size)]
        if self.drop_last and batches and \
                len(batches[-1]) < self.batch_size:
            batches.pop()
        return iter(batches)

    def __len__(self):
        per_replica = int(np.ceil(len(self.dataset) / self.num_replicas))
        if self.drop_last:
            return per_replica // self.batch_size
        return int(np.ceil(per_replica / self.batch_size))


class BatchSampler(Sampler):
    def __init__(self, sampler=None, dataset=None, batch_size: int = 1,
                 shuffle: bool = False, drop_last: bool = False) -> None:
        if sampler is None:
            sampler = RandomSampler(dataset) if shuffle \
                else SequenceSampler(dataset)
        self.sampler = sampler
        self.batch_size = batch_size
        self.drop_last = drop_last

    def __iter__(self):
        batch: List[int] = []
        for idx in self.sampler:
            batch.append(idx)
            if len(batch) == self.batch_size:
                yield batch
                batch = []
        if batch and not self.drop_last:
            yield batch

    def __len__(self):
        n = len(self.sampler)
        if self.drop_last:
            return n // self.batch_size
        return (n + self.batch_size - 1) // self.batch_size


def default_collate_fn(batch: Sequence[Any]):
    """Stack samples into a batch (ref: dataloader collate)."""
    first = batch[0]
    if isinstance(first, (tuple, list)):
        return tuple(default_collate_fn([b[i] for b in batch])
                     for i in range(len(first)))
    if isinstance(first, dict):
        return {k: default_collate_fn([b[k] for b in batch]) for k in first}
    return np.stack([np.asarray(b) for b in batch])


class DataLoader:
    """(ref: reader.py:123, dataloader_iter.py:237,335).

    ``num_workers=0``: batches are produced inline in the calling thread.
    ``num_workers>0``: that many **worker processes** parse and collate
    batches, shipping them to the parent through shared-memory segments
    (see data/worker.py); batch order matches the sampler regardless of
    worker count, and a dead worker raises instead of hanging.
    """

    def __init__(self, dataset, batch_size: int = 1, shuffle: bool = False,
                 drop_last: bool = False, collate_fn: Optional[Callable]
                 = None, num_workers: int = 0, batch_sampler=None,
                 prefetch_factor: int = 2, places=None,
                 return_list: bool = True,
                 mp_start_method: str = "fork",
                 worker_auto_shard: bool = True) -> None:
        self.dataset = dataset
        self.collate_fn = collate_fn or default_collate_fn
        self.num_workers = num_workers
        self.prefetch_factor = max(prefetch_factor, 1)
        self.mp_start_method = mp_start_method
        self.worker_auto_shard = worker_auto_shard
        if batch_sampler is not None:
            self.batch_sampler = batch_sampler
        elif isinstance(dataset, IterableDataset):
            self.batch_sampler = None
            self.batch_size = batch_size
            self.drop_last = drop_last
        else:
            self.batch_sampler = BatchSampler(
                dataset=dataset, batch_size=batch_size, shuffle=shuffle,
                drop_last=drop_last)

    def _iter_batches(self):
        if self.batch_sampler is None:
            # iterable dataset: batch on the fly
            it = iter(self.dataset)
            while True:
                batch = list(itertools.islice(it, self.batch_size))
                if not batch:
                    return
                if len(batch) < self.batch_size and self.drop_last:
                    return
                yield self.collate_fn(batch)
        else:
            for indices in self.batch_sampler:
                yield self.collate_fn([self.dataset[i] for i in indices])

    def __iter__(self):
        if not _obs_metrics.enabled():
            yield from self._iter_raw()
            return
        # production-visibility path: count batches and measure how
        # long the consumer waited on the pipeline for each one
        batches = _obs_metrics.counter(
            "data_batches_total", "batches produced by DataLoader")
        wait_h = _obs_metrics.histogram(
            "data_batch_wait_seconds",
            "time the training loop waited on the data pipeline")
        it = self._iter_raw()
        try:
            while True:
                t0 = time.perf_counter()
                try:
                    b = next(it)
                except StopIteration:
                    return
                wait_h.observe(time.perf_counter() - t0)
                batches.inc()
                yield b
        finally:
            it.close()

    def _iter_raw(self):
        if self.num_workers <= 0:
            yield from self._iter_batches()
            return
        from .worker import IterableMultiprocessIter, MultiprocessIter
        if self.batch_sampler is None:
            it = IterableMultiprocessIter(
                self.dataset, self.collate_fn, self.batch_size,
                self.drop_last, self.num_workers,
                mp_start_method=self.mp_start_method,
                prefetch_factor=self.prefetch_factor,
                auto_shard=self.worker_auto_shard)
        else:
            it = MultiprocessIter(
                self.dataset, self.collate_fn, list(self.batch_sampler),
                self.num_workers, prefetch_factor=self.prefetch_factor,
                mp_start_method=self.mp_start_method)
        try:
            yield from it
        finally:
            it.shutdown()

    def __len__(self):
        if self.batch_sampler is None:
            raise TypeError("IterableDataset has no length")
        return len(self.batch_sampler)

    def iter_from(self, batch_offset: int):
        """One epoch's batches starting at ``batch_offset``, skipping
        the earlier ones WITHOUT fetching or collating them — the
        checkpoint-resume fast path (docs/fault_tolerance.md
        "Numerical faults & exact resume"). The batch sampler is still
        consumed for the skipped positions, so a seeded shuffle yields
        exactly the batches the uninterrupted epoch would have
        produced from ``batch_offset`` on. Iterable datasets have no
        indexable sampler and fall back to consuming raw samples."""
        batch_offset = max(0, int(batch_offset))
        if batch_offset == 0:
            yield from self
            return
        if self.batch_sampler is None:
            # iterable path: samples must be drawn to advance the
            # stream; only collation is skipped
            for j, batch in enumerate(self._iter_batches()):
                if j >= batch_offset:
                    yield batch
            return
        indices = list(self.batch_sampler)[batch_offset:]
        if not indices:
            return
        if self.num_workers > 0:
            from .worker import MultiprocessIter
            it = MultiprocessIter(
                self.dataset, self.collate_fn, indices,
                self.num_workers, prefetch_factor=self.prefetch_factor,
                mp_start_method=self.mp_start_method)
            try:
                yield from it
            finally:
                it.shutdown()
            return
        for batch_indices in indices:
            yield self.collate_fn([self.dataset[i]
                                   for i in batch_indices])


class DeviceLoader:
    """Async host→device prefetch (ref: buffered_reader.h:46 ReadAsync).

    With FLAGS_allocator_strategy="arena" (or use_arena=True), host
    batches are staged through a :class:`core.arena.HostStagingArena`
    before device_put — steady state does zero host mallocs per batch
    (the reference's pinned staging + auto-growth reuse, SURVEY §2.3).
    """

    def __init__(self, loader: Iterable, buffer_size: int = 2,
                 sharding=None, use_arena: Optional[bool] = None) -> None:
        self.loader = loader
        self.buffer_size = buffer_size
        self.sharding = sharding
        if use_arena is None:
            from ..flags import GLOBAL_FLAGS
            use_arena = GLOBAL_FLAGS.get(
                "allocator_strategy") == "arena"
        self._arena = None
        if use_arena:
            # CPU backend zero-copy-aliases page-aligned host arrays
            # (verified), so recycling a block would corrupt live
            # arrays; staging only pays off across a real host→device
            # boundary anyway.
            if jax.default_backend() == "cpu":
                use_arena = False
        if use_arena:
            from ..core.arena import HostStagingArena
            # in-flight window: prefetch ring + the batch being consumed
            self._arena = HostStagingArena(depth=buffer_size + 2)

    def _put(self, batch):
        if self._arena is not None:
            batch = self._arena.stage(batch)
        if self.sharding is not None:
            out = jax.tree.map(
                lambda x: jax.device_put(x, self.sharding), batch)
        else:
            out = jax.tree.map(jax.device_put, batch)
        if self._arena is not None:
            # hand the device refs to the arena so the generation's
            # buffers are only recycled after their DMAs complete
            self._arena.advance(live_refs=out)
        return out

    def __iter__(self):
        it = iter(self.loader)
        buf: List[Any] = []
        try:
            for _ in range(self.buffer_size):
                buf.append(self._put(next(it)))
        # ptlint: disable=silent-failure -- StopIteration is normal exhaustion: the source had fewer items than the prefetch depth
        except StopIteration:
            pass
        while buf:
            out = buf.pop(0)
            try:
                buf.append(self._put(next(it)))
            # ptlint: disable=silent-failure -- StopIteration is normal exhaustion: drain the remaining buffer
            except StopIteration:
                pass
            yield out

    def __len__(self):
        return len(self.loader)

from .dataset import (DatasetBase, DatasetFactory, InMemoryDataset,
                      QueueDataset)  # noqa: E402,F401
