"""MultiSlot data generators (ref:
python/paddle/fluid/incubate/data_generator/__init__.py).

User subclasses implement ``generate_sample(line)`` returning an
iterator that yields ``[(slot_name, [values]), ...]`` per sample; the
generator renders the native slot line format consumed by
``csrc/data_feed.cc`` (``<count> v1 ... vcount`` per slot, slots in
declaration order) and the ``data.DatasetFactory`` pipeline.

The reference streams stdin->stdout so generators plug into its
MPI/yarn file pipelines; both that mode (``run_from_stdin``) and a
direct files mode (``run_from_files``) are provided.
"""

from __future__ import annotations

import sys
from typing import Callable, Iterable, Optional, Sequence


class DataGenerator:
    def __init__(self) -> None:
        self._proto_info: Optional[list] = None
        self.batch_size_ = 32

    # -------------------------------------------------------- user API
    def set_batch(self, batch_size: int) -> None:
        self.batch_size_ = int(batch_size)

    def generate_sample(self, line: Optional[str]) -> Callable:
        """Return an iterator function yielding one or more samples —
        each ``[(slot_name, [values]), ...]`` — for one input line
        (``line is None`` for generators that synthesize data)."""
        raise NotImplementedError(
            "subclass DataGenerator and implement generate_sample")

    def generate_batch(self, samples: Sequence) -> Iterable:
        """Optional batch-level hook (ref parity): receives
        ``batch_size_`` samples, yields samples. Default passthrough."""
        def local_iter():
            for s in samples:
                yield s
        return local_iter

    # ------------------------------------------------------ renderers
    def _gen_str(self, line) -> str:
        raise NotImplementedError

    # --------------------------------------------------------- drivers
    def run_from_stdin(self) -> None:
        """stdin lines -> slot-format stdout (the reference's pipeline
        mode)."""
        self._proto_info = None  # fresh schema per run
        self._drive(sys.stdin, sys.stdout)

    def run_from_files(self, inputs: Sequence[str], output: str) -> None:
        """Render input text files into one slot-format dataset file
        consumable by DatasetFactory/InMemoryDataset. Files chain into
        ONE stream so a generate_batch override sees full batches
        across file boundaries (reference single-stream behavior)."""
        self._proto_info = None  # fresh schema per run

        def lines():
            for path in inputs:
                with open(path) as f:
                    yield from f

        with open(output, "w") as out:
            self._drive(lines(), out)

    def _drive(self, lines: Iterable[str], out) -> None:
        batch = []
        for line in lines:
            it = self.generate_sample(line)
            for sample in it():
                if sample is None:
                    continue  # ref parity: None drops a malformed line
                batch.append(sample)
                if len(batch) >= self.batch_size_:
                    self._flush(batch, out)
                    batch = []
        if batch:
            self._flush(batch, out)

    def _flush(self, batch, out) -> None:
        for sample in self.generate_batch(batch)():
            out.write(self._gen_str(sample))


class MultiSlotDataGenerator(DataGenerator):
    """Numeric slots: int ids (sparse) or floats (dense). Output per
    sample: ``count v1 ... vcount`` for every slot, one line."""

    def _gen_str(self, line) -> str:
        if not isinstance(line, (list, tuple)):
            raise ValueError(
                "generate_sample must yield [(name, [values]), ...]; "
                f"got {type(line).__name__}")
        def kind_of(elements):
            return "float" if any(isinstance(v, float)
                                  for v in elements) else "uint64"

        if self._proto_info is None:
            self._proto_info = []
            for name, elements in line:
                if not isinstance(name, str):
                    raise ValueError(
                        f"slot name must be str, got {name!r}")
                self._proto_info.append((name, kind_of(elements)))
        else:
            if len(line) != len(self._proto_info):
                raise ValueError(
                    f"sample has {len(line)} slots; first sample had "
                    f"{len(self._proto_info)}")
            for (name, elements), (want, want_kind) in zip(
                    line, self._proto_info):
                if name != want:
                    raise ValueError(
                        f"slot order changed: got {name!r}, expected "
                        f"{want!r}")
                kind = kind_of(elements)
                if kind == "float" and want_kind == "uint64":
                    # drift int->float corrupts the typed feed; the
                    # reference upgrades the slot only pre-emptively —
                    # here the schema froze on sample 1
                    raise ValueError(
                        f"slot {name!r} was uint64 from the first "
                        f"sample but sample has float values; keep "
                        f"one type per slot (cast ids to int or make "
                        f"every sample float)")
        parts = []
        for name, elements in line:
            if not elements:
                raise ValueError(f"slot {name!r} has no values")
            parts.append(str(len(elements)))
            parts.extend(str(v) for v in elements)
        return " ".join(parts) + "\n"


class MultiSlotStringDataGenerator(DataGenerator):
    """Values are pre-stringified by the user (fast path, no type
    bookkeeping — ref MultiSlotStringDataGenerator)."""

    def _gen_str(self, line) -> str:
        if not isinstance(line, (list, tuple)):
            raise ValueError(
                "generate_sample must yield [(name, [strs]), ...]")
        parts = []
        for _name, elements in line:
            parts.append(str(len(elements)))
            parts.extend(str(v) for v in elements)
        return " ".join(parts) + "\n"
