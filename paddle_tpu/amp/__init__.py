"""Automatic mixed precision.

TPU-native redesign of the reference's AMP stack (static rewrite:
/root/reference/python/paddle/fluid/contrib/mixed_precision/decorator.py:218
+ fp16_utils.py white/black-list casting + update_loss_scaling :169; eager:
paddle/fluid/imperative/amp_auto_cast.cc:87; the finiteness op
operators/amp/amp_check_finite_and_scale_op.cc).

On TPU the native low precision is **bfloat16**: same exponent range as
fp32, so loss scaling is unnecessary — ``auto_cast`` simply runs whitelisted
ops in bf16. fp16-style dynamic loss scaling (:class:`GradScaler`) is kept
for API/capability parity and for fp16 experiments; its entire
check-finite + scale-update logic compiles into the train step (the
reference runs it as separate graph ops).
"""

from __future__ import annotations

import contextlib
import threading
from typing import Any, Dict, Optional

import jax
import jax.numpy as jnp

from ..core.dtype import convert_dtype

# ops that benefit from low precision (matmul/conv MXU ops)
WHITE_LIST = {"matmul", "mul", "conv2d", "conv3d", "bmm", "einsum", "linear"}
# ops that must stay fp32 (reductions, norms, softmax, exp)
BLACK_LIST = {"softmax", "log_softmax", "cross_entropy", "layer_norm",
              "batch_norm", "mean", "sum", "exp", "log"}


class _AmpState(threading.local):
    def __init__(self) -> None:
        self.enabled = False
        self.dtype = jnp.bfloat16
        self.level = "O1"


_amp_state = _AmpState()


@contextlib.contextmanager
def auto_cast(enable: bool = True, dtype="bfloat16", level: str = "O1",
              custom_white_list=None, custom_black_list=None):
    """(ref: amp_guard, dygraph/amp/auto_cast.py:90)."""
    prev = (_amp_state.enabled, _amp_state.dtype, _amp_state.level)
    _amp_state.enabled = enable
    _amp_state.dtype = convert_dtype(dtype)
    _amp_state.level = level
    try:
        yield
    finally:
        _amp_state.enabled, _amp_state.dtype, _amp_state.level = prev


amp_guard = auto_cast


def amp_enabled() -> bool:
    return _amp_state.enabled


def amp_dtype():
    return _amp_state.dtype


def cast_model_to_low_precision(model, dtype="bfloat16"):
    """O2-style whole-model cast (ref: fp16_utils cast_model_to_fp16)."""
    return model.to(dtype=dtype)


def low_precision_policy(x, op_name: str = "matmul"):
    """Cast an input per white/black list when amp is active."""
    if not _amp_state.enabled:
        return x
    if op_name in BLACK_LIST:
        return x.astype(jnp.float32) if x.dtype == _amp_state.dtype else x
    if op_name in WHITE_LIST and jnp.issubdtype(x.dtype, jnp.floating):
        return x.astype(_amp_state.dtype)
    return x


def all_finite(tree) -> jax.Array:
    """Scalar bool: every floating leaf of ``tree`` is finite — the
    check half of the reference's amp_check_finite_and_scale op,
    usable standalone (the bf16/fp32 skip-step guard). Integer leaves
    (sparse RowSlices rows, step counters) are ignored."""
    checks = []
    for g in jax.tree.leaves(tree):
        dt = getattr(g, "dtype", None)
        if dt is not None and jnp.issubdtype(dt, jnp.inexact):
            checks.append(jnp.all(jnp.isfinite(g)))
    if not checks:
        return jnp.asarray(True)
    return jnp.all(jnp.stack(checks))


def select_update(found_inf, updated, current):
    """Per-leaf ``where(found_inf, current, updated)`` over two
    same-structure pytrees: the skip-step half of the reference's AMP
    stack, compiled into the train step — no host sync, the whole
    update is discarded in-graph when the step saw non-finite grads."""
    return jax.tree.map(
        lambda u, c: jnp.where(found_inf, c, u), updated, current)


class GradScaler:
    """Dynamic loss scaling (ref: loss_scaler.py:27 AmpScaler;
    update rule: update_loss_scaling op — incr every
    ``incr_every_n_steps`` clean steps, decr after n nan steps).

    Functional usage inside a jitted step::

        scaler_state = scaler.init()
        scaled_loss = scaler.scale(loss, scaler_state)
        grads = ...  # grads of scaled loss
        grads, found_inf = scaler.unscale(grads, scaler_state)
        new_params = where(found_inf, params, updated_params)
        scaler_state = scaler.update(scaler_state, found_inf)
    """

    def __init__(self, enable: bool = True,
                 init_loss_scaling: float = 2.0 ** 15,
                 incr_ratio: float = 2.0, decr_ratio: float = 0.5,
                 incr_every_n_steps: int = 1000,
                 decr_every_n_nan_or_inf: int = 2) -> None:
        self.enable = enable
        self.init_loss_scaling = init_loss_scaling
        self.incr_ratio = incr_ratio
        self.decr_ratio = decr_ratio
        self.incr_every_n_steps = incr_every_n_steps
        self.decr_every_n_nan_or_inf = decr_every_n_nan_or_inf

    def init(self) -> Dict[str, Any]:
        return {
            "scale": jnp.asarray(self.init_loss_scaling, jnp.float32),
            "good_steps": jnp.zeros((), jnp.int32),
            "bad_steps": jnp.zeros((), jnp.int32),
        }

    def scale(self, loss, state):
        if not self.enable:
            return loss
        return loss * state["scale"].astype(loss.dtype)

    def unscale(self, grads, state):
        """Returns (unscaled_grads, found_inf) — the
        amp_check_finite_and_scale op fused in."""
        if not self.enable:
            return grads, jnp.zeros((), bool)
        inv = 1.0 / state["scale"]
        unscaled = jax.tree.map(
            lambda g: g * inv.astype(g.dtype)
            if jnp.issubdtype(getattr(g, "dtype", jnp.int32),
                              jnp.inexact) else g, grads)
        found_inf = ~all_finite(unscaled)
        return unscaled, found_inf

    def update(self, state, found_inf):
        if not self.enable:
            return state
        good = jnp.where(found_inf, 0, state["good_steps"] + 1)
        bad = jnp.where(found_inf, state["bad_steps"] + 1, 0)
        scale = state["scale"]
        # increase after n good steps
        incr = good >= self.incr_every_n_steps
        scale = jnp.where(incr, scale * self.incr_ratio, scale)
        good = jnp.where(incr, 0, good)
        # decrease after n bad steps
        decr = bad >= self.decr_every_n_nan_or_inf
        scale = jnp.where(decr, jnp.maximum(scale * self.decr_ratio, 1.0),
                          scale)
        bad = jnp.where(decr, 0, bad)
        return {"scale": scale, "good_steps": good, "bad_steps": bad}

    # eager-style helpers (dygraph AmpScaler parity)
    def minimize(self, *args, **kwargs):
        raise NotImplementedError(
            "use the functional scale/unscale/update inside a TrainStep")


def decorate(optimizer, amp_lists=None, init_loss_scaling: float = 2.0 ** 15,
             use_dynamic_loss_scaling: bool = True):
    """(ref: decorator.py:218) returns (optimizer, GradScaler)."""
    scaler = GradScaler(enable=use_dynamic_loss_scaling,
                        init_loss_scaling=init_loss_scaling)
    return optimizer, scaler
