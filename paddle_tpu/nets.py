"""``fluid.nets`` composite layers (ref: python/paddle/fluid/nets.py).

The reference's five ``__all__`` names: ``simple_img_conv_pool``
(nets.py:29), ``img_conv_group`` (nets.py:141), ``sequence_conv_pool``
(nets.py:256), ``glu`` (nets.py:328), ``scaled_dot_product_attention``
(nets.py:372).

Functional convention: like ``layers.fc``/``layers.embedding``, the
composites take weights explicitly (the tracing world has no
LayerHelper to mint parameters); parameter-owning users compose
``nn.Conv2D``/``nn.BatchNorm2D``/``nn.Sequential`` instead.
"""

from __future__ import annotations

from typing import Optional, Sequence

import jax.numpy as jnp

from .ops import activation as _act
from .ops import nn_functional as _F
from .ops import sequence as _seq
from .ops.activation import glu  # noqa: F401  (ref nets.py:328)
from .ops.attention import \
    scaled_dot_product_attention  # noqa: F401  (ref nets.py:372)

__all__ = ["simple_img_conv_pool", "img_conv_group",
           "sequence_conv_pool", "glu", "scaled_dot_product_attention"]


def _check_kernel(weight, filter_size, fn_name: str) -> None:
    fs = (filter_size, filter_size) if isinstance(filter_size, int) \
        else tuple(filter_size)
    if tuple(weight.shape[2:]) != fs:
        raise ValueError(
            f"{fn_name}: conv weight kernel {tuple(weight.shape[2:])} "
            f"does not match filter_size {fs}")


def _apply_act(x, act: Optional[str]):
    return x if act is None else getattr(_act, act)(x)


def _pool2d(x, pool_size, pool_type: str, pool_stride=1, pool_padding=0,
            global_pooling: bool = False):
    if global_pooling:
        pool_size = x.shape[2:]
        pool_stride, pool_padding = 1, 0
    fn = _F.max_pool2d if pool_type == "max" else _F.avg_pool2d
    return fn(x, pool_size, stride=pool_stride, padding=pool_padding)


def simple_img_conv_pool(input, num_filters: int, filter_size,
                         pool_size, pool_stride, conv_weight,
                         conv_bias=None, pool_padding=0,
                         pool_type: str = "max",
                         global_pooling: bool = False, conv_stride=1,
                         conv_padding=0, conv_dilation=1,
                         conv_groups: int = 1,
                         act: Optional[str] = None):
    """conv2d → activation → pool2d (ref: fluid/nets.py:29).

    ``conv_weight``: [num_filters, C/groups, kh, kw]; pass
    ``pool_type="avg"`` / ``global_pooling=True`` as in the reference.
    """
    if conv_weight.shape[0] != num_filters:
        raise ValueError(
            f"simple_img_conv_pool: conv_weight has "
            f"{conv_weight.shape[0]} output channels, expected "
            f"{num_filters}")
    _check_kernel(conv_weight, filter_size, "simple_img_conv_pool")
    out = _F.conv2d(input, conv_weight, conv_bias, stride=conv_stride,
                    padding=conv_padding, dilation=conv_dilation,
                    groups=conv_groups)
    out = _apply_act(out, act)
    return _pool2d(out, pool_size, pool_type, pool_stride, pool_padding,
                   global_pooling)


def img_conv_group(input, conv_num_filter: Sequence[int], pool_size,
                   conv_weights: Sequence, conv_biases=None,
                   bn_params=None, conv_padding=1, conv_filter_size=3,
                   conv_act: Optional[str] = None,
                   conv_with_batchnorm: bool = False,
                   conv_batchnorm_drop_rate: float = 0.0,
                   pool_stride=1, pool_type: str = "max",
                   training: bool = True):
    """Stacked conv(+BN)(+dropout) blocks then one pool — the VGG block
    (ref: fluid/nets.py:141).

    ``conv_weights``: one [out, in, k, k] kernel per entry of
    ``conv_num_filter``. With ``conv_with_batchnorm=True`` pass
    ``bn_params`` as a list of (gamma, beta, running_mean, running_var)
    tuples, one per conv; like the reference, dropout after BN uses
    ``conv_batchnorm_drop_rate`` (0 disables).
    """
    n = len(conv_num_filter)
    if len(conv_weights) != n:
        raise ValueError(
            f"img_conv_group: {len(conv_weights)} weights for {n} convs")
    if isinstance(conv_filter_size, list):
        fsizes = conv_filter_size
        if len(fsizes) != n:
            raise ValueError(
                f"img_conv_group: {len(fsizes)} filter sizes for {n} "
                f"convs")
    else:  # one size (int or (kh, kw) tuple) shared by every conv
        fsizes = [conv_filter_size] * n
    for i, (w_, fs) in enumerate(zip(conv_weights, fsizes)):
        _check_kernel(w_, fs, f"img_conv_group conv {i}")
    if conv_with_batchnorm and (bn_params is None or len(bn_params) != n):
        raise ValueError(
            "img_conv_group: conv_with_batchnorm=True needs one "
            "(gamma, beta, mean, var) tuple per conv in bn_params")

    def per_conv(val):
        return val if isinstance(val, (list, tuple)) else [val] * n

    paddings = per_conv(conv_padding)
    out = input
    for i in range(n):
        bias = conv_biases[i] if conv_biases is not None else None
        out = _F.conv2d(out, conv_weights[i], bias,
                        padding=paddings[i])
        if out.shape[1] != conv_num_filter[i]:
            raise ValueError(
                f"img_conv_group: conv {i} produced {out.shape[1]} "
                f"channels, expected {conv_num_filter[i]}")
        if conv_with_batchnorm:
            gamma, beta, mean, var = bn_params[i]
            out, _, _ = _F.batch_norm(out, mean, var, gamma, beta,
                                      training=training)
            out = _apply_act(out, conv_act)
            if conv_batchnorm_drop_rate > 0.0:
                out = _F.dropout(out, conv_batchnorm_drop_rate,
                                 training=training)
        else:
            out = _apply_act(out, conv_act)
    return _pool2d(out, pool_size, pool_type, pool_stride)


def sequence_conv_pool(input, length, num_filters: int, filter_size: int,
                       weight, bias=None, act: Optional[str] = "sigmoid",
                       pool_type: str = "max"):
    """sequence_conv → activation → sequence_pool (ref:
    fluid/nets.py:256; text-conv building block).

    Dense redesign: ``input`` is [B, T, D] with per-row ``length``
    (the LoD analogue); ``weight`` is [filter_size * D, num_filters].
    Returns [B, num_filters].
    """
    d = input.shape[-1]
    if weight.shape != (filter_size * d, num_filters):
        raise ValueError(
            f"sequence_conv_pool: weight shape {tuple(weight.shape)} != "
            f"({filter_size * d}, {num_filters})")
    length = jnp.asarray(length)
    # reference: context_start = -floor(filter_size/2) centers the window
    out = _seq.sequence_conv(input, length, weight, filter_size,
                             context_start=-(filter_size // 2), bias=bias)
    out = _apply_act(out, act)
    return _seq.sequence_pool(out, length, pool_type)
