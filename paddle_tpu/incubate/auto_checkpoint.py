"""Auto-checkpoint: resumable epoch ranges for elastic training.

TPU-native rebuild of the reference's auto-checkpoint subsystem
(/root/reference/python/paddle/fluid/incubate/checkpoint/
auto_checkpoint.py:71 AutoCheckpointChecker, :265 TrainEpochRange — wraps
the epoch loop, periodically saves to persistent storage via
checkpoint_saver.py, and on job restart fast-forwards past completed
epochs). The reference gates on PADDLE_RUNNING_ENV; here the directory
comes from the constructor or PT_CHECKPOINT_DIR. Saves are async
(io.AsyncCheckpointer) and sharded-state friendly: any pytree the caller
registers (TrainStep.state, custom dicts) rides along.

The elastic story this enables (SURVEY.md §5 "Failure detection"): a
restarted job constructs the same TrainEpochRange and resumes from the
last completed epoch — slice-level restart on top of checkpoints, which
the reference's `DistributedStrategy.elastic` stub never implemented.
"""

from __future__ import annotations

import os
from typing import Any, Callable, Dict, Iterator, Optional

import jax
import numpy as np

from .. import io as io_mod
from .. import preemption as _preempt
from ..observability import flight as _flight

__all__ = ["TrainEpochRange", "train_epoch_range"]


class TrainEpochRange:
    """Iterate epochs with automatic save/resume.

    Usage::

        r = TrainEpochRange(max_epoch=10, save_dir=ckdir, name="job1")
        r.register("train", lambda: step.state,
                   lambda s: setattr(step, "state", restore(s)))
        for epoch in r:           # skips epochs already completed
            ... train one epoch ...
    """

    def __init__(self, max_epoch: int, save_dir: Optional[str] = None,
                 name: str = "acp", save_interval: int = 1,
                 max_to_keep: int = 3) -> None:
        save_dir = save_dir or os.environ.get("PT_CHECKPOINT_DIR")
        if save_dir is None:
            raise ValueError(
                "TrainEpochRange needs save_dir (or PT_CHECKPOINT_DIR)")
        self.max_epoch = int(max_epoch)
        self.save_interval = max(1, int(save_interval))
        self.name = name
        self._ckpt = io_mod.AsyncCheckpointer(
            os.path.join(save_dir, name), max_to_keep=max_to_keep)
        self._getters: Dict[str, Callable[[], Any]] = {}
        self._setters: Dict[str, Callable[[Any], None]] = {}
        self._start_epoch = 0
        self._restored_state: Optional[Dict[str, Any]] = None
        # restore_latest skips corrupt/uncommitted checkpoints and
        # falls back to the newest intact one — _start_epoch must track
        # the checkpoint actually restored, not the newest on disk
        self._restored_state, at = self._ckpt.restore_latest()
        if self._restored_state is not None:
            self._start_epoch = int(at)
            _flight.record("checkpoint_restore", name=name, epoch=at)
        self.restored = self._restored_state is not None

    def register(self, key: str, getter: Callable[[], Any],
                 setter: Optional[Callable[[Any], None]] = None) -> None:
        """Attach a state pytree to the checkpoint under `key`. If a
        restore happened at construction, `setter` is invoked now."""
        self._getters[key] = getter
        if setter is not None:
            self._setters[key] = setter
            if self._restored_state is not None:
                sub = {k.split("/", 1)[1]: v
                       for k, v in self._restored_state.items()
                       if k.startswith(key + "/")}
                if sub:
                    setter(sub)

    def _save(self, step: int) -> None:
        state = {k: g() for k, g in self._getters.items()}
        self._ckpt.save(state, step=step)
        _flight.record("checkpoint_save", name=self.name, epoch=step)

    def get(self) -> Iterator[int]:
        """The epoch iterator (ref: TrainEpochRange.get :265).

        SIGTERM (scheduler preemption) is handled gracefully: the
        in-flight epoch finishes, an off-interval checkpoint is forced
        and flushed, and the signal is re-raised (preemption.guard) —
        the restarted job resumes from the preempted epoch."""
        with _preempt.guard() as guard:
            for epoch in range(self._start_epoch, self.max_epoch):
                yield epoch
                saved = False
                if (epoch + 1) % self.save_interval == 0 or \
                        epoch + 1 == self.max_epoch:
                    self._save(epoch + 1)
                    saved = True
                if guard.preempted:
                    if not saved:
                        self._save(epoch + 1)
                    self._ckpt.wait()
                    _flight.record("preempt_checkpoint", force=True,
                                   name=self.name, epoch=epoch + 1)
                    guard.reraise()
            self._ckpt.wait()

    def __iter__(self) -> Iterator[int]:
        return self.get()

    @property
    def start_epoch(self) -> int:
        return self._start_epoch


def train_epoch_range(max_epoch: int, save_checkpoint_inter: int = 1,
                      save_dir: Optional[str] = None,
                      name: str = "acp") -> TrainEpochRange:
    """Functional spelling matching the reference helper
    (auto_checkpoint.py train_epoch_range)."""
    return TrainEpochRange(max_epoch, save_dir=save_dir, name=name,
                           save_interval=save_checkpoint_inter)
