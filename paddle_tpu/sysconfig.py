"""Build-integration paths (ref: /root/reference/python/paddle/
sysconfig.py get_include/get_lib — where extension authors find the
native headers and shared library).

Here the native surface is the C API in csrc/ptnative.h and the
auto-built libptnative.so in the native package; extensions link
against those the same way reference extensions link
libpaddle_framework.
"""

from __future__ import annotations

import os

__all__ = ["get_include", "get_lib", "enable_compile_cache"]


def get_include() -> str:
    """Directory containing ptnative.h: the source checkout's csrc/ when
    present, else the header copy the native build stages inside the
    package (installed wheels ship no csrc/ — same split native
    _needs_build handles for the .so)."""
    from .native import _CSRC
    if os.path.isdir(_CSRC):
        return _CSRC
    pkg_inc = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                           "include")
    if os.path.isdir(pkg_inc):
        return pkg_inc
    raise FileNotFoundError(
        "no native headers found (csrc/ missing and no packaged "
        "include/); reinstall with sources or run native.build()")


def get_lib() -> str:
    """Directory containing libptnative.so (built on first use)."""
    from . import native
    native.build()
    return os.path.join(os.path.dirname(os.path.abspath(__file__)),
                        "native")


def enable_compile_cache(cache_dir: str = None,
                         min_compile_secs: float = 0.5) -> None:
    """Enable JAX's persistent compilation cache (repo-root
    ``.jax_cache/`` by default). The ONE implementation — bench.py,
    verify, conftest and perf_lab all call this, so the path and the
    min-compile threshold can't drift between entry points. Safe to
    call repeatedly; failures are swallowed (the cache is an
    optimization, never a correctness dependency)."""
    import jax

    if cache_dir is None:
        cache_dir = os.path.join(
            os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
            ".jax_cache")
    try:
        jax.config.update("jax_compilation_cache_dir", cache_dir)
        jax.config.update("jax_persistent_cache_min_compile_time_secs",
                          min_compile_secs)
    except Exception:  # noqa: BLE001
        pass
