"""Build-integration paths (ref: /root/reference/python/paddle/
sysconfig.py get_include/get_lib — where extension authors find the
native headers and shared library).

Here the native surface is the C API in csrc/ptnative.h and the
auto-built libptnative.so in the native package; extensions link
against those the same way reference extensions link
libpaddle_framework.
"""

from __future__ import annotations

import os

__all__ = ["get_include", "get_lib"]


def get_include() -> str:
    """Directory containing ptnative.h (the native C API)."""
    pkg = os.path.dirname(os.path.abspath(__file__))
    return os.path.join(os.path.dirname(pkg), "csrc")


def get_lib() -> str:
    """Directory containing libptnative.so (built on first use)."""
    from . import native
    native.build()
    return os.path.join(os.path.dirname(os.path.abspath(__file__)),
                        "native")
