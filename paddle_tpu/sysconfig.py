"""Build-integration paths (ref: /root/reference/python/paddle/
sysconfig.py get_include/get_lib — where extension authors find the
native headers and shared library).

Here the native surface is the C API in csrc/ptnative.h and the
auto-built libptnative.so in the native package; extensions link
against those the same way reference extensions link
libpaddle_framework.
"""

from __future__ import annotations

import os
import threading

__all__ = ["get_include", "get_lib", "enable_compile_cache",
           "apply_compile_cache_flag", "compile_cache_stats"]


def get_include() -> str:
    """Directory containing ptnative.h: the source checkout's csrc/ when
    present, else the header copy the native build stages inside the
    package (installed wheels ship no csrc/ — same split native
    _needs_build handles for the .so)."""
    from .native import _CSRC
    if os.path.isdir(_CSRC):
        return _CSRC
    pkg_inc = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                           "include")
    if os.path.isdir(pkg_inc):
        return pkg_inc
    raise FileNotFoundError(
        "no native headers found (csrc/ missing and no packaged "
        "include/); reinstall with sources or run native.build()")


def get_lib() -> str:
    """Directory containing libptnative.so (built on first use)."""
    from . import native
    native.build()
    return os.path.join(os.path.dirname(os.path.abspath(__file__)),
                        "native")


def enable_compile_cache(cache_dir: str = None,
                         min_compile_secs: float = 0.5) -> None:
    """Enable JAX's persistent compilation cache (repo-root
    ``.jax_cache/`` by default). The ONE implementation — bench.py,
    verify, conftest and perf_lab all call this, so the path and the
    min-compile threshold can't drift between entry points. Safe to
    call repeatedly; failures are swallowed (the cache is an
    optimization, never a correctness dependency)."""
    import jax

    if cache_dir is None:
        cache_dir = os.path.join(
            os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
            ".jax_cache")
    try:
        jax.config.update("jax_compilation_cache_dir", cache_dir)
        jax.config.update("jax_persistent_cache_min_compile_time_secs",
                          min_compile_secs)
        # tiny CPU executables (tests, the self-test drill) are below
        # the default entry-size floor — persist everything; dedup is
        # the cache key's job
        jax.config.update("jax_persistent_cache_min_entry_size_bytes",
                          -1)
    # ptlint: disable=silent-failure -- these config keys vary across jax versions; a missing one means that knob does not exist to set
    except Exception:  # noqa: BLE001
        pass
    _install_cache_listener()


# --------------------------------------------------------- cache stats
# process-wide persistent-cache traffic counters, fed by jax.monitoring
# events and read by observability.goodput (the jit_compile_{cold,
# cache_hit} ledger split and the compile_cache_*_total counters)

_CACHE_STATS = {"hits": 0, "misses": 0}
_LISTENER_LOCK = threading.Lock()
_LISTENER_INSTALLED = False
_FLAG_APPLIED_DIR = None


def _on_cache_event(event: str, **kw) -> None:
    if event.endswith("/compilation_cache/cache_hits"):
        _CACHE_STATS["hits"] += 1
    elif event.endswith("/compilation_cache/cache_misses"):
        _CACHE_STATS["misses"] += 1


def _install_cache_listener() -> None:
    global _LISTENER_INSTALLED
    with _LISTENER_LOCK:
        if _LISTENER_INSTALLED:
            return
        try:
            from jax import monitoring
            monitoring.register_event_listener(_on_cache_event)
            _LISTENER_INSTALLED = True
        # ptlint: disable=silent-failure -- jax.monitoring is an optional surface; without it cache hit/miss counters simply stay absent
        except Exception:  # noqa: BLE001
            pass


def compile_cache_stats() -> dict:
    """{'hits': int, 'misses': int} persistent-cache lookups so far."""
    return dict(_CACHE_STATS)


def apply_compile_cache_flag() -> None:
    """Point jax's persistent compilation cache at
    FLAGS_compile_cache_dir if set. Idempotent and cheap — the entry
    points that trigger compiles (hapi.Model.fit, jit.to_static,
    inference.Predictor/Server) all call it, because env-provided flag
    values never fire on_change hooks. Threshold 0: when an operator
    asks for a persistent cache they mean every executable, including
    the sub-second CPU ones the proof drill measures."""
    global _FLAG_APPLIED_DIR
    from .flags import GLOBAL_FLAGS
    try:
        cache_dir = GLOBAL_FLAGS.get("compile_cache_dir")
    except KeyError:  # registry not fully imported yet
        return
    if not cache_dir or cache_dir == _FLAG_APPLIED_DIR:
        return
    _FLAG_APPLIED_DIR = cache_dir
    enable_compile_cache(cache_dir, min_compile_secs=0.0)
