"""``fluid.transpiler`` redirects (ref: python/paddle/fluid/
transpiler/distribute_transpiler.py). The transpiler rewrote a built
Program into PS/collective variants; in the tracing design the
distributed step transforms live in ``paddle_tpu.fleet`` /
``paddle_tpu.parallel`` and the PS stack is ``distributed.ps``."""

from __future__ import annotations


class DistributeTranspilerConfig:
    """Accepted for import parity; its knobs map to
    fleet.DistributedStrategy fields."""

    def __init__(self) -> None:
        self.slice_var_up = True
        self.split_method = None
        self.min_block_size = 8192


class DistributeTranspiler:
    def __init__(self, config=None) -> None:
        self.config = config or DistributeTranspilerConfig()

    def transpile(self, *a, **k):
        raise NotImplementedError(
            "program transpilation has no tracing analogue: use "
            "fleet.DistributedStrategy + parallel.ShardedTrainStep for "
            "collective training, or distributed.ps for the parameter-"
            "server mode (sync/async/geo)")


class PSDispatcher:
    """(ref: transpiler/ps_dispatcher.py:18) dispatch(varlist) -> one
    endpoint per var; reset() rewinds the round-robin step."""

    def __init__(self, pserver_endpoints) -> None:
        self._eps = list(pserver_endpoints)
        self._step = 0

    @property
    def eps(self):
        return self._eps

    def reset(self) -> None:
        self._step = 0

    def dispatch(self, varlist):
        raise NotImplementedError("use HashName or RoundRobin")

    @staticmethod
    def _var_name(v) -> str:
        if isinstance(v, str):
            return v
        name = getattr(v, "name", None)
        return name() if callable(name) else str(name)


class HashName(PSDispatcher):
    """(ref: ps_dispatcher.py:55) stable name-hash placement."""

    @staticmethod
    def _hash_block(name: str, total: int) -> int:
        import hashlib
        # stable across processes (builtin hash() is salted per run —
        # workers and servers must agree on placement)
        return int(hashlib.md5(name.encode()).hexdigest(), 16) % total

    def dispatch(self, varlist):
        return [self._eps[self._hash_block(self._var_name(v),
                                           len(self._eps))]
                for v in varlist]


class RoundRobin(PSDispatcher):
    """(ref: ps_dispatcher.py:93)."""

    def dispatch(self, varlist):
        out = []
        for _ in varlist:
            out.append(self._eps[self._step])
            self._step = (self._step + 1) % len(self._eps)
        return out
