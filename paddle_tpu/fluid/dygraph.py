"""``fluid.dygraph`` migration surface (ref:
python/paddle/fluid/dygraph/__init__.py).

Eager execution is the default in the TPU-native design, so the
graph/dygraph mode switch collapses: ``guard()`` is a no-op context,
``to_variable`` is array conversion, and the dygraph layer classes are
the ``nn`` layers (same math, functional buffers under jit).
"""

from __future__ import annotations

import contextlib

import jax.numpy as jnp

from ..autograd import grad, no_grad  # noqa: F401
from ..io import load_dygraph, save_dygraph  # noqa: F401
from ..nn import (GRU, LSTM, RNN, BatchNorm1D, BatchNorm2D,  # noqa: F401
                  BatchNorm3D, Conv2D, Conv3D, Dropout, Embedding,
                  Layer, LayerList, Linear, ParameterList, Sequential)
from ..nn.layer import Parameter  # noqa: F401

BatchNorm = BatchNorm2D  # fluid.dygraph.BatchNorm's common case


class Pool2D(Layer):
    """(ref: dygraph/nn.py Pool2D) — thin wrapper over the functional
    pools with the fluid constructor spellings."""

    def __init__(self, pool_size=-1, pool_type: str = "max",
                 pool_stride=1, pool_padding=0,
                 global_pooling: bool = False, ceil_mode: bool = False,
                 exclusive: bool = True, data_format: str = "NCHW"):
        super().__init__()
        if pool_type not in ("max", "avg"):
            raise ValueError(f"pool_type must be max/avg, got {pool_type!r}")
        self._kw = dict(pool_size=pool_size, pool_type=pool_type,
                        pool_stride=pool_stride,
                        pool_padding=pool_padding,
                        global_pooling=global_pooling,
                        ceil_mode=ceil_mode, exclusive=exclusive,
                        data_format=data_format)

    def forward(self, x):
        from ..ops.nn_functional import pool2d
        return pool2d(x, **self._kw)


@contextlib.contextmanager
def guard(place=None):
    """(ref: dygraph/base.py guard) — eager is always on; kept so
    ``with fluid.dygraph.guard():`` blocks port unchanged."""
    yield


def to_variable(value, name=None, zero_copy=None, dtype=None):
    """(ref: dygraph/base.py to_variable)."""
    out = jnp.asarray(value)
    return out.astype(dtype) if dtype is not None else out


def enabled() -> bool:
    return True
