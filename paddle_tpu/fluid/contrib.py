"""``fluid.contrib`` routing (ref: python/paddle/fluid/contrib/) —
the graduated capabilities live at their first-class homes."""

from __future__ import annotations

from .. import amp as mixed_precision  # noqa: F401  (contrib.mixed_precision)
from .. import slim  # noqa: F401  (contrib.slim quantization)
from ..utils import op_bench  # noqa: F401


def memory_usage(*a, **k):
    raise NotImplementedError(
        "contrib.memory_usage estimated ProgramDesc memory; XLA owns "
        "buffer planning here — profile with paddle_tpu.profiler "
        "(xplane) or jax.profiler instead")
