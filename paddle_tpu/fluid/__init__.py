"""``paddle.fluid`` migration namespace.

A reference user's ``import paddle.fluid as fluid`` becomes
``import paddle_tpu.fluid as fluid`` and the fluid spellings resolve
(ref surface: python/paddle/fluid/__init__.py:35-78 — framework,
executor, io, layers, dygraph, nets, optimizer, regularizer, metrics,
initializer, clip, profiler, ParamAttr, places, data).

Graph-construction APIs whose semantics inverted in the tracing design
(``default_main_program``/``program_guard``) raise with the working
equivalent named, same policy as ``layers.DynamicRNN``; everything else
routes to working code. ``tests/test_fluid_namespace.py`` drives a
fluid-style train loop end to end through this namespace.
"""

from __future__ import annotations

import contextlib

from .. import clip  # noqa: F401
from .. import io  # noqa: F401
from .. import layers  # noqa: F401
from .. import nets  # noqa: F401
from .. import optimizer  # noqa: F401
from .. import profiler  # noqa: F401
from .. import reader  # noqa: F401
from .. import regularizer  # noqa: F401
from .. import metric as metrics  # noqa: F401
from ..autograd import grad as _grad  # noqa: F401
from ..core.lod import (RaggedBatch, create_lod_tensor,  # noqa: F401
                        create_random_int_lodtensor)
from ..core.place import (CPUPlace, CUDAPlace,  # noqa: F401
                          TPUPlace)

#: pinned host staging has no user-facing device in the TPU design
#: (core/arena.py owns page-aligned staging); alias keeps imports alive
CUDAPinnedPlace = CPUPlace
from ..flags import get_flags, set_flags  # noqa: F401
from ..nn import initializer  # noqa: F401
from ..param_attr import ParamAttr, WeightNormParamAttr  # noqa: F401
from ..static import (Executor, Program, Scope, data,  # noqa: F401
                      default_main_program, global_scope)
from ..tensor import Tensor  # noqa: F401
from . import dygraph  # noqa: F401

@contextlib.contextmanager
def scope_guard(scope: Scope):
    """(ref: executor.py scope_guard) — run Executor calls against a
    different scope. Swaps the process-global scope for the block;
    Executors resolve the scope at run time, so Executors constructed
    before the guard are covered too."""
    from .. import static as _static
    old = _static._global_scope
    _static._global_scope = scope
    try:
        yield
    finally:
        _static._global_scope = old


# real submodules so `from paddle_tpu.fluid.executor import Executor`
# style imports port unchanged (ref: fluid/__init__.py:35-78)
from . import average  # noqa: E402,F401
from . import backward  # noqa: E402,F401
from . import contrib  # noqa: E402,F401
from . import core  # noqa: E402,F401
from . import executor  # noqa: E402,F401
from . import framework  # noqa: E402,F401
from . import incubate  # noqa: E402,F401
from . import transpiler  # noqa: E402,F401
from . import unique_name  # noqa: E402,F401
from .framework import Variable, in_dygraph_mode  # noqa: E402,F401
from .transpiler import (DistributeTranspiler,  # noqa: E402,F401
                         DistributeTranspilerConfig)

# fluid.input re-exports (ref: fluid/input.py)
embedding = layers.embedding
one_hot = layers.one_hot


def default_startup_program():
    raise NotImplementedError(
        "parameter initialization is eager in the TPU design: layers "
        "initialize on construction (pt.seed(n) for determinism); there "
        "is no startup program to run")


@contextlib.contextmanager
def program_guard(main_program=None, startup_program=None):
    raise NotImplementedError(
        "program construction is tracing: wrap the computation in a "
        "function and build paddle_tpu.static.Program(fn) instead of "
        "recording ops under program_guard")


def is_compiled_with_cuda() -> bool:
    """One answer for both spellings (fluid.is_compiled_with_cuda and
    fluid.framework.is_compiled_with_cuda): True when an accelerator is
    configured — CUDAPlace aliases TPUPlace here, so ported
    'CUDAPlace(0) if is_compiled_with_cuda() else CPUPlace()' device
    selection keeps choosing the accelerator. NON-BLOCKING: never
    initializes the backend, so a wedged tunnel can't hang device
    selection."""
    from ..core.place import accelerator_configured
    return accelerator_configured()


class DataFeeder:
    """(ref: data_feeder.py DataFeeder) — converts a minibatch of
    sample tuples into the feed dict Executor.run takes."""

    def __init__(self, feed_list, place=None, program=None) -> None:
        import numpy as _np

        self._np = _np
        self.names = [f if isinstance(f, str) else getattr(f, "name", None)
                      or str(f) for f in feed_list]
        self.place = place

    def feed(self, iterable):
        cols = list(zip(*iterable))
        if len(cols) != len(self.names):
            raise ValueError(
                f"DataFeeder: batch rows have {len(cols)} fields for "
                f"{len(self.names)} feed names {self.names}")
        out = {}
        for n, col in zip(self.names, cols):
            arrs = [self._np.asarray(v) for v in col]
            if len({a.shape for a in arrs}) > 1:
                raise ValueError(
                    f"DataFeeder: field {n!r} has ragged sample shapes "
                    f"{sorted({a.shape for a in arrs})}. LoD-style "
                    "variable-length feeding is a dense redesign here: "
                    "pad to a fixed seq_len and pass lengths as their "
                    "own field (see paddle_tpu.ops.sequence — every op "
                    "takes (x, length))")
            out[n] = self._np.stack(arrs)
        return out
