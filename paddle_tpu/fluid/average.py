"""``fluid.average`` (ref: python/paddle/fluid/average.py)."""

from __future__ import annotations

import numpy as np


class WeightedAverage:
    """(ref: average.py WeightedAverage — the numerator keeps the
    VALUE's shape, so array inputs average elementwise and eval()
    returns an array of the same shape)."""

    def __init__(self) -> None:
        self.reset()

    def reset(self) -> None:
        self._total = None
        self._weight = 0.0

    def add(self, value, weight=1) -> None:
        v = np.asarray(value, np.float64) * float(weight)
        self._total = v if self._total is None else self._total + v
        self._weight += float(weight)

    def eval(self):
        if self._weight == 0 or self._total is None:
            raise ValueError("WeightedAverage.eval() before any add()")
        return self._total / self._weight
