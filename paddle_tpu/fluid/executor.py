"""``fluid.executor`` submodule spelling (ref:
python/paddle/fluid/executor.py) — the real implementations live in
``paddle_tpu.static``; ``from paddle_tpu.fluid.executor import
Executor`` ports unchanged."""

from ..static import Executor, global_scope  # noqa: F401
from . import scope_guard  # noqa: F401
