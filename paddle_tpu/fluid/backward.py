"""``fluid.backward`` (ref: python/paddle/fluid/backward.py) —
autodiff is a functional transform in the TPU design;
``gradients``/``append_backward`` map to ``paddle_tpu.autograd``."""

from ..autograd import grad as gradients  # noqa: F401


def append_backward(loss, parameter_list=None, no_grad_set=None):
    raise NotImplementedError(
        "append_backward records grad ops into a Program; in the "
        "tracing design use jax.value_and_grad (or "
        "paddle_tpu.static.TrainStep, which builds the whole "
        "forward+backward+update program)")
