"""``fluid.framework`` surface (ref: python/paddle/fluid/framework.py).

The graph-description machinery (Program/Block/OpDesc) inverted into
tracing; the names user code actually touches route here."""

from __future__ import annotations

from ..core.place import CPUPlace, CUDAPlace  # noqa: F401
from ..core.place import \
    accelerator_configured as is_compiled_with_cuda  # noqa: F401
from ..nn.layer import Parameter  # noqa: F401
from ..static import (Program, default_main_program,  # noqa: F401
                      global_scope)
from ..tensor import Tensor

Variable = Tensor  # traced arrays fill the Variable role


def in_dygraph_mode() -> bool:
    """Eager is always on (the mode switch collapsed under jit)."""
    return True


def _non_static_mode() -> bool:
    return True
