"""``fluid.core`` compatibility surface (ref: paddle/fluid/pybind/ —
the reference's C++ binding module). The handful of names user code
touches route to their TPU-native homes."""

from ..core.place import CPUPlace, CUDAPlace, TPUPlace  # noqa: F401
from ..static import Scope  # noqa: F401

CUDAPinnedPlace = CPUPlace  # host staging is arena-managed here


def globals():  # noqa: A001  (reference spelling)
    """(ref: pybind global_var_getter) zero-arg mapping over the flag
    registry: ``core.globals()['FLAGS_check_nan_inf']``."""
    from ..flags import GLOBAL_FLAGS
    return _FlagsView(GLOBAL_FLAGS)


class _FlagsView:
    def __init__(self, registry) -> None:
        self._r = registry

    def _key(self, name: str) -> str:
        return name[6:] if name.startswith("FLAGS_") else name

    def __getitem__(self, name: str):
        return self._r.get(self._key(name))

    def __setitem__(self, name: str, value) -> None:
        self._r.set(self._key(name), value)

    def __contains__(self, name: str) -> bool:
        try:
            self._r.get(self._key(name))
            return True
        except Exception:  # noqa: BLE001
            return False

    def keys(self):
        return self._r.names()
