"""``fluid.unique_name`` (ref: python/paddle/fluid/unique_name.py) —
process-wide unique name generation with guard/switch scoping."""

from __future__ import annotations

import contextlib
import threading


class _Generator:
    """(ref: unique_name.py:25 UniqueNameGenerator — optional name
    prefix prepended to every generated name)."""

    def __init__(self, prefix: str = "") -> None:
        self._counts: dict = {}
        self._lock = threading.Lock()
        self.prefix = prefix or ""

    def __call__(self, key: str) -> str:
        with self._lock:
            n = self._counts.get(key, 0)
            self._counts[key] = n + 1
        return f"{self.prefix}{key}_{n}"


_generator = _Generator()


def generate(key: str) -> str:
    return _generator(key)


def switch(new_generator=None):
    """Replace the generator; returns the old one (ref switch())."""
    global _generator
    old = _generator
    _generator = new_generator if new_generator is not None \
        else _Generator()
    return old


@contextlib.contextmanager
def guard(new_generator=None):
    """(ref: unique_name.py guard) — a str/bytes argument is a name
    PREFIX for the guarded namespace; a _Generator is used directly."""
    if isinstance(new_generator, bytes):
        new_generator = new_generator.decode()
    if isinstance(new_generator, str):
        new_generator = _Generator(new_generator)
    elif new_generator is not None and not isinstance(new_generator,
                                                      _Generator):
        raise TypeError(
            f"unique_name.guard expects a str/bytes prefix or a "
            f"generator, got {type(new_generator).__name__}")
    old = switch(new_generator)
    try:
        yield
    finally:
        switch(old)
