"""``fluid.incubate`` (ref: python/paddle/fluid/incubate/__init__.py)
— fleet and data_generator live here in 1.8-era user code."""

from . import data_generator  # noqa: F401
from . import fleet  # noqa: F401
