"""``from paddle.fluid.incubate.fleet.parameter_server
.distribute_transpiler import fleet`` — the 1.8 PS-mode entry (ref:
incubate/fleet/parameter_server/distribute_transpiler/__init__.py).
The PS stack here is ``paddle_tpu.distributed.ps`` over the native
control plane + csrc/ps_service.cc; the fleet singleton drives it via
DistributedStrategy(ps_mode=...)."""

from .....distributed.fleet import (DistributedStrategy,  # noqa: F401
                                    fleet)
from ..... import distributed as _distributed

ps = _distributed.ps  # the sync/async/geo PS runtime
