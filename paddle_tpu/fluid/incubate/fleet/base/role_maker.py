"""``fluid.incubate.fleet.base.role_maker`` (ref: incubate/fleet/base/
role_maker.py) — role makers resolve rank/size/endpoints from the
environment the launcher sets."""

from .....distributed.fleet.base import (  # noqa: F401
    PaddleCloudRoleMaker, UserDefinedRoleMaker)
