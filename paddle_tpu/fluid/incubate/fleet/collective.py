"""``from paddle.fluid.incubate.fleet.collective import fleet`` —
the 1.8 collective-training entry (ref: incubate/fleet/collective/
__init__.py). Routes to the framework fleet singleton; the NCCL
collective transport is XLA collectives over the device mesh here."""

from ....distributed.fleet import (DistributedStrategy,  # noqa: F401
                                   fleet)
