"""``fluid.incubate.fleet`` (ref: incubate/fleet/) — the 1.8 fleet
import tree; all roads lead to the framework's fleet singleton."""

from . import base  # noqa: F401
from . import collective  # noqa: F401
from . import parameter_server  # noqa: F401
