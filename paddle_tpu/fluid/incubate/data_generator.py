"""``fluid.incubate.data_generator`` (ref: incubate/data_generator/
__init__.py) — re-exports the framework's MultiSlot generators."""

from ...data.data_generator import (DataGenerator,  # noqa: F401
                                    MultiSlotDataGenerator,
                                    MultiSlotStringDataGenerator)
