"""ParamAttr (ref: python/paddle/fluid/param_attr.py:30).

The reference attaches per-parameter config (name, initializer,
regularizer, learning_rate, trainable, gradient clip) to LayerHelper
parameter creation. In the TPU-native design the layer system owns
naming and the optimizer owns regularization/clipping globally, so
``ParamAttr`` carries the pieces that still have per-parameter meaning
here — the initializer above all — and documents where the rest moved.
``nn.initializer._resolve`` accepts a ParamAttr anywhere a
``weight_attr``/``bias_attr`` is taken, so fluid-style call sites
(``param_attr=fluid.ParamAttr(initializer=...)``) port unchanged.
"""

from __future__ import annotations

from typing import Any, Optional


class ParamAttr:
    def __init__(self, name: Optional[str] = None, initializer=None,
                 learning_rate: float = 1.0, regularizer=None,
                 trainable: bool = True, do_model_average: bool = False,
                 need_clip: bool = True) -> None:
        self.name = name
        self.initializer = initializer
        self.learning_rate = learning_rate
        self.regularizer = regularizer
        self.trainable = trainable
        self.do_model_average = do_model_average
        self.need_clip = need_clip

    def __repr__(self) -> str:  # debugging aid
        return (f"ParamAttr(name={self.name!r}, "
                f"initializer={self.initializer!r}, "
                f"learning_rate={self.learning_rate}, "
                f"trainable={self.trainable})")


class WeightNormParamAttr(ParamAttr):
    """(ref: param_attr.py:216) — weight-norm reparameterization is a
    training-time transform here: use ``nn.utils.weight_norm`` on the
    layer instead of a creation-time attr; this class is accepted (its
    initializer is honored) so imports don't break."""

    def __init__(self, dim: Optional[int] = None, **kw: Any) -> None:
        super().__init__(**kw)
        self.dim = dim
