"""Graceful preemption handling (SIGTERM).

On every TPU scheduler — GCE preemptible/spot VMs, GKE eviction, batch
schedulers — the preemption warning is a SIGTERM with a short grace
window. Before this module the framework's only SIGTERM behavior was
the flight recorder's dump-and-die: correct forensics, but all work
since the last checkpoint was thrown away.

:class:`PreemptionGuard` turns SIGTERM into a cooperative request:
the handler only sets a flag (and counts ``preemptions_total`` + a
flight event); the wrapped training loop (``hapi.Model.fit``,
``incubate.TrainEpochRange``) checks the flag at step/epoch
boundaries, finishes the in-flight step, forces a final *synchronous*
checkpoint, and then calls :meth:`PreemptionGuard.reraise` — which
restores the previous handler chain and re-delivers the signal so the
process still dies with the scheduler-visible SIGTERM wait status
(``distributed.launch_elastic`` classifies that exit as a preemption,
not a crash). A preempted worker therefore resumes from the step it
died at, not the last epoch. See docs/fault_tolerance.md.
"""

from __future__ import annotations

import os
import signal
import threading
from typing import Optional, Tuple

__all__ = ["PreemptedError", "PreemptionGuard", "guard"]


class PreemptedError(RuntimeError):
    """Raised by :meth:`PreemptionGuard.reraise` when re-delivering the
    signal did not terminate the process (a chained handler swallowed
    it) — unwinds the stack so outer loops can run their own final
    saves and re-raise in turn."""


def _note_preempted(signum: int) -> None:
    try:
        from .observability import flight as _flight
        from .observability import metrics as _metrics
        _metrics.counter(
            "preemptions_total",
            "SIGTERM preemption notices caught by a preemption guard "
            "(graceful: finish step, checkpoint, re-raise)",
            always=True).inc()
        _flight.record("preemption_notice", force=True,
                       signum=int(signum))
    # ptlint: disable=silent-failure -- signal-handler context: telemetry must never block setting the preemption flag, which already happened above
    except Exception:  # noqa: BLE001 — telemetry never blocks the flag
        pass


class PreemptionGuard:
    """Context manager that converts SIGTERM into a checked flag.

    Usage::

        with preemption.guard() as g:
            for step in steps:
                run(step)
                if g.preempted:
                    checkpoint_now()
                    g.reraise()   # dies with SIGTERM wait status

    Installing a handler is only possible from the main thread; in any
    other thread the guard is inert (``preempted`` stays False) so
    library code can use it unconditionally.
    """

    def __init__(self, signals: Tuple[int, ...] = (signal.SIGTERM,)
                 ) -> None:
        self._signals = tuple(signals)
        self._prev: dict = {}
        self._installed = False
        self._flag = threading.Event()
        self.signum: Optional[int] = None

    @property
    def preempted(self) -> bool:
        return self._flag.is_set()

    @property
    def active(self) -> bool:
        """Whether handlers are actually installed (main thread)."""
        return self._installed

    def _handler(self, signum, frame) -> None:
        self.signum = int(signum)
        if not self._flag.is_set():
            self._flag.set()
            _note_preempted(signum)

    def __enter__(self) -> "PreemptionGuard":
        try:
            for sig in self._signals:
                self._prev[sig] = signal.signal(sig, self._handler)
            self._installed = True
        except (ValueError, OSError):  # not the main thread: stay inert
            self._restore()
        return self

    def __exit__(self, *exc) -> bool:
        self._restore()
        return False

    def _restore(self) -> None:
        for sig, prev in list(self._prev.items()):
            try:
                signal.signal(sig, prev if prev is not None
                              else signal.SIG_DFL)
            # ptlint: disable=silent-failure -- restoring handlers from a non-main thread raises ValueError; the guard is exiting either way
            except (ValueError, OSError):
                pass
        self._prev.clear()
        self._installed = False

    def reraise(self) -> None:
        """Restore the previous handler chain and re-deliver the
        signal — the graceful detour is over; the process must still
        die with the correct wait status. The flight recorder (if
        installed underneath) dumps and re-delivers in turn. If every
        chained handler swallows the signal, raises
        :class:`PreemptedError` so the stack still unwinds."""
        signum = self.signum or self._signals[0]
        self._restore()
        os.kill(os.getpid(), signum)
        # Reached only if a chained Python handler caught the
        # re-delivery (e.g. an outer guard): unwind via exception.
        raise PreemptedError(f"preempted by signal {signum}")


def guard(signals: Tuple[int, ...] = (signal.SIGTERM,)
          ) -> PreemptionGuard:
    """Factory spelling used by the training loops."""
    return PreemptionGuard(signals)
