"""Device mesh construction.

TPU-native replacement for the reference's device topology handling
(NCCLContextMap per-device comms, /root/reference/paddle/fluid/platform/
nccl_helper.h:92; hierarchical inter/intra rings nccl_helper.h:185). On TPU
the topology is a named :class:`jax.sharding.Mesh`; collectives ride ICI
along mesh axes and DCN across slices — XLA picks the rings. Standard axis
names: ``dp`` (data), ``mp`` (tensor/model), ``pp`` (pipeline), ``sp``
(sequence/context), ``ep`` (expert).
"""

from __future__ import annotations

from typing import Dict, List, Optional, Sequence, Tuple

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec

DP, MP, PP, SP, EP = "dp", "mp", "pp", "sp", "ep"


def create_mesh(axes: Optional[Dict[str, int]] = None,
                devices: Optional[Sequence] = None,
                allow_submesh: bool = False) -> Mesh:
    """Build a mesh from an axis→size dict, e.g. {"dp": 4, "mp": 2}.

    Sizes of -1 (at most one) absorb the remaining devices. Axis sizes that
    cover fewer devices than available are an error unless
    ``allow_submesh=True`` (which builds the mesh on the first ``total``
    devices and leaves the rest idle).
    """
    devices = list(devices) if devices is not None else jax.devices()
    axes = dict(axes) if axes else {DP: len(devices)}
    n = len(devices)
    known = 1
    wild = None
    for name, size in axes.items():
        if size == -1:
            wild = name
        else:
            known *= size
    if wild is not None:
        if known <= 0 or n % known != 0:
            raise ValueError(
                f"mesh axes {axes} with wildcard: {n} devices not "
                f"divisible by {known}")
        axes[wild] = n // known
    total = int(np.prod(list(axes.values())))
    if total > n or total <= 0:
        raise ValueError(f"mesh axes {axes} need {total} devices, have {n}")
    if total < n and not allow_submesh:
        raise ValueError(
            f"mesh axes {axes} cover {total} of {n} devices; use -1 to "
            f"absorb the rest or allow_submesh=True to idle them")
    arr = np.array(devices[:total]).reshape(tuple(axes.values()))
    return Mesh(arr, tuple(axes))


def num_slices(devices: Optional[Sequence] = None) -> int:
    """Number of distinct TPU slices among ``devices`` (1 on CPU/GPU or a
    single slice). Multi-slice topologies expose ``slice_index`` on each
    device; collectives between different slice_index values ride DCN."""
    devices = list(devices) if devices is not None else jax.devices()
    idx = {getattr(d, "slice_index", 0) for d in devices}
    return len(idx)


def create_multislice_mesh(dcn_axes: Dict[str, int],
                           ici_axes: Dict[str, int],
                           devices: Optional[Sequence] = None) -> Mesh:
    """Slice-aware mesh: ``dcn_axes`` (outermost) cross slice boundaries
    and ride DCN; ``ici_axes`` stay within a slice and ride ICI.

    TPU-native equivalent of the reference's hierarchical allreduce
    (/root/reference/paddle/fluid/platform/nccl_helper.h:185
    NCCLCommunicator inter/exter rings;
    framework/distributed_strategy.proto:110 use_hierarchical_allreduce).
    Where the reference builds explicit two-level NCCL rings, here the
    mesh layout makes XLA emit the two-level reduction: sharding a batch
    over ``P(("dcn", "dp"))`` produces an intra-slice (ICI) reduce
    followed by an inter-slice (DCN) allreduce of the partial sums.

    On real multi-slice hardware the device→coordinate assignment comes
    from ``mesh_utils.create_hybrid_device_mesh`` (slice_index-aware); on
    a single slice or the virtual CPU backend, devices are grouped into
    ``prod(dcn_axes)`` contiguous synthetic slices so the same program
    (and tests) run anywhere. One ici axis may be -1 to absorb the
    remaining per-slice devices.
    """
    devices = list(devices) if devices is not None else jax.devices()
    dcn_axes = dict(dcn_axes)
    ici_axes = dict(ici_axes)
    n = len(devices)
    n_dcn = int(np.prod(list(dcn_axes.values())))
    if n_dcn <= 0 or n % n_dcn != 0:
        raise ValueError(
            f"dcn axes {dcn_axes} do not divide {n} devices")
    per_slice = n // n_dcn
    wild = [k for k, v in ici_axes.items() if v == -1]
    if len(wild) > 1:
        raise ValueError("at most one ici axis may be -1")
    if wild:
        known = int(np.prod([v for v in ici_axes.values() if v != -1]))
        if known <= 0 or per_slice % known != 0:
            raise ValueError(
                f"ici axes {ici_axes}: {per_slice} per-slice devices not "
                f"divisible by {known}")
        ici_axes[wild[0]] = per_slice // known
    if int(np.prod(list(ici_axes.values()))) != per_slice:
        raise ValueError(
            f"ici axes {ici_axes} must cover {per_slice} devices/slice")

    names = tuple(dcn_axes) + tuple(ici_axes)
    shape = tuple(dcn_axes.values()) + tuple(ici_axes.values())
    if num_slices(devices) == n_dcn and n_dcn > 1:
        from jax.experimental import mesh_utils
        # same-length shape vectors: each dim is either a DCN or ICI dim
        ici_shape = (1,) * len(dcn_axes) + tuple(ici_axes.values())
        dcn_shape = tuple(dcn_axes.values()) + (1,) * len(ici_axes)
        arr = mesh_utils.create_hybrid_device_mesh(
            ici_shape, dcn_shape, devices=devices)
        return Mesh(arr, names)
    # synthetic slices: contiguous groups (device order is host order,
    # which keeps intra-group collectives local on multi-process CPU too)
    arr = np.array(devices).reshape(shape)
    return Mesh(arr, names)


def multislice_data_spec(mesh: Mesh, dcn_axis: str = "dcn",
                         dp_axis: str = DP) -> PartitionSpec:
    """Batch spec sharding over (dcn, dp) jointly — the hierarchical
    data-parallel layout."""
    axes = tuple(a for a in (dcn_axis, dp_axis) if a in mesh.shape)
    return PartitionSpec(axes if len(axes) > 1 else axes[0])


def data_parallel_mesh(n: Optional[int] = None) -> Mesh:
    devs = jax.devices()[:n] if n else jax.devices()
    return create_mesh({DP: len(devs)}, devs)


def mesh_axis_size(mesh: Mesh, axis: str) -> int:
    return mesh.shape[axis] if axis in mesh.shape else 1


def named_sharding(mesh: Mesh, *spec) -> NamedSharding:
    return NamedSharding(mesh, PartitionSpec(*spec))


def replicated(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, PartitionSpec())


def batch_sharding(mesh: Mesh, axis: str = DP) -> NamedSharding:
    """Shard leading (batch) dim over the data axis."""
    return NamedSharding(mesh, PartitionSpec(axis))
