"""Device mesh construction.

TPU-native replacement for the reference's device topology handling
(NCCLContextMap per-device comms, /root/reference/paddle/fluid/platform/
nccl_helper.h:92; hierarchical inter/intra rings nccl_helper.h:185). On TPU
the topology is a named :class:`jax.sharding.Mesh`; collectives ride ICI
along mesh axes and DCN across slices — XLA picks the rings. Standard axis
names: ``dp`` (data), ``mp`` (tensor/model), ``pp`` (pipeline), ``sp``
(sequence/context), ``ep`` (expert).
"""

from __future__ import annotations

from typing import Dict, List, Optional, Sequence, Tuple

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec

DP, MP, PP, SP, EP = "dp", "mp", "pp", "sp", "ep"


def create_mesh(axes: Optional[Dict[str, int]] = None,
                devices: Optional[Sequence] = None,
                allow_submesh: bool = False) -> Mesh:
    """Build a mesh from an axis→size dict, e.g. {"dp": 4, "mp": 2}.

    Sizes of -1 (at most one) absorb the remaining devices. Axis sizes that
    cover fewer devices than available are an error unless
    ``allow_submesh=True`` (which builds the mesh on the first ``total``
    devices and leaves the rest idle).
    """
    devices = list(devices) if devices is not None else jax.devices()
    axes = dict(axes) if axes else {DP: len(devices)}
    n = len(devices)
    known = 1
    wild = None
    for name, size in axes.items():
        if size == -1:
            wild = name
        else:
            known *= size
    if wild is not None:
        if known <= 0 or n % known != 0:
            raise ValueError(
                f"mesh axes {axes} with wildcard: {n} devices not "
                f"divisible by {known}")
        axes[wild] = n // known
    total = int(np.prod(list(axes.values())))
    if total > n or total <= 0:
        raise ValueError(f"mesh axes {axes} need {total} devices, have {n}")
    if total < n and not allow_submesh:
        raise ValueError(
            f"mesh axes {axes} cover {total} of {n} devices; use -1 to "
            f"absorb the rest or allow_submesh=True to idle them")
    arr = np.array(devices[:total]).reshape(tuple(axes.values()))
    return Mesh(arr, tuple(axes))


def data_parallel_mesh(n: Optional[int] = None) -> Mesh:
    devs = jax.devices()[:n] if n else jax.devices()
    return create_mesh({DP: len(devs)}, devs)


def mesh_axis_size(mesh: Mesh, axis: str) -> int:
    return mesh.shape[axis] if axis in mesh.shape else 1


def named_sharding(mesh: Mesh, *spec) -> NamedSharding:
    return NamedSharding(mesh, PartitionSpec(*spec))


def replicated(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, PartitionSpec())


def batch_sharding(mesh: Mesh, axis: str = DP) -> NamedSharding:
    """Shard leading (batch) dim over the data axis."""
    return NamedSharding(mesh, PartitionSpec(axis))
