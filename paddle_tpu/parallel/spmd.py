"""SPMD sharded training step.

TPU-native replacement for the reference's ParallelExecutor + multi-device
graph pass + allreduce op-handles
(/root/reference/paddle/fluid/framework/parallel_executor.cc:443,
ir/multi_devices_graph_pass/multi_devices_graph_pass.cc,
details/all_reduce_op_handle.cc:48). Where the reference clones the graph
per device and inserts NCCL allreduce ops per gradient, here ONE program is
compiled with sharding annotations over a Mesh and **XLA inserts the ICI
collectives** — grad allreduce appears automatically from "batch sharded ×
params replicated" propagation; tensor parallelism from sharded param
specs; no pass pipeline needed.

Param placement rules (:func:`make_param_specs`) are the analogue of
BuildStrategy: a callable from param name/shape → PartitionSpec.
"""

from __future__ import annotations

from typing import Any, Callable, Dict, Optional, Tuple

from ..core import as_label_tuple
import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..core import random as _random
from ..nn.layer import Layer, functional_call
from ..optimizer import Optimizer
from . import mesh as mesh_lib


def make_param_specs(params: Dict[str, Any],
                     rule: Optional[Callable[[str, Any], P]] = None) \
        -> Dict[str, P]:
    """Default: replicate everything (pure DP). A rule can shard params
    (e.g. megatron-style: q/k/v column-parallel over 'mp')."""
    if rule is None:
        return jax.tree.map(lambda _: P(), params)
    out = {}
    for name, value in params.items():
        out[name] = rule(name, value)
    return out


def host_lr_of(optimizer) -> Optional[float]:
    """Current LR of a host-driven scheduler (ReduceOnPlateau), else
    None. Pure host state — no device sync (get_lr is overridden to
    return the python float)."""
    sched = getattr(optimizer, "learning_rate", None)
    if getattr(sched, "host_driven", False):
        return float(sched.get_lr())
    return None


def inject_host_lr(batch: Dict[str, Any], optimizer) -> Dict[str, Any]:
    """Single place all jit-based step classes feed a host-driven
    scheduler's live LR into the compiled step (as a runtime scalar
    input; shard_map-based steps pass it as a separate argument
    instead — a rank-0 leaf can't ride a P('dp') batch spec)."""
    lr = host_lr_of(optimizer)
    if lr is not None:
        batch["lr"] = jnp.float32(lr)
    return batch


_shardable_warned: set = set()
_note_counts: Dict[str, int] = {}
_MAX_NOTES_PER_NAME = 2


def _note_auto_shard(name: str, shape, rule: str) -> None:
    """One-time-per-(name, shape) visibility for the silent convention
    that classifies a model-forward KWARG as per-sample data — keyed on
    the shape too so a later model whose same-named kwarg is a
    different (possibly coincident) tensor still gets noticed, but
    capped per name so a variable-length kwarg (a new shape per
    sequence bucket) cannot spam the log or grow the set unboundedly.
    The classification cannot be inspected, only assumed — a replicated
    table/mask whose dims merely coincide would be sharded wrong with
    no diagnostic — so the first time each kwarg name is classified,
    say so. Emitted through logging (printed by logging's last-resort
    handler even unconfigured) rather than warnings.warn, so correct
    per-sample kwargs — the common case — don't explode under
    warnings-as-errors test setups."""
    key = (name, tuple(shape))
    if key in _shardable_warned \
            or _note_counts.get(name, 0) >= _MAX_NOTES_PER_NAME:
        return
    _shardable_warned.add(key)
    _note_counts[name] = _note_counts.get(name, 0) + 1
    import logging
    logging.getLogger("paddle_tpu.parallel").warning(
        "model-forward kwarg '%s' (shape %s) auto-classified as "
        "per-sample data (%s); it will be batch-sharded/micro-sliced. "
        "If it is actually replicated (a table/mask whose dims "
        "coincide), give it a non-batch leading dim, e.g. reshape to "
        "[1, ...].", name, tuple(shape), rule)


def split_kwargs_by_shardable(kwargs: Dict[str, Any],
                              batch_size: Optional[int],
                              note: bool = True):
    """Partition model-forward kwargs into (dp-shardable, replicated):
    a leaf whose leading dim EQUALS the batch size is per-sample data
    and rides the sharded batch tree; everything else (broadcast
    masks, tables, scalars) is replicated — the shard_map analogue of
    ShardedTrainStep's _place_batch placement, using the same
    leading-dim convention the grad-accum micro-slicer documents.
    Every auto-classification is surfaced once per kwarg name
    (_note_auto_shard) so a coincidental match is visible; callers on
    a trivial (size-1) mesh pass note=False — sharding is a no-op
    there, so the notice would be misleading noise (same gate as
    _place_batch's _batch_spec_nontrivial)."""
    sh, rep = {}, {}
    for n, v in kwargs.items():
        nd = getattr(v, "ndim", None)
        shp = getattr(v, "shape", None)
        if nd is None and hasattr(v, "__len__"):
            import numpy as _np
            v = _np.asarray(v)
            nd, shp = v.ndim, v.shape
        if (batch_size is not None and nd and shp
                and shp[0] == batch_size):
            if note:
                _note_auto_shard(n, shp, "leading dim equals the "
                                         f"batch size {batch_size}")
            sh[n] = v
        else:
            rep[n] = v
    return sh, rep


def leading_batch_size(args, labels) -> Optional[int]:
    """Batch size from the first arg (else first label) with a rank
    guard — the one convention every step class shares."""
    lead = args[0] if args else (labels[0] if labels else None)
    if getattr(lead, "ndim", 0) >= 1:
        return lead.shape[0]
    return None


def _global_put(value, sharding: NamedSharding):
    """device_put that also works on a multi-process mesh.

    Single-process: plain device_put. Multi-process (jax.distributed,
    mesh spans non-addressable devices — the reference's multi-node NCCL
    ring case): each process supplies its addressable shards from the
    (identical) host value via make_array_from_callback.
    """
    if isinstance(value, jax.Array) and value.sharding == sharding:
        return value
    if sharding.is_fully_addressable:
        return jax.device_put(value, sharding)
    if hasattr(value, "dtype") and jnp.issubdtype(value.dtype,
                                                  jax.dtypes.prng_key):
        raw = _global_put(jax.random.key_data(value), sharding)
        return jax.random.wrap_key_data(
            raw, impl=jax.random.key_impl(value))
    arr = np.asarray(value)
    return jax.make_array_from_callback(arr.shape, sharding,
                                        lambda idx: arr[idx])


def _zero_shard_spec(base: P, value, mesh: Mesh, axis: str) -> P:
    """ZeRO-style spec: extend `base` by sharding the largest still-
    replicated dimension of `value` over `axis` (if divisible)."""
    if not hasattr(value, "ndim") or value.ndim == 0:
        return base
    n = mesh.shape[axis] if axis in mesh.shape else 1
    if n <= 1:
        return base
    if any(axis == e or (isinstance(e, tuple) and axis in e)
           for e in base):
        return base  # already sharded over this axis
    entries = list(base) + [None] * (value.ndim - len(list(base)))
    # pick the largest unsharded, divisible dim
    cand = [(value.shape[d], d) for d in range(value.ndim)
            if entries[d] is None and value.shape[d] % n == 0]
    if not cand:
        return base
    _, dim = max(cand)
    entries[dim] = axis
    return P(*entries)


class ShardedTrainStep:
    """Compile model+loss+optimizer into one pjit program over a mesh.

    - batch_spec: PartitionSpec for every leaf of the batch
      (default P('dp'): leading dim sharded over the data axis).
    - param_rule: name→PartitionSpec callable for TP/EP-style placement.
    - zero_stage: ZeRO optimizer/param partitioning over the dp axis
      (ref capability analogue: ReduceStrategy::kReduce's param-sharded
      update, /root/reference/paddle/fluid/framework/details/
      build_strategy.h:58, generalized to the modern ZeRO formulation).
      stage 1/2 shard optimizer slots over dp (XLA emits reduce-scatter +
      gather around the update); stage 3 also shards the params
      themselves (XLA gathers them per-layer on use).
    - donate: state buffers are donated (in-place update in HBM).
    """

    def __init__(self, model: Layer, optimizer: Optimizer,
                 loss_fn: Callable, mesh: Mesh,
                 batch_spec: P = P("dp"),
                 param_rule: Optional[Callable] = None,
                 seed: int = 0,
                 extra_metrics: Optional[Dict[str, Callable]] = None,
                 zero_stage: int = 0, dp_axis: str = "dp",
                 amp_dtype=None, scaler=None) -> None:
        self.model = model
        self.optimizer = optimizer
        from ..static import _wire_param_meta, _skip_guard_default
        _wire_param_meta(model, optimizer)
        self.loss_fn = loss_fn
        self.mesh = mesh
        self.batch_spec = batch_spec
        self.axis = dp_axis  # straggler detector keys the dp exchange
        self.extra_metrics = extra_metrics or {}
        # AMP / skip-step guard (same contract as TrainStep). getattr
        # defaults keep subclasses that set these before super().__init__
        # (_ComposedTrainStep) authoritative.
        if scaler is not None and not scaler.enable:
            scaler = None
        self.scaler = scaler if scaler is not None \
            else getattr(self, "scaler", None)
        self.amp_dtype = amp_dtype if amp_dtype is not None \
            else getattr(self, "amp_dtype", None)
        self._skip_guard = _skip_guard_default()
        self.lr_scale = 1.0

        params = model.param_dict()
        buffers = model.buffer_dict()
        param_specs = make_param_specs(params, param_rule)
        if zero_stage >= 3:
            param_specs = {n: _zero_shard_spec(s, params[n], mesh, dp_axis)
                           for n, s in param_specs.items()}
        opt_state = optimizer.init(params)

        if zero_stage >= 1:
            slot_specs = {n: _zero_shard_spec(param_specs[n], params[n],
                                              mesh, dp_axis)
                          for n in params}
        else:
            slot_specs = param_specs

        opt_specs = {
            "step": P(),
            "slots": {n: jax.tree.map(
                lambda x, _n=n: slot_specs[_n]
                if hasattr(x, "ndim") and x.ndim > 0 else P(), s)
                      for n, s in opt_state["slots"].items()},
        }
        if "fused" in opt_state:
            # flat fused optimizer state is replicated; it only makes
            # sense when the params themselves are replicated — with
            # ZeRO/TP the flat vector would force all-gathers of every
            # grad and un-shard the slot memory
            sharded_params = [n for n, s in param_specs.items()
                              if s != P()]
            if zero_stage >= 1 or sharded_params:
                raise ValueError(
                    "optimizer_fused_state is incompatible with ZeRO "
                    f"sharding / sharded params ({sharded_params[:3]}...)"
                    if sharded_params else
                    "optimizer_fused_state is incompatible with ZeRO "
                    "slot sharding; construct the optimizer with "
                    "fused_state=False for this strategy")
            opt_specs["fused"] = jax.tree.map(lambda _: P(),
                                              opt_state["fused"])
        self.state_specs = {
            "params": param_specs,
            "buffers": jax.tree.map(lambda _: P(), buffers),
            "opt": opt_specs,
            "rng": P(),
        }
        state = {"params": params, "buffers": buffers, "opt": opt_state,
                 "rng": _random.make_key(seed)}
        # subclass extension point: extra carried state (AMP loss-scale,
        # custom counters) with its sharding specs
        for name, (val, spec) in self.extra_state().items():
            state[name] = val
            self.state_specs[name] = spec
        state_shardings = jax.tree.map(
            lambda s: NamedSharding(mesh, s), self.state_specs,
            is_leaf=lambda x: isinstance(x, P))
        self._state_shardings = state_shardings
        # place initial state according to specs (multi-controller safe:
        # on a mesh spanning multiple processes every process holds the
        # same host value — same seed — and contributes its addressable
        # shards)
        self.state = jax.tree.map(_global_put, state, state_shardings)
        self.batch_sharding = NamedSharding(mesh, batch_spec)

        # Batch shardings are decided per leaf at call time (committed
        # arrays carry their sharding into jit): a leaf the batch_spec
        # can't shard — rank-0 sample weight, tail batch not divisible by
        # the axis size — is replicated alone instead of silently turning
        # off data parallelism for the whole batch. The reference's
        # ParallelExecutor simply rejects such feeds (it splits by device
        # count).
        from ..observability import instrumented_jit
        self._span_name = f"ShardedTrainStep({type(model).__name__})"
        self._jitted = instrumented_jit(
            self._step, self._span_name,
            in_shardings=(state_shardings, None),
            out_shardings=(state_shardings, None),
            donate_argnums=(0,))
        self._replicated_sharding = NamedSharding(mesh, P())
        # invariant for the life of the step object (mesh + batch_spec
        # are fixed here); used on the per-step path by _place_batch
        self._note_kwargs = self._batch_spec_nontrivial()

    def _leaf_shardable(self, x) -> bool:
        spec = tuple(self.batch_spec)
        sizes = self.mesh.shape
        ndim = getattr(x, "ndim", None)
        if ndim is None:
            return False
        for d, entry in enumerate(spec):
            if entry is None:
                continue
            axes = entry if isinstance(entry, tuple) else (entry,)
            n = int(np.prod([sizes[a] for a in axes]))
            if n <= 1:
                continue
            if ndim <= d or x.shape[d] % n != 0:
                return False
        return True

    def _batch_spec_nontrivial(self) -> bool:
        """True when the batch sharding actually splits something: on a
        mesh whose batch-spec axes all have size 1, _leaf_shardable is
        vacuously True for every leaf and 'sharding' is a no-op, so the
        coincidence notice would be pure noise there."""
        sizes = self.mesh.shape
        for entry in tuple(self.batch_spec):
            if entry is None:
                continue
            axes = entry if isinstance(entry, tuple) else (entry,)
            if int(np.prod([sizes[a] for a in axes])) > 1:
                return True
        return False

    def _place_batch(self, batch):
        note = self._note_kwargs

        def put(x, kwarg_name=None):
            shardable = self._leaf_shardable(x)
            if shardable and kwarg_name is not None and note:
                # args/labels are per-sample by contract; a KWARG that
                # happens to satisfy the divisibility rule is the
                # silent-coincidence hazard — surface it once
                _note_auto_shard(kwarg_name, getattr(x, "shape", ()),
                                 "dims divisible by the batch spec")
            dst = (self.batch_sharding if shardable
                   else self._replicated_sharding)
            if not dst.is_fully_addressable and not isinstance(x, jax.Array):
                # A host array here would be each process's LOCAL batch
                # masquerading as the global one — half of every rank's
                # rows silently dropped. Make the contract explicit.
                raise ValueError(
                    "on a multi-process mesh, feed ShardedTrainStep "
                    "global jax.Arrays (jax.make_array_from_process_"
                    "local_data(sharding, local_batch, global_shape)); "
                    f"got {type(x).__name__} for sharding {dst}")
            return _global_put(jnp.asarray(x), dst)

        kwargs = batch.get("kwargs") if isinstance(batch, dict) else None
        if kwargs:
            placed = jax.tree.map(
                put, {k: v for k, v in batch.items() if k != "kwargs"})
            placed["kwargs"] = {
                n: jax.tree.map(lambda x, n=n: put(x, kwarg_name=n), v)
                for n, v in kwargs.items()}
            return placed
        return jax.tree.map(put, batch)

    def extra_state(self):
        """Subclass hook: {name: (initial_value, PartitionSpec tree)}
        merged into the carried state before compilation. The base
        class registers the GradScaler state here (replicated)."""
        if getattr(self, "scaler", None) is None:
            return {}
        st = self.scaler.init()
        return {"scaler": (st, jax.tree.map(lambda _: P(), st))}

    def _step(self, state, batch):
        import contextlib

        from .. import amp as _amp
        from ..static import apply_fault_mults, probe_nonfinite
        params = state["params"]
        buffers = state["buffers"]
        rng, step_key = jax.random.split(state["rng"])
        scaler = self.scaler if "scaler" in state else None

        def loss_of(p):
            ctx = _amp.auto_cast(enable=True, dtype=self.amp_dtype) \
                if self.amp_dtype is not None \
                else contextlib.nullcontext()
            with ctx, _random.rng_scope(default=step_key,
                                        dropout=step_key):
                out, new_buffers = functional_call(
                    self.model, p, buffers, *batch["args"],
                    capture_buffers=True, **batch.get("kwargs", {}))
                loss = self.loss_fn(out, *batch["labels"])
            if scaler is not None:
                loss = scaler.scale(loss, state["scaler"])
            return loss, (new_buffers, out)

        (loss, (new_buffers, out)), grads = jax.value_and_grad(
            loss_of, has_aux=True)(params)
        loss, grads = apply_fault_mults(loss, grads, batch)
        found_inf = None
        if scaler is not None:
            grads, found_inf = scaler.unscale(grads, state["scaler"])
            loss = loss / state["scaler"]["scale"].astype(loss.dtype)
        elif self._skip_guard:
            found_inf = ~_amp.all_finite(grads)
        lr = batch.get("lr")
        if "lr_scale" in batch:
            from ..optimizer.lr import resolve_lr
            base = lr if lr is not None else resolve_lr(
                self.optimizer.learning_rate, state["opt"]["step"] + 1)
            lr = base * batch["lr_scale"]
        new_params, new_opt = self.optimizer.apply_gradients(
            params, grads, state["opt"], lr_override=lr)
        if found_inf is not None:
            # skip-step guard: discard the whole update in-graph on
            # non-finite grads (no host sync; XLA keeps the select
            # local per shard)
            new_params = _amp.select_update(found_inf, new_params,
                                            params)
            new_opt = _amp.select_update(found_inf, new_opt,
                                         state["opt"])
            new_buffers = _amp.select_update(found_inf, new_buffers,
                                             buffers)
            probe_nonfinite(found_inf)
        metrics = {"loss": loss}
        for name, fn in self.extra_metrics.items():
            metrics[name] = fn(out, *batch["labels"])
        new_state = {**state, "params": new_params,
                     "buffers": new_buffers, "opt": new_opt,
                     "rng": rng}
        if scaler is not None:
            new_state["scaler"] = scaler.update(state["scaler"],
                                                found_inf)
        # **state first above: subclass-registered extra state
        # (extra_state()) passes through untouched
        return (new_state, metrics)

    def shard_batch(self, *arrays):
        """Place host arrays onto the mesh with the batch sharding."""
        return tuple(jax.device_put(jnp.asarray(a), self.batch_sharding)
                     for a in arrays)

    def __call__(self, *args, labels=(), **kwargs):
        # model-forward kwargs ride the batch like args (same contract
        # as TrainStep — e.g. BERT's masked_positions); their leaves
        # shard per batch_spec when shardable, else replicate
        batch = inject_host_lr(
            {"args": args, "labels": as_label_tuple(labels),
             "kwargs": kwargs},
            self.optimizer)
        from ..static import inject_fault_mults
        inject_fault_mults(batch)
        if self.lr_scale != 1.0:
            batch["lr_scale"] = jnp.float32(self.lr_scale)
        batch = self._place_batch(batch)
        from ..observability import metrics as _obs_metrics
        if _obs_metrics.enabled():
            from ..observability import span as _obs_span
            with _obs_span(self._span_name), self.mesh:
                self.state, metrics = self._jitted(self.state, batch)
            _obs_metrics.counter("optimizer_steps_total",
                                 "optimizer update steps applied").inc()
        else:
            with self.mesh:
                self.state, metrics = self._jitted(self.state, batch)
        return metrics

    @property
    def params(self):
        return self.state["params"]

    def sync_to_model(self) -> None:
        state = {**self.state["params"], **self.state["buffers"]}
        # A step that failed mid-execution may have consumed (deleted) the
        # donated buffers with no result to replace them; skip those rather
        # than raise from cleanup paths (same contract as TrainStep).
        alive = {k: v for k, v in state.items()
                 if not (hasattr(v, "is_deleted") and v.is_deleted())}
        if len(alive) < len(state):
            import warnings
            warnings.warn(
                f"sync_to_model: {len(state) - len(alive)} donated buffers "
                "were lost to a failed step; those weights keep their "
                "previous values in the eager model")
        host = jax.tree.map(jax.device_get, alive)
        self.model.set_state_dict(host, strict=False)

    def reset_from_model(self) -> None:
        """Re-shard the eager model's (possibly mutated) weights into the
        training state — same contract as TrainStep.reset_from_model."""
        self.state = dict(
            self.state,
            params=jax.device_put(self.model.param_dict(),
                                  self._state_shardings["params"]),
            buffers=jax.device_put(self.model.buffer_dict(),
                                   self._state_shardings["buffers"]))


def megatron_param_rule(mp_axis: str = "mp"):
    """Example TP rule: shard large 2-D matmul weights column-wise, their
    paired output projections row-wise, replicate the rest. Heuristic by
    name; models can pass their own rule."""

    def rule(name: str, value) -> P:
        shape = getattr(value, "shape", ())
        if len(shape) == 2:
            if any(tag in name for tag in ("q_proj", "k_proj", "v_proj",
                                           "linear1", "fc1")):
                return P(None, mp_axis)
            if any(tag in name for tag in ("out_proj", "linear2", "fc2")):
                return P(mp_axis, None)
        return P()

    return rule
