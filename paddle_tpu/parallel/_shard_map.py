"""Version-portable ``shard_map``.

``jax.shard_map`` only exists as a top-level API in newer jax; on the
pinned 0.4.x line it lives at ``jax.experimental.shard_map.shard_map``
and spells the replication-check kwarg ``check_rep`` instead of
``check_vma``. Every shard_map call in the framework routes through
here so the version split lives in one place (the same pattern as the
``lax.axis_size`` fallback in collective.py).
"""

from __future__ import annotations

from typing import Any, Optional

import jax

__all__ = ["shard_map"]


def shard_map(f, mesh, in_specs, out_specs,
              check_vma: Optional[bool] = None) -> Any:
    kwargs = {}
    if hasattr(jax, "shard_map"):
        if check_vma is not None:
            kwargs["check_vma"] = check_vma
        return jax.shard_map(f, mesh=mesh, in_specs=in_specs,
                             out_specs=out_specs, **kwargs)
    from jax.experimental.shard_map import shard_map as _sm
    if check_vma is not None:
        kwargs["check_rep"] = check_vma  # old spelling, same meaning
    return _sm(f, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
               **kwargs)
