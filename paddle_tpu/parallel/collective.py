"""Collective communication ops.

TPU-native replacement for the reference's collective operator family
(/root/reference/paddle/fluid/operators/collective/: c_allreduce_op.h:72
(ring_id keyed), c_broadcast_op.cc, c_allgather_op.cc, c_reducescatter_op.cc,
c_scatter_op.cc; comm registry platform/collective_helper.h:62
NCCLCommContext). The NCCL ring becomes a **mesh axis**: a
:class:`CommGroup` names a set of axes (the ring_id analogue), and each
collective lowers to the XLA ICI/DCN primitive via jax.lax inside
shard_map/pjit-traced code. Outside traced code, the same API falls back to
single-process semantics (identity), matching the reference's behavior with
world_size=1.
"""

from __future__ import annotations

from typing import Dict, Optional, Sequence, Union

import jax
import jax.numpy as jnp
from jax import lax

from ..observability import metrics as _obs_metrics

AxisName = Union[str, Sequence[str]]


def _account(op: str, x) -> None:
    """Count collective launches + payload bytes. Runs in host Python:
    inside shard_map/pjit that is ONCE per trace (compiled steady state
    pays nothing), eagerly it is per call — both gated on
    FLAGS_enable_metrics."""
    if not _obs_metrics.enabled():
        return
    _obs_metrics.counter("collective_calls_total",
                         "collective ops (per trace when jitted)"
                         ).inc(op=op)
    try:
        nbytes = sum(int(l.size) * int(l.dtype.itemsize)
                     for l in jax.tree.leaves(x))
    except (AttributeError, TypeError):
        nbytes = 0
    _obs_metrics.counter("collective_bytes_total",
                         "payload bytes handed to collectives "
                         "(per trace when jitted)").inc(nbytes, op=op)

# ring_id → axis-name registry (ref: NCCLCommContext keyed by ring_id,
# collective_helper.h:62)
_groups: Dict[int, "CommGroup"] = {}


class CommGroup:
    """A named communicator (≈ one NCCL ring)."""

    def __init__(self, ring_id: int, axis: AxisName) -> None:
        self.ring_id = ring_id
        self.axis = axis

    def __repr__(self) -> str:
        return f"CommGroup(ring_id={self.ring_id}, axis={self.axis!r})"


def new_group(axis: AxisName, ring_id: Optional[int] = None) -> CommGroup:
    """(ref: c_comm_init_op.cc) register a communicator over mesh axes."""
    rid = ring_id if ring_id is not None else (max(_groups) + 1
                                               if _groups else 0)
    g = CommGroup(rid, axis)
    _groups[rid] = g
    return g


def get_group(ring_id: int = 0) -> CommGroup:
    if ring_id not in _groups:
        _groups[ring_id] = CommGroup(ring_id, "dp")
    return _groups[ring_id]


def _axis(group: Optional[Union[CommGroup, AxisName]]) -> AxisName:
    if group is None:
        return get_group(0).axis
    if isinstance(group, CommGroup):
        return group.axis
    return group


def _axis_size(axis: AxisName) -> int:
    # lax.axis_size is recent; on older jax the psum-of-static-1 idiom
    # gives the same bound-axis size (and raises NameError unbound)
    if hasattr(lax, "axis_size"):
        return lax.axis_size(axis)
    return lax.psum(1, axis)


def _in_traced_collective(axis: AxisName) -> bool:
    try:
        _axis_size(axis)
        return True
    except (NameError, KeyError, Exception):
        return False


def all_reduce(x, op: str = "sum", group=None):
    """(ref: c_allreduce_op.h:72; kernels :105 call ncclAllReduce)."""
    axis = _axis(group)
    if not _in_traced_collective(axis):
        return x
    _account("all_reduce", x)
    if op == "sum":
        return lax.psum(x, axis)
    if op == "mean":
        return lax.pmean(x, axis)
    if op == "max":
        return lax.pmax(x, axis)
    if op == "min":
        return lax.pmin(x, axis)
    if op == "prod":
        return jnp.exp(lax.psum(jnp.log(x), axis))
    raise ValueError(f"unknown reduce op '{op}'")


def all_gather(x, axis: int = 0, group=None):
    """(ref: c_allgather_op.cc)."""
    a = _axis(group)
    if not _in_traced_collective(a):
        return x
    _account("all_gather", x)
    return lax.all_gather(x, a, axis=axis, tiled=True)


def reduce_scatter(x, axis: int = 0, group=None):
    """(ref: c_reducescatter_op.cc)."""
    a = _axis(group)
    if not _in_traced_collective(a):
        return x
    _account("reduce_scatter", x)
    return lax.psum_scatter(x, a, scatter_dimension=axis, tiled=True)


def broadcast(x, src: int = 0, group=None):
    """(ref: c_broadcast_op.cc) — take src's shard everywhere."""
    a = _axis(group)
    if not _in_traced_collective(a):
        return x
    _account("broadcast", x)
    n = _axis_size(a)
    return lax.all_gather(x, a)[src] if n > 1 else x

def reduce(x, dst: int = 0, op: str = "sum", group=None):
    """(ref: c_reduce_op.h) — result valid on dst, others get the
    reduction too (psum); matches capability, XLA has no cheaper reduce."""
    return all_reduce(x, op, group)


def scatter(x, src: int = 0, group=None):
    """(ref: c_scatter_op.cc) — each rank takes its slice of src's value."""
    a = _axis(group)
    if not _in_traced_collective(a):
        return x
    _account("scatter", x)
    n = _axis_size(a)
    idx = lax.axis_index(a)
    full = lax.all_gather(x, a)[src]
    size = full.shape[0] // n
    return lax.dynamic_slice_in_dim(full, idx * size, size, axis=0)


def all_to_all(x, split_axis: int = 0, concat_axis: int = 0, group=None):
    """(ref capability: alltoall in later fleet; needed for Ulysses SP/EP)."""
    a = _axis(group)
    if not _in_traced_collective(a):
        return x
    _account("all_to_all", x)
    return lax.all_to_all(x, a, split_axis=split_axis,
                          concat_axis=concat_axis, tiled=True)


def ppermute(x, perm, group=None):
    """Ring shift primitive (ring attention building block)."""
    a = _axis(group)
    if not _in_traced_collective(a):
        return x
    _account("ppermute", x)
    return lax.ppermute(x, a, perm)


def barrier(group=None):
    """(ref: barrier via gloo GlooWrapper::Barrier gloo_wrapper.h:146).
    In traced code a psum serves as a barrier; eagerly it's a no-op in
    single-process, jax.distributed-level barrier otherwise."""
    a = _axis(group)
    if _in_traced_collective(a):
        return lax.psum(jnp.ones(()), a)
    try:
        import jax._src.distributed as dist
        if dist.global_state.client is not None:
            dist.global_state.client.wait_at_barrier("paddle_tpu_barrier",
                                                     60_000)
    # ptlint: disable=silent-failure -- jax._src.distributed is a private API probed opportunistically; without it the psum below is still a barrier
    except Exception:
        pass
    return jnp.ones(())


def rank(group=None):
    a = _axis(group)
    if _in_traced_collective(a):
        return lax.axis_index(a)
    return jnp.zeros((), jnp.int32)


def world_size(group=None) -> int:
    a = _axis(group)
    if _in_traced_collective(a):
        return _axis_size(a)
    return 1
