"""DataParallel layer wrapper (dygraph parity).

Reference: /root/reference/python/paddle/fluid/dygraph/parallel.py:225
(DataParallel: scale_loss :289, coalesce + allreduce + split
apply_collective_grads :386) and imperative/all_reduce.cc. In the TPU
design the wrapper is thin: grads are reduced by XLA inside the sharded
step (spmd.py), so DataParallel only (a) carries the mesh/env metadata,
(b) provides scale_loss / apply_collective_grads API parity for code
written against the reference, where apply_collective_grads is the
explicit shard_map grad-psum path.
"""

from __future__ import annotations

from typing import Optional, Sequence

import jax
from jax.sharding import Mesh

from ..nn.layer import Layer
from . import collective
from .env import ParallelEnv
from .mesh import data_parallel_mesh


class DataParallel(Layer):
    def __init__(self, layers: Layer, strategy=None,
                 mesh: Optional[Mesh] = None) -> None:
        super().__init__()
        self._layers = layers
        self.mesh = mesh if mesh is not None else data_parallel_mesh()
        self.env = ParallelEnv()
        self.nranks = int(jax.device_count())

    def forward(self, *args, **kwargs):
        return self._layers(*args, **kwargs)

    def scale_loss(self, loss):
        """(ref: parallel.py:289) — with pmean-based reduction this is an
        identity; kept for API parity when loss_sum + allreduce is used."""
        return loss

    def apply_collective_grads(self, grads):
        """psum grads over the dp axis (valid inside shard_map)."""
        return jax.tree.map(
            lambda g: collective.all_reduce(g, "mean", group="dp"), grads)

    def state_dict(self, *args, **kwargs):
        return self._layers.state_dict(*args, **kwargs)

    def set_state_dict(self, *args, **kwargs):
        return self._layers.set_state_dict(*args, **kwargs)
