"""Distributed / parallel runtime (SURVEY.md §2.8-2.9).

One `comm` design: ICI collectives are XLA ops over mesh axes (collective.py
keyed by ring_id-style CommGroups), DCN multi-host comes from
jax.distributed (env.py), data/tensor parallel training compiles through
ShardedTrainStep (spmd.py), pipeline parallelism through pipeline.py.
"""

from . import collective, mesh, spmd
from .collective import (all_gather, all_reduce, all_to_all, barrier,
                         broadcast, get_group, new_group, ppermute,
                         reduce, reduce_scatter, scatter)
from .data_parallel import DataParallel
from .env import ParallelEnv, get_rank, get_world_size, init_parallel_env
from .mesh import (batch_sharding, create_mesh, create_multislice_mesh,
                   data_parallel_mesh, multislice_data_spec, named_sharding,
                   num_slices, replicated)
from .spmd import ShardedTrainStep, make_param_specs, megatron_param_rule
from .localsgd import LocalSGDStep  # noqa: E402,F401
from .dgc import DGCTrainStep, dgc_allreduce, topk_sparsify  # noqa: E402,F401
from .long_context import ring_attention, ulysses_attention  # noqa: E402,F401
