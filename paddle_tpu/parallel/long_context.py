"""Long-context attention: ring attention + Ulysses sequence parallelism.

The reference (2020-era) has no sequence/context parallelism (SURVEY.md §5
"Long-context: Absent") — its long-sequence story was recompute+pipeline.
This module provides the modern TPU-native capability the survey schedules
as the idiomatic equivalent:

- **Ring attention** (context parallelism): Q stays put, K/V shards rotate
  around the 'sp' mesh axis via lax.ppermute over ICI while an
  online-softmax accumulator folds in each block — peak memory O(T/N),
  comms overlap with the per-block matmuls (XLA pipelines the ppermute
  with the dot). Composes the same math as kernels/flash_attention.py,
  distributed across chips.
- **Ulysses** (sequence → head re-sharding): all-to-all flips the sharding
  from the sequence axis to the head axis, runs ordinary (flash) attention
  locally with full sequence per head group, and flips back. Cheaper than
  ring for models with many heads; needs head_count % sp == 0.

Both run inside shard_map; wrappers build the shard_map for [B, H, T, D]
inputs sharded on T.
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P

from .collective import _axis_size
from ._shard_map import shard_map as _compat_shard_map


def _shard_map(fn, mesh, in_specs, out_specs):
    # check_vma=False: carries mix replicated inits with ppermute-varying
    # values, which strict VMA checking rejects
    return _compat_shard_map(fn, mesh=mesh, in_specs=in_specs,
                             out_specs=out_specs, check_vma=False)

_NEG_INF = -1e30


def _default_use_flash(head_dim: int) -> bool:
    """One gate for both long-context paths (ring + Ulysses): the flash
    kernel is the default local attention whenever Pallas can lower it
    (TPU + lane-aligned head dim). O(T) memory is the point of these
    paths, so the gate deliberately ignores flash_attention_min_seq."""
    from ..kernels import pallas_enabled
    return pallas_enabled() and head_dim % 8 == 0


def _ring_attention_local(q, k, v, axis: str, causal: bool,
                          scale: Optional[float]):
    """Runs inside shard_map. q/k/v: [B, H, Tl, D] local shards."""
    n = _axis_size(axis)
    idx = lax.axis_index(axis)
    t_local = q.shape[2]
    d = q.shape[-1]
    if scale is None:
        scale = 1.0 / (d ** 0.5)
    qf = q.astype(jnp.float32) * scale
    perm = [(i, (i + 1) % n) for i in range(n)]

    q_pos = (idx * t_local
             + lax.broadcasted_iota(jnp.int32, (t_local, t_local), 0))

    def fold(acc, m_prev, l_prev, k_cur, v_cur, step):
        # K/V arriving at `step` originated on rank (idx - step) mod n
        src = (idx - step) % n
        s = jnp.einsum("bhqd,bhkd->bhqk", qf, k_cur.astype(jnp.float32))
        if causal:
            k_pos = (src * t_local
                     + lax.broadcasted_iota(jnp.int32,
                                            (t_local, t_local), 1))
            s = jnp.where(q_pos >= k_pos, s, _NEG_INF)
        m_cur = jnp.max(s, axis=-1, keepdims=True)
        m_new = jnp.maximum(m_prev, m_cur)
        p = jnp.exp(s - m_new)
        alpha = jnp.exp(m_prev - m_new)
        l_new = l_prev * alpha + jnp.sum(p, axis=-1, keepdims=True)
        acc = acc * alpha + jnp.einsum(
            "bhqk,bhkd->bhqd", p, v_cur.astype(jnp.float32))
        return acc, m_new, l_new

    def block(carry, step):
        acc, m_prev, l_prev, k_cur, v_cur = carry
        acc, m_new, l_new = fold(acc, m_prev, l_prev, k_cur, v_cur, step)
        # rotate K/V one hop around the ring (overlaps with next block)
        k_next = lax.ppermute(k_cur, axis, perm)
        v_next = lax.ppermute(v_cur, axis, perm)
        return (acc, m_new, l_new, k_next, v_next), None

    b, h = q.shape[:2]
    acc0 = jnp.zeros((b, h, t_local, d), jnp.float32)
    m0 = jnp.full((b, h, t_local, 1), _NEG_INF, jnp.float32)
    l0 = jnp.zeros((b, h, t_local, 1), jnp.float32)
    carry = (acc0, m0, l0, k, v)
    if n > 1:
        # scan the first n-1 blocks (each ends with a rotation); the last
        # block folds outside the scan so its K/V are not rotated again
        carry, _ = lax.scan(block, carry, jnp.arange(n - 1))
    acc, m_prev, l_prev, k_last, v_last = carry
    acc, _, l_fin = fold(acc, m_prev, l_prev, k_last, v_last, n - 1)
    return (acc / jnp.maximum(l_fin, 1e-30)).astype(q.dtype)


def _ring_attention_local_flash(q, k, v, axis: str, causal: bool,
                                scale: Optional[float],
                                interpret: bool):
    """Ring attention whose per-hop local attention is the Pallas flash
    kernel — O(Tl) memory on-rank instead of the XLA fold's [Tl, Tl]
    score blocks, so each rank can hold a much longer local context.

    Per hop the kernel returns ``(o_i, lse_i)``; the exact merge is
    ``out = sum_i exp(lse_i - m) o_i / sum_i exp(lse_i - m)`` (both
    outputs differentiable — kernels.flash_attention_with_lse folds the
    lse cotangent into the backward's delta). Causal routing per hop:
    K/V originating before this rank attend fully, the diagonal hop
    runs the kernel's causal mode, later ranks are skipped with
    lse = -inf (zero weight).
    """
    from ..kernels.flash_attention import (_NEG_INF,
                                           flash_attention_with_lse)
    n = _axis_size(axis)
    idx = lax.axis_index(axis)
    b, h, t_local, d = q.shape
    if scale is None:
        scale = 1.0 / (d ** 0.5)
    perm = [(i, (i + 1) % n) for i in range(n)]

    def partial_attn(k_cur, v_cur, step):
        if not causal:
            o, lse = flash_attention_with_lse(q, k_cur, v_cur, False,
                                              scale, interpret)
            return o.astype(jnp.float32), lse
        src = (idx - step) % n

        def diag(_):
            return flash_attention_with_lse(q, k_cur, v_cur, True,
                                            scale, interpret)

        def full(_):
            return flash_attention_with_lse(q, k_cur, v_cur, False,
                                            scale, interpret)

        def skip(_):
            return (jnp.zeros((b, h, t_local, d), q.dtype),
                    jnp.full((b, h, t_local), _NEG_INF, jnp.float32))

        o, lse = lax.cond(
            src == idx, diag,
            lambda u: lax.cond(src < idx, full, skip, u), None)
        return o.astype(jnp.float32), lse

    def merge(carry, o, lse):
        acc, m_prev, l_prev = carry
        lse_e = lse[..., None]                       # [B, H, Tl, 1]
        m_new = jnp.maximum(m_prev, lse_e)
        w_prev = jnp.exp(m_prev - m_new)
        w_cur = jnp.exp(lse_e - m_new)
        return (acc * w_prev + o * w_cur,
                m_new,
                l_prev * w_prev + w_cur)

    def block(carry, step):
        acc_ml, k_cur, v_cur = carry
        o, lse = partial_attn(k_cur, v_cur, step)
        acc_ml = merge(acc_ml, o, lse)
        k_next = lax.ppermute(k_cur, axis, perm)
        v_next = lax.ppermute(v_cur, axis, perm)
        return (acc_ml, k_next, v_next), None

    acc0 = jnp.zeros((b, h, t_local, d), jnp.float32)
    m0 = jnp.full((b, h, t_local, 1), _NEG_INF, jnp.float32)
    l0 = jnp.zeros((b, h, t_local, 1), jnp.float32)
    carry = ((acc0, m0, l0), k, v)
    if n > 1:
        carry, _ = lax.scan(block, carry, jnp.arange(n - 1))
    (acc, m_prev, l_prev), k_last, v_last = carry
    o, lse = partial_attn(k_last, v_last, n - 1)
    acc, _, l_fin = merge((acc, m_prev, l_prev), o, lse)
    return (acc / jnp.maximum(l_fin, 1e-30)).astype(q.dtype)


def ring_attention(q, k, v, mesh: Mesh, axis: str = "sp",
                   causal: bool = False, scale: Optional[float] = None,
                   use_flash: Optional[bool] = None,
                   interpret: bool = False):
    """Context-parallel attention over full [B, H, T, D] inputs; T is
    sharded over ``axis``, output keeps the same sharding.

    ``use_flash`` selects the per-hop implementation: the Pallas flash
    kernel (O(Tl) on-rank memory) or the XLA online-softmax fold.
    Default (None) routes like kernels.maybe_flash_attention: flash on
    TPU when the pallas master switch is on. ``interpret`` runs the
    kernel under the Pallas interpreter (CPU tests)."""
    if use_flash is None:
        use_flash = _default_use_flash(q.shape[-1])
    spec = P(None, None, axis, None)

    def fn(q_, k_, v_):
        if use_flash:
            return _ring_attention_local_flash(q_, k_, v_, axis, causal,
                                               scale, interpret)
        return _ring_attention_local(q_, k_, v_, axis, causal, scale)

    return _shard_map(fn, mesh=mesh, in_specs=(spec, spec, spec),
                      out_specs=spec)(q, k, v)


def _ulysses_local(q, k, v, axis: str, causal: bool,
                   scale: Optional[float], use_flash: bool,
                   interpret: bool):
    """Inside shard_map: seq-sharded [B, H, Tl, D] → a2a to head-sharded
    [B, H/n, T, D] → local flash attention → a2a back."""
    n = _axis_size(axis)

    def seq_to_head(x):
        # split heads across ranks, gather full sequence
        return lax.all_to_all(x, axis, split_axis=1, concat_axis=2,
                              tiled=True)

    def head_to_seq(x):
        return lax.all_to_all(x, axis, split_axis=2, concat_axis=1,
                              tiled=True)

    qh = seq_to_head(q)
    kh = seq_to_head(k)
    vh = seq_to_head(v)
    # Route to the flash kernel DIRECTLY (same gate as ring_attention),
    # not via maybe_flash_attention's min-seq gate: the gathered
    # sequence here is the full T, so O(T) memory is the point of this
    # path regardless of the measured speed crossover.
    if use_flash:
        from ..kernels.flash_attention import flash_attention
        out = flash_attention(qh, kh, vh, causal=causal, scale=scale,
                              interpret=interpret)
    else:
        from ..ops.attention import scaled_dot_product_attention
        out = scaled_dot_product_attention(qh, kh, vh, causal=causal,
                                           scale=scale)
    return head_to_seq(out)


def ulysses_attention(q, k, v, mesh: Mesh, axis: str = "sp",
                      causal: bool = False,
                      scale: Optional[float] = None,
                      use_flash: Optional[bool] = None,
                      interpret: bool = False):
    """Ulysses sequence parallelism; needs num_heads % mesh[axis] == 0.

    ``use_flash``/``interpret`` mirror ring_attention: flash is the
    default local attention whenever Pallas can lower it, and
    ``interpret`` runs the kernel under the Pallas interpreter so the
    flash branch is testable off-TPU."""
    n = mesh.shape[axis]
    if q.shape[1] % n != 0:
        raise ValueError(
            f"num_heads={q.shape[1]} not divisible by sp={n}; "
            "use ring_attention")
    if use_flash is None:
        use_flash = _default_use_flash(q.shape[-1])
    spec = P(None, None, axis, None)

    def fn(q_, k_, v_):
        return _ulysses_local(q_, k_, v_, axis, causal, scale,
                              use_flash, interpret)

    return _shard_map(fn, mesh=mesh, in_specs=(spec, spec, spec),
                      out_specs=spec)(q, k, v)
