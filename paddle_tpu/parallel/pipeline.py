"""Pipeline parallelism.

TPU-native redesign of the reference's pipeline stack
(/root/reference/python/paddle/fluid/optimizer.py:3627 PipelineOptimizer
splits the program by device_guard into section programs;
framework/pipeline_trainer.cc:24 + section_worker.cc:82 run sections in
threads, passing tensors via queues/condvars). That thread/queue schedule
doesn't map to XLA; the TPU idiom is **SPMD pipelining inside one compiled
program**: every device holds one stage's params (stacked pytree sharded on
a 'pp' mesh axis), and a fori_loop runs the GPipe schedule where activations
hop stage→stage via lax.ppermute over ICI. Bubbles are the standard
(S-1)/(M+S-1) GPipe fraction; microbatch count M trades bubble for memory.

The stage function must be shape-preserving (transformer-trunk style);
embedding/head run outside the pipeline (as the reference runs the reader
and loss sections on first/last devices).
"""

from __future__ import annotations

from typing import Any, Callable, Optional, Sequence

from ..core import as_label_tuple
import jax

from ..core import random as _random
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P
from ._shard_map import shard_map as _shard_map

from ..nn.layer import Layer, functional_call


def stack_stage_params(stage_layers: Sequence[Layer]):
    """Stack per-stage param dicts along a new leading 'stage' axis.

    All stages must share one structure (homogeneous trunk)."""
    dicts = [l.param_dict() for l in stage_layers]
    return jax.tree.map(lambda *xs: jnp.stack(xs, axis=0), *dicts)


def gpipe(stage_fn: Callable, stacked_params, x, num_microbatches: int,
          mesh: Mesh, axis: str = "pp"):
    """Run the GPipe schedule over the 'pp' mesh axis.

    stage_fn(params_slice, x_mb) -> y_mb, shape-preserving.
    stacked_params: pytree with leading dim == n_stages (sharded on axis).
    x: [B, ...] with B divisible by num_microbatches.
    Returns y with x's shape: the composition of all stages.
    """
    n_stages = mesh.shape[axis]
    m = num_microbatches
    b = x.shape[0]
    mb = b // m
    micro = x.reshape((m, mb) + x.shape[1:])

    fwd_perm = [(i, (i + 1) % n_stages) for i in range(n_stages)]

    def spmd_fn(params, micro_all):
        # params leaves: [1, ...] (this device's stage); squeeze stage dim
        local = jax.tree.map(lambda p: p[0], params)
        stage_id = lax.axis_index(axis)
        is_first = stage_id == 0
        is_last = stage_id == n_stages - 1

        zero_mb = jnp.zeros_like(micro_all[0])
        outputs0 = jnp.zeros_like(micro_all)

        def tick(t, carry):
            recv, outputs = carry
            # stage 0 consumes microbatch t (while valid); others consume
            # what arrived from the previous stage last tick
            idx = jnp.minimum(t, m - 1)
            inp = jnp.where(is_first, micro_all[idx], recv)
            out = stage_fn(local, inp)
            # last stage records its result for microbatch t-(S-1)
            out_idx = t - (n_stages - 1)
            valid_out = jnp.logical_and(is_last, out_idx >= 0)
            outputs = lax.cond(
                valid_out,
                lambda o: lax.dynamic_update_index_in_dim(
                    o, out, jnp.maximum(out_idx, 0), axis=0),
                lambda o: o, outputs)
            recv_next = lax.ppermute(out, axis, fwd_perm)
            return (recv_next, outputs)

        _, outputs = lax.fori_loop(0, m + n_stages - 1, tick,
                                   (zero_mb, outputs0))
        # replicate the last stage's outputs to all devices: zero elsewhere
        # then psum over the stage axis
        outputs = jnp.where(is_last, outputs, jnp.zeros_like(outputs))
        return lax.psum(outputs, axis)

    param_specs = jax.tree.map(lambda _: P(axis), stacked_params)
    out = _shard_map(
        spmd_fn, mesh=mesh,
        in_specs=(param_specs, P()),
        out_specs=P(),
        check_vma=False,
    )(stacked_params, micro)
    return out.reshape((b,) + x.shape[1:])


class GPipeTrainStep:
    """Full pipeline-parallel training step: embed → pipelined trunk →
    head, jax.grad through the whole schedule, optimizer update.

    Replaces PipelineOptimizer + PipelineTrainer + SectionWorker for the
    TPU: one compiled program, grads flow backward through the same
    ppermute schedule automatically (XLA transposes ppermute).
    """

    def __init__(self, embed: Layer, stage_layers: Sequence[Layer],
                 head: Layer, optimizer, loss_fn: Callable, mesh: Mesh,
                 num_microbatches: int, axis: str = "pp",
                 remat_stages: bool = False, seed: int = 0) -> None:
        self.embed = embed
        self.head = head
        self.stage_layers = list(stage_layers)
        self.optimizer = optimizer
        self.loss_fn = loss_fn
        self.mesh = mesh
        self.m = num_microbatches
        self.axis = axis
        n_stages = mesh.shape[axis]
        assert len(self.stage_layers) == n_stages, \
            f"need {n_stages} stages, got {len(self.stage_layers)}"

        params = {
            "embed": embed.param_dict(),
            "stages": stack_stage_params(self.stage_layers),
            "head": head.param_dict(),
        }
        opt_state = optimizer.init(params)
        if "fused" in opt_state:
            raise ValueError(
                "optimizer_fused_state is incompatible with pipeline "
                "stage-stacked optimizer state; construct the optimizer "
                "with fused_state=False")
        stage_spec = jax.tree.map(lambda _: P(axis), params["stages"])
        self.param_specs = {
            "embed": jax.tree.map(lambda _: P(), params["embed"]),
            "stages": stage_spec,
            "head": jax.tree.map(lambda _: P(), params["head"]),
        }
        opt_slot_specs = {
            "step": P(),
            "slots": {
                "embed": jax.tree.map(lambda _: P(),
                                      opt_state["slots"]["embed"]),
                "stages": jax.tree.map(
                    lambda x: P(axis) if hasattr(x, "ndim") and x.ndim > 0
                    else P(), opt_state["slots"]["stages"]),
                "head": jax.tree.map(lambda _: P(),
                                     opt_state["slots"]["head"]),
            },
        }
        self.state_specs = {"params": self.param_specs,
                            "opt": opt_slot_specs, "rng": P()}
        state = {"params": params, "opt": opt_state,
                 "rng": _random.make_key(seed)}
        shardings = jax.tree.map(lambda s: NamedSharding(mesh, s),
                                 self.state_specs,
                                 is_leaf=lambda s: isinstance(s, P))
        self.state = jax.device_put(state, shardings)
        self._jitted = jax.jit(self._step, donate_argnums=(0,),
                               in_shardings=(shardings, None),
                               out_shardings=(shardings, None))

        template = self.stage_layers[0]

        def stage_fn(stage_params, x_mb):
            return functional_call(template, stage_params, None, x_mb)

        if remat_stages:
            # GPipe's peak lives in the stored per-microbatch stage
            # activations; rematerializing the stage body trades one
            # extra stage forward in the backward pass for dropping
            # those intermediates — the reference exposes the same knob
            # as recompute+pipeline (DistributedStrategy.recompute)
            stage_fn = jax.checkpoint(stage_fn)
        self._stage_fn = stage_fn

    def _forward(self, params, x):
        h = functional_call(self.embed, params["embed"], None, x)
        h = gpipe(self._stage_fn, params["stages"], h, self.m, self.mesh,
                  self.axis)
        return functional_call(self.head, params["head"], None, h)

    def _step(self, state, batch):
        rng, _ = jax.random.split(state["rng"])

        def loss_of(p):
            out = self._forward(p, batch["x"])
            return self.loss_fn(out, *batch["labels"])

        loss, grads = jax.value_and_grad(loss_of)(state["params"])
        new_params, new_opt = self.optimizer.apply_gradients(
            state["params"], grads, state["opt"],
            lr_override=batch.get("lr"))
        return ({"params": new_params, "opt": new_opt, "rng": rng},
                {"loss": loss})

    def __call__(self, x, labels=()):
        from .spmd import inject_host_lr
        batch = inject_host_lr({"x": x, "labels": as_label_tuple(labels)},
                               self.optimizer)
        with self.mesh:
            self.state, metrics = self._jitted(self.state, batch)
        return metrics
