"""DGC: deep gradient compression — top-k sparsified grad exchange.

TPU-native rebuild of the reference's DGC stack (DGCMomentumOptimizer
/root/reference/python/paddle/fluid/optimizer.py:1142, dgc_op +
SparseAllReduce op-handle details/sparse_all_reduce_op_handle.cc, external
libdgc): each worker keeps only the top-k largest-magnitude gradient
entries, accumulates the rest locally (error feedback + momentum
correction per the DGC paper), and exchanges just the sparse entries.

On TPU the sparse exchange is an ``all_gather`` of (values, indices) over
the dp axis inside shard_map — k is small so the gather is cheap — then a
dense scatter-add rebuild. XLA cannot do this transformation itself
because it changes numerics; everything else (the dense path) stays with
the automatic pjit collectives.

Numerics follow the DGC paper (and the reference's DGCMomentumOptimizer,
which replaces the plain momentum update rather than stacking on top of
it):

- **momentum correction** — each replica accumulates a local velocity
  ``u = m*u + g`` and sparsifies ``residual + u``, so the exchanged
  entries carry momentum-accumulated mass instead of raw gradients
  (momentum applied *after* sparsification amplifies the bursty top-k
  arrivals and destabilises early training);
- **momentum factor masking** — entries that were transmitted are
  zeroed in the velocity too, so stale momentum can't re-send them;
- the outer optimizer's own momentum is neutralised at trace time
  (``_with_zeroed_attr``) because the velocity already carries it —
  double-applying m would square the effective momentum;
- **dense warm-up** — the first ``rampup_steps`` steps exchange the
  full velocity densely (exactly momentum SGD), the paper's warm-up
  that lets early large gradients through before sparsity bites.
"""

from __future__ import annotations

from typing import Callable, Dict, Optional

from ..core import as_label_tuple
import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P
from ._shard_map import shard_map as _shard_map
from .collective import _axis_size

from ..core import random as _random
from ..nn.layer import Layer, functional_call
from ..optimizer import Optimizer


def topk_sparsify(g: jnp.ndarray, k: int):
    """Keep the k largest-|g| entries. Returns (values[k], indices[k],
    residual) with residual = g minus the kept entries."""
    flat = g.reshape(-1)
    _, idx = lax.top_k(jnp.abs(flat), k)
    vals = flat[idx]
    residual = flat.at[idx].set(0.0).reshape(g.shape)
    return vals, idx, residual


def dgc_allreduce(local_grad: jnp.ndarray, residual: jnp.ndarray,
                  axis: str, sparsity: float = 0.99):
    """Compress-exchange-rebuild one gradient tensor inside shard_map.

    local_grad: this replica's gradient; residual: error feedback carried
    from previous steps. Returns (dense mean gradient, new residual).
    """
    n = _axis_size(axis)
    acc = local_grad + residual
    size = acc.size
    k = max(1, int(size * (1.0 - sparsity)))
    vals, idx, new_residual = topk_sparsify(acc, k)
    # gather all replicas' sparse entries: [n, k]
    all_vals = lax.all_gather(vals, axis)
    all_idx = lax.all_gather(idx, axis)
    dense = jnp.zeros((size,), acc.dtype)
    dense = dense.at[all_idx.reshape(-1)].add(all_vals.reshape(-1))
    return (dense / n).reshape(acc.shape), new_residual


def dgc_momentum_exchange(grad: jnp.ndarray, velocity: jnp.ndarray,
                          residual: jnp.ndarray, use_dgc, axis: str,
                          sparsity: float, momentum: float):
    """One leaf of the momentum-corrected DGC step (paper §3.2).

    Per-replica: accumulate velocity ``u = m*u + g``, add the error
    residual, top-k sparsify, exchange the sparse entries, and apply
    momentum factor masking (transmitted entries leave both residual
    and velocity). ``use_dgc`` is a traced bool — False (warm-up)
    delivers ``pmean(residual + u)`` densely and carries the velocity
    forward untouched, which is exactly momentum SGD.

    Returns (delivered dense update, new velocity, new residual).
    """
    n = _axis_size(axis)
    u = momentum * velocity + grad
    acc = residual + u
    size = acc.size
    k = max(1, int(size * (1.0 - sparsity)))
    vals, idx, sparse_residual = topk_sparsify(acc, k)
    sparse_velocity = u.reshape(-1).at[idx].set(0.0).reshape(u.shape)
    all_vals = lax.all_gather(vals, axis)
    all_idx = lax.all_gather(idx, axis)
    sparse_update = jnp.zeros((size,), acc.dtype) \
        .at[all_idx.reshape(-1)].add(all_vals.reshape(-1)) \
        .reshape(acc.shape) / n
    dense_update = lax.pmean(acc, axis)
    update = jnp.where(use_dgc, sparse_update, dense_update)
    new_velocity = jnp.where(use_dgc, sparse_velocity, u)
    new_residual = jnp.where(use_dgc, sparse_residual,
                             jnp.zeros_like(residual))
    return update, new_velocity, new_residual


class DGCTrainStep:
    """Data-parallel train step whose grad allreduce is DGC-compressed.

    Per-replica grads are computed under shard_map (no automatic psum),
    momentum-corrected, compressed, exchanged sparsely, and fed to the
    optimizer identically on every replica (params stay replicated).
    The optimizer's own momentum is zeroed at trace time — the DGC
    velocity subsumes it (reference: DGCMomentumOptimizer *replaces*
    Momentum rather than wrapping it).
    """

    def __init__(self, model: Layer, optimizer: Optimizer,
                 loss_fn: Callable, mesh: Mesh, sparsity: float = 0.99,
                 rampup_steps: int = 3, seed: int = 0,
                 dp_axis: str = "dp") -> None:
        self.model = model
        self.optimizer = optimizer
        self.loss_fn = loss_fn
        self.mesh = mesh
        self.sparsity = float(sparsity)
        self.rampup_steps = int(rampup_steps)
        self.axis = dp_axis
        # momentum correction coefficient: lifted from the optimizer
        # (Momentum/LarsMomentum); optimizers without a momentum attr
        # (Adam, SGD) run with m=0 — velocity degenerates to the grad
        self.momentum = float(getattr(optimizer, "momentum", 0.0))

        params = model.param_dict()
        buffers = model.buffer_dict()
        opt_state = optimizer.init(params)
        state = {
            "params": params,
            "buffers": buffers,
            "opt": opt_state,
            "residual": jax.tree.map(jnp.zeros_like, params),
            "velocity": jax.tree.map(jnp.zeros_like, params),
            "rng": _random.make_key(seed),
            "step_count": jnp.zeros((), jnp.int32),
        }

        def rep(tree):
            return jax.tree.map(lambda _: P(), tree)

        self.state_specs = {
            "params": rep(params), "buffers": rep(buffers),
            "opt": rep(opt_state), "residual": rep(params),
            "velocity": rep(params),
            "rng": P(), "step_count": P(),
        }
        shardings = jax.tree.map(lambda s: NamedSharding(mesh, s),
                                 self.state_specs,
                                 is_leaf=lambda x: isinstance(x, P))
        self.state = jax.device_put(state, shardings)
        self.batch_sharding = NamedSharding(mesh, P(dp_axis))

        from .spmd import host_lr_of
        self._host_lr_active = host_lr_of(optimizer) is not None

        def step(state, batch, rep_kwargs, lr):
            params = state["params"]
            buffers = state["buffers"]
            rng, step_key = jax.random.split(state["rng"])

            def loss_of(p):
                with _random.rng_scope(default=step_key, dropout=step_key):
                    out, new_buffers = functional_call(
                        self.model, p, buffers, *batch["args"],
                        **batch.get("kwargs", {}), **rep_kwargs,
                        capture_buffers=True)
                return self.loss_fn(out, *batch["labels"]), new_buffers

            (loss, new_buffers), grads = jax.value_and_grad(
                loss_of, has_aux=True)(params)

            # momentum-corrected compress+exchange, leaf-wise over the
            # grads PYTREE — positional and kwargs-fed batches produce
            # the same tree, so velocity/residual always pair with the
            # right leaf regardless of how the batch arrived; rampup
            # runs dense (ref: DGCMomentumOptimizer rampup_begin_step)
            use_dgc = state["step_count"] >= self.rampup_steps
            exchanged = jax.tree.map(
                lambda g, u, r: dgc_momentum_exchange(
                    g, u, r, use_dgc, dp_axis, self.sparsity,
                    self.momentum),
                grads, state["velocity"], state["residual"])
            is_triple = lambda x: isinstance(x, tuple)  # noqa: E731
            new_grads = jax.tree.map(lambda t: t[0], exchanged,
                                     is_leaf=is_triple)
            new_vel = jax.tree.map(lambda t: t[1], exchanged,
                                   is_leaf=is_triple)
            new_res = jax.tree.map(lambda t: t[2], exchanged,
                                   is_leaf=is_triple)

            def apply():
                return self.optimizer.apply_gradients(
                    params, new_grads, state["opt"],
                    lr_override=lr if self._host_lr_active else None)

            if self.momentum:
                # trace-time momentum bypass: the exchanged update
                # already carries the velocity accumulation
                new_params, new_opt = self.optimizer._with_zeroed_attr(
                    "momentum", apply)
            else:
                new_params, new_opt = apply()
            loss = lax.pmean(loss, dp_axis)
            return ({"params": new_params, "buffers": new_buffers,
                     "opt": new_opt, "residual": new_res,
                     "velocity": new_vel, "rng": rng,
                     "step_count": state["step_count"] + 1},
                    {"loss": loss})

        # host-driven LR rides as its own replicated scalar argument — a
        # rank-0 leaf can't satisfy the batch's P(dp_axis) shard_map spec
        self._jitted = jax.jit(
            _shard_map(step, mesh=mesh,
                          in_specs=(self.state_specs, P(dp_axis), P(),
                                    P()),
                          out_specs=(self.state_specs, P()),
                          check_vma=False),
            donate_argnums=(0,))

    def __call__(self, *args, labels=(), **kwargs):
        from .spmd import host_lr_of
        from .spmd import (leading_batch_size,
                           split_kwargs_by_shardable)
        # same kwargs split as LocalSGDStep (see _split_kwargs)
        sh_kwargs, rep_kwargs = split_kwargs_by_shardable(
            kwargs, leading_batch_size(args, labels),
            note=self.mesh.shape[self.axis] > 1)
        batch = {"args": args, "labels": as_label_tuple(labels),
                 "kwargs": sh_kwargs}
        lr = host_lr_of(self.optimizer) if self._host_lr_active else 0.0
        with self.mesh:
            self.state, metrics = self._jitted(self.state, batch,
                                               rep_kwargs,
                                               jnp.float32(lr))
        return metrics

    @property
    def params(self):
        return self.state["params"]
