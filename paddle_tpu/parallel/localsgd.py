"""LocalSGD: per-replica local updates with periodic parameter averaging.

TPU-native rebuild of the reference's LocalSGD meta-optimizer
(/root/reference/python/paddle/distributed/fleet/meta_optimizers/
localsgd_optimizer.py: each worker steps locally, every k steps params are
allreduce-averaged). There each GPU process owns its own params; here the
replicas live in ONE SPMD program: every param carries a leading replica
axis sharded over ``dp``, local steps run under shard_map with **no
cross-replica collective**, and the sync step pmean-averages params (and
resets optimizer slots' divergence) over the dp axis. Two compiled
programs — Python picks sync every k-th call, mirroring the reference's
step-counter conditional block.
"""

from __future__ import annotations

from typing import Callable, Dict, Optional

from ..core import as_label_tuple
import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P
from ._shard_map import shard_map as _shard_map

from ..core import random as _random
from ..nn.layer import Layer, functional_call
from ..optimizer import Optimizer


class LocalSGDStep:
    """Train step with k-step local updates then cross-replica averaging.

    Batch layout: arrays with global batch leading dim, sharded over dp
    like ShardedTrainStep; each replica trains on its own shard.
    """

    def __init__(self, model: Layer, optimizer: Optimizer,
                 loss_fn: Callable, mesh: Mesh, k_steps: int = 4,
                 seed: int = 0, dp_axis: str = "dp") -> None:
        self.model = model
        self.optimizer = optimizer
        self.loss_fn = loss_fn
        self.mesh = mesh
        self.k_steps = max(1, int(k_steps))
        self.axis = dp_axis
        self._calls = 0
        n = mesh.shape[dp_axis]
        self.n_replicas = n

        params = model.param_dict()
        buffers = model.buffer_dict()
        opt_state = optimizer.init(params)
        if "fused" in opt_state:
            raise ValueError(
                "optimizer_fused_state is incompatible with LocalSGD's "
                "replica-stacked optimizer state; construct the "
                "optimizer with fused_state=False")

        def stack(tree):
            return jax.tree.map(
                lambda x: jnp.broadcast_to(
                    x[None], (n,) + tuple(x.shape)).astype(x.dtype)
                if hasattr(x, "ndim") else x, tree)

        # replica-stacked state: leading axis = replica, sharded over dp
        state = {
            "params": stack(params),
            "buffers": stack(buffers),
            "opt": {"step": opt_state["step"],
                    "slots": stack(opt_state["slots"])},
            "rng": jax.random.split(_random.make_key(seed), n),
        }

        def rep_spec(tree):
            return jax.tree.map(
                lambda x: P(dp_axis) if hasattr(x, "ndim") and x.ndim > 0
                else P(), tree)

        self.state_specs = {
            "params": rep_spec(state["params"]),
            "buffers": rep_spec(state["buffers"]),
            "opt": {"step": P(), "slots": rep_spec(state["opt"]["slots"])},
            "rng": P(dp_axis),
        }
        shardings = jax.tree.map(lambda s: NamedSharding(mesh, s),
                                 self.state_specs,
                                 is_leaf=lambda x: isinstance(x, P))
        self.state = jax.device_put(state, shardings)
        self.batch_sharding = NamedSharding(mesh, P(dp_axis))

        from .spmd import host_lr_of
        self._host_lr_active = host_lr_of(optimizer) is not None

        def local_step(state, batch, rep_kwargs, lr):
            # inside shard_map: leading replica axis is size 1 locally
            def unstack(tree):
                return jax.tree.map(
                    lambda x: x[0] if hasattr(x, "ndim") and x.ndim > 0
                    else x, tree)

            def restack(tree):
                return jax.tree.map(
                    lambda x: x[None] if hasattr(x, "ndim") else x, tree)

            params = unstack(state["params"])
            buffers = unstack(state["buffers"])
            slots = unstack(state["opt"]["slots"])
            rng = state["rng"][0]
            rng, step_key = jax.random.split(rng)

            def loss_of(p):
                with _random.rng_scope(default=step_key, dropout=step_key):
                    out, new_buffers = functional_call(
                        self.model, p, buffers, *batch["args"],
                        **batch.get("kwargs", {}), **rep_kwargs,
                        capture_buffers=True)
                return self.loss_fn(out, *batch["labels"]), new_buffers

            (loss, new_buffers), grads = jax.value_and_grad(
                loss_of, has_aux=True)(params)
            new_params, new_opt = self.optimizer.apply_gradients(
                params, grads, {"step": state["opt"]["step"],
                                "slots": slots},
                lr_override=lr if self._host_lr_active else None)
            # mean loss across replicas for reporting only
            loss = lax.pmean(loss, dp_axis)
            return ({"params": restack(new_params),
                     "buffers": restack(new_buffers),
                     "opt": {"step": new_opt["step"],
                             "slots": restack(new_opt["slots"])},
                     "rng": rng[None]}, {"loss": loss})

        def sync(state):
            # average params across replicas (ref: localsgd_optimizer.py
            # allreduce(param)/nranks); optimizer slots averaged too so
            # replicas restart from identical state
            def avg(tree):
                return jax.tree.map(
                    lambda x: jnp.broadcast_to(
                        lax.pmean(x, dp_axis), x.shape)
                    if hasattr(x, "ndim") and x.ndim > 0 else x, tree)

            return {**state, "params": avg(state["params"]),
                    "opt": {"step": state["opt"]["step"],
                            "slots": avg(state["opt"]["slots"])}}

        smap = dict(mesh=mesh, check_vma=False)
        # host-driven LR rides as its own replicated scalar argument — a
        # rank-0 leaf can't satisfy the batch's P(dp_axis) shard_map spec
        self._local = jax.jit(
            _shard_map(local_step,
                          in_specs=(self.state_specs, P(dp_axis), P(),
                                    P()),
                          out_specs=(self.state_specs, P()), **smap),
            donate_argnums=(0,))
        self._sync = jax.jit(
            _shard_map(sync, in_specs=(self.state_specs,),
                          out_specs=self.state_specs, **smap),
            donate_argnums=(0,))

    def __call__(self, *args, labels=(), **kwargs):
        from .spmd import host_lr_of
        from .spmd import (leading_batch_size,
                           split_kwargs_by_shardable)
        # model-forward kwargs: dp-shardable leaves (leading dim
        # divisible by the dp size) ride the batch tree; the rest
        # (broadcast masks, tables, scalars) go replicated — the same
        # split ShardedTrainStep._place_batch makes
        sh_kwargs, rep_kwargs = split_kwargs_by_shardable(
            kwargs, leading_batch_size(args, labels),
            note=self.mesh.shape[self.axis] > 1)
        batch = {"args": args, "labels": as_label_tuple(labels),
                 "kwargs": sh_kwargs}
        lr = host_lr_of(self.optimizer) if self._host_lr_active else 0.0
        with self.mesh:
            self.state, metrics = self._local(self.state, batch,
                                              rep_kwargs,
                                              jnp.float32(lr))
            self._calls += 1
            if self._calls % self.k_steps == 0:
                self.state = self._sync(self.state)
        return metrics

    def averaged_params(self) -> Dict:
        """Replica-mean parameters (what the synced model would hold)."""
        return jax.tree.map(
            lambda x: jnp.mean(x, axis=0) if hasattr(x, "ndim") and
            x.ndim > 0 else x, self.state["params"])

    def replica_divergence(self) -> float:
        """Max abs spread across replicas — 0 right after a sync."""
        div = 0.0
        for v in jax.tree.leaves(self.state["params"]):
            if hasattr(v, "ndim") and v.ndim > 0:
                spread = jnp.max(jnp.abs(v - v[0:1]))
                div = max(div, float(spread))
        return div
