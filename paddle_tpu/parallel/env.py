"""Multi-process environment bootstrap.

TPU-native replacement for the reference's launch/bootstrap machinery
(/root/reference/python/paddle/distributed/launch.py:193 env plumbing,
c_gen_nccl_id_op.cc:49-60 id exchange over RPC, role_maker.py env parsing).
jax.distributed's coordination service plays the role of the gRPC
id-exchange server: PADDLE-style env vars are read for parity and mapped
onto jax.distributed.initialize.
"""

from __future__ import annotations

import os
from typing import Optional

import jax


class ParallelEnv:
    """(ref: dygraph/parallel.py ParallelEnv) env-derived rank info."""

    def __init__(self) -> None:
        self.rank = int(os.environ.get(
            "PADDLE_TRAINER_ID", os.environ.get("RANK", "0")))
        self.world_size = int(os.environ.get(
            "PADDLE_TRAINERS_NUM", os.environ.get("WORLD_SIZE", "1")))
        eps = os.environ.get("PADDLE_TRAINER_ENDPOINTS", "")
        self.trainer_endpoints = eps.split(",") if eps else []
        self.current_endpoint = os.environ.get(
            "PADDLE_CURRENT_ENDPOINT", "")

    @property
    def local_rank(self) -> int:
        return self.rank

    @property
    def nranks(self) -> int:
        return self.world_size


_initialized = False


def init_parallel_env(coordinator_address: Optional[str] = None,
                      num_processes: Optional[int] = None,
                      process_id: Optional[int] = None) -> ParallelEnv:
    """(ref: paddle.distributed.init_parallel_env). Single-process runs
    (incl. 1 process driving all local TPU chips) need no coordination
    service; multi-host runs initialize jax.distributed, whose coordination
    server replaces the reference's c_gen_nccl_id gRPC exchange."""
    global _initialized
    env = ParallelEnv()
    if _initialized:
        return env
    world = num_processes if num_processes is not None else env.world_size
    if world > 1:
        addr = coordinator_address
        if addr is None and env.trainer_endpoints:
            addr = env.trainer_endpoints[0]
        rank = process_id if process_id is not None else env.rank
        jax.distributed.initialize(coordinator_address=addr,
                                   num_processes=world, process_id=rank)
    _initialized = True
    return env


def get_rank() -> int:
    return ParallelEnv().rank


def get_world_size() -> int:
    return ParallelEnv().world_size
