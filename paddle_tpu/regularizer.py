"""Weight-decay regularizers.

Analogue of /root/reference/python/paddle/fluid/regularizer.py
(L1DecayRegularizer, L2DecayRegularizer — emitted as grad-append ops there;
here applied functionally inside the optimizer step).
"""

from __future__ import annotations

import jax.numpy as jnp


class WeightDecayRegularizer:
    def __call__(self, param, grad):
        raise NotImplementedError


class L2Decay(WeightDecayRegularizer):
    def __init__(self, coeff: float = 0.0) -> None:
        self.coeff = coeff

    def __call__(self, param, grad):
        return grad + self.coeff * param


class L1Decay(WeightDecayRegularizer):
    def __init__(self, coeff: float = 0.0) -> None:
        self.coeff = coeff

    def __call__(self, param, grad):
        return grad + self.coeff * jnp.sign(param)


L2DecayRegularizer = L2Decay
L1DecayRegularizer = L1Decay
