"""Tensor facade.

The reference's Tensor/LoDTensor/Variable triplet
(/root/reference/paddle/fluid/framework/tensor.h:37, lod_tensor.h:104,
variable.h:26) collapses on TPU to **jax.Array**: device placement, dtype,
and layout are owned by XLA/PJRT, autograd comes from functional transforms,
and ragged sequences use the dense-padded representation in ops.sequence.
``Tensor`` is therefore an alias plus conversion helpers — the idiomatic
design is that every framework function accepts and returns jax arrays
directly (zero wrapper overhead in traced code).
"""

from __future__ import annotations

from typing import Any, Optional

import jax
import jax.numpy as jnp
import numpy as np

from .core.dtype import convert_dtype
from .core.place import Place

Tensor = jax.Array


def to_tensor(data: Any, dtype=None, place: Optional[Place] = None,
              stop_gradient: bool = True) -> jax.Array:
    """Mirrors paddle.to_tensor. ``stop_gradient`` is advisory only —
    differentiation is selected by what you pass to jax.grad."""
    dt = convert_dtype(dtype) if dtype is not None else None
    arr = jnp.asarray(data, dtype=dt)
    if place is not None:
        arr = jax.device_put(arr, place.jax_device())
    return arr


def to_numpy(x: Any) -> np.ndarray:
    return np.asarray(x)


def is_tensor(x: Any) -> bool:
    return isinstance(x, jax.Array)
