"""Built-in datasets.

Capability parity with the reference's dataset package
(/root/reference/python/paddle/dataset/: mnist.py, cifar.py, imdb.py,
uci_housing.py; and the hapi vision datasets
python/paddle/incubate/hapi/datasets/). Design difference, on purpose:
the reference downloads from paddlepaddle.org at import; this package
**reads the standard archive formats from a local cache** (``DATA_HOME``,
default ``~/.cache/paddle_tpu/datasets``, override with env
``PT_DATA_HOME``) and never touches the network — TPU pods routinely run
with zero egress, and a training job that silently downloads is a bug
there. A missing file raises with the exact path and the official
source URL so the operator can stage it; every dataset also offers
``mode="synthetic"`` generating a small deterministic stand-in with the
real shapes/dtypes for smoke tests and CI.
"""

from __future__ import annotations

import gzip
import io
import os
import pickle
import struct
import tarfile
from typing import Callable, Optional, Sequence

import numpy as np

from ..data import Dataset

__all__ = ["DATA_HOME", "MNIST", "FashionMNIST", "Cifar10", "Cifar100",
           "UCIHousing", "Imdb", "Imikolov", "Movielens", "WMT14",
           "WMT16", "MQ2007", "Conll05", "Flowers", "VOC2012",
           "MovieReviews"]


def DATA_HOME() -> str:
    return os.environ.get(
        "PT_DATA_HOME",
        os.path.join(os.path.expanduser("~"), ".cache", "paddle_tpu",
                     "datasets"))


def _require(path: str, url_hint: str) -> str:
    if not os.path.exists(path):
        raise FileNotFoundError(
            f"dataset file not found: {path}\n"
            f"This framework does not download (zero-egress by design; "
            f"ref capability: paddle.dataset download cache). Stage the "
            f"file there manually, e.g. from {url_hint}, or use "
            f"mode='synthetic'.")
    return path


class _ArrayDataset(Dataset):
    """images/labels pair with an optional per-sample transform."""

    def __init__(self, images: np.ndarray, labels: np.ndarray,
                 transform: Optional[Callable] = None) -> None:
        self.images = images
        self.labels = labels
        self.transform = transform

    def __getitem__(self, idx: int):
        img = self.images[idx]
        if self.transform is not None:
            img = self.transform(img)
        return img, self.labels[idx]

    def __len__(self) -> int:
        return len(self.images)


def _parse_idx_images(path: str) -> np.ndarray:
    """MNIST idx3 format (ref: dataset/mnist.py reader_creator parses the
    same magic/count/rows/cols header)."""
    opener = gzip.open if path.endswith(".gz") else open
    with opener(path, "rb") as f:
        magic, n, rows, cols = struct.unpack(">IIII", f.read(16))
        if magic != 2051:
            raise ValueError(f"{path}: bad idx3 magic {magic}")
        data = np.frombuffer(f.read(n * rows * cols), np.uint8)
    return data.reshape(n, 1, rows, cols)


def _parse_idx_labels(path: str) -> np.ndarray:
    opener = gzip.open if path.endswith(".gz") else open
    with opener(path, "rb") as f:
        magic, n = struct.unpack(">II", f.read(8))
        if magic != 2049:
            raise ValueError(f"{path}: bad idx1 magic {magic}")
        return np.frombuffer(f.read(n), np.uint8).astype(np.int64)


class MNIST(_ArrayDataset):
    """(ref: dataset/mnist.py, hapi/datasets/mnist.py).

    Expects ``{DATA_HOME}/mnist/{train,t10k}-images-idx3-ubyte.gz`` (+
    labels). Images are float32 in [0, 1], shape [1, 28, 28].
    """

    _URL = "http://yann.lecun.com/exdb/mnist/"
    _NAME = "mnist"

    def __init__(self, mode: str = "train",
                 transform: Optional[Callable] = None,
                 data_home: Optional[str] = None) -> None:
        if mode == "synthetic":
            rng = np.random.default_rng(42)
            labels = np.arange(256) % 10
            means = rng.normal(0.3, 0.15, (10, 1, 28, 28))
            images = np.clip(
                means[labels] + rng.normal(0, 0.05, (256, 1, 28, 28)),
                0, 1).astype(np.float32)
            super().__init__(images, labels.astype(np.int64), transform)
            return
        prefix = {"train": "train", "test": "t10k"}[mode]
        home = data_home or os.path.join(DATA_HOME(), self._NAME)
        imgs = labs = None
        for ext in (".gz", ""):
            p = os.path.join(home, f"{prefix}-images-idx3-ubyte{ext}")
            if os.path.exists(p):
                imgs = _parse_idx_images(p)
                labs = _parse_idx_labels(os.path.join(
                    home, f"{prefix}-labels-idx1-ubyte{ext}"))
                break
        if imgs is None:
            _require(os.path.join(
                home, f"{prefix}-images-idx3-ubyte.gz"), self._URL)
        images = (imgs.astype(np.float32) / 255.0)
        super().__init__(images, labs, transform)


class FashionMNIST(MNIST):
    """Same idx format, different archive directory (ref:
    hapi/datasets/mnist.py FashionMNIST)."""

    _URL = "http://fashion-mnist.s3-website.eu-central-1.amazonaws.com/"
    _NAME = "fashion-mnist"


def _load_cifar_archive(path: str, n_classes: int, want_test: bool):
    """CIFAR python-pickle batches inside tar.gz (ref: dataset/cifar.py
    reader_creator: same 'data'/'labels'/'fine_labels' keys; cifar-10
    ships data_batch_1..5 + test_batch, cifar-100 ships train + test)."""
    images, labels = [], []
    key = "labels" if n_classes == 10 else "fine_labels"
    with tarfile.open(path, "r:*") as tar:
        for member in sorted(tar.getnames()):
            base = os.path.basename(member)
            is_train = base.startswith("data_batch") or base == "train"
            is_test = base in ("test_batch", "test")
            if want_test != is_test or not (is_train or is_test):
                continue
            f = tar.extractfile(member)
            if f is None:
                continue
            batch = pickle.loads(f.read(), encoding="latin1")
            images.append(np.asarray(batch["data"], np.uint8))
            labels.extend(batch[key])
    return images, labels


class Cifar10(_ArrayDataset):
    """(ref: dataset/cifar.py). Expects
    ``{DATA_HOME}/cifar/cifar-10-python.tar.gz``. Images float32 [0,1],
    shape [3, 32, 32]."""

    _URL = "https://www.cs.toronto.edu/~kriz/cifar-10-python.tar.gz"
    _N = 10

    def __init__(self, mode: str = "train",
                 transform: Optional[Callable] = None,
                 data_home: Optional[str] = None) -> None:
        if mode == "synthetic":
            rng = np.random.default_rng(7)
            labels = np.arange(128) % self._N
            means = rng.normal(0.45, 0.2, (self._N, 3, 32, 32))
            images = np.clip(
                means[labels % self._N]
                + rng.normal(0, 0.08, (128, 3, 32, 32)),
                0, 1).astype(np.float32)
            super().__init__(images, labels.astype(np.int64), transform)
            return
        home = data_home or os.path.join(DATA_HOME(), "cifar")
        path = _require(os.path.join(
            home, os.path.basename(self._URL)), self._URL)
        batches, labs = _load_cifar_archive(path, self._N,
                                            want_test=mode == "test")
        data = np.concatenate(batches).reshape(-1, 3, 32, 32)
        super().__init__(data.astype(np.float32) / 255.0,
                         np.asarray(labs, np.int64), transform)


class Cifar100(Cifar10):
    _URL = "https://www.cs.toronto.edu/~kriz/cifar-100-python.tar.gz"
    _N = 100


class UCIHousing(Dataset):
    """(ref: dataset/uci_housing.py — 13 features, normalized, 80/20
    train/test split by the same UCI_TRAIN_DATA ratio)."""

    _URL = ("https://archive.ics.uci.edu/ml/machine-learning-databases/"
            "housing/housing.data")

    def __init__(self, mode: str = "train",
                 data_home: Optional[str] = None) -> None:
        if mode == "synthetic":
            rng = np.random.default_rng(3)
            x = rng.normal(0, 1, (100, 13)).astype(np.float32)
            w = rng.normal(0, 1, (13,)).astype(np.float32)
            y = (x @ w + rng.normal(0, 0.1, (100,))).astype(np.float32)
            self.x, self.y = x, y[:, None]
            return
        home = data_home or os.path.join(DATA_HOME(), "uci_housing")
        path = _require(os.path.join(home, "housing.data"), self._URL)
        raw = np.loadtxt(path, dtype=np.float32)
        feats, target = raw[:, :-1], raw[:, -1:]
        # normalize per feature (ref: feature_range maximums/minimums)
        mins, maxs = feats.min(0), feats.max(0)
        feats = (feats - mins) / np.maximum(maxs - mins, 1e-12)
        split = int(len(feats) * 0.8)
        if mode == "train":
            self.x, self.y = feats[:split], target[:split]
        else:
            self.x, self.y = feats[split:], target[split:]

    def __getitem__(self, idx: int):
        return self.x[idx], self.y[idx]

    def __len__(self) -> int:
        return len(self.x)


class Imdb(Dataset):
    """IMDB sentiment (ref: dataset/imdb.py — parses aclImdb_v1.tar.gz,
    builds a frequency-sorted word dict, yields (token_ids, 0/1)).

    Sequences are padded/truncated to ``seq_len`` with 0 (the reference
    yields ragged LoD sequences; dense padded is the TPU-native layout,
    SURVEY §7 'LoD/ragged' decision).
    """

    _URL = ("https://ai.stanford.edu/~amaas/data/sentiment/"
            "aclImdb_v1.tar.gz")

    def __init__(self, mode: str = "train", cutoff: int = 150,
                 seq_len: int = 256,
                 data_home: Optional[str] = None) -> None:
        self.seq_len = seq_len
        if mode == "synthetic":
            rng = np.random.default_rng(11)
            n, vocab = 128, 512
            self.word_idx = {f"w{i}": i for i in range(vocab)}
            self.docs = rng.integers(
                2, vocab, (n, seq_len)).astype(np.int64)
            self.labels = (np.arange(n) % 2).astype(np.int64)
            # class signal: positive docs lean on low ids
            self.docs[self.labels == 1] //= 2
            return
        import re
        home = data_home or os.path.join(DATA_HOME(), "imdb")
        path = _require(os.path.join(home, "aclImdb_v1.tar.gz"),
                        self._URL)
        sub = "train" if mode == "train" else "test"
        pat_pos = re.compile(rf"aclImdb/{sub}/pos/.*\.txt$")
        pat_neg = re.compile(rf"aclImdb/{sub}/neg/.*\.txt$")
        # vocab over train AND test (ref: imdb.py build_dict walks both
        # patterns) — a per-split vocab would permute token ids between
        # the splits and silently break evaluation
        pat_vocab = re.compile(r"aclImdb/(train|test)/(pos|neg)/.*\.txt$")
        tok = re.compile(r"[a-z]+")
        docs_words, labels = [], []
        freq: dict = {}
        with tarfile.open(path, "r:*") as tar:
            for member in tar.getmembers():
                if not pat_vocab.match(member.name):
                    continue
                f = tar.extractfile(member)
                words = tok.findall(
                    f.read().decode("utf-8", "ignore").lower())
                for w in words:
                    freq[w] = freq.get(w, 0) + 1
                lab = 1 if pat_pos.match(member.name) else \
                    0 if pat_neg.match(member.name) else None
                if lab is not None:
                    docs_words.append(words)
                    labels.append(lab)
        # frequency-sorted dict, ids from 2 (0=pad, 1=OOV) — ref
        # build_dict sorts by (-count, word)
        vocab = sorted((w for w, c in freq.items() if c >= cutoff),
                       key=lambda w: (-freq[w], w))
        self.word_idx = {w: i + 2 for i, w in enumerate(vocab)}
        docs = np.zeros((len(docs_words), seq_len), np.int64)
        for i, words in enumerate(docs_words):
            ids = [self.word_idx.get(w, 1) for w in words[:seq_len]]
            docs[i, :len(ids)] = ids
        self.docs = docs
        self.labels = np.asarray(labels, np.int64)

    def __getitem__(self, idx: int):
        return self.docs[idx], self.labels[idx]

    def __len__(self) -> int:
        return len(self.docs)


class Imikolov(Dataset):
    """PTB language-model dataset (ref: dataset/imikolov.py — parses
    simple-examples.tgz, frequency-sorted dict with <s>/<e>/<unk>,
    yields n-grams or full sequences).

    ``data_type="ngram"`` yields (context [n-1], next_word);
    ``data_type="seq"`` yields (padded sequence [seq_len], length) —
    padding uses a DEDICATED ``pad_id`` (one past <unk>), never a real
    word id, and the true length rides along so losses can mask.
    """

    _URL = "http://www.fit.vutbr.cz/~imikolov/rnnlm/simple-examples.tgz"

    def __init__(self, mode: str = "train", data_type: str = "ngram",
                 window_size: int = 5, seq_len: int = 64,
                 min_word_freq: int = 50,
                 data_home: Optional[str] = None,
                 use_native_tokenizer: bool = False) -> None:
        self.data_type = data_type
        self.window_size = window_size
        self.use_native_tokenizer = use_native_tokenizer
        if mode == "synthetic":
            rng = np.random.default_rng(13)
            vocab = 200
            self.word_idx = {f"w{i}": i for i in range(vocab)}
            if data_type == "ngram":
                n = 512
                self.ctx = rng.integers(0, vocab, (n, window_size - 1)) \
                    .astype(np.int64)
                self.nxt = (self.ctx.sum(1) % vocab).astype(np.int64)
            else:
                n = 64
                self.pad_id = vocab
                self.seqs = rng.integers(0, vocab, (n, seq_len)) \
                    .astype(np.int64)
                self.seq_lens = np.full((n,), seq_len, np.int64)
            return
        home = data_home or os.path.join(DATA_HOME(), "imikolov")
        path = _require(os.path.join(home, "simple-examples.tgz"),
                        self._URL)
        fname = ("./simple-examples/data/ptb.train.txt" if mode == "train"
                 else "./simple-examples/data/ptb.valid.txt")
        with tarfile.open(path, "r:*") as tar:
            # dict over the TRAIN split only (ref: build_dict(train()))
            f = tar.extractfile("./simple-examples/data/ptb.train.txt")
            train_text = f.read().decode("utf-8")
            train_lines = train_text.splitlines()
            if mode == "train":
                lines_cache = train_lines
            else:
                f = tar.extractfile(fname)
                lines_cache = f.read().decode("utf-8").splitlines()
        # The C++ tokenizer splits on ASCII whitespace (istream >>);
        # Python str.split() also splits on Unicode whitespace. PTB is
        # ASCII, but a user-staged corpus may not be — fall back to the
        # Python path rather than silently diverge.
        _uni_ws = "\u00a0\u1680\u2000\u2028\u2029\u202f\u205f\u3000\u0085"
        if use_native_tokenizer and any(c in train_text for c in _uni_ws):
            use_native_tokenizer = False
        if use_native_tokenizer:
            # threaded C++ counting (csrc/tokenizer.cc) — same
            # frequency-ranked ordering as the Python path below, so the
            # resulting word ids are identical (tested)
            import os as _os
            import tempfile

            from ..native import Tokenizer
            with tempfile.NamedTemporaryFile("w", suffix=".txt",
                                             encoding="utf-8",
                                             delete=False) as tf:
                tf.write(train_text)
                tmp_corpus = tf.name
            try:
                with Tokenizer.build([tmp_corpus], min_freq=1) as tok:
                    # counts come straight from the build (one C call);
                    # words via the saved vocab file (one I/O) instead
                    # of a per-word ctypes round-trip
                    cnts = tok.freqs()
                    vpath = tmp_corpus + ".vocab"
                    tok.save(vpath)
                    with open(vpath, encoding="utf-8") as vf:
                        vocab_words = vf.read().splitlines()
                    _os.unlink(vpath)
            finally:
                _os.unlink(tmp_corpus)
            freq = {w: int(c) for w, c in zip(vocab_words, cnts)
                    if c > min_word_freq and w != "<unk>"}
        else:
            freq = {}
            for line in train_lines:
                for w in line.strip().split():
                    freq[w] = freq.get(w, 0) + 1
            freq = {w: c for w, c in freq.items() if c > min_word_freq
                    and w != "<unk>"}
        words = sorted(freq.items(), key=lambda kv: (-kv[1], kv[0]))
        # ids: 0.. for frequency-sorted corpus words, then <s>/<e>/<unk>
        # appended. NOTE: internally consistent but NOT identical to the
        # reference's build_dict ids (imikolov.py counts <s>/<e> once
        # per line so they land frequency-ranked, and builds over
        # train+valid); re-encode rather than mixing with
        # reference-derived id artifacts.
        self.word_idx = {w: i for i, (w, _) in enumerate(words)}
        self.word_idx["<s>"] = len(self.word_idx)
        self.word_idx["<e>"] = len(self.word_idx)
        unk = self.word_idx["<unk>"] = len(self.word_idx)
        bos, eos = self.word_idx["<s>"], self.word_idx["<e>"]
        if data_type == "ngram":
            ctxs, nxts = [], []
            n = window_size
            for line in lines_cache:
                ids = [bos] + [self.word_idx.get(w, unk)
                               for w in line.strip().split()] + [eos]
                for i in range(n - 1, len(ids)):
                    ctxs.append(ids[i - n + 1: i])
                    nxts.append(ids[i])
            self.ctx = np.asarray(ctxs, np.int64)
            self.nxt = np.asarray(nxts, np.int64)
        else:
            self.pad_id = len(self.word_idx)  # one past <unk>
            seqs, lens = [], []
            for line in lines_cache:
                ids = [bos] + [self.word_idx.get(w, unk)
                               for w in line.strip().split()] + [eos]
                row = np.full((seq_len,), self.pad_id, np.int64)
                n_ids = min(len(ids), seq_len)
                row[:n_ids] = ids[:seq_len]
                seqs.append(row)
                lens.append(n_ids)
            self.seqs = np.stack(seqs)
            self.seq_lens = np.asarray(lens, np.int64)

    def __len__(self):
        return len(self.ctx) if self.data_type == "ngram" \
            else len(self.seqs)

    def __getitem__(self, i):
        if self.data_type == "ngram":
            return self.ctx[i], self.nxt[i]
        return self.seqs[i], self.seq_lens[i]


class Movielens(Dataset):
    """MovieLens 1-M ratings (ref: dataset/movielens.py — parses
    ml-1m.zip's ::-separated users.dat/movies.dat/ratings.dat; yields
    (user_id, gender, age_bucket, job, movie_id, first_category,
    rating)).

    Dense int features sized for the framework's RecommenderSystem
    model; ``holdout`` fraction becomes the test split (the reference
    random-splits 9:1 per user).
    """

    _URL = "http://files.grouplens.org/datasets/movielens/ml-1m.zip"
    AGE_TABLE = (1, 18, 25, 35, 45, 50, 56)

    def __init__(self, mode: str = "train", holdout: float = 0.1,
                 data_home: Optional[str] = None) -> None:
        if mode == "synthetic":
            rng = np.random.default_rng(17)
            n = 256
            self.rows = np.stack([
                rng.integers(1, 100, n), rng.integers(0, 2, n),
                rng.integers(0, 7, n), rng.integers(0, 21, n),
                rng.integers(1, 120, n), rng.integers(0, 19, n),
            ], 1).astype(np.int64)
            self.ratings = rng.integers(1, 6, (n, 1)).astype(np.float32)
            self.categories = [f"c{i}" for i in range(19)]
            return
        import io
        import zipfile
        home = data_home or os.path.join(DATA_HOME(), "movielens")
        path = _require(os.path.join(home, "ml-1m.zip"), self._URL)
        users, movies = {}, {}
        cats: dict = {}
        with zipfile.ZipFile(path) as z:
            with z.open("ml-1m/users.dat") as f:
                for line in io.TextIOWrapper(f, "latin-1"):
                    uid, gender, age, job, _zip = line.strip().split("::")
                    users[int(uid)] = (
                        0 if gender == "M" else 1,
                        self.AGE_TABLE.index(int(age)), int(job))
            with z.open("ml-1m/movies.dat") as f:
                for line in io.TextIOWrapper(f, "latin-1"):
                    mid, _title, genres = line.strip().split("::")
                    g0 = genres.split("|")[0]
                    cats.setdefault(g0, len(cats))
                    movies[int(mid)] = cats[g0]
            rows, ratings = [], []
            with z.open("ml-1m/ratings.dat") as f:
                for line in io.TextIOWrapper(f, "latin-1"):
                    uid, mid, rate, _ts = line.strip().split("::")
                    uid, mid = int(uid), int(mid)
                    if uid not in users or mid not in movies:
                        continue
                    g, a, j = users[uid]
                    rows.append((uid, g, a, j, mid, movies[mid]))
                    ratings.append(float(rate))
        rows_np = np.asarray(rows, np.int64)
        ratings_np = np.asarray(ratings, np.float32)[:, None]
        # deterministic split (ref uses a seeded random 9:1)
        rng = np.random.default_rng(0)
        take_test = rng.random(len(rows_np)) < holdout
        pick = take_test if mode == "test" else ~take_test
        self.rows = rows_np[pick]
        self.ratings = ratings_np[pick]
        self.categories = sorted(cats, key=cats.get)

    def __len__(self):
        return len(self.rows)

    def __getitem__(self, i):
        return self.rows[i], self.ratings[i]


class WMT16(Dataset):
    """Multi30K EN-DE translation pairs (ref: dataset/wmt16.py — parses
    wmt16.tar.gz's tab-separated "en<TAB>de" train/val/test members,
    builds frequency-sorted dicts per language with <s>/<e>/<unk> at ids
    0/1/2, yields (src_ids, trg_ids, trg_ids_next)).

    Dense padded redesign: sequences pad to ``seq_len`` with <e> after
    the end mark; per-row lengths ride along so losses can mask. The
    (trg_ids, trg_ids_next) teacher-forcing pair follows the reference
    exactly: trg = <s> + words, trg_next = words + <e>.
    """

    _URL = "http://paddlemodels.bj.bcebos.com/wmt/wmt16.tar.gz"
    START, END, UNK = 0, 1, 2

    def __init__(self, mode: str = "train", src_dict_size: int = 4000,
                 trg_dict_size: int = 4000, src_lang: str = "en",
                 seq_len: int = 50,
                 data_home: Optional[str] = None) -> None:
        self.seq_len = seq_len
        if mode == "synthetic":
            rng = np.random.default_rng(19)
            n, v = 128, 200
            self.src_dict = {f"w{i}": i for i in range(v)}
            self.trg_dict = dict(self.src_dict)
            self.src = rng.integers(3, v, (n, seq_len)).astype(np.int64)
            self.trg = np.roll(self.src, 1, axis=1)
            self.trg[:, 0] = self.START
            self.trg_next = self.src.copy()
            self.src_len = np.full((n,), seq_len, np.int64)
            self.trg_len = np.full((n,), seq_len, np.int64)
            return
        home = data_home or os.path.join(DATA_HOME(), "wmt16")
        path = _require(os.path.join(home, "wmt16.tar.gz"), self._URL)
        member = {"train": "wmt16/train", "val": "wmt16/val",
                  "test": "wmt16/test"}[mode]
        src_col = 0 if src_lang == "en" else 1

        # ONE pass over the gzip'd train member counts both language
        # columns (dicts always come from train, whatever the mode);
        # the decoded lines are cached so mode="train" never re-streams
        # the archive
        self._line_cache = {}
        freqs = ({}, {})
        for raw in self._member_lines(path, "wmt16/train"):
            parts = raw.strip().split("\t")
            if len(parts) != 2:
                continue
            for col in (0, 1):
                for w in parts[col].split():
                    freqs[col][w] = freqs[col].get(w, 0) + 1

        def build_dict(col, size):
            # ref ordering: specials then frequency-sorted, cut to size.
            # Corpus tokens spelled like the specials are skipped — a
            # literal "<unk>" would otherwise clobber id 2 (same filter
            # Imikolov applies).
            d = {"<s>": 0, "<e>": 1, "<unk>": 2}
            for w, _ in sorted(freqs[col].items(), key=lambda kv: -kv[1]):
                if len(d) >= size:
                    break
                if w not in d:
                    d[w] = len(d)
            return d

        self.src_dict = build_dict(src_col, src_dict_size)
        self.trg_dict = build_dict(1 - src_col, trg_dict_size)
        src_rows, trg_rows, trg_next_rows = [], [], []
        src_lens, trg_lens = [], []

        def pad(ids):
            row = np.full((seq_len,), self.END, np.int64)
            n_ids = min(len(ids), seq_len)
            row[:n_ids] = ids[:seq_len]
            return row, n_ids

        for raw in self._member_lines(path, member):
                parts = raw.strip().split("\t")
                if len(parts) != 2:
                    continue
                # truncate WORDS first so <s>/<e> always survive — the
                # padded row's invariant (row[len-1] == <e>) is what
                # decode-until-<e> consumers key on
                sw = parts[src_col].split()[: seq_len - 2]
                tw = parts[1 - src_col].split()[: seq_len - 2]
                src_ids = [self.START] + [
                    self.src_dict.get(w, self.UNK) for w in sw] \
                    + [self.END]
                t_ids = [self.trg_dict.get(w, self.UNK) for w in tw]
                trg_ids = [self.START] + t_ids
                trg_next = t_ids + [self.END]
                s_row, s_len = pad(src_ids)
                t_row, t_len = pad(trg_ids)
                tn_row, _ = pad(trg_next)
                src_rows.append(s_row)
                trg_rows.append(t_row)
                trg_next_rows.append(tn_row)
                src_lens.append(s_len)
                trg_lens.append(t_len)
        self.src = np.stack(src_rows)
        self.trg = np.stack(trg_rows)
        self.trg_next = np.stack(trg_next_rows)
        self.src_len = np.asarray(src_lens, np.int64)
        self.trg_len = np.asarray(trg_lens, np.int64)

    def _member_lines(self, path, member):
        if member not in self._line_cache:
            with tarfile.open(path, "r:*") as tar:
                text = tar.extractfile(member).read().decode("utf-8")
            self._line_cache[member] = text.splitlines()
        return self._line_cache[member]

    def __len__(self):
        return len(self.src)

    def __getitem__(self, i):
        return (self.src[i], self.trg[i], self.trg_next[i],
                self.src_len[i], self.trg_len[i])


class WMT14(Dataset):
    """WMT14 EN-FR shrunk set (ref: dataset/wmt14.py:117 — the archive
    ships PRE-BUILT ``src.dict``/``trg.dict`` members whose word id is
    the line number (cut to ``dict_size``), plus tab-separated
    "src<TAB>trg" data members; unlike wmt16 no dict is built from the
    corpus). Reference semantics kept: <s>/<e>/<unk> at ids 0/1/2
    (UNK_IDX=2), sequences longer than 80 tokens are dropped,
    src = <s> + words + <e>, and the teacher-forcing pair is
    trg = <s> + words / trg_next = words + <e>.

    Dense padded redesign like WMT16: rows pad to ``seq_len`` with <e>
    and per-row lengths ride along so losses can mask.
    """

    _URL = "http://paddlemodels.bj.bcebos.com/wmt/wmt14.tgz"
    START, END, UNK = 0, 1, 2
    _MAX_LEN = 80  # ref wmt14.py: "remove sequence whose length > 80"

    def __init__(self, mode: str = "train", dict_size: int = 30000,
                 seq_len: int = 50,
                 data_home: Optional[str] = None) -> None:
        self.seq_len = seq_len
        if mode == "synthetic":
            rng = np.random.default_rng(29)
            n, v = 128, 200
            self.src_dict = {f"w{i}": i for i in range(v)}
            self.trg_dict = dict(self.src_dict)
            self.src = rng.integers(3, v, (n, seq_len)).astype(np.int64)
            self.trg = np.roll(self.src, 1, axis=1)
            self.trg[:, 0] = self.START
            self.trg_next = self.src.copy()
            self.src_len = np.full((n,), seq_len, np.int64)
            self.trg_len = np.full((n,), seq_len, np.int64)
            return
        home = data_home or os.path.join(DATA_HOME(), "wmt14")
        path = _require(os.path.join(home, "wmt14.tgz"), self._URL)
        member_suffix = {"train": "train/train", "test": "test/test",
                         "gen": "gen/gen"}[mode]

        def to_dict(lines):
            # ref __read_to_dict: id = line number, cut to dict_size
            return {ln.strip(): i for i, ln in enumerate(lines)
                    if i < dict_size}

        with tarfile.open(path, "r:*") as tar:
            names = tar.getnames()

            def one(suffix):
                hits = [n for n in names if n.endswith(suffix)]
                if len(hits) != 1:
                    raise ValueError(
                        f"wmt14 archive: expected exactly one member "
                        f"ending in {suffix!r}, found {hits}")
                return tar.extractfile(hits[0]).read().decode(
                    "utf-8").splitlines()

            self.src_dict = to_dict(one("src.dict"))
            self.trg_dict = to_dict(one("trg.dict"))
            data_lines = one(member_suffix)

        def pad(ids):
            row = np.full((seq_len,), self.END, np.int64)
            n_ids = min(len(ids), seq_len)
            row[:n_ids] = ids[:seq_len]
            return row, n_ids

        src_rows, trg_rows, trg_next_rows = [], [], []
        src_lens, trg_lens = [], []
        for raw in data_lines:
            parts = raw.strip().split("\t")
            if len(parts) != 2:
                continue
            src_ids = [self.src_dict.get(w, self.UNK)
                       for w in ["<s>"] + parts[0].split() + ["<e>"]]
            t_words = [self.trg_dict.get(w, self.UNK)
                       for w in parts[1].split()]
            if len(src_ids) > self._MAX_LEN or len(t_words) > self._MAX_LEN:
                continue
            trg_ids = [self.START] + t_words
            trg_next = t_words + [self.END]
            s_row, s_len = pad(src_ids)
            t_row, t_len = pad(trg_ids)
            tn_row, _ = pad(trg_next)
            src_rows.append(s_row)
            trg_rows.append(t_row)
            trg_next_rows.append(tn_row)
            src_lens.append(s_len)
            trg_lens.append(t_len)
        if not src_rows:
            raise ValueError(f"wmt14 {mode}: no parseable pairs")
        self.src = np.stack(src_rows)
        self.trg = np.stack(trg_rows)
        self.trg_next = np.stack(trg_next_rows)
        self.src_len = np.asarray(src_lens, np.int64)
        self.trg_len = np.asarray(trg_lens, np.int64)

    def __len__(self):
        return len(self.src)

    def __getitem__(self, i):
        return (self.src[i], self.trg[i], self.trg_next[i],
                self.src_len[i], self.trg_len[i])


class MQ2007(Dataset):
    """LETOR MQ2007 learning-to-rank (ref: dataset/mq2007.py — parses
    "rel qid:N 1:v .. 46:v #docid" lines; pairwise/listwise readers).

    Dense layout: per-row (features [46], relevance, query_id); use
    ``query_groups()`` for listwise batching (contiguous row ranges per
    query, the analogue of the reference's per-query yield).
    """

    _URL = ("https://download.microsoft.com/download/E/7/E/"
            "E7EABEF1-4C7B-4E31-ACE5-73927950ED5E/Querylevelnorm.rar")
    N_FEATURES = 46

    def __init__(self, mode: str = "train",
                 data_home: Optional[str] = None) -> None:
        if mode == "synthetic":
            rng = np.random.default_rng(23)
            n = 120
            self.features = rng.normal(0, 1, (n, self.N_FEATURES)) \
                .astype(np.float32)
            self.labels = rng.integers(0, 3, (n,)).astype(np.int64)
            self.qids = np.repeat(np.arange(n // 8), 8).astype(np.int64)[:n]
            return
        home = data_home or os.path.join(DATA_HOME(), "mq2007")
        fname = {"train": "train.txt", "val": "vali.txt",
                 "test": "test.txt"}[mode]
        path = _require(os.path.join(home, fname), self._URL)
        feats, labels, qids = [], [], []
        with open(path) as f:
            for line in f:
                line = line.split("#", 1)[0].strip()
                if not line:
                    continue
                parts = line.split()
                labels.append(int(parts[0]))
                qids.append(int(parts[1].split(":", 1)[1]))
                row = np.zeros((self.N_FEATURES,), np.float32)
                for tok in parts[2:]:
                    k, v = tok.split(":", 1)
                    idx = int(k) - 1
                    if 0 <= idx < self.N_FEATURES:
                        row[idx] = float(v)
                feats.append(row)
        self.features = np.stack(feats)
        self.labels = np.asarray(labels, np.int64)
        self.qids = np.asarray(qids, np.int64)

    def query_groups(self):
        """[(qid, start, end)] contiguous ranges (listwise batching)."""
        out = []
        start = 0
        for i in range(1, len(self.qids) + 1):
            if i == len(self.qids) or self.qids[i] != self.qids[start]:
                out.append((int(self.qids[start]), start, i))
                start = i
        return out

    def __len__(self):
        return len(self.labels)

    def __getitem__(self, i):
        return self.features[i], self.labels[i], self.qids[i]


class Conll05(Dataset):
    """CoNLL-2005 semantic role labeling (ref: dataset/conll05.py —
    words.gz/props.gz pairs inside conll05st-tests.tar.gz; bracketed
    span columns convert to BIO tags; one example per predicate).

    Zero-egress adaptation: word/tag dicts build from the parsed corpus
    (frequency-ranked, <unk>=0 like the reference's UNK_IDX) instead of
    the reference's downloaded dict files. Yields dense padded
    (word_ids [T], predicate_mark [T], tag_ids [T], length) — the exact
    input contract of models.SRLBiLSTMCRF.
    """

    _URL = ("http://paddlemodels.bj.bcebos.com/conll05st/"
            "conll05st-tests.tar.gz")

    def __init__(self, mode: str = "test", seq_len: int = 64,
                 data_home: Optional[str] = None,
                 words_member: str = ("conll05st-release/test.wsj/words/"
                                      "test.wsj.words.gz"),
                 props_member: str = ("conll05st-release/test.wsj/props/"
                                      "test.wsj.props.gz")) -> None:
        self.seq_len = seq_len
        if mode not in ("test", "synthetic"):
            raise ValueError(
                f"Conll05 mode={mode!r}: the public CoNLL-05 release "
                "ships only the test splits (conll05st-tests.tar.gz); "
                "use mode='test' (default members) or 'synthetic'")
        if mode == "synthetic":
            rng = np.random.default_rng(29)
            n, v, t = 64, 120, 9
            self.word_dict = {f"w{i}": i for i in range(v)}
            self.label_dict = {f"T{i}": i for i in range(t)}
            self.words = rng.integers(1, v, (n, seq_len)).astype(np.int64)
            self.marks = (rng.random((n, seq_len)) < 0.1).astype(np.int64)
            self.tags = rng.integers(0, t, (n, seq_len)).astype(np.int64)
            self.lengths = np.full((n,), seq_len, np.int64)
            return
        home = data_home or os.path.join(DATA_HOME(), "conll05")
        path = _require(os.path.join(home, "conll05st-tests.tar.gz"),
                        self._URL)
        sentences = self._parse(path, words_member, props_member)
        # dicts: <unk>=0, then frequency-ranked words (ref UNK_IDX = 0)
        freq: dict = {}
        tagset = set()
        for words, preds in sentences:
            for w in words:
                freq[w] = freq.get(w, 0) + 1
            for _, bio in preds:
                tagset.update(bio)
        self.word_dict = {"<unk>": 0}
        for w, _ in sorted(freq.items(), key=lambda kv: (-kv[1], kv[0])):
            self.word_dict[w] = len(self.word_dict)
        self.label_dict = {t: i for i, t in enumerate(sorted(tagset))}
        rows_w, rows_m, rows_t, lens = [], [], [], []
        for words, preds in sentences:
            wid = [self.word_dict.get(w, 0) for w in words]
            for verb_idx, bio in preds:
                n_tok = min(len(words), seq_len)
                w_row = np.zeros((seq_len,), np.int64)
                m_row = np.zeros((seq_len,), np.int64)
                t_row = np.zeros((seq_len,), np.int64)
                w_row[:n_tok] = wid[:seq_len]
                if verb_idx < seq_len:
                    m_row[verb_idx] = 1
                t_row[:n_tok] = [self.label_dict[b]
                                 for b in bio[:seq_len]]
                rows_w.append(w_row)
                rows_m.append(m_row)
                rows_t.append(t_row)
                lens.append(n_tok)
        self.words = np.stack(rows_w)
        self.marks = np.stack(rows_m)
        self.tags = np.stack(rows_t)
        self.lengths = np.asarray(lens, np.int64)

    @staticmethod
    def _parse(path, words_member, props_member):
        """[(words, [(verb_index, bio_tags)])] per sentence."""
        with tarfile.open(path, "r:*") as tar:
            wf = tar.extractfile(words_member)
            pf = tar.extractfile(props_member)
            words_text = gzip.decompress(wf.read()).decode("utf-8")
            props_text = gzip.decompress(pf.read()).decode("utf-8")
        w_lines = words_text.splitlines()
        p_lines = props_text.splitlines()
        if len(w_lines) != len(p_lines):
            raise ValueError(
                f"conll05 words/props line counts differ "
                f"({len(w_lines)} vs {len(p_lines)}) — mispaired or "
                "truncated files would silently misalign every tag")
        sentences = []
        cur_words: list = []
        cur_props: list = []
        for wline, pline in zip(w_lines, p_lines):
            w = wline.strip()
            p = pline.strip().split()
            if not w:  # sentence boundary
                if cur_words:
                    sentences.append(
                        Conll05._finish(cur_words, cur_props))
                cur_words, cur_props = [], []
                continue
            cur_words.append(w)
            cur_props.append(p)
        if cur_words:
            sentences.append(Conll05._finish(cur_words, cur_props))
        return sentences

    @staticmethod
    def _finish(words, props):
        """props rows: [verb_lemma_or_-, span_col_per_predicate...];
        bracket spans -> BIO (the reference's corpus_reader walk)."""
        n_pred = len(props[0]) - 1 if props else 0
        preds = []
        for col in range(1, n_pred + 1):
            bio = []
            cur = None
            verb_idx = 0
            for i, row in enumerate(props):
                tok = row[col]
                if tok.startswith("("):
                    tag = tok[1:].split("*", 1)[0]
                    bio.append("B-" + tag)
                    cur = tag
                    if tag == "V":
                        verb_idx = i
                    if tok.endswith(")"):
                        cur = None
                elif cur is not None:
                    bio.append("I-" + cur)
                    if tok.endswith(")"):
                        cur = None
                else:
                    bio.append("O")
            preds.append((verb_idx, bio))
        return words, preds

    def __len__(self):
        return len(self.words)

    def __getitem__(self, i):
        return (self.words[i], self.marks[i], self.tags[i],
                self.lengths[i])


class Flowers(Dataset):
    """Oxford 102 flowers (ref: dataset/flowers.py — 102flowers.tgz of
    jpg/*.jpg, imagelabels.mat 1-based labels, setid.mat split ids).

    Images decode+resize at access time (PIL), [C, H, W] float32 in
    [0, 1]; labels shift to 0-based.
    """

    _URL = ("http://www.robots.ox.ac.uk/~vgg/data/flowers/102/"
            "102flowers.tgz (+ imagelabels.mat, setid.mat)")

    def __init__(self, mode: str = "train", image_size: int = 64,
                 transform=None, data_home: Optional[str] = None) -> None:
        self.image_size = image_size
        self.transform = transform
        if mode == "synthetic":
            rng = np.random.default_rng(31)
            n = 32
            self.images = rng.random((n, 3, image_size, image_size)) \
                .astype(np.float32)
            self.labels = rng.integers(0, 102, (n,)).astype(np.int64)
            return
        self.images = None
        import scipy.io as sio
        home = data_home or os.path.join(DATA_HOME(), "flowers")
        tgz = _require(os.path.join(home, "102flowers.tgz"), self._URL)
        labels_mat = _require(os.path.join(home, "imagelabels.mat"),
                              self._URL)
        setid_mat = _require(os.path.join(home, "setid.mat"), self._URL)
        all_labels = sio.loadmat(labels_mat)["labels"].ravel() - 1
        splits = sio.loadmat(setid_mat)
        key = {"train": "trnid", "val": "valid", "test": "tstid"}[mode]
        ids = splits[key].ravel()  # 1-based image ids
        self._tgz = tgz
        self._ids = ids
        self.labels = all_labels[ids - 1].astype(np.int64)
        # ONE long-lived TarFile per dataset: reopening a .tgz per item
        # would re-decompress from byte 0 on every member seek (gzip has
        # no random access) — O(archive) work per sample. Opened LAZILY
        # per process (not here) so the dataset pickles cleanly into
        # multiprocess DataLoader workers; each process gets its own
        # handle on first access.
        self._tar = None
        self._members = None
        self._tar_lock = None

    def __getstate__(self):
        # drop the per-process tar handle/lock; workers reopen lazily
        state = self.__dict__.copy()
        state["_tar"] = state["_members"] = state["_tar_lock"] = None
        return state

    _TAR_INIT_LOCK = __import__("threading").Lock()

    def _ensure_tar(self):
        if self._tar is not None and self._tar_lock is not None:
            return
        with Flowers._TAR_INIT_LOCK:  # two threads racing first access
            if self._tar_lock is None:
                self._tar_lock = __import__("threading").Lock()
            if self._tar is None:
                self._members = None
                tar = tarfile.open(self._tgz, "r:*")
                self._members = {m.name: m for m in tar.getmembers()
                                 if m.name.endswith(".jpg")}
                self._tar = tar

    def _load_image(self, image_id: int) -> np.ndarray:
        from PIL import Image
        name = f"jpg/image_{image_id:05d}.jpg"
        self._ensure_tar()
        with self._tar_lock:  # TarFile seeks are not thread-safe
            f = self._tar.extractfile(self._members[name])
            data = f.read()
        img = Image.open(io.BytesIO(data)).convert("RGB")
        img = img.resize((self.image_size, self.image_size))
        arr = np.asarray(img, np.float32) / 255.0
        return np.transpose(arr, (2, 0, 1))

    def __len__(self):
        return len(self.labels)

    def __getitem__(self, i):
        if self.images is not None:  # synthetic
            img = self.images[i]
        else:
            img = self._load_image(int(self._ids[i]))
        if self.transform is not None:
            img = self.transform(img)
        return img, self.labels[i]


class VOC2012(Dataset):
    """PASCAL VOC2012 detection (ref: dataset/voc2012.py — VOCdevkit
    JPEGImages + Annotations XML; the reference yields segmentation,
    PaddleCV's detection readers yield boxes — this serves the
    detection family, feeding models.SSDLite directly).

    Per item: (image [3, S, S] float32, gt_boxes [max_boxes, 4]
    normalized corners 0-padded, gt_labels [max_boxes] with -1 padding;
    class ids 1..20, 0 reserved for background). Images with MORE than
    ``max_boxes`` objects are truncated to the first max_boxes (raise
    the limit for crowded-scene training — VOC has images with 40+
    boxes; the default 20 covers ~99% of trainval).
    """

    _URL = ("http://host.robots.ox.ac.uk/pascal/VOC/voc2012/"
            "VOCtrainval_11-May-2012.tar")
    CLASSES = ("aeroplane", "bicycle", "bird", "boat", "bottle", "bus",
               "car", "cat", "chair", "cow", "diningtable", "dog",
               "horse", "motorbike", "person", "pottedplant", "sheep",
               "sofa", "train", "tvmonitor")

    def __init__(self, mode: str = "train", image_size: int = 128,
                 max_boxes: int = 20,
                 data_home: Optional[str] = None) -> None:
        self.image_size = image_size
        self.max_boxes = max_boxes
        self._cls_id = {c: i + 1 for i, c in enumerate(self.CLASSES)}
        if mode == "synthetic":
            rng = np.random.default_rng(37)
            n = 16
            self.images = rng.random((n, 3, image_size, image_size)) \
                .astype(np.float32)
            self.boxes = np.zeros((n, max_boxes, 4), np.float32)
            self.labels = np.full((n, max_boxes), -1, np.int64)
            for i in range(n):
                k = rng.integers(1, 4)
                c = rng.uniform(0.2, 0.8, (k, 2))
                wh = rng.uniform(0.05, 0.15, (k, 2))
                self.boxes[i, :k] = np.concatenate([c - wh, c + wh], 1)
                self.labels[i, :k] = rng.integers(1, 21, (k,))
            return
        self.images = None
        home = data_home or os.path.join(DATA_HOME(), "voc2012")
        tar_path = _require(
            os.path.join(home, "VOCtrainval_11-May-2012.tar"), self._URL)
        self._tar_path = tar_path
        base = "VOCdevkit/VOC2012"
        split = {"train": "train", "val": "val",
                 "trainval": "trainval"}[mode]
        with tarfile.open(tar_path, "r:*") as tar:
            names = tar.extractfile(
                f"{base}/ImageSets/Main/{split}.txt") \
                .read().decode().split()
            self._names = names
            self._members = {m.name: m for m in tar.getmembers()}
        self._base = base

    def _parse_item(self, name: str):
        import xml.etree.ElementTree as ET

        from PIL import Image
        with tarfile.open(self._tar_path, "r:*") as tar:
            xml_bytes = tar.extractfile(self._members[
                f"{self._base}/Annotations/{name}.xml"]).read()
            jpg_bytes = tar.extractfile(self._members[
                f"{self._base}/JPEGImages/{name}.jpg"]).read()
        root = ET.fromstring(xml_bytes)
        w = float(root.find("size/width").text)
        h = float(root.find("size/height").text)
        boxes = np.zeros((self.max_boxes, 4), np.float32)
        labels = np.full((self.max_boxes,), -1, np.int64)
        k = 0
        for obj in root.iter("object"):
            if k >= self.max_boxes:
                break
            cls = obj.find("name").text.strip()
            if cls not in self._cls_id:
                continue
            bb = obj.find("bndbox")
            x1 = float(bb.find("xmin").text) / w
            y1 = float(bb.find("ymin").text) / h
            x2 = float(bb.find("xmax").text) / w
            y2 = float(bb.find("ymax").text) / h
            boxes[k] = (x1, y1, x2, y2)
            labels[k] = self._cls_id[cls]
            k += 1
        img = Image.open(io.BytesIO(jpg_bytes)).convert("RGB") \
            .resize((self.image_size, self.image_size))
        arr = np.transpose(np.asarray(img, np.float32) / 255.0,
                           (2, 0, 1))
        return arr, boxes, labels

    def __len__(self):
        return len(self.labels) if self.images is not None \
            else len(self._names)

    def __getitem__(self, i):
        if self.images is not None:  # synthetic
            return self.images[i], self.boxes[i], self.labels[i]
        return self._parse_item(self._names[i])


def _freq_vocab_and_pad(docs_words, freq, seq_len):
    """Shared text contract: frequency-ranked vocab (ties
    lexicographic), ids from 2 (0=pad, 1=OOV), dense pad/truncate to
    seq_len. One definition so Imdb/MovieReviews cannot drift."""
    vocab = sorted(freq, key=lambda w: (-freq[w], w))
    word_idx = {w: i + 2 for i, w in enumerate(vocab)}
    docs = np.zeros((len(docs_words), seq_len), np.int64)
    for i, words in enumerate(docs_words):
        ids = [word_idx.get(w, 1) for w in words[:seq_len]]
        docs[i, :len(ids)] = ids
    return word_idx, docs


class MovieReviews(Dataset):
    """NLTK movie_reviews sentiment corpus (ref: dataset/sentiment.py —
    the reference shells out to nltk.download; zero-egress here: stage
    the corpus directory (movie_reviews/{pos,neg}/*.txt) and this
    parses it directly, same frequency-ranked vocab + (ids, 0/1 label)
    contract, dense padded like Imdb).
    """

    _URL = ("https://www.nltk.org/nltk_data/ (movie_reviews corpus; "
            "extract so DATA_HOME/sentiment/movie_reviews/{pos,neg} "
            "hold the .txt files)")

    def __init__(self, mode: str = "train", seq_len: int = 256,
                 holdout: float = 0.1,
                 data_home: Optional[str] = None) -> None:
        self.seq_len = seq_len
        if mode == "synthetic":
            rng = np.random.default_rng(41)
            n, vocab = 64, 300
            self.word_idx = {f"w{i}": i for i in range(vocab)}
            self.docs = rng.integers(2, vocab, (n, seq_len)) \
                .astype(np.int64)
            self.labels = (np.arange(n) % 2).astype(np.int64)
            self.docs[self.labels == 1] //= 2
            return
        home = data_home or os.path.join(DATA_HOME(), "sentiment")
        root = _require(os.path.join(home, "movie_reviews"), self._URL)
        docs_words, labels = [], []
        freq: dict = {}
        for label, sub in ((1, "pos"), (0, "neg")):
            subdir = os.path.join(root, sub)
            if not os.path.isdir(subdir):
                raise FileNotFoundError(
                    f"expected {subdir} with .txt reviews ({self._URL})")
            for fname in sorted(os.listdir(subdir)):
                if not fname.endswith(".txt"):
                    continue
                with open(os.path.join(subdir, fname),
                          encoding="utf-8", errors="ignore") as f:
                    words = f.read().lower().split()
                for w in words:
                    freq[w] = freq.get(w, 0) + 1
                docs_words.append(words)
                labels.append(label)
        self.word_idx, docs = _freq_vocab_and_pad(docs_words, freq,
                                                  seq_len)
        labels_np = np.asarray(labels, np.int64)
        # deterministic STRATIFIED split: a per-class shuffled
        # round-robin pick, so both classes appear in both splits even
        # for tiny corpora (an iid Bernoulli draw cannot promise that)
        take_test = np.zeros(len(docs), bool)
        rng = np.random.default_rng(0)
        for cls in (0, 1):
            idx = np.flatnonzero(labels_np == cls)
            rng.shuffle(idx)
            n_test = max(1, int(round(len(idx) * holdout))) \
                if len(idx) > 1 else 0
            take_test[idx[:n_test]] = True
        pick = take_test if mode == "test" else ~take_test
        self.docs = docs[pick]
        self.labels = labels_np[pick]

    def __len__(self):
        return len(self.labels)

    def __getitem__(self, i):
        return self.docs[i], self.labels[i]
