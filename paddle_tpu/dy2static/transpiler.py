"""AST transpiler: Python control flow → converted (traceable) calls.

TPU-native rebuild of the reference's dygraph_to_static program
translator (/root/reference/python/paddle/fluid/dygraph/
dygraph_to_static/program_translator.py + the 23 transformer files:
ifelse_transformer.py, loop_transformer.py, logical_transformer.py,
return_transformer.py…). The reference rewrites Python source into
calls that build ProgramDesc while/conditional_block ops; here the
rewrite targets the runtime dispatchers in convert_ops.py, which lower
to lax.cond/while_loop/fori_loop only when the condition is traced —
eager calls keep exact Python semantics.

Rewrites:
- returns inside `if` → flag rewrite: `__pt_ret/__pt_did` assignments,
                      trailing statements guarded by `if not __pt_did`,
                      one final return (ref: return_transformer.py)
- ``if``            → convert_ifelse_stmt
- ``while``         → convert_while      (break/continue/return: left
                      as Python; traced carries then raise in jax)
- ``for i in range``→ convert_for_range
- ``and/or/not``    → convert_logical_*  (short-circuit kept in eager)

State crosses the boundary via generated get/set closures using
``nonlocal``; names that may be unbound get an UNDEFINED preamble (the
reference's undefined-var placeholders).
"""

from __future__ import annotations

import ast
import inspect
import textwrap
from typing import List, Optional, Set

from . import convert_ops

_JST = "_pt_jst"
_UNDEF = "_PT_UNDEF"


def _assigned_names(stmts: List[ast.stmt]) -> Set[str]:
    """Names stored by these statements, not descending into nested
    function/class definitions."""
    names: Set[str] = set()

    class V(ast.NodeVisitor):
        def visit_FunctionDef(self, node):
            names.add(node.name)

        visit_AsyncFunctionDef = visit_FunctionDef

        def visit_ClassDef(self, node):
            names.add(node.name)

        def visit_Lambda(self, node):
            pass

        def visit_Name(self, node):
            if isinstance(node.ctx, (ast.Store, ast.Del)):
                names.add(node.id)

        def visit_For(self, node):
            self.generic_visit(node)

    v = V()
    for s in stmts:
        v.visit(s)
    return names


def _has_toplevel_return(stmts: List[ast.stmt]) -> bool:
    return any(isinstance(s, ast.Return) for s in stmts)


def _contains_return(stmts: List[ast.stmt]) -> bool:
    class V(ast.NodeVisitor):
        found = False

        def visit_Return(self, node):
            self.found = True

        def visit_FunctionDef(self, node):
            pass

        visit_AsyncFunctionDef = visit_FunctionDef

        def visit_Lambda(self, node):
            pass

    v = V()
    for s in stmts:
        v.visit(s)
    return v.found


def _contains_break_or_continue(stmts: List[ast.stmt]) -> bool:
    class V(ast.NodeVisitor):
        found = False

        def visit_Break(self, node):
            self.found = True

        def visit_Continue(self, node):
            self.found = True

        def visit_For(self, node):  # their break belongs to them
            pass

        def visit_While(self, node):
            pass

        def visit_FunctionDef(self, node):
            pass

        visit_AsyncFunctionDef = visit_FunctionDef

    v = V()
    for s in stmts:
        v.visit(s)
    return v.found


_RET = "__pt_ret"
_DID = "__pt_did"

# generated helper functions (never carried as state; __pt_ret/__pt_did
# ARE carried)
_HELPER_RE = None  # set below


def _is_helper_name(n: str) -> bool:
    import re
    global _HELPER_RE
    if _HELPER_RE is None:
        _HELPER_RE = re.compile(
            r"^__pt_(tf|ff|get|set|cond|body|outer|unused|v)(_\d+)?$")
    return bool(_HELPER_RE.match(n))


def _needs_return_rewrite(stmts: List[ast.stmt]) -> bool:
    """True if any `if` OUTSIDE loops/with/try contains a return."""
    for s in stmts:
        if isinstance(s, ast.If):
            if _contains_return(s.body) or _contains_return(s.orelse):
                return True
            if _needs_return_rewrite(s.body) \
                    or _needs_return_rewrite(s.orelse):
                return True
    return False


def _rewrite_returns(fdef: ast.FunctionDef) -> None:
    """The reference return_transformer's capability, flag-based:
    `return X` inside an `if` becomes `__pt_ret = X; __pt_did = True`;
    statements following a maybe-returning `if` are wrapped in
    `if not __pt_did:`; the function ends with `return __pt_ret`.
    Loop/with/try bodies keep their real returns (the statement
    converter leaves such constructs as Python)."""
    if not _needs_return_rewrite(fdef.body):
        return
    body, _ = _rewrite_block(fdef.body)
    pre = [
        ast.Assign(targets=[_name(_RET, ast.Store())],
                   value=ast.Constant(value=None)),
        ast.Assign(targets=[_name(_DID, ast.Store())],
                   value=ast.Constant(value=False)),
    ]
    fdef.body = pre + body + [ast.Return(value=_name(_RET))]


def _rewrite_block(stmts: List[ast.stmt]):
    """Returns (rewritten statements, may_have_set_did)."""
    out: List[ast.stmt] = []
    for i, s in enumerate(stmts):
        if isinstance(s, ast.Return):
            out.append(ast.Assign(
                targets=[_name(_RET, ast.Store())],
                value=s.value if s.value is not None
                else ast.Constant(value=None)))
            out.append(ast.Assign(targets=[_name(_DID, ast.Store())],
                                  value=ast.Constant(value=True)))
            return out, True  # rest of this block is unreachable
        if isinstance(s, ast.If):
            s.body, b1 = _rewrite_block(s.body)
            s.orelse, b2 = _rewrite_block(s.orelse)
            if not s.body:
                s.body = [ast.Pass()]
            out.append(s)
            if b1 or b2:
                rest, _ = _rewrite_block(stmts[i + 1:])
                if rest:
                    out.append(ast.If(
                        test=ast.UnaryOp(op=ast.Not(),
                                         operand=_name(_DID)),
                        body=rest, orelse=[]))
                return out, True
            continue
        # loops / with / try keep real returns; eager semantics exact,
        # and the statement converter leaves them as Python
        out.append(s)
    return out, False


class _LogicalTransformer(ast.NodeTransformer):
    """and/or/not → convert_logical_* with lambda-wrapped operands
    (ref: logical_transformer.py)."""

    def visit_BoolOp(self, node: ast.BoolOp):
        self.generic_visit(node)
        fn = ("convert_logical_and" if isinstance(node.op, ast.And)
              else "convert_logical_or")
        expr = node.values[-1]
        for left in reversed(node.values[:-1]):
            expr = ast.Call(
                func=ast.Attribute(value=ast.Name(id=_JST, ctx=ast.Load()),
                                   attr=fn, ctx=ast.Load()),
                args=[ast.Lambda(args=_empty_args(), body=left),
                      ast.Lambda(args=_empty_args(), body=expr)],
                keywords=[])
        return expr

    def visit_UnaryOp(self, node: ast.UnaryOp):
        self.generic_visit(node)
        if isinstance(node.op, ast.Not):
            return ast.Call(
                func=ast.Attribute(value=ast.Name(id=_JST, ctx=ast.Load()),
                                   attr="convert_logical_not",
                                   ctx=ast.Load()),
                args=[node.operand], keywords=[])
        return node


def _empty_args() -> ast.arguments:
    return ast.arguments(posonlyargs=[], args=[], vararg=None,
                         kwonlyargs=[], kw_defaults=[], kwarg=None,
                         defaults=[])


def _name(id_: str, ctx=None) -> ast.Name:
    return ast.Name(id=id_, ctx=ctx or ast.Load())


def _jst_call(attr: str, args: List[ast.expr]) -> ast.Call:
    return ast.Call(
        func=ast.Attribute(value=_name(_JST), attr=attr, ctx=ast.Load()),
        args=args, keywords=[])


def _tuple_of(names: List[str], ctx) -> ast.expr:
    return ast.Tuple(elts=[ast.Name(id=n, ctx=ctx) for n in names],
                     ctx=ctx)


class _ControlFlowTransformer:
    """Statement-level rewriting with bound-name tracking."""

    def __init__(self) -> None:
        self._uid = 0

    def _fresh(self, kind: str) -> str:
        self._uid += 1
        return f"__pt_{kind}_{self._uid}"

    def transform_function(self, fdef: ast.FunctionDef) -> None:
        bound = {a.arg for a in (fdef.args.posonlyargs + fdef.args.args
                                 + fdef.args.kwonlyargs)}
        if fdef.args.vararg:
            bound.add(fdef.args.vararg.arg)
        if fdef.args.kwarg:
            bound.add(fdef.args.kwarg.arg)
        _rewrite_returns(fdef)
        fdef.body = self._block(fdef.body, bound)

    def _helpers(self, names: List[str], carry_defs: List[ast.stmt],
                 bound: Set[str]) -> (str, str, List[ast.stmt]):
        """Emit UNDEF preambles + get/set helper defs for `names`."""
        pre: List[ast.stmt] = []
        for n in names:
            if n not in bound:
                pre.append(ast.Assign(targets=[_name(n, ast.Store())],
                                      value=_name(_UNDEF)))
        get = self._fresh("get")
        set_ = self._fresh("set")
        get_def = ast.FunctionDef(
            name=get, args=_empty_args(),
            body=[ast.Return(value=_tuple_of(names, ast.Load()))],
            decorator_list=[])
        vparam = "__pt_v"
        set_body: List[ast.stmt] = []
        if names:
            set_body.append(ast.Nonlocal(names=list(names)))
        set_body.append(ast.Assign(
            targets=[_tuple_of(names, ast.Store())]
            if names else [ast.Name(id="__pt_unused", ctx=ast.Store())],
            value=_name(vparam)))
        set_def = ast.FunctionDef(
            name=set_, args=ast.arguments(
                posonlyargs=[], args=[ast.arg(arg=vparam)], vararg=None,
                kwonlyargs=[], kw_defaults=[], kwarg=None, defaults=[]),
            body=set_body, decorator_list=[])
        carry_defs.extend(pre + [get_def, set_def])
        return get, set_

    def _branch_fn(self, kind: str, body: List[ast.stmt],
                   nonlocals: List[str],
                   params: Optional[List[str]] = None) -> (str, ast.stmt):
        name = self._fresh(kind)
        stmts: List[ast.stmt] = []
        if nonlocals:
            stmts.append(ast.Nonlocal(names=list(nonlocals)))
        stmts.extend(body if body else [ast.Pass()])
        args = _empty_args()
        if params:
            args = ast.arguments(
                posonlyargs=[], args=[ast.arg(arg=p) for p in params],
                vararg=None, kwonlyargs=[], kw_defaults=[], kwarg=None,
                defaults=[])
        return name, ast.FunctionDef(name=name, args=args, body=stmts,
                                     decorator_list=[])

    def _block(self, stmts: List[ast.stmt], bound: Set[str]) \
            -> List[ast.stmt]:
        out: List[ast.stmt] = []
        for s in stmts:
            out.extend(self._stmt(s, bound))
        return out

    def _stmt(self, s: ast.stmt, bound: Set[str]) -> List[ast.stmt]:
        if isinstance(s, ast.If):
            return self._convert_if(s, bound)
        if isinstance(s, ast.While):
            return self._convert_while(s, bound)
        if isinstance(s, ast.For):
            return self._convert_for(s, bound)
        if isinstance(s, (ast.With, ast.Try)):
            for attr in ("body", "orelse", "finalbody"):
                if hasattr(s, attr) and getattr(s, attr):
                    setattr(s, attr, self._block(getattr(s, attr), bound))
        bound |= _assigned_names([s])
        return [s]

    def _convert_if(self, s: ast.If, bound: Set[str]) -> List[ast.stmt]:
        if _contains_return(s.body) or _contains_return(s.orelse) \
                or _contains_break_or_continue(s.body) \
                or _contains_break_or_continue(s.orelse):
            # only reachable inside a Python-kept loop/with/try (the
            # return rewrite handled every other return): leave the `if`
            # as Python so return/break/continue keep their meaning
            inner_t, inner_f = set(bound), set(bound)
            s.body = self._block(s.body, inner_t)
            s.orelse = self._block(s.orelse, inner_f)
            bound |= _assigned_names([s])
            return [s]
        inner_bound_t = set(bound)
        inner_bound_f = set(bound)
        body = self._block(s.body, inner_bound_t)
        orelse = self._block(s.orelse, inner_bound_f)
        names = sorted((_assigned_names(s.body)
                        | _assigned_names(s.orelse)) - {"_"})
        names = [n for n in names if not _is_helper_name(n)]
        defs: List[ast.stmt] = []
        get, set_ = self._helpers(names, defs, bound)
        tname, tdef = self._branch_fn("tf", body, names)
        fname, fdef = self._branch_fn("ff", orelse, names)
        call = _jst_call(
            "convert_ifelse_stmt",
            [s.test, _name(tname), _name(fname), _name(get), _name(set_)])
        bound |= set(names)
        return defs + [tdef, fdef, ast.Expr(value=call)]

    def _convert_while(self, s: ast.While, bound: Set[str]) \
            -> List[ast.stmt]:
        if _contains_break_or_continue(s.body) \
                or _contains_return(s.body) or s.orelse:
            # leave as Python (break/continue/else unsupported in
            # lax.while_loop; eager semantics preserved)
            inner = set(bound)
            s.body = self._block(s.body, inner)
            bound |= _assigned_names([s])
            return [s]
        inner = set(bound) | _assigned_names(s.body)
        body = self._block(s.body, set(inner))
        names = sorted(_assigned_names(s.body) - {"_"})
        names = [n for n in names if not _is_helper_name(n)]
        defs: List[ast.stmt] = []
        get, set_ = self._helpers(names, defs, bound)
        cname, cdef = self._branch_fn(
            "cond", [ast.Return(value=s.test)], [])
        bname, bdef = self._branch_fn("body", body, names)
        call = _jst_call("convert_while",
                         [_name(cname), _name(bname), _name(get),
                          _name(set_)])
        bound |= set(names)
        return defs + [cdef, bdef, ast.Expr(value=call)]

    def _convert_for(self, s: ast.For, bound: Set[str]) -> List[ast.stmt]:
        is_range = (isinstance(s.iter, ast.Call)
                    and isinstance(s.iter.func, ast.Name)
                    and s.iter.func.id == "range"
                    and not s.iter.keywords
                    and 1 <= len(s.iter.args) <= 3
                    and isinstance(s.target, ast.Name))
        if (not is_range or _contains_break_or_continue(s.body)
                or _contains_return(s.body) or s.orelse):
            inner = set(bound) | {s.target.id} \
                if isinstance(s.target, ast.Name) else set(bound)
            s.body = self._block(s.body, inner)
            bound |= _assigned_names([s])
            return [s]
        inner = set(bound) | {s.target.id} | _assigned_names(s.body)
        body = self._block(s.body, set(inner))
        names = sorted(_assigned_names(s.body) - {"_", s.target.id})
        names = [n for n in names if not _is_helper_name(n)]
        defs: List[ast.stmt] = []
        get, set_ = self._helpers(names, defs, bound)
        bname, bdef = self._branch_fn("body", body, names,
                                      params=[s.target.id])
        a = s.iter.args
        if len(a) == 1:
            start, stop, step = ast.Constant(0), a[0], ast.Constant(1)
        elif len(a) == 2:
            start, stop, step = a[0], a[1], ast.Constant(1)
        else:
            start, stop, step = a
        call = _jst_call("convert_for_range",
                         [start, stop, step, _name(bname), _name(get),
                          _name(set_)])
        bound |= set(names)
        return defs + [bdef, ast.Expr(value=call)]


def convert_control_flow(fn):
    """Return `fn` rewritten so data-dependent Python control flow
    lowers to lax.cond/while/fori under tracing (the reference's
    @declarative AST path). Falls back to `fn` unchanged (with the
    reason) when the source is unavailable."""
    try:
        source = textwrap.dedent(inspect.getsource(fn))
    except (OSError, TypeError) as e:
        return fn, f"source unavailable: {e}"
    try:
        tree = ast.parse(source)
    except SyntaxError as e:
        return fn, f"unparsable source: {e}"
    fdef = tree.body[0]
    if not isinstance(fdef, (ast.FunctionDef, ast.AsyncFunctionDef)):
        return fn, "not a function definition"
    fdef.decorator_list = []

    _ControlFlowTransformer().transform_function(fdef)
    new_tree = _LogicalTransformer().visit(tree)
    ast.fix_missing_locations(new_tree)

    glb = dict(fn.__globals__)
    glb[_JST] = convert_ops
    glb[_UNDEF] = convert_ops.UNDEFINED

    freevars = fn.__code__.co_freevars
    if freevars:
        # Compile inside a synthetic outer function whose parameters are
        # the original freevars — the inner def then has real freevars —
        # and rebind the ORIGINAL closure cells onto the inner code
        # object, so the converted function reads the live cells (a
        # later `nonlocal` write in the enclosing scope stays visible),
        # not a value snapshot.
        import types
        outer = ast.FunctionDef(
            name="__pt_outer",
            args=ast.arguments(
                posonlyargs=[], args=[ast.arg(arg=v) for v in freevars],
                vararg=None, kwonlyargs=[], kw_defaults=[], kwarg=None,
                defaults=[]),
            body=[new_tree.body[0],
                  ast.Return(value=_name(fdef.name))],
            decorator_list=[])
        module = ast.Module(body=[outer], type_ignores=[])
        ast.fix_missing_locations(module)
        code = compile(module, f"<dy2static {fn.__qualname__}>", "exec")
        outer_code = next(
            c for c in code.co_consts
            if isinstance(c, types.CodeType)
            and c.co_name == "__pt_outer")
        inner_code = next(
            c for c in outer_code.co_consts
            if isinstance(c, types.CodeType) and c.co_name == fdef.name)
        cell_by_name = dict(zip(fn.__code__.co_freevars,
                                fn.__closure__ or ()))
        closure = tuple(cell_by_name[v] for v in inner_code.co_freevars)
        new_fn = types.FunctionType(inner_code, glb, fdef.name,
                                    fn.__defaults__, closure)
    else:
        code = compile(new_tree, f"<dy2static {fn.__qualname__}>", "exec")
        ns = {}
        exec(code, glb, ns)
        new_fn = ns[fdef.name]
    new_fn.__defaults__ = fn.__defaults__
    new_fn.__kwdefaults__ = fn.__kwdefaults__
    new_fn.__wrapped__ = fn
    return new_fn, None
