"""Runtime dispatchers for AST-converted control flow.

TPU-native counterpart of the reference's convert_operators
(/root/reference/python/paddle/fluid/dygraph/dygraph_to_static/
convert_operators.py: convert_ifelse :202, convert_while_loop :38,
convert_logical_and/or/not). The transpiler rewrites Python `if`/
`while`/`for`/`and`/`or`/`not` into calls here; each dispatcher checks
at RUN time whether the condition depends on a traced value — plain
Python control flow stays plain (exact semantics, zero overhead in
eager mode), traced control flow lowers to lax.cond / lax.while_loop /
lax.fori_loop (the reference lowers to conditional_block / while_op).

State passes through get_args/set_args closures over the enclosing
function's locals (``nonlocal`` write-back), mirroring the reference's
design: under tracing each branch/iteration starts with set_args() of
the operand tracers, so both lax.cond branches trace from identical
state.
"""

from __future__ import annotations

from typing import Callable

import jax
import jax.numpy as jnp
from jax import core as jax_core
from jax import lax


class _Undefined:
    """Placeholder for names that may be unbound before a converted
    statement (ref: dygraph_to_static undefined-var placeholders)."""

    _inst = None

    def __new__(cls):
        if cls._inst is None:
            cls._inst = super().__new__(cls)
        return cls._inst

    def __repr__(self):
        return "<undefined>"


UNDEFINED = _Undefined()


def is_traced(x) -> bool:
    return isinstance(x, jax_core.Tracer)


def _any_traced(tree) -> bool:
    return any(isinstance(l, jax_core.Tracer)
               for l in jax.tree.leaves(tree))


def _check_defined(init, kind: str) -> None:
    if any(v is UNDEFINED for v in init):
        raise ValueError(
            f"a variable assigned inside a traced `{kind}` is read "
            f"before being defined on all paths; initialize it before "
            f"the `{kind}` (XLA structured control flow requires every "
            f"carried value to exist on entry — same constraint as the "
            f"reference's while_op/conditional_block)")


def _defined_ops(init):
    """Split the carry into (defined operand values, rebuild fn).

    A name first assigned INSIDE both branches needs no initial value —
    lax.cond does not require matching in/out structure — so UNDEFINED
    slots are held out of the operands and re-inserted on entry."""
    mask = [v is not UNDEFINED for v in init]
    ops = tuple(v for v, m in zip(init, mask) if m)

    def rebuild(vals):
        it = iter(vals)
        return tuple(next(it) if m else UNDEFINED for m in mask)

    return ops, rebuild


_BRANCH_MISMATCH_HINT = (
    "; a variable assigned in only one branch of a traced `if` (or left "
    "undefined on one path) cannot be used after it — assign it on both "
    "paths (lax.cond requires matching branch outputs, the same "
    "constraint as the reference's conditional_block)")


def _placeholder_like(x):
    """Dead-slot placeholder (the reference's RETURN_NO_VALUE magic
    number, convert_operators.py). NaN-filled for floats so that if a
    traced function CAN fall through without returning — the one case
    where the placeholder escapes through the final `return __pt_ret` —
    the result is loudly wrong (NaN propagates) instead of plausible
    zeros. Eager calls are unaffected (they return None exactly)."""
    if jnp.issubdtype(x.dtype, jnp.floating):
        return jnp.full(x.shape, jnp.nan, x.dtype)
    return jnp.full(x.shape, jnp.iinfo(x.dtype).min
                    if jnp.issubdtype(x.dtype, jnp.signedinteger)
                    else 0, x.dtype)


def convert_ifelse_stmt(pred, true_fn: Callable, false_fn: Callable,
                        get_args: Callable, set_args: Callable) -> None:
    """`if` with no return statements: pure state mutation
    (ref: convert_operators.py:202).

    One-sided carries — a slot that one branch leaves as None/UNDEFINED
    while the other assigns an array (the return-flag rewrite's
    ``__pt_ret``, or a name first assigned in a single branch) — are
    repaired with a zero placeholder on the unassigned side, the
    reference's RETURN_NO_VALUE mechanism (convert_operators.py). The
    placeholder is dead by construction: the ``__pt_did`` flag (or the
    user's own control flow) guards any later read.
    """
    if not is_traced(pred):
        if pred:
            true_fn()
        else:
            false_fn()
        return
    init = get_args()
    ops, rebuild = _defined_ops(init)

    def make(branch):
        def run(args):
            set_args(rebuild(args))
            branch()
            return get_args()
        return run

    tf, ff = make(true_fn), make(false_fn)

    # Probe output structures abstractly (restoring the enclosing locals
    # afterwards — set_args mutates them during the probe). A branch
    # whose output contains UNDEFINED (user variable assigned on one
    # path, read later) fails the probe; no repair then — the cond
    # below raises with the mismatch hint.
    snapshot = get_args()
    try:
        t_out = jax.eval_shape(tf, ops)
        f_out = jax.eval_shape(ff, ops)
    except TypeError:
        t_out = f_out = None
    finally:
        set_args(snapshot)

    if t_out is not None and f_out is not None:
        # repair None-holes only: the return-flag rewrite's __pt_ret is
        # None on the non-returning side and provably dead there
        holes_t = [i for i, (t, f) in enumerate(zip(t_out, f_out))
                   if t is None and f is not None]
        holes_f = [i for i, (t, f) in enumerate(zip(t_out, f_out))
                   if f is None and t is not None]

        def patch(run, holes, other_out):
            if not holes:
                return run

            def patched(args):
                out = list(run(args))
                for i in holes:
                    out[i] = _placeholder_like(other_out[i])
                return tuple(out)
            return patched

        tf = patch(tf, holes_t, f_out)
        ff = patch(ff, holes_f, t_out)

    try:
        out = lax.cond(pred, tf, ff, ops)
    except TypeError as e:
        raise ValueError(str(e) + _BRANCH_MISMATCH_HINT) from e
    set_args(out)


def convert_while(cond_fn: Callable, body_fn: Callable,
                  get_args: Callable, set_args: Callable) -> None:
    """(ref: convert_operators.py:38 convert_while_loop)."""
    probe = cond_fn()
    if not (is_traced(probe) or _any_traced(get_args())):
        # plain Python do-while on the probe result: the condition is
        # evaluated exactly once per iteration (a side-effecting
        # condition must not be re-probed)
        ok = bool(probe)
        while ok:
            body_fn()
            ok = bool(cond_fn())
        return

    init = get_args()
    _check_defined(init, "while")

    def cond(args):
        set_args(args)
        return jnp.asarray(cond_fn(), bool)

    def body(args):
        set_args(args)
        body_fn()
        return get_args()

    set_args(lax.while_loop(cond, body, init))


def convert_for_range(start, stop, step, body_fn: Callable,
                      get_args: Callable, set_args: Callable) -> None:
    """`for i in range(...)` — lax.fori_loop when the bounds or carried
    state are traced, plain Python range otherwise."""
    traced = any(map(is_traced, (start, stop, step))) \
        or _any_traced(get_args())
    if not traced:
        for i in range(start, stop, step):
            body_fn(i)
        return
    init = get_args()
    _check_defined(init, "for")
    start = jnp.asarray(start, jnp.int32)
    stop = jnp.asarray(stop, jnp.int32)
    step = jnp.asarray(step, jnp.int32)
    n = jnp.maximum((stop - start + step - jnp.sign(step))
                    // jnp.where(step == 0, 1, step), 0)

    def body(k, args):
        set_args(args)
        body_fn(start + k * step)
        return get_args()

    set_args(lax.fori_loop(0, n, body, init))


def convert_logical_and(lhs: Callable, rhs: Callable):
    """`a and b` — short-circuit preserved for Python values
    (ref: convert_operators.py convert_logical_and)."""
    a = lhs()
    if not is_traced(a):
        return a and rhs()
    return jnp.logical_and(a, rhs())


def convert_logical_or(lhs: Callable, rhs: Callable):
    a = lhs()
    if not is_traced(a):
        return a or rhs()
    return jnp.logical_or(a, rhs())


def convert_logical_not(x):
    if not is_traced(x):
        return not x
    return jnp.logical_not(x)
