"""dygraph→static AST conversion (the reference's @declarative path).

See transpiler.py for the rewrite rules and convert_ops.py for the
runtime lax lowering.
"""

from .convert_ops import (UNDEFINED, convert_for_range,  # noqa: F401
                          convert_ifelse_stmt, convert_logical_and,
                          convert_logical_not, convert_logical_or,
                          convert_while, is_traced)
from .transpiler import convert_control_flow  # noqa: F401
