"""jit: dygraph→static capture, traced layers, and serialized inference.

TPU-native rebuild of the reference's dygraph-to-static stack
(/root/reference/python/paddle/fluid/dygraph/jit.py: @declarative/
TracedLayer/jit.save+load; dygraph_to_static/program_translator.py). The
reference transpiles Python ASTs into ProgramDesc ops; on TPU **tracing is
compilation** — jax traces the function once into a jaxpr and XLA compiles
it, so:

- ``to_static(fn)``    → a :class:`StaticFunction`: cached jax.jit over the
  eager code (AST transpiling collapses into tracing; data-dependent
  control flow must use lax.cond/scan, matching the reference's
  while_op/conditional_block constraint).
- ``TracedLayer.trace``→ capture a Layer + example inputs into a frozen
  (params, compiled-fn) pair for deployment.
- ``jit.save/load``    → portable artifacts: parameters + a serialized
  ``jax.export`` StableHLO module (versioned, runnable without the model's
  Python class — the analogue of save_inference_model's pruned
  ProgramDesc, io.py:52).
"""

from __future__ import annotations

import json
import os
from typing import Any, Callable, Dict, Optional, Sequence, Tuple

import jax
import jax.export  # noqa: F401 — jax.export is not eagerly imported by jax
import jax.numpy as jnp
import numpy as np

from . import io as io_mod
from .nn.layer import Layer, functional_call
from .observability import instrumented_jit

__all__ = ["to_static", "declarative", "not_to_static", "StaticFunction",
           "TracedLayer",
           "save", "load", "TranslatedLayer", "InputSpec"]


class InputSpec:
    """Declarative input signature (ref: static/input.py InputSpec).

    None leading dims mark symbolic batch: export uses jax shape
    polymorphism so any batch size can be served.
    """

    def __init__(self, shape: Sequence[Optional[int]], dtype="float32",
                 name: Optional[str] = None):
        self.shape = tuple(shape)
        self.dtype = dtype
        self.name = name

    def to_sds(self, symbol: str = "b") -> jax.ShapeDtypeStruct:
        from .core.dtype import convert_dtype
        if any(s is None for s in self.shape):
            dims = ",".join(symbol if s is None else str(s)
                            for s in self.shape)
            shp = jax.export.symbolic_shape(f"({dims})")
        else:
            shp = self.shape
        return jax.ShapeDtypeStruct(shp, convert_dtype(self.dtype))

    def __repr__(self):
        return f"InputSpec(shape={self.shape}, dtype={self.dtype})"


class StaticFunction:
    """A callable captured for compilation (ref: jit.py @declarative →
    StaticFunction in dygraph_to_static/program_translator.py).

    Data-dependent Python control flow (``if tensor:``, ``while
    tensor:``, ``for i in range(tensor)``) is AST-converted to
    lax.cond/while/fori first (dy2static/transpiler.py — the
    reference's 23-transformer @declarative pipeline); if the source is
    unavailable (C callables, REPL lambdas) the function compiles
    trace-only, like the reference's TracedLayer path.
    """

    def __init__(self, fn: Callable, input_spec=None,
                 convert_cf: bool = True,
                 name: Optional[str] = None) -> None:
        self._fn = fn
        self._name = name
        self._input_spec = input_spec
        self.conversion_note = None
        run = fn
        if convert_cf and not getattr(fn, "__pt_not_to_static__", False):
            from .dy2static import convert_control_flow
            try:
                run, self.conversion_note = convert_control_flow(fn)
            except NotImplementedError as e:
                raise  # explicit unsupported pattern: surface it
            except Exception as e:  # noqa: BLE001
                run, self.conversion_note = fn, f"conversion failed: {e}"
        self._converted = run
        # env-set FLAGS_compile_cache_dir applies at the compile entry
        # points (define() fires no on_change)
        from . import sysconfig as _sysconfig
        _sysconfig.apply_compile_cache_flag()
        # jit through the recompile tracker: every retrace of this
        # function is counted (and storm-warned) per display name
        if self._name is None:
            self._name = "to_static:" + getattr(
                fn, "__qualname__", getattr(fn, "__name__", "fn"))
        self._jitted = instrumented_jit(run, self._name)
        self.__wrapped__ = fn

    def __call__(self, *args, **kwargs):
        return self._jitted(*args, **kwargs)

    @property
    def concrete_program(self):
        """Trace with the declared input_spec and return the jaxpr — the
        analogue of inspecting the generated ProgramDesc."""
        if self._input_spec is None:
            raise ValueError("concrete_program needs input_spec")
        sds = [s.to_sds() if isinstance(s, InputSpec) else s
               for s in self._input_spec]
        return jax.make_jaxpr(self._converted)(*sds)

    def rollback(self) -> Callable:
        """Return the original eager function, undoing any in-place
        forward conversion on a wrapped Layer (ref: jit.py rollback —
        the reference restores the dygraph forward the same way)."""
        restore = getattr(self, "_restore", None)
        if restore is not None:
            restore()
        return self._fn


def to_static(function=None, input_spec=None):
    """Decorator/wrapper marking a function or Layer for compilation
    (ref: @fluid.dygraph.jit.declarative, jit.py)."""

    def wrap(fn):
        if isinstance(fn, Layer):
            layer = fn
            # convert the forward METHOD's control flow, then drive the
            # layer through its normal __call__ (hooks intact)
            from .dy2static import convert_control_flow
            import types
            note = None
            orig_forward = layer.forward
            try:
                conv, note = convert_control_flow(
                    orig_forward.__func__
                    if hasattr(orig_forward, "__func__")
                    else orig_forward)
                if note is None:
                    layer.forward = types.MethodType(conv, layer)
            except NotImplementedError:
                raise
            except Exception as e:  # noqa: BLE001
                note = f"conversion failed: {e}"

            def call(*args, **kwargs):
                return layer(*args, **kwargs)

            sf = StaticFunction(call, input_spec, convert_cf=False,
                                name=f"to_static:{type(layer).__name__}")
            sf.conversion_note = note
            sf.layer = layer

            def _restore():
                if layer.forward is not orig_forward:
                    try:
                        del layer.forward  # uncover the class method
                    except AttributeError:
                        layer.forward = orig_forward
            sf._restore = _restore
            return sf
        return StaticFunction(fn, input_spec)

    if function is None:
        return wrap
    return wrap(function)


declarative = to_static  # reference alias (@fluid.dygraph.declarative)


def not_to_static(fn: Callable) -> Callable:
    """Marker parity shim (ref: jit.not_to_static): returns fn unchanged —
    in the tracing design only explicitly wrapped functions compile."""
    fn.__pt_not_to_static__ = True
    return fn


class TracedLayer:
    """Frozen (params, compiled forward) capture of a Layer
    (ref: jit.py TracedLayer.trace/save_inference_model)."""

    def __init__(self, layer: Layer, params: Dict[str, Any],
                 buffers: Dict[str, Any], example_args: Tuple) -> None:
        self._layer = layer
        self._params = params
        self._buffers = buffers
        self._example_args = example_args

        def fwd(params, buffers, *args):
            was_training = layer.training
            layer.eval()
            try:
                return functional_call(layer, params, buffers, *args)
            finally:
                if was_training:
                    layer.train()

        self._fwd = fwd
        self._jitted = jax.jit(fwd)

    @staticmethod
    def trace(layer: Layer, inputs: Sequence) -> Tuple[Any, "TracedLayer"]:
        inputs = tuple(jnp.asarray(np.asarray(x)) for x in inputs)
        traced = TracedLayer(layer, layer.param_dict(), layer.buffer_dict(),
                             inputs)
        out = traced(*inputs)
        return out, traced

    def __call__(self, *args):
        return self._jitted(self._params, self._buffers, *args)

    def save_inference_model(self, dirname: str) -> None:
        save(self._layer, dirname,
             input_spec=[InputSpec(x.shape, str(x.dtype))
                         for x in self._example_args])


def save(layer, path: str, input_spec: Optional[Sequence] = None) -> None:
    """Serialize a Layer (or StaticFunction over one) for serving
    (ref: jit.py save → TranslatedLayer; io.py save_inference_model:52).

    Writes under ``path``:
      - ``params/``      parameter+buffer checkpoint
      - ``module.bin``   jax.export StableHLO artifact of the eval forward
      - ``meta.json``    input specs + platforms
    """
    if isinstance(layer, StaticFunction):
        if not hasattr(layer, "layer"):
            raise ValueError("jit.save needs a Layer or to_static(Layer)")
        layer = layer.layer
    if input_spec is None:
        raise ValueError("jit.save requires input_spec (shapes may use "
                         "None for a polymorphic batch dim)")
    specs = [s if isinstance(s, InputSpec) else InputSpec(*s)
             for s in input_spec]
    params = layer.param_dict()
    buffers = layer.buffer_dict()

    def serving(params, buffers, *args):
        was_training = layer.training
        layer.eval()
        try:
            return functional_call(layer, params, buffers, *args)
        finally:
            if was_training:
                layer.train()

    sds = [s.to_sds() for s in specs]
    p_sds = jax.tree.map(lambda x: jax.ShapeDtypeStruct(x.shape, x.dtype),
                         params)
    b_sds = jax.tree.map(lambda x: jax.ShapeDtypeStruct(x.shape, x.dtype),
                         buffers)
    exported = jax.export.export(jax.jit(serving))(p_sds, b_sds, *sds)

    os.makedirs(path, exist_ok=True)
    io_mod.save({"params": params, "buffers": buffers},
                os.path.join(path, "params"))
    with open(os.path.join(path, "module.bin"), "wb") as f:
        f.write(exported.serialize())
    meta = {
        "format": "paddle_tpu_jit", "version": 1,
        "platforms": list(exported.platforms),
        "input_spec": [{"shape": [None if s is None else int(s)
                                  for s in sp.shape],
                        "dtype": str(sp.dtype),
                        "name": sp.name or f"x{i}"}
                       for i, sp in enumerate(specs)],
    }
    with open(os.path.join(path, "meta.json"), "w") as f:
        json.dump(meta, f, indent=1)


class TranslatedLayer:
    """A loaded serving module (ref: jit.py TranslatedLayer): runs the
    deserialized StableHLO with the stored weights — no Python model class
    required."""

    def __init__(self, path: str) -> None:
        with open(os.path.join(path, "meta.json")) as f:
            self.meta = json.load(f)
        if self.meta.get("format") != "paddle_tpu_jit":
            raise ValueError(f"{path} is not a paddle_tpu jit artifact")
        with open(os.path.join(path, "module.bin"), "rb") as f:
            self._exported = jax.export.deserialize(f.read())
        flat = io_mod.load(os.path.join(path, "params"))
        # io.load flattens pytrees to "/"-joined keys; param/buffer names
        # are the dotted layer paths after the first segment
        self._params = {k.split("/", 1)[1]: v for k, v in flat.items()
                        if k.startswith("params/")}
        self._buffers = {k.split("/", 1)[1]: v for k, v in flat.items()
                         if k.startswith("buffers/")}

    def __call__(self, *args):
        args = tuple(jnp.asarray(np.asarray(a)) for a in args)
        return self._exported.call(self._params, self._buffers, *args)

    @property
    def input_spec(self):
        return [InputSpec(tuple(s["shape"]), s["dtype"])
                for s in self.meta["input_spec"]]


def load(path: str) -> TranslatedLayer:
    """(ref: jit.py load)."""
    return TranslatedLayer(path)
